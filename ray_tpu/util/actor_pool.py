"""ActorPool: load-balance tasks over a fixed set of actors.

Reference parity: python/ray/util/actor_pool.py (submit/map/
map_unordered/get_next/get_next_unordered/push/pop_idle).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, List, Optional

import ray_tpu


class ActorPool:
    def __init__(self, actors: Iterable[Any]):
        self._idle: List[Any] = list(actors)
        self._future_to_actor = {}
        self._index_to_future = {}
        self._next_task_index = 0
        self._next_return_index = 0

    # -- submission ---------------------------------------------------------
    def submit(self, fn: Callable[[Any, Any], Any], value: Any) -> None:
        """fn(actor, value) -> ObjectRef; runs when an actor is idle."""
        if not self._idle:
            raise RuntimeError(
                "no idle actors; call get_next()/get_next_unordered() "
                "to harvest results first")
        actor = self._idle.pop()
        future = fn(actor, value)
        self._future_to_actor[future] = (self._next_task_index, actor)
        self._index_to_future[self._next_task_index] = future
        self._next_task_index += 1

    def has_free(self) -> bool:
        return bool(self._idle)

    def has_next(self) -> bool:
        return bool(self._future_to_actor)

    # -- harvesting ---------------------------------------------------------
    def get_next(self, timeout: Optional[float] = None) -> Any:
        """Next result in SUBMISSION order."""
        if self._next_return_index >= self._next_task_index:
            raise StopIteration("no pending results")
        future = self._index_to_future.pop(self._next_return_index)
        self._next_return_index += 1
        value = ray_tpu.get(future, timeout=timeout)
        _, actor = self._future_to_actor.pop(future)
        self._idle.append(actor)
        return value

    def get_next_unordered(self, timeout: Optional[float] = None) -> Any:
        """Next COMPLETED result, any order."""
        if not self._future_to_actor:
            raise StopIteration("no pending results")
        ready, _ = ray_tpu.wait(list(self._future_to_actor),
                                num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("get_next_unordered timed out")
        future = ready[0]
        idx, actor = self._future_to_actor.pop(future)
        self._index_to_future.pop(idx, None)
        self._idle.append(actor)
        return ray_tpu.get(future)

    # -- bulk ---------------------------------------------------------------
    def map(self, fn, values: Iterable[Any]):
        for v in values:
            if not self._idle:
                yield self.get_next()
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn, values: Iterable[Any]):
        for v in values:
            if not self._idle:
                yield self.get_next_unordered()
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    # -- membership ---------------------------------------------------------
    def push(self, actor: Any) -> None:
        self._idle.append(actor)

    def pop_idle(self) -> Optional[Any]:
        return self._idle.pop() if self._idle else None
