"""Functions/classes callable from the C++ worker API by descriptor.

The C++ API (src/cpp/ray_api.h) submits tasks as cross-language function
descriptors — module + qualname — instead of pickled code (reference
parity: ray.cross_language / FunctionDescriptor). This module doubles as
the demo target and the test fixture for that path.
"""

from __future__ import annotations


def add(a, b):
    return a + b


def concat(*parts):
    return "".join(parts)


def big_bytes(n: int) -> bytes:
    """> INLINE_OBJECT_LIMIT results exercise the shm-location push and
    the C++ side's daemon fetch."""
    return b"x" * int(n)


def echo(x):
    return x


class Counter:
    def __init__(self, start=0):
        self.n = start

    def incr(self, k=1):
        self.n += k
        return self.n

    def total(self):
        return self.n
