"""Runtime enforcement of the engine's dispatch discipline.

jaxlint (tools/jaxlint) checks the invariant statically; this module
checks it at runtime: the steady-state engine tick must run with ZERO
host->device transfers (the decode loop is device-resident; tokens
feed back on device) and ZERO new XLA compilations (shape buckets are
warm). A single stray `jnp.asarray(host_array)` per tick or a
shape-bucket churn reintroduces exactly the host syncs / recompile
storms PR 1/2 removed — this harness turns them into test failures
instead of bench regressions.

Usage (see tests/test_dispatch_guard.py):

    with dispatch_guard() as report:
        for _ in range(32):
            engine.step()
    # raises GuardViolation on any compile; a host->device transfer
    # raises inside the block via jax.transfer_guard

Two mechanisms, both armed for the duration of the context:

- `jax.transfer_guard_host_to_device("disallow_explicit")`: any h2d
  transfer — implicit (scalar/ndarray commits during op dispatch) or
  explicit (`jax.device_put`, `jnp.asarray(host_array)`) — raises
  immediately at the offending call, so the traceback points at the
  exact engine line. Plain "disallow" would let explicit uploads
  through, which is precisely the `self._dev(jnp.asarray(...))` form
  a stray engine upload takes. Device->host stays ALLOWED by
  default: the engine's one per-tick token readback is the
  sanctioned sync point (pass d2h="disallow" to forbid it too).
- a log_compiles sentinel: `jax_log_compiles` emits one "Compiling
  <name> ..." record per XLA build; a logging.Handler on the jax
  loggers collects them, and leaving the context raises
  GuardViolation if more than `max_compiles` were seen.
"""

from __future__ import annotations

import contextlib
import dataclasses
import logging
from typing import List

import jax

__all__ = ["GuardViolation", "GuardReport", "dispatch_guard"]

# the jax-internal loggers that carry compile events (0.4.x: pxla logs
# "Compiling <fn> with global shapes and types ..."; kept broad so a
# jax upgrade moving the message keeps the sentinel alive)
_COMPILE_LOGGERS = (
    "jax._src.interpreters.pxla",
    "jax._src.dispatch",
    "jax._src.compiler",
)
_COMPILE_PREFIX = "Compiling "


class GuardViolation(RuntimeError):
    """Dispatch-discipline violation observed inside a dispatch_guard
    block (compiles over budget; transfer violations raise at the
    transfer site via jax.transfer_guard instead)."""


@dataclasses.dataclass
class GuardReport:
    """What the guard observed; yielded by dispatch_guard so tests can
    assert exact counts (e.g. allow N warmup compiles explicitly)."""
    compiles: List[str] = dataclasses.field(default_factory=list)

    @property
    def n_compiles(self) -> int:
        return len(self.compiles)


class _CompileSentinel(logging.Handler):
    def __init__(self, report: GuardReport):
        super().__init__(level=logging.DEBUG)
        self._report = report

    def emit(self, record: logging.LogRecord) -> None:
        try:
            msg = record.getMessage()
        except Exception:            # never let logging break the run
            return
        if msg.startswith(_COMPILE_PREFIX):
            self._report.compiles.append(msg)


@contextlib.contextmanager
def dispatch_guard(max_compiles: int = 0,
                   h2d: str = "disallow_explicit",
                   d2h: str = "allow", raise_on_violation: bool = True,
                   recorder=None):
    """Arm transfer guards + the compile sentinel around a hot-path
    section.

    max_compiles: XLA builds tolerated before GuardViolation (0 for
        steady state; warmup sections can pass an explicit budget).
    h2d / d2h: jax.transfer_guard levels for host->device /
        device->host ("allow" | "log" | "disallow" | "log_explicit" |
        "disallow_explicit"; the h2d default is strict because a
        stray engine upload is usually an EXPLICIT jnp.asarray).
    raise_on_violation: False collects the report without raising
        (observability mode for benches) — "disallow" transfer
        levels are downgraded to their "log" forms so a stray
        transfer cannot crash the observed run either.
    recorder: optional flight recorder — any object with a
        `.record(kind, **fields)` method, e.g. the LLM engine's
        `telemetry.recorder` — given one, a compile-budget violation
        lands as a structured "guard_violation" event (ISSUE 5: the
        post-mortem dump at GET /debug/events shows it even when a
        retry layer swallows the raise, and report-only mode records
        without raising at all).
    """
    if not raise_on_violation:
        downgrade = {"disallow": "log",
                     "disallow_explicit": "log_explicit"}
        h2d = downgrade.get(h2d, h2d)
        d2h = downgrade.get(d2h, d2h)
    report = GuardReport()
    sentinel = _CompileSentinel(report)
    loggers = [logging.getLogger(name) for name in _COMPILE_LOGGERS]
    prev_log_compiles = bool(jax.config.jax_log_compiles)
    jax.config.update("jax_log_compiles", True)
    # fail CLOSED: a host app that muted logging (logging.disable or
    # raised logger levels — bench scripts do) would otherwise drop
    # the "Compiling ..." records before the sentinel sees them and
    # the guard would silently pass a recompile storm. Un-mute the
    # jax loggers for the guarded section, restore after.
    prev_disable = logging.root.manager.disable
    if prev_disable >= logging.WARNING:
        logging.disable(logging.NOTSET)
    prev_levels = [(lg, lg.level) for lg in loggers]
    for lg in loggers:
        if lg.getEffectiveLevel() > logging.WARNING:
            lg.setLevel(logging.WARNING)
        lg.addHandler(sentinel)
    try:
        with jax.transfer_guard_host_to_device(h2d), \
                jax.transfer_guard_device_to_host(d2h):
            yield report
    finally:
        for lg, level in prev_levels:
            lg.removeHandler(sentinel)
            lg.setLevel(level)
        logging.disable(prev_disable)
        jax.config.update("jax_log_compiles", prev_log_compiles)
    if report.n_compiles > max_compiles:
        if recorder is not None:
            try:
                recorder.record(
                    "guard_violation", cause="compile",
                    n_compiles=report.n_compiles,
                    budget=max_compiles,
                    first=report.compiles[0] if report.compiles
                    else "")
            except Exception:
                pass         # observability must never mask the raise
        if raise_on_violation:
            shown = "\n  ".join(report.compiles[:8])
            raise GuardViolation(
                f"{report.n_compiles} XLA compilation(s) inside a "
                f"dispatch_guard block (budget {max_compiles}) — shape "
                f"bucket churn or an untracked retrace on the hot path:"
                f"\n  {shown}")
