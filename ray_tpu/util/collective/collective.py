"""Host-side actor collectives (cross-process, object-store transport).

Reference parity: ray.util.collective's group management + GLOO backend
(python/ray/util/collective/collective.py:123-625,
collective_group/gloo_collective_group.py) — rendezvous through a named
actor instead of Redis; payloads move through the shm object store. This
is the control-plane collective for host coordination (e.g. Train worker
groups exchanging addresses/metrics); in-program tensor collectives run
over ICI via the xla module.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np


class ReduceOp:
    SUM = "sum"
    PRODUCT = "product"
    MIN = "min"
    MAX = "max"


_PAIRWISE = {
    ReduceOp.SUM: np.add,
    ReduceOp.PRODUCT: np.multiply,
    ReduceOp.MIN: np.minimum,
    ReduceOp.MAX: np.maximum,
}


def _tree_reduce(op, parts: List[Any]):
    """Elementwise-reduce matching pytrees (dicts/lists of arrays — e.g.
    whole gradient pytrees — or bare arrays)."""
    import functools

    import jax

    return jax.tree_util.tree_map(
        lambda *leaves: functools.reduce(op, leaves), *parts)


class _GroupActor:
    """Rendezvous + reduction point for one collective group (async actor)."""

    def __init__(self, world_size: int,
                 declared_ranks: Optional[Dict[str, int]] = None):
        import asyncio
        self.world_size = world_size
        # actor_id -> rank, set by create_collective_group so member
        # actors can auto-join without an explicit init call.
        self.declared_ranks = declared_ranks or {}
        self._ops: Dict[str, dict] = {}
        self._mailbox: Dict[tuple, Any] = {}
        self._lock = asyncio.Lock()
        self._events: Dict[str, Any] = {}
        self._departed: set = set()

    async def deregister(self, rank: int) -> int:
        """A rank leaving the group (destroy is collective). Returns the
        number of ranks still registered — the caller that drops it to
        zero kills the rendezvous actor, so teardown by a fast-finishing
        rank can't yank the mailbox out from under peers mid-send."""
        self._departed.add(rank)
        return self.world_size - len(self._departed)

    async def remaining(self) -> int:
        return self.world_size - len(self._departed)

    async def declared_rank_of(self, actor_id: str):
        return self.declared_ranks.get(actor_id)

    async def get_world_size(self) -> int:
        return self.world_size

    async def _op_slot(self, key: str):
        import asyncio
        async with self._lock:
            slot = self._ops.get(key)
            if slot is None:
                slot = {"parts": {}, "event": asyncio.Event(), "result": None}
                self._ops[key] = slot
            return slot

    async def contribute(self, key: str, rank: int, payload,
                         op: Optional[str], mode: str):
        """All ranks call; returns the collective result for this op key.

        Reduction ops fold contributions into a running accumulator in
        RANK order (deterministic fp results); out-of-order arrivals are
        buffered until their turn, so memory is O(pending prefix gap),
        typically one payload, instead of the old O(world x payload).
        Only allgather inherently retains every part.
        """
        import asyncio
        slot = await self._op_slot(key)
        seen = slot.setdefault("seen", set())
        if rank in seen:
            raise ValueError(
                f"rank {rank} contributed twice to collective {key!r} "
                f"(duplicate rank assignment or replayed call)")
        seen.add(rank)
        if mode in ("allreduce", "reducescatter"):
            reduce_op = _PAIRWISE[ReduceOp.SUM if mode == "reducescatter"
                                  else (op or ReduceOp.SUM)]
            buf = slot.setdefault("buffer", {})
            buf[rank] = payload
            nxt = slot.setdefault("next_rank", 0)
            while nxt in buf:
                part = buf.pop(nxt)
                acc = slot.get("acc")
                slot["acc"] = part if acc is None else _tree_reduce(
                    reduce_op, [acc, part])
                nxt += 1
            slot["next_rank"] = nxt
        elif mode == "allgather":
            slot["parts"][rank] = payload
        elif mode == "broadcast":
            if rank == int(op or 0):
                slot["acc"] = payload
        if len(seen) == self.world_size:
            if mode in ("allreduce", "reducescatter"):
                slot["result"] = slot.pop("acc")
            elif mode == "allgather":
                slot["result"] = [slot["parts"][r]
                                  for r in range(self.world_size)]
            elif mode == "broadcast":
                slot["result"] = slot.pop("acc")
            elif mode == "barrier":
                slot["result"] = True
            slot["event"].set()
        await asyncio.wait_for(slot["event"].wait(), timeout=300.0)
        result = slot["result"]
        slot.setdefault("claimed", 0)
        slot["claimed"] += 1
        if slot["claimed"] >= self.world_size:
            self._ops.pop(key, None)
        if mode == "reducescatter":
            # Each rank gets its shard of the reduced tensor.
            arr = np.asarray(result)
            return np.array_split(arr, self.world_size, axis=0)[rank]
        return result

    async def post(self, dst_rank: int, tag: str, payload):
        import asyncio
        key = (dst_rank, tag)
        self._mailbox[key] = payload
        ev = self._events.pop(key, None)
        if ev is not None:
            ev.set()
        return True

    async def take(self, dst_rank: int, tag: str):
        import asyncio
        key = (dst_rank, tag)
        if key not in self._mailbox:
            ev = self._events.setdefault(key, asyncio.Event())
            await asyncio.wait_for(ev.wait(), timeout=300.0)
        return self._mailbox.pop(key)


class _GroupHandle:
    def __init__(self, actor, world_size: int, rank: int, name: str):
        self.actor = actor
        self.world_size = world_size
        self.rank = rank
        self.name = name
        self.op_counter = 0
        self.send_tags: Dict[int, int] = {}
        self.recv_tags: Dict[int, int] = {}


_groups: Dict[str, _GroupHandle] = {}
_groups_lock = threading.Lock()


def _group_actor_name(group_name: str) -> str:
    return f"__collective_group__{group_name}"


def init_collective_group(world_size: int, rank: int,
                          backend: str = "host",
                          group_name: str = "default") -> None:
    """Join a collective group (all members must call this)."""
    import ray_tpu

    name = _group_actor_name(group_name)
    actor = None
    if rank == 0:
        _reap_stale_group(name)
        GroupActor = ray_tpu.remote(_GroupActor)
        actor = GroupActor.options(name=name, lifetime="detached").remote(
            world_size)
    else:
        # Bind only to a FRESH group actor (remaining == world_size).
        # A stale actor from a previous run can briefly hold the name
        # while rank 0 reaps + recreates it; binding to that one would
        # leave this member holding a dead handle (TOCTOU), so keep
        # polling until the fresh incarnation appears.
        deadline = time.time() + 60
        while time.time() < deadline:
            try:
                candidate = ray_tpu.get_actor(name)
                remaining = ray_tpu.get(candidate.remaining.remote(),
                                        timeout=10)
                world = ray_tpu.get(candidate.get_world_size.remote(),
                                    timeout=10)
                if remaining == world == world_size:
                    actor = candidate
                    break
            except Exception:
                pass
            time.sleep(0.2)
        if actor is None:
            raise TimeoutError(
                f"collective group {group_name!r} rendezvous timed out")
    with _groups_lock:
        _groups[group_name] = _GroupHandle(actor, world_size, rank,
                                           group_name)


def create_collective_group(actors, world_size: int, ranks: List[int],
                            backend: str = "host",
                            group_name: str = "default") -> None:
    """Declarative variant (reference collective.py:160): the caller
    declares the members and their ranks; member actors then auto-join on
    their first collective op (or call init_collective_group explicitly)."""
    import ray_tpu
    if len(actors) != world_size or len(ranks) != world_size:
        raise ValueError(
            f"need exactly world_size={world_size} actors and ranks "
            f"(got {len(actors)} actors, {len(ranks)} ranks)")
    if sorted(ranks) != list(range(world_size)):
        raise ValueError(f"ranks must be a permutation of 0..{world_size-1}, "
                         f"got {ranks}")
    declared = {a._actor_id: r for a, r in zip(actors, ranks)}
    name = _group_actor_name(group_name)
    _reap_stale_group(name)
    GroupActor = ray_tpu.remote(_GroupActor)
    h = GroupActor.options(name=name, lifetime="detached").remote(
        world_size, declared)
    # Surface creation failures (e.g. a live group already owns the name)
    # instead of silently letting members autojoin a stale actor.
    ray_tpu.get(h.get_world_size.remote(), timeout=60)


def _reap_stale_group(name: str) -> None:
    """If a previous group actor with this name is dead, or its teardown
    has begun (any member deregistered — including the case where a peer
    crashed and the survivors drained partway), kill it so the name is
    reusable. Only a fully-registered live group (remaining == world_size)
    is left alone — creating over it then fails loudly."""
    import ray_tpu
    try:
        existing = ray_tpu.get_actor(name)
    except Exception:
        return
    try:
        remaining = ray_tpu.get(existing.remaining.remote(), timeout=10)
        world = ray_tpu.get(existing.get_world_size.remote(), timeout=10)
        stale = remaining < world
    except Exception:
        stale = True          # dead/unresponsive actor holds the name
    if stale:
        try:
            ray_tpu.kill(existing)
        except Exception:
            pass


def _handle(group_name: str) -> _GroupHandle:
    h = _groups.get(group_name)
    if h is None:
        h = _try_autojoin(group_name)
    if h is None:
        raise RuntimeError(
            f"collective group {group_name!r} not initialized in this "
            f"process; call init_collective_group first (or declare it "
            f"with create_collective_group)")
    return h


def _try_autojoin(group_name: str) -> Optional[_GroupHandle]:
    """Inside an actor declared via create_collective_group: look up our
    rank from the group actor and join."""
    import ray_tpu
    actor_id = ray_tpu.get_runtime_context().get_actor_id()
    if actor_id is None:
        return None
    try:
        group_actor = ray_tpu.get_actor(_group_actor_name(group_name))
        rank = ray_tpu.get(group_actor.declared_rank_of.remote(actor_id),
                           timeout=30)
    except Exception:
        return None
    if rank is None:
        return None
    world_size = ray_tpu.get(group_actor.get_world_size.remote(), timeout=30)
    with _groups_lock:
        h = _groups.setdefault(
            group_name, _GroupHandle(group_actor, world_size, rank,
                                     group_name))
    return h


def _run_op(group_name: str, payload, op, mode: str):
    import ray_tpu
    h = _handle(group_name)
    key = f"{mode}:{h.op_counter}"
    h.op_counter += 1
    return ray_tpu.get(h.actor.contribute.remote(key, h.rank, payload, op,
                                                 mode), timeout=300)


def allreduce(tensor, group_name: str = "default",
              op: str = ReduceOp.SUM):
    return _run_op(group_name, tensor, op, "allreduce")


def allgather(tensor, group_name: str = "default") -> List:
    return _run_op(group_name, tensor, None, "allgather")


def reducescatter(tensor, group_name: str = "default"):
    return _run_op(group_name, tensor, None, "reducescatter")


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    return _run_op(group_name, tensor, src_rank, "broadcast")


def barrier(group_name: str = "default") -> None:
    _run_op(group_name, None, None, "barrier")


def send(tensor, dst_rank: int, group_name: str = "default") -> None:
    import ray_tpu
    h = _handle(group_name)
    tag = h.send_tags.get(dst_rank, 0)
    h.send_tags[dst_rank] = tag + 1
    ray_tpu.get(h.actor.post.remote(dst_rank,
                                    f"{h.rank}->{dst_rank}:{tag}", tensor),
                timeout=300)


def recv(src_rank: int, group_name: str = "default"):
    import ray_tpu
    h = _handle(group_name)
    tag = h.recv_tags.get(src_rank, 0)
    h.recv_tags[src_rank] = tag + 1
    return ray_tpu.get(h.actor.take.remote(h.rank,
                                           f"{src_rank}->{h.rank}:{tag}"),
                       timeout=300)


def destroy_collective_group(group_name: str = "default") -> None:
    """Collective teardown: each rank deregisters; whichever rank drops
    the registration count to zero kills the detached rendezvous actor.
    If a member crashed without deregistering, the count never reaches
    zero and the actor outlives the group — _reap_stale_group then
    detects the partial teardown (remaining < world_size) and reclaims
    the name on the next create/init with this group name."""
    import ray_tpu
    with _groups_lock:
        h = _groups.pop(group_name, None)
    if h is not None:
        try:
            remaining = ray_tpu.get(h.actor.deregister.remote(h.rank),
                                    timeout=30)
            if remaining <= 0:
                ray_tpu.kill(ray_tpu.get_actor(
                    _group_actor_name(group_name)))
        except Exception:
            pass
