from .collective import (init_collective_group, destroy_collective_group,
                         allreduce, allgather, reducescatter, broadcast,
                         barrier, send, recv, ReduceOp,
                         create_collective_group)
from . import xla
