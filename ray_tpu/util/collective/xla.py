"""In-mesh collectives: the TPU replacement for NCCL groups.

Reference parity: the NCCL backend of ray.util.collective
(collective_group/nccl_collective_group.py) — but on TPU, in-program
collectives belong to the compiler: these are named-axis wrappers over
jax.lax primitives, usable inside shard_map/pjit, compiled onto ICI by XLA.
Group management is the mesh itself (parallel/mesh.py), not a rendezvous.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp

Axis = Union[str, Sequence[str]]


def allreduce(x, axis_name: Axis, op: str = "sum"):
    if op == "sum":
        return jax.lax.psum(x, axis_name)
    if op == "max":
        return jax.lax.pmax(x, axis_name)
    if op == "min":
        return jax.lax.pmin(x, axis_name)
    if op == "mean":
        return jax.lax.pmean(x, axis_name)
    raise ValueError(f"unsupported op {op!r}")


def allgather(x, axis_name: Axis, axis: int = 0, tiled: bool = True):
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reducescatter(x, axis_name: Axis, axis: int = 0):
    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=axis,
                                tiled=True)


def broadcast(x, axis_name: Axis, src_rank: int = 0):
    """Every member gets src_rank's value."""
    idx = jax.lax.axis_index(axis_name)
    masked = jnp.where(idx == src_rank, x, jnp.zeros_like(x))
    return jax.lax.psum(masked, axis_name)


def permute(x, axis_name: Axis, perm):
    return jax.lax.ppermute(x, axis_name, perm)


def all_to_all(x, axis_name: Axis, split_axis: int = 0,
               concat_axis: int = 0):
    return jax.lax.all_to_all(x, axis_name, split_axis, concat_axis,
                              tiled=True)


def rank(axis_name: Axis):
    return jax.lax.axis_index(axis_name)


def world_size(axis_name: str) -> int:
    return jax.lax.axis_size(axis_name)
