"""Serializability inspection.

Reference parity: python/ray/util/check_serialize.py
(inspect_serializability — walk an object's closure/attributes and name
which inner object fails to pickle).
"""

from __future__ import annotations

import inspect
from typing import Any, Set, Tuple

from .._private.serialization import serialize


class FailureTuple:
    def __init__(self, obj: Any, name: str, parent: Any):
        self.obj = obj
        self.name = name
        self.parent = parent

    def __repr__(self):
        return f"FailureTuple(obj={self.name!r}, parent={self.parent!r})"


def _try(obj) -> bool:
    try:
        serialize(obj)
        return True
    except Exception:
        return False


def inspect_serializability(obj: Any, name: str = None
                            ) -> Tuple[bool, Set[FailureTuple]]:
    """Returns (serializable, failures); failures name the innermost
    unserializable members."""
    name = name or getattr(obj, "__name__", repr(obj)[:40])
    if _try(obj):
        return True, set()
    failures: Set[FailureTuple] = set()
    found_inner = False
    # closures
    if inspect.isfunction(obj) and obj.__closure__:
        for var, cell in zip(obj.__code__.co_freevars, obj.__closure__):
            try:
                inner = cell.cell_contents
            except ValueError:
                continue
            if not _try(inner):
                found_inner = True
                ok, inner_fails = inspect_serializability(inner, var)
                failures |= inner_fails or {FailureTuple(inner, var, name)}
    # instance attributes
    d = getattr(obj, "__dict__", None)
    if isinstance(d, dict):
        for attr, val in d.items():
            if not _try(val):
                found_inner = True
                ok, inner_fails = inspect_serializability(val, attr)
                failures |= inner_fails or {FailureTuple(val, attr, name)}
    if not found_inner:
        failures.add(FailureTuple(obj, name, None))
    return False, failures
