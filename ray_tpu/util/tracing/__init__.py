"""Task/actor tracing: spans + Chrome-trace export.

Reference parity: python/ray/util/tracing/tracing_helper.py (opt-in
OpenTelemetry spans around submit/execute with context propagated in
task specs) and the dashboard's Chrome-trace timeline. Here spans are
recorded in a process-local ring and exported as Chrome trace events
(chrome://tracing / Perfetto "traceEvents" JSON); enable with
RAY_TPU_TRACE=1 or tracing.enable().
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

_lock = threading.Lock()
_events: List[Dict[str, Any]] = []
_enabled = bool(os.environ.get("RAY_TPU_TRACE"))
_MAX_EVENTS = 100_000


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def is_enabled() -> bool:
    return _enabled


@contextlib.contextmanager
def span(name: str, category: str = "task", **attrs):
    """Record one duration span (no-op unless tracing is enabled)."""
    if not _enabled:
        yield
        return
    start = time.perf_counter_ns()
    try:
        yield
    finally:
        end = time.perf_counter_ns()
        with _lock:
            if len(_events) < _MAX_EVENTS:
                _events.append({
                    "name": name, "cat": category, "ph": "X",
                    "ts": start / 1e3, "dur": (end - start) / 1e3,
                    "pid": os.getpid(),
                    "tid": threading.get_ident() % 100000,
                    "args": attrs,
                })


def get_events() -> List[Dict[str, Any]]:
    with _lock:
        return list(_events)


def clear() -> None:
    with _lock:
        _events.clear()


def export_chrome_trace(path: Optional[str] = None) -> str:
    """Write (or return) the Chrome trace JSON for this process."""
    doc = json.dumps({"traceEvents": get_events(),
                      "displayTimeUnit": "ms"})
    if path:
        with open(path, "w") as f:
            f.write(doc)
    return doc


__all__ = ["enable", "disable", "is_enabled", "span", "get_events",
           "clear", "export_chrome_trace"]
