"""Task/actor tracing: spans + Chrome-trace export + ctx propagation.

Reference parity: python/ray/util/tracing/tracing_helper.py (opt-in
OpenTelemetry spans around submit/execute, with the span CONTEXT
propagated inside task specs — _DictPropagator, tracing_helper.py:165)
and the dashboard's Chrome-trace timeline. Spans are recorded in a
process-local ring and exported as Chrome trace events ("traceEvents"
JSON); enable with RAY_TPU_TRACE=1 or tracing.enable().

Propagation: the submitting side calls inject_context() (the ambient
span's ids + a Perfetto flow-start event) and ships the dict in the
task spec; the executing worker wraps the task in
span(..., parent=ctx), which emits the matching flow-finish — in
Perfetto the driver's submit span gets an arrow to the worker's
execute span, across processes and hosts.
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import itertools
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

_MAX_EVENTS = 100_000


class BoundedRing:
    """Thread-safe deque(maxlen) ring with displacement accounting —
    the shared bounded-buffer primitive (this module's event ring, the
    serve.llm ingress trace buffer). A true ring: at capacity the
    OLDEST item is displaced and counted, so a long-lived process
    keeps the events that matter and a truncated buffer is legible as
    truncated (`stats()["dropped"]`)."""

    def __init__(self, capacity: int):
        self._ring: "collections.deque[Any]" = collections.deque(
            maxlen=capacity)
        self._lock = threading.Lock()
        self.total = 0               # ever appended (monotone)
        self.dropped = 0             # displaced by the capacity bound

    def append(self, *items: Any) -> int:
        """Append items; returns the new monotone total."""
        with self._lock:
            for it in items:
                if len(self._ring) == self._ring.maxlen:
                    self.dropped += 1
                self._ring.append(it)
                self.total += 1
            return self.total

    def items(self) -> List[Any]:
        with self._lock:
            return list(self._ring)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"capacity": self._ring.maxlen or 0,
                    "events": len(self._ring), "total": self.total,
                    "dropped": self.dropped}

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self.total = 0
            self.dropped = 0

    def tail_since(self, since_total: int) -> "tuple[List[Any], int]":
        """Items appended after the `since_total`-th append that are
        still resident (displaced ones are gone — counted, not
        recoverable), plus the current total. The incremental-flush
        primitive."""
        with self._lock:
            n = min(self.total - since_total, len(self._ring))
            if n <= 0:
                return [], self.total
            return (list(itertools.islice(
                self._ring, len(self._ring) - n, len(self._ring))),
                self.total)


# the process event ring (ring_stats() exposes its displaced count;
# /debug/trace surfaces it in metadata)
_ring = BoundedRing(_MAX_EVENTS)
_enabled = bool(os.environ.get("RAY_TPU_TRACE"))
_span_counter = itertools.count(1)

# One wall-clock anchor per process (satellite of ISSUE 7): durations
# and ordering must come from the MONOTONIC clock — an NTP step in
# time.time() would otherwise skew every latency histogram and
# misorder trace events — while cross-process trace alignment needs
# epoch timestamps. The anchor is sampled once at import; converting
# monotonic stamps through it yields epoch-like timestamps whose
# DIFFERENCES are NTP-immune for the life of the process.
_MONO_ANCHOR = time.time() - time.monotonic()


def wall_anchor() -> float:
    """This process's wall-clock anchor (epoch - monotonic at import)."""
    return _MONO_ANCHOR


def mono_to_epoch(mono_ts: float) -> float:
    """Monotonic timestamp -> epoch seconds via the process anchor."""
    return _MONO_ANCHOR + mono_ts
# the ambient span: {"trace_id", "span_id"} (reference: the OTel
# current-span context _DictPropagator serializes into task specs)
_current: "contextvars.ContextVar[Optional[Dict[str, str]]]" = \
    contextvars.ContextVar("ray_tpu_trace_ctx", default=None)


def current_context() -> Optional[Dict[str, str]]:
    """The ambient span context ({"trace_id","span_id"}) or None."""
    return _current.get()


def _new_span_id() -> str:
    return f"{os.getpid():x}.{next(_span_counter):x}"


def new_span_id() -> str:
    """Mint a process-unique span/trace/flow id (public: the serve.llm
    fleet ingress mints trace contexts without opening a span)."""
    return _new_span_id()


def _append(*evs: Dict[str, Any]) -> None:
    _ring.append(*evs)


def inject_context() -> Optional[Dict[str, str]]:
    """Serialize the ambient context for a task spec; emits the
    Perfetto flow-start so the consumer side can draw the arrow.
    Returns None when tracing is off or no span is open."""
    ctx = _current.get()
    if not _enabled or ctx is None:
        return None
    # one flow id PER SUBMISSION: reusing the span id would chain every
    # task submitted under one driver span into a single flow path
    flow_id = _new_span_id()
    now = mono_to_epoch(time.monotonic()) * 1e6
    _append({
        "name": "submit", "cat": "flow", "ph": "s",
        "id": flow_id, "ts": now,
        "pid": os.getpid(),
        "tid": threading.get_ident() % 100000})
    return {**ctx, "flow_id": flow_id}


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def is_enabled() -> bool:
    return _enabled


@contextlib.contextmanager
def span(name: str, category: str = "task",
         parent: Optional[Dict[str, str]] = None, **attrs):
    """Record one duration span (no-op unless tracing is enabled).

    `parent` is a propagated context from inject_context() (a task spec
    crossing processes): the span joins that trace and emits the
    Perfetto flow-finish binding it to the submitter's arrow. Without
    `parent`, the span nests under the ambient span of this process.
    Yields the span context dict when tracing is ON and None when OFF —
    guard any use of the yielded value."""
    if not _enabled:
        yield
        return
    prev = _current.get()
    remote_parent = parent is not None
    parent = parent or prev
    ctx = {"trace_id": (parent or {}).get("trace_id") or _new_span_id(),
           "span_id": _new_span_id()}
    token = _current.set(ctx)
    # monotonic for the duration (NTP-step immune), rendered as epoch
    # through the per-process anchor so cross-process events align
    start = time.monotonic()
    try:
        yield ctx
    finally:
        end = time.monotonic()
        _current.reset(token)
        tid = threading.get_ident() % 100000
        start_us = mono_to_epoch(start) * 1e6
        evs = []
        if remote_parent:
            evs.append({
                "name": "submit", "cat": "flow", "ph": "f",
                "bp": "e",
                "id": parent.get("flow_id", parent["span_id"]),
                "ts": start_us, "pid": os.getpid(),
                "tid": tid})
        evs.append({
            "name": name, "cat": category, "ph": "X",
            "ts": start_us, "dur": (end - start) * 1e6,
            "pid": os.getpid(), "tid": tid,
            "args": {**attrs,
                     "trace_id": ctx["trace_id"],
                     "span_id": ctx["span_id"],
                     **({"parent_span_id": parent["span_id"]}
                        if parent else {})},
        })
        _append(*evs)


def complete_event(name: str, category: str, start_s: float,
                   dur_s: float, pid: Optional[int] = None,
                   tid: int = 0,
                   args: Optional[Dict[str, Any]] = None
                   ) -> Dict[str, Any]:
    """Build one Chrome-trace complete ("X") event dict from epoch
    SECONDS — the same schema span() emits (ts/dur in microseconds),
    for code that only knows a span's bounds after the fact (the LLM
    engine's request-lifecycle timelines render through this so the
    two event sources stay field-compatible in one viewer)."""
    return {"name": name, "cat": category, "ph": "X",
            "ts": start_s * 1e6, "dur": max(dur_s, 0.0) * 1e6,
            "pid": os.getpid() if pid is None else pid,
            "tid": tid, "args": dict(args or {})}


def instant_event(name: str, category: str, ts_s: float,
                  pid: Optional[int] = None, tid: int = 0,
                  args: Optional[Dict[str, Any]] = None
                  ) -> Dict[str, Any]:
    """Chrome-trace instant ("i") event at epoch seconds (thread
    scope) — point-in-time marks like a prefill chunk landing."""
    return {"name": name, "cat": category, "ph": "i", "s": "t",
            "ts": ts_s * 1e6,
            "pid": os.getpid() if pid is None else pid,
            "tid": tid, "args": dict(args or {})}


def get_events() -> List[Dict[str, Any]]:
    return _ring.items()


def ring_stats() -> Dict[str, int]:
    """Ring fill level + displacement: `dropped` counts events the
    capacity bound displaced (surfaced in /debug/trace metadata so a
    truncated trace is legible as truncated, not complete)."""
    return _ring.stats()


def clear() -> None:
    global _flushed_upto
    _ring.clear()
    _flushed_upto = 0


_last_flush = 0.0
_flushed_upto = 0
_flush_seq = 0


def flush_to_kv(min_interval_s: float = 1.0) -> None:
    """Publish this process's NEW events to the controller KV so the
    driver can assemble a CLUSTER trace. Incremental: each flush ships
    only the events recorded since the last one (chunked keys
    __trace__/{wid}/{seq}), so cost is O(new), not O(ring). Workers
    call this after traced executions (rate-limited), on a trailing
    timer, and at shutdown with min_interval_s=0."""
    global _last_flush, _flushed_upto, _flush_seq
    now = time.monotonic()
    if now - _last_flush < min_interval_s:
        return
    from ..._private import state as _state
    client = _state.current_client_or_none()
    if client is None:
        return
    # NEW events addressed by the ring's monotone append counter —
    # events displaced before a flush are simply gone (counted in
    # ring_stats()["dropped"])
    new, total = _ring.tail_since(_flushed_upto)
    if not new:
        return
    _flushed_upto = total
    _flush_seq += 1
    seq = _flush_seq
    _last_flush = now
    wid = getattr(client, "worker_id", None) or f"pid{os.getpid()}"
    key = f"__trace__/{wid}/{seq:06d}"
    blob = json.dumps(new).encode()
    try:
        if client.loop_runner.on_loop_thread():
            # worker RPC handlers run ON the loop: fire-and-forget the
            # put (the sync kv_put would deadlock-guard and raise)
            client.loop_runner.call_soon(client._controller().call(
                "kv_put", key=key, value=blob, overwrite=True))
        else:
            client.kv_put(key, blob)
    except Exception:
        pass


def collect_cluster() -> List[Dict[str, Any]]:
    """This process's events merged with every flushed worker ring —
    the cross-process trace (flow arrows pair up across pids because
    timestamps are epoch-based)."""
    from ..._private import state as _state
    events = get_events()
    client = _state.current_client_or_none()
    if client is None:
        return events
    try:
        for key in client.controller_rpc("kv_keys", prefix="__trace__/"):
            blob = client.kv_get(key)
            if blob:
                events.extend(json.loads(blob))
    except Exception:
        pass
    return events


def export_chrome_trace(path: Optional[str] = None,
                        cluster: bool = False) -> str:
    """Write (or return) the Chrome trace JSON — this process's ring,
    or (cluster=True) merged with every flushed worker's."""
    doc = json.dumps({
        "traceEvents": collect_cluster() if cluster else get_events(),
        "displayTimeUnit": "ms"})
    if path:
        with open(path, "w") as f:
            f.write(doc)
    return doc


__all__ = ["enable", "disable", "is_enabled", "span", "get_events",
           "clear", "export_chrome_trace", "inject_context",
           "current_context", "flush_to_kv", "collect_cluster",
           "complete_event", "instant_event", "ring_stats",
           "new_span_id", "wall_anchor", "mono_to_epoch",
           "BoundedRing"]
