"""Placement group public API.

Reference parity: python/ray/util/placement_group.py:145 (placement_group,
PlacementGroup.ready/wait, remove_placement_group, placement_group_table)
plus a TPU pod-slice helper that builds the gang bundles the reference
derives from TPU-{type}-head resources (accelerators/tpu.py:352-375).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .._private import state
from .._private.ids import PlacementGroupID


class PlacementGroup:
    def __init__(self, pg_id: str, bundles: List[Dict[str, float]],
                 strategy: str):
        self.id = pg_id
        self.bundle_specs = bundles
        self.strategy = strategy

    def ready(self, timeout: Optional[float] = None) -> bool:
        """Block until all bundles are reserved. Returns True if CREATED."""
        client = state.current_client()
        if getattr(client, "is_local_mode", False):
            return True
        reply = client.loop_runner.run_sync(
            client._controller().call("pg_wait_ready", pg_id=self.id,
                                      timeout=timeout),
            timeout=(timeout + 5.0) if timeout else None)
        if reply.get("state") == "FAILED" or (
                reply.get("timeout") and reply.get("reason")
                not in (None, "", "timeout")):
            # FAILED, or timed out while infeasible on the current nodes
            # (the PG itself stays PENDING server-side, matching the
            # reference: the cluster may still scale up).
            from ..exceptions import PlacementGroupUnavailableError
            raise PlacementGroupUnavailableError(
                f"placement group not placeable: {reply.get('reason')}")
        return reply.get("state") == "CREATED"

    def wait(self, timeout_seconds: Optional[float] = None) -> bool:
        try:
            return self.ready(timeout=timeout_seconds)
        except Exception:
            return False

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundle_specs, self.strategy))

    def __repr__(self):
        return f"PlacementGroup({self.id[:12]}, {self.strategy}, " \
               f"{len(self.bundle_specs)} bundles)"


def placement_group(bundles: List[Dict[str, float]],
                    strategy: str = "PACK",
                    name: str = "") -> PlacementGroup:
    if not bundles or any(not b for b in bundles):
        raise ValueError("bundles must be a non-empty list of non-empty "
                         "resource dicts")
    from .._private.placement import VALID_STRATEGIES
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"invalid strategy {strategy!r}; "
                         f"one of {VALID_STRATEGIES}")
    client = state.current_client()
    pg_id = PlacementGroupID.generate().hex()
    pg = PlacementGroup(pg_id, [dict(b) for b in bundles], strategy)
    if getattr(client, "is_local_mode", False):
        client.placement_groups[pg_id] = pg
        return pg
    client.loop_runner.run_sync(client._controller().call(
        "create_placement_group", pg_id=pg_id,
        bundles=[dict(b) for b in bundles], strategy=strategy, name=name))
    return pg


def remove_placement_group(pg: PlacementGroup) -> None:
    client = state.current_client()
    if getattr(client, "is_local_mode", False):
        client.placement_groups.pop(pg.id, None)
        return
    client.loop_runner.run_sync(client._controller().call(
        "remove_placement_group", pg_id=pg.id))


def placement_group_table() -> Dict[str, dict]:
    client = state.current_client()
    if getattr(client, "is_local_mode", False):
        return {pg_id: {"state": "CREATED"}
                for pg_id in client.placement_groups}
    return client.loop_runner.run_sync(
        client._controller().call("placement_group_table"))


def tpu_pod_placement_group(num_hosts: int, chips_per_host: int = 4,
                            accelerator_type: Optional[str] = None,
                            include_head_resource: bool = True
                            ) -> PlacementGroup:
    """Gang-reserve a whole TPU pod slice: one bundle per host
    (STRICT_SPREAD), each holding the host's chips; bundle 0 additionally
    anchors on the slice-head resource so multi-host jobs land on exactly
    one slice."""
    bundles: List[Dict[str, float]] = []
    for i in range(num_hosts):
        b: Dict[str, float] = {"TPU": float(chips_per_host)}
        if accelerator_type:
            b[f"TPU-{accelerator_type}"] = float(chips_per_host)
            if i == 0 and include_head_resource:
                b[f"TPU-{accelerator_type}-head"] = 1.0
        bundles.append(b)
    return placement_group(bundles, strategy="STRICT_SPREAD",
                           name="tpu_pod_slice")
