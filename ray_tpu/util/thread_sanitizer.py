"""Runtime thread sanitizer: the dynamic half of racelint.

tools/racelint checks lock discipline statically; this module checks
it at RUNTIME in tier-1 stress tests, at zero steady-state cost:

- `make_lock(name)` — returns a plain `threading.Lock` while the
  sanitizer is disarmed (the production default: no wrapper, no
  indirection, structurally overhead-free) and a traced lock while
  armed. The traced lock records each thread's acquisition ORDER into
  a global graph keyed by lock *name* and reports an inversion the
  moment two locks are ever taken in both orders — the deadlock that
  RL003 catches statically, caught here even across modules.
- `guarded_by("_step_lock")` — a data descriptor for engine-state
  fields: while armed, reads/writes check that the owning lock is
  held by the current thread and record a violation otherwise (the
  dynamic RL001/RL004). Disarmed, it is a `__dict__`-backed attribute
  with no checks. `unguarded()` marks a lock-free-by-contract scope
  (the blackbox crash path) so its sanctioned bare reads don't
  trip it.

Violations are RECORDED, not raised (strict=True raises): a stress
test hammers the engine from many threads and asserts
`assert_clean()` at the end, so one report shows every violation
rather than dying on the first.

Stdlib-only, imports nothing from ray_tpu (the engine imports us).
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any, Dict, List, Optional, Set, Tuple

__all__ = [
    "arm", "disarm", "armed", "sanitized", "reset", "make_lock",
    "guarded_by", "unguarded", "violations", "assert_clean",
]


class _State:
    def __init__(self) -> None:
        self.armed = False
        self.strict = False
        self.lock = threading.Lock()           # guards the fields below
        self.violations: List[str] = []
        # lock-NAME order graph: edges (held -> acquired) with the
        # first thread/name pair that created each edge
        self.order: Dict[Tuple[str, str], str] = {}
        self.inverted: Set[Tuple[str, str]] = set()


_state = _State()
_tls = threading.local()


def _held_stack() -> List["_TracedLock"]:
    stack = getattr(_tls, "held", None)
    if stack is None:
        stack = _tls.held = []
    return stack


def _bypass_depth() -> int:
    return getattr(_tls, "bypass", 0)


def armed() -> bool:
    return _state.armed


def arm(strict: bool = False) -> None:
    """Arm the sanitizer for locks/fields created AFTER this call
    (and checks on already-guarded fields). strict=True raises on
    the violating thread instead of recording."""
    _state.armed = True
    _state.strict = strict


def disarm() -> None:
    _state.armed = False


def reset() -> None:
    """Clear recorded violations and the acquisition-order graph
    (per-test isolation)."""
    with _state.lock:
        _state.violations.clear()
        _state.order.clear()
        _state.inverted.clear()


def violations() -> List[str]:
    with _state.lock:
        return list(_state.violations)


def assert_clean() -> None:
    got = violations()
    if got:
        raise AssertionError(
            "thread sanitizer recorded %d violation(s):\n  %s"
            % (len(got), "\n  ".join(got)))


@contextlib.contextmanager
def sanitized(strict: bool = False):
    """Arm + reset for a scope; disarm on exit (violations survive
    for inspection)."""
    reset()
    arm(strict=strict)
    try:
        yield
    finally:
        disarm()


def _report(msg: str) -> None:
    with _state.lock:
        _state.violations.append(msg)
    if _state.strict:
        raise AssertionError(f"thread sanitizer: {msg}")


class _TracedLock:
    """threading.Lock with per-thread held-stack + global
    acquisition-order tracking. Non-reentrant, like the real thing —
    re-acquisition by the owner is reported (it would deadlock) and
    NOT attempted, so the sanitizer itself never wedges the test."""

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._owner: Optional[int] = None

    # -- order graph ---------------------------------------------------
    def _check_order(self) -> None:
        me = threading.current_thread().name
        held = _held_stack()
        msgs = []
        # collect under _state.lock, report AFTER releasing — _report
        # re-takes _state.lock (non-reentrant), the very RL006 shape
        # this module exists to catch
        with _state.lock:
            for h in held:
                if h.name == self.name:
                    continue
                edge = (h.name, self.name)
                rev = (self.name, h.name)
                if edge not in _state.order:
                    _state.order[edge] = me
                if rev in _state.order and edge not in _state.inverted \
                        and rev not in _state.inverted:
                    _state.inverted.add(edge)
                    first = _state.order[rev]
                    msgs.append(
                        f"lock-order inversion: thread {me} acquires "
                        f"{self.name} while holding {h.name}, but "
                        f"thread {first} acquired them in the "
                        f"opposite order")
        for m in msgs:
            _report(m)

    # -- Lock API ------------------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ident = threading.get_ident()
        if self._owner == ident:
            _report(f"re-acquisition of non-reentrant lock "
                    f"{self.name} by its owner thread "
                    f"{threading.current_thread().name} (deadlock)")
            return False
        self._check_order()
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            self._owner = ident
            _held_stack().append(self)
        return ok

    def release(self) -> None:
        self._owner = None
        stack = _held_stack()
        if self in stack:
            stack.remove(self)
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def held_by_me(self) -> bool:
        return self._owner == threading.get_ident()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<_TracedLock {self.name} locked={self.locked()}>"


def make_lock(name: str):
    """A lock for engine/fleet state. Disarmed (the default): exactly
    `threading.Lock()` — callers hold a plain stdlib lock with zero
    wrapper overhead. Armed: a traced lock that feeds the
    order-inversion detector and `guarded_by` ownership checks."""
    if _state.armed:
        return _TracedLock(name)
    return threading.Lock()


@contextlib.contextmanager
def unguarded():
    """Mark the current thread's scope as lock-free-by-contract: a
    crash/forensics path that reads guarded fields WITHOUT the lock
    on purpose (engine.dump_blackbox). Guarded-field checks are
    skipped inside."""
    _tls.bypass = _bypass_depth() + 1
    try:
        yield
    finally:
        _tls.bypass -= 1


class guarded_by:
    """Data descriptor: `field = guarded_by("_step_lock")` makes
    reads+writes of `self.field` assert (record) that `self._step_lock`
    is held by the current thread — but ONLY while the sanitizer is
    armed AND the lock is a traced lock (production plain Locks can't
    answer "who holds me", and cost nothing). writes_only=True checks
    stores but not loads, for fields whose bare reads of a published
    reference are part of the design."""

    def __init__(self, lock_attr: str, writes_only: bool = False):
        self.lock_attr = lock_attr
        self.writes_only = writes_only
        self.name = "<unset>"
        self.slot = "<unset>"

    def __set_name__(self, owner, name: str) -> None:
        self.name = name
        self.slot = f"__guarded_{name}"

    def _check(self, obj, op: str) -> None:
        if not _state.armed or _bypass_depth():
            return
        lock = getattr(obj, self.lock_attr, None)
        if not isinstance(lock, _TracedLock):
            return      # plain production lock (or not created yet)
        if not lock.held_by_me():
            _report(
                f"unguarded {op} of {type(obj).__name__}.{self.name} "
                f"on thread {threading.current_thread().name} without "
                f"{self.lock_attr}")

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        if not self.writes_only:
            self._check(obj, "read")
        try:
            return obj.__dict__[self.slot]
        except KeyError:
            raise AttributeError(self.name) from None

    def __set__(self, obj, value) -> None:
        self._check(obj, "write")
        obj.__dict__[self.slot] = value

    def __delete__(self, obj) -> None:
        self._check(obj, "delete")
        del obj.__dict__[self.slot]
