"""User-defined metrics: Counter, Gauge, Histogram.

Reference parity: python/ray/util/metrics.py:41-294 (Counter/Gauge/
Histogram over the C++ OpenCensus pipeline → Prometheus). Here metrics
live in a process-local registry; any process can render the Prometheus
text exposition (export_prometheus), and worker registries are scraped
into the dashboard via the controller KV (flush_to_kv / collect_cluster).
"""

from __future__ import annotations

import bisect
import json
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

_registry_lock = threading.Lock()
_registry: Dict[str, "Metric"] = {}

DEFAULT_BOUNDARIES = [0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
                      2.5, 5.0, 10.0]


class Metric:
    metric_type = "untyped"

    def __new__(cls, name: str, *args, **kwargs):
        # Re-registration returns the EXISTING instance (same type +
        # tag keys) instead of silently clobbering the registry entry —
        # the old behavior orphaned every prior handle: their writes
        # kept landing on the shadowed object and vanished from the
        # exposition. Shared construction is the normal pattern (every
        # engine in a process builds "its" TTFT histogram); a
        # type-mismatched reuse of a name is a programming error and
        # raises. Lookup, field init, and registry insert all happen
        # inside ONE critical section: two threads constructing the
        # same name concurrently can never both create (check-then-act
        # clobber), and a merge-path winner can never observe a
        # half-initialized instance. __init__ then runs the pure
        # compat/merge check on whichever instance came back.
        with _registry_lock:
            existing = _registry.get(name)
            if existing is not None:
                if type(existing) is not cls:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{type(existing).__name__}; cannot "
                        f"re-register as {cls.__name__}")
                return existing
            inst = super().__new__(cls)
            inst._init_fields(name, *args, **kwargs)
            _registry[name] = inst
            return inst

    def _init_fields(self, name: str, description: str = "",
                     tag_keys: Optional[Sequence[str]] = None) -> None:
        """First-construction initialization — runs under the registry
        lock in __new__, BEFORE the instance becomes visible."""
        if not name or not name.replace("_", "a").isalnum():
            raise ValueError(f"invalid metric name {name!r}")
        self._name = name
        self._description = description
        self._tag_keys = tuple(tag_keys or ())
        self._default_tags: Dict[str, str] = {}
        self._values: Dict[Tuple[str, ...], float] = {}
        self._lock = threading.Lock()

    def __init__(self, name: str, description: str = "",
                 tag_keys: Optional[Sequence[str]] = None):
        # always the merge/compat path (field init happened in
        # __new__): tag keys must agree (samples are keyed by them) —
        # trivially true for the creating caller — and description
        # backfills if the first registration left it empty
        if tuple(tag_keys or ()) != self._tag_keys:
            raise ValueError(
                f"metric {name!r} already registered with tag_keys="
                f"{self._tag_keys}; got {tuple(tag_keys or ())}")
        if description and not self._description:
            self._description = description

    # -- tags ---------------------------------------------------------------
    def set_default_tags(self, tags: Dict[str, str]) -> "Metric":
        self._default_tags = dict(tags)
        return self

    def _key(self, tags: Optional[Dict[str, str]]) -> Tuple[str, ...]:
        merged = {**self._default_tags, **(tags or {})}
        missing = set(self._tag_keys) - set(merged)
        if missing:
            raise ValueError(f"missing tag(s) {sorted(missing)} for "
                             f"metric {self._name}")
        return tuple(merged.get(k, "") for k in self._tag_keys)

    # -- introspection ------------------------------------------------------
    @property
    def info(self) -> Dict[str, object]:
        return {"name": self._name, "description": self._description,
                "tag_keys": self._tag_keys,
                "default_tags": dict(self._default_tags)}

    def _samples(self) -> List[Tuple[Dict[str, str], float]]:
        with self._lock:
            return [(dict(zip(self._tag_keys, key)), val)
                    for key, val in self._values.items()]


class Counter(Metric):
    metric_type = "counter"

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None) -> None:
        if value < 0:
            raise ValueError("counters only increase")
        key = self._key(tags)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value


class Gauge(Metric):
    metric_type = "gauge"

    def set(self, value: float,
            tags: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._values[self._key(tags)] = float(value)


class Histogram(Metric):
    metric_type = "histogram"

    def _init_fields(self, name: str, description: str = "",
                     boundaries: Optional[Sequence[float]] = None,
                     tag_keys: Optional[Sequence[str]] = None) -> None:
        super()._init_fields(name, description, tag_keys)
        self.boundaries = sorted(boundaries or DEFAULT_BOUNDARIES)
        self._buckets: Dict[Tuple[str, ...], List[int]] = {}
        self._sums: Dict[Tuple[str, ...], float] = {}
        self._counts: Dict[Tuple[str, ...], int] = {}

    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[Sequence[float]] = None,
                 tag_keys: Optional[Sequence[str]] = None):
        # merge/compat path (see Metric.__init__): bucket layouts must
        # agree or the shared bucket counts would be meaningless —
        # trivially true for the creating caller
        bounds = sorted(boundaries or DEFAULT_BOUNDARIES)
        if bounds != self.boundaries:
            raise ValueError(
                f"histogram {name!r} already registered with "
                f"boundaries {self.boundaries}; got {bounds}")
        super().__init__(name, description, tag_keys)

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None) -> None:
        key = self._key(tags)
        with self._lock:
            buckets = self._buckets.setdefault(
                key, [0] * (len(self.boundaries) + 1))
            buckets[bisect.bisect_left(self.boundaries, value)] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._counts[key] = self._counts.get(key, 0) + 1

    def _samples(self):
        with self._lock:
            out = []
            for key, buckets in self._buckets.items():
                tags = dict(zip(self._tag_keys, key))
                out.append((tags, {"buckets": list(buckets),
                                   "sum": self._sums[key],
                                   "count": self._counts[key]}))
            return out


# ----------------------------------------------------------------- export

def _esc_label(v: str) -> str:
    # Prometheus text exposition: escape backslash, double-quote, newline.
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_tags(tags: Dict[str, str], extra: str = "") -> str:
    # empty-valued labels are omitted: in the Prometheus data model a
    # label set to "" IS the label being absent, so rendering it would
    # only add noise — and lets optional tag keys (e.g. the LLM
    # telemetry's `replica`, empty outside fleets) stay invisible
    # until something sets them
    parts = [f'{k}="{_esc_label(v)}"' for k, v in tags.items()
             if str(v) != ""]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def export_prometheus() -> str:
    """This process's registry in Prometheus text exposition format."""
    lines: List[str] = []
    with _registry_lock:
        metrics = list(_registry.values())
    for m in metrics:
        lines.append(f"# HELP {m._name} {m._description}")
        lines.append(f"# TYPE {m._name} {m.metric_type}")
        if isinstance(m, Histogram):
            for tags, data in m._samples():
                cumulative = 0
                for bound, n in zip(m.boundaries + [float("inf")],
                                    data["buckets"]):
                    cumulative += n
                    le = "+Inf" if bound == float("inf") else repr(bound)
                    lines.append(
                        f"{m._name}_bucket"
                        + _fmt_tags(tags, 'le="%s"' % le)
                        + f" {cumulative}")
                lines.append(
                    f"{m._name}_sum{_fmt_tags(tags)} {data['sum']}")
                lines.append(
                    f"{m._name}_count{_fmt_tags(tags)} {data['count']}")
        else:
            for tags, val in m._samples():
                lines.append(f"{m._name}{_fmt_tags(tags)} {val}")
    return "\n".join(lines) + "\n"


def merge_expositions(texts: Sequence[str]) -> str:
    """Merge several Prometheus text expositions into ONE valid
    document. Naive concatenation is invalid twice over: in-process
    replicas each render the same process-wide registry, so every
    sample appears once per replica (Prometheus rejects duplicate
    series as a parse error), and even across processes the family
    headers repeat (all samples of a family must sit under a single
    # TYPE). Families keep first-appearance order, # HELP/# TYPE come
    from the first block declaring them, and duplicate series keep
    the FIRST value seen — dedup keys on series identity (name +
    label set), not line text, because a live counter can advance
    between two sequential renders of the same registry."""
    order: List[str] = []
    headers: Dict[str, Dict[str, str]] = {}
    samples: Dict[str, List[str]] = {}
    seen: Dict[str, set] = {}
    for text in texts:
        fam = None
        for line in text.splitlines():
            if not line.strip():
                continue
            if line.startswith("# HELP ") or line.startswith("# TYPE "):
                _, kind, fam = line.split(" ", 3)[:3]
                if fam not in headers:
                    headers[fam] = {}
                    samples[fam] = []
                    seen[fam] = set()
                    order.append(fam)
                headers[fam].setdefault(kind, line)
            elif fam is not None:
                series = line.rsplit(" ", 1)[0]
                if series not in seen[fam]:
                    seen[fam].add(series)
                    samples[fam].append(line)
    lines: List[str] = []
    for fam in order:
        for kind in ("HELP", "TYPE"):
            if kind in headers[fam]:
                lines.append(headers[fam][kind])
        lines.extend(samples[fam])
    return "\n".join(lines) + "\n"


def relabel_exposition(text: str, tags: Dict[str, str]) -> str:
    """Inject labels into every sample of a Prometheus text
    exposition, returning a new document. A label already present
    with a NON-empty value wins (the series owner knew better);
    absent or empty labels are (re)written.

    This is the multi-replica scrape primitive (ISSUE 6 satellite):
    replicas in separate processes render identical series from their
    own registries, so the fleet proxy relabels each scrape with
    `replica="<id>"` before merge_expositions — otherwise the merged
    document would either collide (duplicate series, a Prometheus
    parse error) or silently attribute one replica's counts to
    another. Comment/header lines pass through untouched."""
    import re as _re

    label_re = _re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')
    out: List[str] = []
    for line in text.splitlines():
        if not line.strip() or line.startswith("#"):
            out.append(line)
            continue
        m = _re.match(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?"
                      r"( .+)$", line)
        if m is None:
            out.append(line)
            continue
        name, labels, value = m.group(1), m.group(2) or "", m.group(3)
        present = dict(label_re.findall(labels))
        parts = [f'{k}="{v}"' for k, v in label_re.findall(labels)
                 if v != ""]
        for k, v in tags.items():
            if present.get(k, "") == "":
                parts.append(f'{k}="{_esc_label(v)}"')
        out.append(name + ("{" + ",".join(parts) + "}" if parts else "")
                   + value)
    return "\n".join(out) + ("\n" if text.endswith("\n") else "")


def snapshot() -> Dict[str, object]:
    """JSON-able snapshot of this process's registry."""
    out = {}
    with _registry_lock:
        metrics = list(_registry.values())
    for m in metrics:
        out[m._name] = {"type": m.metric_type, "info": m.info,
                        "samples": m._samples()}
    return out


def flush_to_kv() -> None:
    """Publish this process's snapshot to the controller KV so the
    dashboard / collect_cluster can aggregate across processes."""
    import ray_tpu
    from .._private import state as _state

    client = _state.current_client_or_none()
    if client is None:
        return
    wid = getattr(client, "worker_id", None) or f"pid{__import__('os').getpid()}"
    client.kv_put(f"__metrics__/{wid}",
                  json.dumps({"ts": time.time(),
                              "metrics": snapshot()}).encode())


def collect_cluster() -> Dict[str, object]:
    """All flushed per-process snapshots, keyed by worker id."""
    from .._private import state as _state

    client = _state.current_client()
    out = {}
    for key in client.controller_rpc("kv_keys", prefix="__metrics__/"):
        blob = client.kv_get(key)
        if blob:
            out[key.split("/", 1)[1]] = json.loads(blob)
    return out


__all__ = ["Counter", "Gauge", "Histogram", "export_prometheus",
           "merge_expositions", "relabel_exposition", "snapshot",
           "flush_to_kv", "collect_cluster"]
