"""State API: introspect tasks, actors, objects, nodes, placement groups.

Reference parity: python/ray/util/state/api.py (`ray list ...` client)
backed by the GCS task manager / dashboard state aggregator. Here the
control-plane controller keeps the bounded task-event table and the
actor/node/PG directories; node daemons report their shm object tables.
"""

from __future__ import annotations

from collections import Counter
from typing import Any, Dict, List, Optional

from ..._private import state as _state


def _client():
    return _state.current_client()


def list_tasks(filters: Optional[Dict[str, Any]] = None,
               detail: bool = False) -> List[Dict[str, Any]]:
    return _client().controller_rpc("list_tasks", filters=filters)


def list_actors(filters: Optional[Dict[str, Any]] = None
                ) -> List[Dict[str, Any]]:
    actors = _client().controller_rpc("list_actors")
    for key, val in (filters or {}).items():
        actors = [a for a in actors if a.get(key) == val]
    return actors


def list_nodes() -> List[Dict[str, Any]]:
    return _client().controller_rpc("list_nodes")


def list_placement_groups() -> List[Dict[str, Any]]:
    table = _client().controller_rpc("placement_group_table")
    return [dict(info, placement_group_id=pg_id)
            for pg_id, info in table.items()]


def list_objects() -> List[Dict[str, Any]]:
    """Sealed shm objects across every node daemon."""
    client = _client()
    out: List[Dict[str, Any]] = []
    for node in client.controller_rpc("list_nodes"):
        addr = node.get("addr") or node.get("address")
        if addr is None:
            continue
        try:
            out.extend(client.daemon_rpc(addr, "list_objects"))
        except Exception:
            continue
    return out


def summarize_tasks() -> Dict[str, Any]:
    """Grouped aggregation (reference parity: `ray summary tasks` /
    dashboard state_aggregator TaskSummaries): per func name, the state
    breakdown plus duration stats of finished runs."""
    tasks = list_tasks()
    by_state = Counter(t["state"] for t in tasks)
    groups: Dict[str, Dict[str, Any]] = {}
    for t in tasks:
        g = groups.setdefault(t["name"] or "?", {
            "state_counts": Counter(), "durations": []})
        g["state_counts"][t["state"]] += 1
        start, end = t.get("start_time"), t.get("end_time")
        if start and end:
            g["durations"].append(end - start)

    def _stats(ds):
        if not ds:
            return None
        ds = sorted(ds)
        return {"count": len(ds),
                "mean_s": round(sum(ds) / len(ds), 4),
                "min_s": round(ds[0], 4), "max_s": round(ds[-1], 4),
                "p50_s": round(ds[len(ds) // 2], 4)}

    return {"total": len(tasks), "by_state": dict(by_state),
            "by_func_name": {
                name: {"state_counts": dict(g["state_counts"]),
                       "duration": _stats(g["durations"])}
                for name, g in groups.items()}}


def summarize_actors() -> Dict[str, Any]:
    actors = list_actors()
    by_state = Counter(a.get("state", "?") for a in actors)
    by_class = Counter(a.get("class_name", "?") for a in actors)
    return {"total": len(actors), "by_state": dict(by_state),
            "by_class_name": dict(by_class)}


def summarize_objects() -> Dict[str, Any]:
    objs = list_objects()
    return {"total": len(objs),
            "total_size_bytes": sum(o["size"] for o in objs),
            "by_backend": dict(Counter(o["backend"] for o in objs))}


def get_task(task_id: str) -> Optional[Dict[str, Any]]:
    for t in list_tasks():
        if t["task_id"] == task_id:
            return t
    return None


def get_actor(actor_id: str) -> Optional[Dict[str, Any]]:
    for a in list_actors():
        if a.get("actor_id") == actor_id:
            return a
    return None


__all__ = ["list_tasks", "list_actors", "list_nodes", "list_objects",
           "list_placement_groups", "summarize_tasks",
           "summarize_actors", "summarize_objects", "get_task",
           "get_actor"]
