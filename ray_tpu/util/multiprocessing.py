"""multiprocessing.Pool-compatible shim over ray_tpu tasks.

Reference parity: python/ray/util/multiprocessing/pool.py — drop-in
`Pool` so existing `multiprocessing` code scales across the cluster
without rewrites.
"""

from __future__ import annotations

import itertools
import threading
from typing import Any, Callable, Iterable, List, Optional

import ray_tpu

__all__ = ["Pool"]


class AsyncResult:
    def __init__(self, refs, single: bool = False):
        self._refs = refs
        self._single = single

    def get(self, timeout: Optional[float] = None):
        results = ray_tpu.get(self._refs, timeout=timeout)
        return results[0] if self._single else results


    def wait(self, timeout: Optional[float] = None) -> None:
        ray_tpu.wait(self._refs, num_returns=len(self._refs),
                     timeout=timeout)

    def ready(self) -> bool:
        ready, _ = ray_tpu.wait(self._refs, num_returns=len(self._refs),
                                timeout=0)
        return len(ready) == len(self._refs)

    def successful(self) -> bool:
        try:
            ray_tpu.get(self._refs)
            return True
        except Exception:
            return False


class Pool:
    """Cluster-backed process pool. `processes` sizes the default
    chunking (work splits into ~4 chunks per process, so at most
    4*processes tasks are in flight); the cluster's CPU accounting does
    the actual execution throttling."""

    def __init__(self, processes: Optional[int] = None,
                 initializer: Optional[Callable] = None,
                 initargs: tuple = ()):
        if not ray_tpu.is_initialized():
            ray_tpu.init(ignore_reinit_error=True)
        self._processes = processes or 8
        self._initializer = initializer
        self._initargs = initargs
        self._closed = False
        # ONE watcher thread per pool multiplexes every pending
        # callback over ray_tpu.wait (a thread per apply_async would
        # scale threads with in-flight joblib batches)
        self._cb_lock = threading.Lock()
        self._cb_pending: dict = {}     # ref -> (callback, error_cb)
        self._cb_wake = threading.Event()
        self._cb_thread: Optional[threading.Thread] = None

    def _register_callback(self, ref, callback, error_callback) -> None:
        with self._cb_lock:
            self._cb_pending[ref] = (callback, error_callback)
            if self._cb_thread is None:
                self._cb_thread = threading.Thread(
                    target=self._callback_loop, daemon=True,
                    name="pool-callbacks")
                self._cb_thread.start()
        self._cb_wake.set()

    def _callback_loop(self) -> None:
        while True:
            with self._cb_lock:
                refs = list(self._cb_pending)
                # exit once the pool is closed and no callback is pending
                # (terminate() drops pending ones), so closed pools don't
                # leak a polling thread for the process lifetime
                if self._closed and not refs:
                    self._cb_thread = None
                    return
            if not refs:
                self._cb_wake.wait(timeout=1.0)
                self._cb_wake.clear()
                continue
            ready, _ = ray_tpu.wait(refs, num_returns=1, timeout=0.5)
            for ref in ready:
                with self._cb_lock:
                    cbs = self._cb_pending.pop(ref, None)
                if cbs is None:
                    continue
                callback, error_callback = cbs
                try:
                    out = ray_tpu.get(ref)
                except Exception as e:          # noqa: BLE001
                    if error_callback is not None:
                        error_callback(e)
                    continue
                if callback is not None:
                    callback(out)

    def _remote_fn(self, func):
        init, initargs = self._initializer, self._initargs

        @ray_tpu.remote
        def call(batch):
            # run the initializer once per WORKER process, not per chunk:
            # the deserialized function object is cached worker-side, so
            # a flag on it survives across this pool's chunk tasks
            if init is not None and not getattr(call_marker, "done", False):
                init(*initargs)
                call_marker.done = True
            return [func(*args) for args in batch]

        def call_marker():       # closure cell shared by all chunk calls
            pass

        return call

    def _run(self, func, iterables, chunksize: Optional[int]):
        if self._closed:
            raise ValueError("Pool not running")
        items = list(iterables)
        chunksize = chunksize or max(len(items) // (self._processes * 4), 1)
        call = self._remote_fn(func)
        refs = [call.remote(items[i:i + chunksize])
                for i in range(0, len(items), chunksize)]
        return refs

    # -- map family --------------------------------------------------------

    def map(self, func, iterable: Iterable,
            chunksize: Optional[int] = None) -> List[Any]:
        return self.map_async(func, iterable, chunksize).get()

    def map_async(self, func, iterable: Iterable,
                  chunksize: Optional[int] = None) -> AsyncResult:
        refs = self._run(func, [(x,) for x in iterable], chunksize)
        return _FlattenedResult(refs)

    def starmap(self, func, iterable: Iterable[tuple],
                chunksize: Optional[int] = None) -> List[Any]:
        return self.starmap_async(func, iterable, chunksize).get()

    def starmap_async(self, func, iterable: Iterable[tuple],
                      chunksize: Optional[int] = None) -> AsyncResult:
        refs = self._run(func, list(iterable), chunksize)
        return _FlattenedResult(refs)

    def imap(self, func, iterable: Iterable,
             chunksize: Optional[int] = None):
        for ref in self._run(func, [(x,) for x in iterable],
                             chunksize or 1):
            yield from ray_tpu.get(ref)

    def imap_unordered(self, func, iterable: Iterable,
                       chunksize: Optional[int] = None):
        pending = self._run(func, [(x,) for x in iterable], chunksize or 1)
        while pending:
            ready, pending = ray_tpu.wait(pending, num_returns=1)
            for ref in ready:
                yield from ray_tpu.get(ref)

    # -- apply family ------------------------------------------------------

    def apply(self, func, args: tuple = (), kwds: Optional[dict] = None):
        return self.apply_async(func, args, kwds).get()

    def apply_async(self, func, args: tuple = (),
                    kwds: Optional[dict] = None,
                    callback: Optional[Callable] = None,
                    error_callback: Optional[Callable] = None
                    ) -> AsyncResult:
        if self._closed:
            raise ValueError("Pool not running")
        kwds = kwds or {}

        @ray_tpu.remote
        def call():
            return func(*args, **kwds)

        ref = call.remote()
        if callback is not None or error_callback is not None:
            self._register_callback(ref, callback, error_callback)
        return AsyncResult([ref], single=True)

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        self._closed = True
        self._cb_wake.set()     # let the watcher thread notice and exit

    def terminate(self) -> None:
        self._closed = True
        with self._cb_lock:
            self._cb_pending.clear()   # drop callbacks; watcher exits
        self._cb_wake.set()

    def join(self) -> None:
        pass

    def __enter__(self) -> "Pool":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class _FlattenedResult(AsyncResult):
    """map chunks return lists; get() flattens back to item order."""

    def get(self, timeout: Optional[float] = None):
        chunks = ray_tpu.get(self._refs, timeout=timeout)
        return list(itertools.chain.from_iterable(chunks))
