from .placement_group import (placement_group, remove_placement_group,
                              placement_group_table, PlacementGroup,
                              tpu_pod_placement_group)
from .scheduling_strategies import (PlacementGroupSchedulingStrategy,
                                    NodeAffinitySchedulingStrategy,
                                    NodeLabelSchedulingStrategy)
