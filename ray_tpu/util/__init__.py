from .placement_group import (placement_group, remove_placement_group,
                              placement_group_table, PlacementGroup,
                              tpu_pod_placement_group)
from .scheduling_strategies import (PlacementGroupSchedulingStrategy,
                                    NodeAffinitySchedulingStrategy,
                                    NodeLabelSchedulingStrategy)

from .actor_pool import ActorPool
from .queue import Queue, Empty, Full
from .check_serialize import inspect_serializability
from . import metrics
from . import state
from . import tracing
