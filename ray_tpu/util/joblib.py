"""joblib backend: run joblib/scikit-learn `Parallel` on the cluster.

Reference parity: python/ray/util/joblib — `register_ray()` registers a
joblib parallel backend so existing sklearn/joblib code scales out with
only a `with joblib.parallel_backend("ray_tpu"):` wrapper. Like the
reference, the backend rides the multiprocessing Pool shim
(util/multiprocessing.py), so batches execute as cluster tasks.

    from ray_tpu.util.joblib import register_ray_tpu
    register_ray_tpu()
    with joblib.parallel_backend("ray_tpu"):
        Parallel()(delayed(f)(i) for i in range(100))
"""

from __future__ import annotations

__all__ = ["RayTpuBackend", "register_ray_tpu"]


def _make_backend_class():
    from joblib._parallel_backends import MultiprocessingBackend

    class RayTpuBackend(MultiprocessingBackend):
        """MultiprocessingBackend whose pool is the cluster Pool shim.

        The stock daemon/nesting guards in effective_n_jobs don't apply:
        cluster workers are real processes owned by the node daemon, not
        multiprocessing children, so nesting is safe."""

        def effective_n_jobs(self, n_jobs: int) -> int:
            if n_jobs == 0:
                raise ValueError("n_jobs == 0 has no meaning")
            if n_jobs is None:
                return 1
            if n_jobs < 0:
                # connect first: n_jobs=-1 on a fresh process must see
                # the cluster's CPUs, not default to 1 job
                import ray_tpu
                total = 1
                try:
                    ray_tpu.init(ignore_reinit_error=True)
                    total = int(
                        ray_tpu.cluster_resources().get("CPU", 1))
                except Exception:
                    pass
                n_jobs = max(total + 1 + n_jobs, 1)
            return n_jobs

        def configure(self, n_jobs=1, parallel=None, prefer=None,
                      require=None, **memmapping_pool_kwargs):
            n_jobs = self.effective_n_jobs(n_jobs)
            from .multiprocessing import Pool
            self._pool = Pool(processes=n_jobs)
            self.parallel = parallel
            return n_jobs

    return RayTpuBackend


# resolved lazily so importing ray_tpu.util never hard-requires joblib;
# module __getattr__ keeps `from ...joblib import RayTpuBackend` working
# without exporting a None placeholder
_backend_cls = None


def _resolve_backend():
    global _backend_cls
    if _backend_cls is None:
        _backend_cls = _make_backend_class()
    return _backend_cls


def __getattr__(name: str):
    if name == "RayTpuBackend":
        return _resolve_backend()
    raise AttributeError(name)


def register_ray_tpu() -> None:
    """Register the "ray_tpu" joblib parallel backend."""
    from joblib import register_parallel_backend
    register_parallel_backend("ray_tpu", _resolve_backend())


# reference-compatible alias (ray.util.joblib.register_ray)
register_ray = register_ray_tpu
