"""Chaos / fault-injection utilities for resilience testing.

Reference parity: src/ray/common/test/rpc_chaos.h:28 (RpcChaos) and the
reference's chaos-testing harnesses (kill-raylet / kill-worker test
utils) — first-class helpers so FT tests (and users validating their
recovery stories) don't hand-roll process surgery.
"""

from __future__ import annotations

import os
import random
import signal
import time
from typing import List, Optional

from .._private import state


def _runtime():
    from .._private.worker import current_runtime
    rt = current_runtime()
    if rt is None:
        raise RuntimeError("chaos utils need an initialized session")
    return rt


def list_worker_pids(node_id: Optional[str] = None) -> List[int]:
    """PIDs of live worker processes (optionally one node's)."""
    rt = _runtime()
    pids = []
    for daemon in [rt.head_daemon] + list(rt.extra_daemons):
        if daemon is None or (node_id and daemon.node_id != node_id):
            continue
        pids.extend(w.pid for w in daemon.workers.values()
                    if w.state != "dead")
    return pids


def kill_worker(pid: Optional[int] = None, sig: int = signal.SIGKILL) -> int:
    """Kill one worker process (random busy/actor worker by default).
    Returns the pid killed."""
    rt = _runtime()
    if pid is None:
        candidates = []
        for daemon in [rt.head_daemon] + list(rt.extra_daemons):
            if daemon is None:
                continue
            candidates.extend(w.pid for w in daemon.workers.values()
                              if w.state in ("busy", "actor"))
        if not candidates:
            candidates = list_worker_pids()
        if not candidates:
            raise RuntimeError("no workers to kill")
        pid = random.choice(candidates)
    os.kill(pid, sig)
    return pid


def kill_actor_worker(actor_handle) -> bool:
    """SIGKILL the worker hosting an actor (exercises max_restarts)."""
    rt = _runtime()
    actor_id = actor_handle._actor_id
    for daemon in [rt.head_daemon] + list(rt.extra_daemons):
        if daemon is None:
            continue
        for w in daemon.workers.values():
            if w.actor_id == actor_id and w.state == "actor":
                os.kill(w.pid, signal.SIGKILL)
                return True
    return False


def kill_node(node_id: str) -> bool:
    """Stop a fake node's daemon (workers die with it)."""
    from .._private.worker import remove_node
    return remove_node(node_id)


def partition_node(node_id: str, duration_s: float) -> None:
    """Simulate a network blip: pause the node's daemon heartbeats by
    SIGSTOP/SIGCONT on its worker... the in-process daemon has no pid of
    its own, so this simply blocks its monitor loop via a time fence."""
    rt = _runtime()
    for daemon in [rt.head_daemon] + list(rt.extra_daemons):
        if daemon is not None and daemon.node_id == node_id:
            import asyncio

            # All asyncio mutation happens ON the loop (Task.cancel is
            # not thread-safe): cancel the monitor loop, sleep, restart.
            async def blip():
                task = daemon._monitor_task
                if task is not None:
                    task.cancel()
                await asyncio.sleep(duration_s)
                daemon._monitor_task = asyncio.ensure_future(
                    daemon._monitor_loop())

            rt.loop_runner.call_soon(blip())
            return
    raise ValueError(f"unknown node {node_id!r}")


class RpcChaos:
    """Inject failures into the RPC layer (reference: rpc_chaos.h).

    with RpcChaos(failure_rate=0.1): every 10th-ish outgoing call raises
    ConnectionLost before hitting the wire — exercises retry paths.
    """

    def __init__(self, failure_rate: float = 0.1,
                 methods: Optional[List[str]] = None,
                 seed: Optional[int] = None):
        self.failure_rate = failure_rate
        self.methods = set(methods or [])
        self.rng = random.Random(seed)
        self._orig = None

    def __enter__(self):
        from . import chaos  # noqa: F401  (self-import keeps patch local)
        from .._private import protocol

        orig = protocol.RpcClient.call
        chaos_self = self

        async def chaotic_call(client_self, _method, **kwargs):
            if (not chaos_self.methods or _method in chaos_self.methods) \
                    and chaos_self.rng.random() < chaos_self.failure_rate:
                raise protocol.ConnectionLost(
                    f"chaos: injected failure for {_method!r}")
            return await orig(client_self, _method, **kwargs)

        self._orig = orig
        protocol.RpcClient.call = chaotic_call
        return self

    def __exit__(self, *exc):
        from .._private import protocol
        protocol.RpcClient.call = self._orig
        return False
