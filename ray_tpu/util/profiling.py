"""Profiling utilities.

Reference parity: dashboard/modules/reporter/profile_manager.py (py-spy
stack dumps, memray memory reports) — implemented with the standard
library (sys._current_frames / tracemalloc / /proc) so nothing external
is shipped — plus the TPU-native piece the reference lacks: a
jax.profiler trace context whose output feeds TensorBoard / xprof.
"""

from __future__ import annotations

import contextlib
import sys
import threading
import traceback
from typing import Iterator, Optional


def dump_stacks() -> str:
    """All threads' current stacks, py-spy style."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for ident, frame in sorted(sys._current_frames().items()):
        out.append(f"--- thread {names.get(ident, '?')} ({ident}) ---")
        out.extend(line.rstrip()
                   for line in traceback.format_stack(frame))
    return "\n".join(out)


def memory_summary() -> dict:
    """Process memory: RSS from /proc plus tracemalloc top allocations
    when tracing is active (start with tracemalloc.start())."""
    import tracemalloc

    summary = {"rss_bytes": None, "top_allocations": []}
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    summary["rss_bytes"] = int(line.split()[1]) * 1024
                    break
    except OSError:
        pass
    if tracemalloc.is_tracing():
        snap = tracemalloc.take_snapshot()
        for stat in snap.statistics("lineno")[:20]:
            summary["top_allocations"].append(
                {"where": str(stat.traceback), "bytes": stat.size,
                 "count": stat.count})
    return summary


@contextlib.contextmanager
def trace(log_dir: str, *, create_perfetto_link: bool = False
          ) -> Iterator[None]:
    """jax.profiler trace scope: XLA execution timeline + HLO ops land in
    `log_dir` for TensorBoard/xprof (`tensorboard --logdir ...`)."""
    import jax

    jax.profiler.start_trace(log_dir,
                             create_perfetto_link=create_perfetto_link)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def profile_step(fn, *args, log_dir: str = "/tmp/ray_tpu/profile"):
    """Run fn under a jax profiler trace; returns (result, log_dir)."""
    with trace(log_dir):
        result = fn(*args)
        import jax

        jax.block_until_ready(result)
    return result, log_dir
