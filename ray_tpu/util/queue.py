"""Distributed FIFO queue backed by an async actor.

Reference parity: python/ray/util/queue.py (Queue over an _QueueActor;
put/get with block/timeout, qsize/empty/full, put_nowait/get_nowait,
shutdown).
"""

from __future__ import annotations

import asyncio
from typing import Any, List, Optional

import ray_tpu


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    def __init__(self, maxsize: int = 0):
        self._q: asyncio.Queue = asyncio.Queue(maxsize=maxsize)

    async def put(self, item: Any, timeout: Optional[float] = None) -> bool:
        try:
            await asyncio.wait_for(self._q.put(item), timeout)
            return True
        except asyncio.TimeoutError:
            return False

    async def put_nowait(self, item: Any) -> bool:
        try:
            self._q.put_nowait(item)
            return True
        except asyncio.QueueFull:
            return False

    async def get(self, timeout: Optional[float] = None):
        try:
            return True, await asyncio.wait_for(self._q.get(), timeout)
        except asyncio.TimeoutError:
            return False, None

    async def get_nowait(self):
        try:
            return True, self._q.get_nowait()
        except asyncio.QueueEmpty:
            return False, None

    async def qsize(self) -> int:
        return self._q.qsize()

    async def empty(self) -> bool:
        return self._q.empty()

    async def full(self) -> bool:
        return self._q.full()


class Queue:
    def __init__(self, maxsize: int = 0,
                 actor_options: Optional[dict] = None):
        cls = ray_tpu.remote(**(actor_options or {"num_cpus": 0}))(
            _QueueActor)
        self.actor = cls.remote(maxsize)

    def put(self, item: Any, block: bool = True,
            timeout: Optional[float] = None) -> None:
        if not block:
            if not ray_tpu.get(self.actor.put_nowait.remote(item)):
                raise Full()
            return
        if not ray_tpu.get(self.actor.put.remote(item, timeout)):
            raise Full()

    def put_nowait(self, item: Any) -> None:
        self.put(item, block=False)

    def get(self, block: bool = True,
            timeout: Optional[float] = None) -> Any:
        if not block:
            ok, item = ray_tpu.get(self.actor.get_nowait.remote())
            if not ok:
                raise Empty()
            return item
        ok, item = ray_tpu.get(self.actor.get.remote(timeout))
        if not ok:
            raise Empty()
        return item

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def qsize(self) -> int:
        return ray_tpu.get(self.actor.qsize.remote())

    def empty(self) -> bool:
        return ray_tpu.get(self.actor.empty.remote())

    def full(self) -> bool:
        return ray_tpu.get(self.actor.full.remote())

    def shutdown(self) -> None:
        try:
            ray_tpu.kill(self.actor)
        except Exception:
            pass
