"""User-visible exception types.

Reference parity: python/ray/exceptions.py (RayError hierarchy).
"""

from __future__ import annotations


class RayTpuError(Exception):
    """Base class for framework errors."""


class TaskError(RayTpuError):
    """A task raised an exception; re-raised at ray_tpu.get()."""

    def __init__(self, task_name: str, remote_traceback: str,
                 cause: Exception | None = None):
        self.task_name = task_name
        self.remote_traceback = remote_traceback
        self.cause = cause
        super().__init__(
            f"task {task_name!r} failed:\n{remote_traceback}")

    def __reduce__(self):
        return (type(self), (self.task_name, self.remote_traceback, None))


class ActorError(TaskError):
    """An actor method raised an exception."""


class ActorDiedError(RayTpuError):
    """The actor is dead (killed, crashed, or owner exited)."""

    def __init__(self, actor_id: str, reason: str = ""):
        self.actor_id = actor_id
        self.reason = reason
        super().__init__(f"actor {actor_id[:12]} is dead. {reason}")

    def __reduce__(self):
        return (type(self), (self.actor_id, self.reason))


class WorkerCrashedError(RayTpuError):
    """The worker process executing a task died unexpectedly."""


class OutOfMemoryError(WorkerCrashedError):
    """The node memory monitor killed this worker to relieve memory
    pressure (reference parity: ray.exceptions.OutOfMemoryError /
    src/ray/common/memory_monitor.h:52). Subclasses WorkerCrashedError so
    the task-retry machinery treats OOM kills as retriable crashes."""


class ObjectLostError(RayTpuError):
    """The object's value is unreachable (owner or storing node gone)."""


class GetTimeoutError(RayTpuError, TimeoutError):
    """ray_tpu.get() timed out."""


class TaskCancelledError(RayTpuError):
    """The task was cancelled before/while running."""


class InfeasibleResourceError(RayTpuError):
    """No node in the cluster can ever satisfy the resource request."""


class RuntimeEnvSetupError(RayTpuError):
    """Failed to set up the runtime environment for a task/actor."""


class PlacementGroupUnavailableError(RayTpuError):
    """The referenced placement group was removed or could not be created."""
