"""Train configs.

Reference parity: python/ray/air/config.py (ScalingConfig :103,
FailureConfig :398, CheckpointConfig :448, RunConfig :597) — TPU-first:
`use_tpu`/`tpus_per_worker` instead of GPU fields, and placement defaults
to STRICT_SPREAD so multi-host TPU workers land one-per-host.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, Optional


@dataclasses.dataclass
class ScalingConfig:
    num_workers: int = 1
    use_tpu: bool = False
    tpus_per_worker: int = 4           # chips per host on most slices
    cpus_per_worker: float = 1.0
    resources_per_worker: Optional[Dict[str, float]] = None
    placement_strategy: str = "PACK"   # STRICT_SPREAD for multi-host TPU
    accelerator_type: Optional[str] = None   # e.g. "v5p-64"
    # Elastic training (reference parity: Train v2 ScalingPolicy): when
    # set, each (re)start runs with as many workers as the cluster can
    # place in [min_workers, num_workers] instead of blocking on the full
    # gang — shrink on failures/lost nodes, grow back on later restarts.
    # The train loop must derive its data sharding from ctx.world_size.
    min_workers: Optional[int] = None

    def worker_bundle(self) -> Dict[str, float]:
        if self.resources_per_worker is not None:
            return dict(self.resources_per_worker)
        bundle: Dict[str, float] = {"CPU": self.cpus_per_worker}
        if self.use_tpu:
            bundle["TPU"] = float(self.tpus_per_worker)
            if self.accelerator_type:
                bundle[f"TPU-{self.accelerator_type}"] = \
                    float(self.tpus_per_worker)
        return bundle


@dataclasses.dataclass
class FailureConfig:
    max_failures: int = 0              # group restarts allowed; -1 = infinite


@dataclasses.dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"


@dataclasses.dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: FailureConfig = dataclasses.field(
        default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = dataclasses.field(
        default_factory=CheckpointConfig)

    def resolved_storage_path(self) -> str:
        """storage_path may be a cloud URI (gs://bucket/dir, mock://...)
        — everything downstream rides train.storage (reference parity:
        train/_internal/storage.py StorageContext)."""
        from .storage import join
        base = self.storage_path or os.path.expanduser("~/ray_tpu_results")
        return join(base, self.name or "train_run")
