"""Per-worker training session: report/get_checkpoint/get_context.

Reference parity: python/ray/train/_internal/session.py (_TrainSession,
report :405/:672, get_checkpoint :786) — simplified to a module-global
session living inside each TrainWorker actor; reports are buffered on the
actor and drained by the controller.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Dict, List, Optional

from .checkpoint import Checkpoint


@dataclasses.dataclass
class TrainContext:
    world_rank: int
    world_size: int
    local_rank: int
    local_world_size: int
    node_rank: int
    experiment_name: str
    storage_path: str
    group_name: str


class _Session:
    def __init__(self, context: TrainContext,
                 starting_checkpoint: Optional[Checkpoint]):
        self.context = context
        self.starting_checkpoint = starting_checkpoint
        self.reports: List[Dict[str, Any]] = []
        self.lock = threading.Lock()
        self.finished = False

    def report(self, metrics: Dict[str, Any],
               checkpoint: Optional[Checkpoint] = None) -> None:
        with self.lock:
            self.reports.append({"metrics": dict(metrics),
                                 "checkpoint": checkpoint})

    def drain(self) -> List[Dict[str, Any]]:
        with self.lock:
            out, self.reports = self.reports, []
            return out


_session: Optional[_Session] = None


def _set_session(session: Optional[_Session]) -> None:
    global _session
    _session = session


def _get_session() -> _Session:
    if _session is None:
        raise RuntimeError(
            "no train session active; this API must be called inside a "
            "train_loop_per_worker")
    return _session


def report(metrics: Dict[str, Any],
           checkpoint: Optional[Checkpoint] = None) -> None:
    """Report metrics (and optionally a checkpoint) from a worker."""
    _get_session().report(metrics, checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    """The checkpoint to resume from, if any."""
    return _get_session().starting_checkpoint


def get_context() -> TrainContext:
    return _get_session().context


def get_world_rank() -> int:
    return _get_session().context.world_rank


def get_world_size() -> int:
    return _get_session().context.world_size


def get_local_rank() -> int:
    return _get_session().context.local_rank
