"""JaxTrainer: SPMD data-parallel training over a gang-scheduled actor group.

Reference parity: Ray Train v2's controller
(train/v2/_internal/execution/controller/controller.py:91 run :446) +
BackendExecutor (train/_internal/backend_executor.py:73 — PG creation :230,
rank/world mappings :378) and WorkerGroup (_internal/worker_group.py:102).
TPU-first differences: workers are one-per-TPU-host gang-scheduled via a
STRICT_SPREAD placement group; the in-program collective plane is the jax
mesh (jax.distributed across hosts + XLA/ICI), not NCCL process groups;
the host-side control collective is util.collective (object-store backed).
"""

from __future__ import annotations

import dataclasses
import os
import time
import traceback
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ..exceptions import RayTpuError
from ..util.placement_group import placement_group, remove_placement_group
from ..util.scheduling_strategies import PlacementGroupSchedulingStrategy
from .checkpoint import Checkpoint, CheckpointManager
from .config import RunConfig, ScalingConfig
from .session import TrainContext, _Session, _set_session


@dataclasses.dataclass
class Result:
    metrics: Dict[str, Any]
    checkpoint: Optional[Checkpoint]
    error: Optional[Exception]
    path: str
    metrics_dataframe: Optional[List[Dict[str, Any]]] = None

    @property
    def best_checkpoints(self):
        return [self.checkpoint] if self.checkpoint else []


class TrainWorker:
    """Actor hosting one rank of the SPMD group (max_concurrency=2 so the
    controller can drain reports while the user loop runs)."""

    def __init__(self, rank: int, world_size: int):
        self.rank = rank
        self.world_size = world_size
        self.session: Optional[_Session] = None
        # reported checkpoints, fetchable by monotonically-increasing id.
        # The driver acks each drain round (discard_checkpoints), which is
        # the real cleanup; the size backstop only guards a driver that
        # died mid-run, and is large enough that no single drain round can
        # lose entries before they are fetched.
        self._ckpts: Dict[int, Checkpoint] = {}
        self._ckpt_seq = 0
        self._ckpt_keep = 64
    def prepare_coordinator(self) -> str:
        """Rank 0 picks the coordination endpoint on ITS host (the jax
        coordination service runs inside rank 0's initialize call, so
        the address must be reachable from every other rank — the
        driver's host would be wrong on multi-host clusters)."""
        import socket

        from .._private.state import current_client
        host = current_client().address[0]
        probe = socket.socket()
        probe.bind((host, 0))
        port = probe.getsockname()[1]
        probe.close()
        return f"{host}:{port}"

    def init_jax(self, jax_coordinator: str) -> None:
        """jax.distributed across the gang: rank 0 hosts the coordination
        service; every rank blocks here until the world is connected
        (reference parity: train/torch/config.py:66 process-group setup;
        ours federates the jax runtime over DCN instead of NCCL)."""
        import jax
        if (os.environ.get("JAX_PLATFORMS") or "").startswith("cpu"):
            # CPU multi-process (the DCN test harness): collectives
            # need the gloo backend; on TPU the ICI/DCN transport is
            # native and this knob is irrelevant.
            jax.config.update(
                "jax_cpu_collectives_implementation", "gloo")
        jax.distributed.initialize(
            coordinator_address=jax_coordinator,
            num_processes=self.world_size, process_id=self.rank)

    def run(self, train_loop_fn: Callable, loop_config: Optional[Dict],
            context: TrainContext,
            starting_checkpoint) -> Dict[str, Any]:
        from .checkpoint import PackedCheckpoint
        if isinstance(starting_checkpoint, PackedCheckpoint):
            import tempfile
            starting_checkpoint = starting_checkpoint.unpack_into(
                tempfile.mkdtemp(prefix="rtpu_resume_"))
        session = _Session(context, starting_checkpoint)
        self.session = session
        _set_session(session)
        try:
            if loop_config is not None:
                train_loop_fn(loop_config)
            else:
                train_loop_fn()
            return {"status": "ok"}
        except Exception:
            return {"status": "error", "traceback": traceback.format_exc()}
        finally:
            session.finished = True
            _set_session(None)

    def drain_reports(self) -> List[Dict[str, Any]]:
        """Reports with checkpoints replaced by fetch ids: content is
        tarred+shipped only for the one rank the driver selects
        (fetch_checkpoint), not by all N ranks every drain round."""
        if self.session is None:
            return []
        reports = self.session.drain()
        for rep in reports:
            ckpt = rep.get("checkpoint")
            if isinstance(ckpt, Checkpoint):
                ckpt_id = self._ckpt_seq
                self._ckpt_seq += 1
                self._ckpts[ckpt_id] = ckpt
                for old in sorted(self._ckpts)[:-self._ckpt_keep]:
                    del self._ckpts[old]
                rep["checkpoint"] = {"__ckpt_id__": ckpt_id}
        return reports

    def fetch_checkpoint(self, ckpt_id: int):
        """Pack + ship one reported checkpoint's content (driver may be on
        a different host, so local directories don't travel)."""
        return self._ckpts[ckpt_id].pack()

    def discard_checkpoints(self, upto_id: int) -> None:
        """Driver ack: everything at or below upto_id was fetched or
        deliberately skipped this drain round."""
        for cid in [c for c in self._ckpts if c <= upto_id]:
            del self._ckpts[cid]

    def ping(self) -> str:
        return "ok"


class JaxTrainer:
    """Data-parallel trainer (reference DataParallelTrainer equivalent)."""

    def __init__(self,
                 train_loop_per_worker: Callable,
                 *,
                 train_loop_config: Optional[Dict[str, Any]] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None,
                 bootstrap_jax_distributed: bool = False):
        self.train_loop = train_loop_per_worker
        self.train_loop_config = train_loop_config
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.resume_from_checkpoint = resume_from_checkpoint
        self.bootstrap_jax = bootstrap_jax_distributed
        self._on_report: Optional[Callable[..., None]] = None

    # ------------------------------------------------------------------ fit

    def fit(self, on_report: Optional[Callable[..., None]] = None) -> Result:
        """Run to completion. `on_report(metrics, checkpoint)` streams each
        rank-0 report as it is drained (Tune integration hooks in here so
        schedulers get per-iteration control)."""
        self._on_report = on_report
        storage = self.run_config.resolved_storage_path()
        ckpt_cfg = self.run_config.checkpoint_config
        manager = CheckpointManager(
            storage, num_to_keep=ckpt_cfg.num_to_keep,
            score_attribute=ckpt_cfg.checkpoint_score_attribute,
            score_order=ckpt_cfg.checkpoint_score_order)
        max_failures = self.run_config.failure_config.max_failures
        attempt = 0
        starting_ckpt = self.resume_from_checkpoint
        history: List[Dict[str, Any]] = []
        while True:
            error = self._run_attempt(manager, starting_ckpt, history)
            if error is None:
                return Result(metrics=history[-1] if history else {},
                              checkpoint=manager.best or manager.latest,
                              error=None, path=storage,
                              metrics_dataframe=history)
            attempt += 1
            if max_failures >= 0 and attempt > max_failures:
                return Result(metrics=history[-1] if history else {},
                              checkpoint=manager.best or manager.latest,
                              error=error, path=storage,
                              metrics_dataframe=history)
            starting_ckpt = manager.latest or starting_ckpt
            time.sleep(1.0)

    def _reserve_workers(self):
        """Reserve this attempt's worker gang. Fixed-size: the full
        num_workers PG or error. Elastic (min_workers set): try sizes from
        num_workers down to min_workers, running with the largest gang the
        cluster can place NOW (reference parity: Train v2 ScalingPolicy
        elastic resize). Returns (pg, n) or (None, error)."""
        sc = self.scaling_config
        sizes = [sc.num_workers]
        if sc.min_workers is not None:
            lo = max(1, sc.min_workers)
            # Seed the probe at what the cluster can fit RIGHT NOW (cheap
            # resource arithmetic), then step down a few sizes to absorb
            # placement races — never a linear scan of 15s timeouts.
            fit = self._max_placeable(sc.worker_bundle())
            start = max(min(sc.num_workers, fit), lo)
            sizes = sorted({start, max(start - 1, lo),
                            max(start // 2, lo), lo}, reverse=True)
        last_err: Optional[Exception] = None
        for i, n in enumerate(sizes):
            pg = placement_group([sc.worker_bundle() for _ in range(n)],
                                 strategy=sc.placement_strategy)
            # full size gets the patient timeout; elastic shrink probes
            # must fail fast so recovery isn't serialized 120s per size
            timeout = 120 if i == 0 and len(sizes) == 1 else 15
            try:
                if pg.ready(timeout=timeout):
                    return (pg, n), None
            except Exception as e:
                last_err = e
            try:
                remove_placement_group(pg)
            except Exception:
                pass
        return None, (last_err or RayTpuError(
            f"no worker gang in [{sizes[-1]}, {sizes[0]}] x "
            f"{self.scaling_config.worker_bundle()} placeable"))

    def _max_placeable(self, bundle: Dict[str, float]) -> int:
        """How many copies of `bundle` the cluster can place NOW under the
        configured strategy: STRICT_PACK = all bundles on one node (max
        per-node fit); STRICT_SPREAD = one bundle per node (count of
        fitting nodes); PACK/SPREAD are soft (sum of per-node fits)."""
        from ray_tpu._private import state
        strategy = self.scaling_config.placement_strategy
        per_node = []
        try:
            nodes = state.current_client().nodes()
        except Exception:
            return 1
        for node in nodes:
            if not node.get("alive", True):
                continue
            avail = node.get("resources_available") or {}
            fits = min((int(avail.get(k, 0.0) // v)
                        for k, v in bundle.items() if v > 0),
                       default=0)
            per_node.append(max(fits, 0))
        if not per_node:
            return 1
        if strategy == "STRICT_PACK":
            total = max(per_node)
        elif strategy == "STRICT_SPREAD":
            total = sum(1 for f in per_node if f > 0)
        else:
            total = sum(per_node)
        return max(total, 1)

    def _run_attempt(self, manager: CheckpointManager,
                     starting_ckpt: Optional[Checkpoint],
                     history: List[Dict[str, Any]]) -> Optional[Exception]:
        sc = self.scaling_config
        workers = []
        reserved, err = self._reserve_workers()
        if reserved is None:
            return err
        pg, n = reserved
        try:
            WorkerCls = ray_tpu.remote(TrainWorker)
            worker_res = sc.worker_bundle()
            workers = [
                WorkerCls.options(
                    max_concurrency=2,
                    num_cpus=worker_res.get("CPU", 0),
                    resources={k: v for k, v in worker_res.items()
                               if k != "CPU"},
                    scheduling_strategy=PlacementGroupSchedulingStrategy(
                        pg, i),
                ).remote(i, n)
                for i in range(n)
            ]
            if self.bootstrap_jax and n > 1:
                # two-phase: rank 0 names the endpoint on its own host,
                # then every rank joins (each init_jax blocks until the
                # full world is connected). Failures — including the
                # unavoidable probe-then-bind port race when several
                # trainers bootstrap on one host — return as attempt
                # errors so FailureConfig retries with a fresh port.
                try:
                    coordinator = ray_tpu.get(
                        workers[0].prepare_coordinator.remote(),
                        timeout=60)
                    ray_tpu.get([w.init_jax.remote(coordinator)
                                 for w in workers], timeout=300)
                except Exception as e:
                    return e
            contexts = [TrainContext(
                world_rank=i, world_size=n, local_rank=0,
                local_world_size=1, node_rank=i,
                experiment_name=self.run_config.name or "train_run",
                storage_path=manager.storage_path,
                group_name=f"train_{id(self)}",
            ) for i in range(n)]
            packed_start = starting_ckpt.pack() if starting_ckpt else None
            run_refs = [w.run.remote(self.train_loop,
                                     self.train_loop_config,
                                     contexts[i], packed_start)
                        for i, w in enumerate(workers)]
            return self._poll(workers, run_refs, manager, history)
        finally:
            for w in workers:
                try:
                    ray_tpu.kill(w)
                except Exception:
                    pass
            try:
                remove_placement_group(pg)
            except Exception:
                pass

    def _poll(self, workers, run_refs, manager: CheckpointManager,
              history: List[Dict[str, Any]]) -> Optional[Exception]:
        pending = list(run_refs)
        while True:
            self._drain(workers, manager, history)
            ready, pending = ray_tpu.wait(
                pending, num_returns=len(pending), timeout=0.5)
            for ref in ready:
                try:
                    status = ray_tpu.get(ref)
                except Exception as e:
                    return e
                if status.get("status") == "error":
                    return RayTpuError(
                        f"train loop failed:\n{status.get('traceback')}")
            if not pending:
                self._drain(workers, manager, history)
                return None

    def _drain(self, workers, manager: CheckpointManager,
               history: List[Dict[str, Any]]) -> None:
        try:
            all_reports = ray_tpu.get(
                [w.drain_reports.remote() for w in workers], timeout=30)
        except Exception:
            return
        # Rank 0's metrics define the run history (reference semantics);
        # any rank may attach a checkpoint — the lowest reporting rank wins
        # when several report in the same drain round (SPMD loops typically
        # report identical global state from every rank), and only that
        # rank's content is packed + shipped.
        ckpt_rank = min((rank for rank, reports in enumerate(all_reports)
                         if any(r.get("checkpoint") is not None
                                for r in reports)), default=0)
        # Pass 1: fetch + persist ckpt_rank's checkpoints, keyed by report
        # round so rank 0's lockstep report in the same round carries them
        # (the checkpoint must reach the session even when ckpt_rank != 0).
        persisted_by_round: Dict[int, Any] = {}
        for i, rep in enumerate(all_reports[ckpt_rank]
                                if ckpt_rank < len(all_reports) else []):
            ckpt = rep.get("checkpoint")
            if ckpt is None:
                continue
            try:
                packed = ray_tpu.get(
                    workers[ckpt_rank].fetch_checkpoint.remote(
                        ckpt["__ckpt_id__"]), timeout=120)
            except Exception:
                packed = None
            if packed is not None:
                persisted_by_round[i] = manager.register(
                    packed, rep.get("metrics") or {})
        # Ack every rank's checkpoint entries for this round so workers
        # free them (non-selected ranks' content is never fetched).
        for rank, reports in enumerate(all_reports):
            ids = [rep["checkpoint"]["__ckpt_id__"] for rep in reports
                   if rep.get("checkpoint") is not None]
            if ids:
                workers[rank].discard_checkpoints.remote(max(ids))
        # Pass 2: rank 0's metrics define the run history.
        for i, rep in enumerate(all_reports[0] if all_reports else []):
            metrics = dict(rep.get("metrics") or {})
            persisted = persisted_by_round.get(i)
            if persisted is not None:
                metrics["_checkpoint_path"] = persisted.path
            history.append(metrics)
            if self._on_report is not None:
                self._on_report(dict(metrics), persisted)


# Reference-parity alias: the generic data-parallel entry point.
DataParallelTrainer = JaxTrainer


def _trainer_as_trainable(trainer: "JaxTrainer") -> type:
    """Wrap a JaxTrainer into a Tune function-trainable: the trial config
    is merged into train_loop_config, fit() runs inside the trial actor,
    and per-report metrics stream to Tune (reference:
    base_trainer.py:808 as_trainable — a trainer *is* a one-trial Tune
    experiment there too)."""
    import copy as _copy

    from ..tune.trainable import wrap_function

    def _tune_fn(config):
        from ..tune import trainable as _tune_session
        run = _copy.copy(trainer)
        run.train_loop_config = {**(trainer.train_loop_config or {}),
                                 **config}
        streamed = [0]

        def on_report(metrics, checkpoint=None):
            # Live bridge into the Tune session: blocks until the
            # controller consumes, so ASHA/PBT decisions land while TPU
            # compute is still pending, not after fit() finished.
            streamed[0] += 1
            _tune_session.report(dict(metrics), checkpoint=checkpoint)

        result = run.fit(on_report=on_report)
        if result.error is not None:
            raise result.error
        if not streamed[0] and result.metrics:
            _tune_session.report(dict(result.metrics))

    fn = _tune_fn
    fn.__name__ = "jax_trainer"
    return wrap_function(fn)


JaxTrainer.as_trainable = _trainer_as_trainable


def _tune_resources_per_trial(trainer: "JaxTrainer") -> Dict[str, float]:
    # A trial actor only coordinates; the trainer's own worker group holds
    # the real resources.
    return {"CPU": 0.1}


JaxTrainer.tune_resources_per_trial = _tune_resources_per_trial
