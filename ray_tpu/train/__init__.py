from .._private.usage import record_library_usage as _rlu
_rlu("train")
del _rlu

from .checkpoint import Checkpoint, CheckpointManager
from .config import (CheckpointConfig, FailureConfig, RunConfig,
                     ScalingConfig)
from .session import (get_checkpoint, get_context, get_local_rank,
                      get_world_rank, get_world_size, report)
from .trainer import DataParallelTrainer, JaxTrainer, Result, TrainWorker
