"""Checkpoint = a directory on storage; manager keeps top-K.

Reference parity: python/ray/train/_checkpoint.py (directory-on-storage
abstraction) + _internal/checkpoint_manager.py (top-K retention). Orbax
handles the tensor serialization when saving jax pytrees
(save_pytree/load_pytree); plain files work too.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
import time
from typing import Any, Dict, List, Optional, Tuple


# remote path -> downloaded local dir; one download per path per process,
# all removed at interpreter exit
_DOWNLOAD_CACHE: Dict[str, str] = {}


def _purge_download_cache() -> None:
    for local in _DOWNLOAD_CACHE.values():
        shutil.rmtree(local, ignore_errors=True)
    _DOWNLOAD_CACHE.clear()


import atexit
atexit.register(_purge_download_cache)


class Checkpoint:
    """A handle to a checkpoint directory — local, or on any storage
    the pyarrow-fs layer resolves (gs://, s3://, mock://; see
    storage.py — reference parity: train/_checkpoint.py Checkpoint
    (path, filesystem))."""

    def __init__(self, path: str):
        from .storage import is_uri
        self.path = path if is_uri(path) else os.path.abspath(path)

    @property
    def is_remote(self) -> bool:
        from .storage import is_uri
        return is_uri(self.path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    def as_directory(self) -> str:
        """A LOCAL directory with this checkpoint's content.

        Remote checkpoints are downloaded ONCE per remote path into a
        process-wide cached temp dir removed at interpreter exit
        (repeated pack()/load_pytree() calls must not re-download or
        leak temp dirs; a per-instance finalizer would dangle the
        returned path when the Checkpoint itself is short-lived)."""
        if not self.is_remote:
            return self.path
        local = _DOWNLOAD_CACHE.get(self.path)
        if local is None or not os.path.isdir(local):
            local = self.to_directory()
            _DOWNLOAD_CACHE[self.path] = local
        return local

    def to_directory(self, dest: Optional[str] = None) -> str:
        dest = dest or tempfile.mkdtemp(prefix="rtpu_ckpt_")
        if self.is_remote:
            from .storage import download_dir
            return download_dir(self.path, dest)
        if os.path.abspath(dest) != self.path:
            shutil.copytree(self.path, dest, dirs_exist_ok=True)
        return dest

    # -- jax pytree helpers (orbax) --

    @classmethod
    def save_pytree(cls, tree: Any, path: str) -> "Checkpoint":
        import orbax.checkpoint as ocp
        path = os.path.abspath(path)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        ckptr = ocp.PyTreeCheckpointer()
        ckptr.save(os.path.join(path, "pytree"), tree, force=True)
        return cls(path)

    def load_pytree(self, abstract_tree: Any = None) -> Any:
        import orbax.checkpoint as ocp
        ckptr = ocp.PyTreeCheckpointer()
        target = os.path.join(self.as_directory(), "pytree")
        if abstract_tree is not None:
            return ckptr.restore(target, item=abstract_tree)
        return ckptr.restore(target)

    def pack(self) -> "PackedCheckpoint":
        """Serialize the directory to bytes so the checkpoint can cross
        host boundaries through the object store (workers may run on a
        different machine than the driver; remote checkpoints are
        downloaded driver-side first, so workers never need storage
        credentials — or, for mock://, the driver's in-memory fs)."""
        import io
        import tarfile
        buf = io.BytesIO()
        with tarfile.open(fileobj=buf, mode="w") as tar:
            tar.add(self.as_directory(), arcname=".")
        return PackedCheckpoint(buf.getvalue())

    def __reduce__(self):
        return (Checkpoint, (self.path,))

    def __repr__(self):
        return f"Checkpoint({self.path})"


class PackedCheckpoint:
    """Checkpoint content as bytes (tar), produced worker-side by
    Checkpoint.pack(); materialized driver-side into storage."""

    def __init__(self, blob: bytes):
        self.blob = blob

    def unpack_into(self, dest: str) -> Checkpoint:
        import io
        import tarfile
        os.makedirs(dest, exist_ok=True)
        with tarfile.open(fileobj=io.BytesIO(self.blob), mode="r") as tar:
            tar.extractall(dest, filter="data")
        return Checkpoint(dest)


class CheckpointManager:
    """Tracks reported checkpoints, retains top-K by score (or recency)."""

    def __init__(self, storage_path: str, num_to_keep: Optional[int] = None,
                 score_attribute: Optional[str] = None,
                 score_order: str = "max"):
        from .storage import StorageContext
        self.storage_path = storage_path
        self.storage = StorageContext(storage_path)
        self.num_to_keep = num_to_keep
        self.score_attribute = score_attribute
        self.score_order = score_order
        self.checkpoints: List[Tuple[float, Checkpoint, Dict]] = []
        self._counter = 0
        self.storage.ensure_dir()

    def register(self, checkpoint: Checkpoint,
                 metrics: Optional[Dict] = None) -> Checkpoint:
        """Persist a reported checkpoint into storage and apply retention."""
        from .storage import join
        metrics = metrics or {}
        self._counter += 1
        name = f"checkpoint_{self._counter:06d}"
        meta = {k: v for k, v in metrics.items()
                if isinstance(v, (int, float, str, bool))}
        if self.storage.is_remote:
            # materialize locally, stamp metrics, upload as one unit
            local = tempfile.mkdtemp(prefix="rtpu_ckpt_up_")
            try:
                if isinstance(checkpoint, PackedCheckpoint):
                    checkpoint.unpack_into(local)
                else:
                    shutil.copytree(checkpoint.path, local,
                                    dirs_exist_ok=True)
                with open(os.path.join(local, ".metrics.json"), "w") as f:
                    json.dump(meta, f)
                persisted = Checkpoint(
                    self.storage.persist_dir(local, name))
            finally:
                shutil.rmtree(local, ignore_errors=True)
        else:
            dest = join(self.storage_path, name)
            if isinstance(checkpoint, PackedCheckpoint):
                persisted = checkpoint.unpack_into(dest)
            else:
                if checkpoint.path != dest:
                    shutil.copytree(checkpoint.path, dest,
                                    dirs_exist_ok=True)
                persisted = Checkpoint(dest)
            with open(os.path.join(dest, ".metrics.json"), "w") as f:
                json.dump(meta, f)
        if self.score_attribute and self.score_attribute in metrics:
            score = float(metrics[self.score_attribute])
            if self.score_order == "min":
                score = -score
        else:
            score = float(self._counter)      # recency
        self.checkpoints.append((score, persisted, metrics))
        self._apply_retention()
        return persisted

    def _apply_retention(self) -> None:
        if self.num_to_keep is None:
            return
        while len(self.checkpoints) > self.num_to_keep:
            worst_idx = min(range(len(self.checkpoints)),
                            key=lambda i: self.checkpoints[i][0])
            _, ckpt, _ = self.checkpoints.pop(worst_idx)
            self.storage.delete(ckpt.path)

    @property
    def latest(self) -> Optional[Checkpoint]:
        return self.checkpoints[-1][1] if self.checkpoints else None

    @property
    def best(self) -> Optional[Checkpoint]:
        if not self.checkpoints:
            return None
        return max(self.checkpoints, key=lambda c: c[0])[1]
