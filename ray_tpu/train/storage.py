"""Checkpoint/result storage over pyarrow filesystems.

Reference parity: python/ray/train/_internal/storage.py:358
(StorageContext: resolves storage_path into (pyarrow.fs.FileSystem,
fs_path); all train/tune checkpoint IO rides it so runs can write to
gs://, s3://, or any fsspec filesystem). A TPU pod job's checkpoints
must land on cloud storage — local disks on preemptible hosts are not
durable.

Schemes:
  /abs/path, file://...  -> LocalFileSystem
  mock://...             -> process-local in-memory fs (tests; the
                            reference uses the same scheme name)
  gs://, s3://, hdfs://  -> pyarrow.fs.FileSystem.from_uri
  custom://              -> register_filesystem("custom", factory)
"""

from __future__ import annotations

import os
import posixpath
from typing import Callable, Dict, Optional, Tuple

_CUSTOM_FS: Dict[str, Callable] = {}
_MOCK_FS = None   # singleton so every caller in-process shares state


def register_filesystem(scheme: str, factory: Callable) -> None:
    """factory() -> pyarrow-compatible FileSystem for `scheme://`."""
    _CUSTOM_FS[scheme] = factory


def is_uri(path: str) -> bool:
    return "://" in (path or "")


def _mock_fs():
    """In-memory filesystem (fsspec memory fs behind the pyarrow
    facade) — one instance per process, like the reference's
    mock:// test filesystem."""
    global _MOCK_FS
    if _MOCK_FS is None:
        import fsspec
        from pyarrow.fs import FSSpecHandler, PyFileSystem
        _MOCK_FS = PyFileSystem(FSSpecHandler(
            fsspec.filesystem("memory")))
    return _MOCK_FS


def get_fs_and_path(path: str) -> Tuple["object", str]:
    """Resolve a storage path/URI to (pyarrow FileSystem, fs path)."""
    from pyarrow import fs as pafs
    if not is_uri(path):
        return pafs.LocalFileSystem(), os.path.abspath(path)
    scheme, rest = path.split("://", 1)
    if scheme == "file":
        return pafs.LocalFileSystem(), os.path.abspath("/" + rest.lstrip("/"))
    if scheme == "mock":
        return _mock_fs(), rest
    if scheme in _CUSTOM_FS:
        return _CUSTOM_FS[scheme](), rest
    fs, fs_path = pafs.FileSystem.from_uri(path)
    return fs, fs_path


def upload_dir(local_dir: str, dest_uri: str) -> None:
    """Recursively copy a local directory to storage."""
    fs, dest = get_fs_and_path(dest_uri)
    fs.create_dir(dest, recursive=True)
    for root, _dirs, files in os.walk(local_dir):
        rel = os.path.relpath(root, local_dir)
        remote_root = dest if rel == "." else posixpath.join(
            dest, rel.replace(os.sep, "/"))
        if rel != ".":
            fs.create_dir(remote_root, recursive=True)
        for name in files:
            with open(os.path.join(root, name), "rb") as src, \
                    fs.open_output_stream(
                        posixpath.join(remote_root, name)) as out:
                while True:
                    chunk = src.read(4 << 20)
                    if not chunk:
                        break
                    out.write(chunk)


def download_dir(src_uri: str, local_dir: str) -> str:
    """Recursively copy a storage directory to a local one."""
    from pyarrow.fs import FileSelector
    fs, src = get_fs_and_path(src_uri)
    os.makedirs(local_dir, exist_ok=True)
    infos = fs.get_file_info(FileSelector(src, recursive=True))
    # some backends (fsspec memory fs) report absolute-normalized paths;
    # compute relatives scheme-agnostically
    src_norm = src.strip("/")
    for info in infos:
        p = info.path.strip("/")
        if p == src_norm or not p.startswith(src_norm + "/"):
            continue
        rel = p[len(src_norm) + 1:]
        target = os.path.join(local_dir, *rel.split("/"))
        if info.type.name == "Directory":
            os.makedirs(target, exist_ok=True)
            continue
        os.makedirs(os.path.dirname(target), exist_ok=True)
        with fs.open_input_stream(info.path) as src_f, \
                open(target, "wb") as out:
            while True:
                chunk = src_f.read(4 << 20)
                if not chunk:
                    break
                out.write(chunk)
    return local_dir


def delete_dir(uri: str) -> None:
    fs, path = get_fs_and_path(uri)
    try:
        fs.delete_dir(path)
    except FileNotFoundError:
        pass


def exists(uri: str) -> bool:
    fs, path = get_fs_and_path(uri)
    info = fs.get_file_info([path])[0]
    return info.type.name != "NotFound"


def join(base: str, *parts: str) -> str:
    """Path join that works for both URIs and local paths."""
    if is_uri(base):
        return posixpath.join(base, *parts)
    return os.path.join(base, *parts)


class StorageContext:
    """Resolved storage for one run: `storage_path/experiment_name`.

    Mirrors the reference StorageContext's role (storage.py:358):
    everything that persists run artifacts asks this object where and
    how, so local paths and cloud URIs behave identically."""

    def __init__(self, storage_path: str,
                 experiment_name: Optional[str] = None):
        self.storage_path = storage_path
        self.experiment_name = experiment_name
        self.run_path = (join(storage_path, experiment_name)
                         if experiment_name else storage_path)
        self.fs, self.fs_path = get_fs_and_path(self.run_path)

    @property
    def is_remote(self) -> bool:
        return is_uri(self.run_path) and not self.run_path.startswith(
            "file://")

    def ensure_dir(self, *parts: str) -> str:
        target = join(self.run_path, *parts)
        fs, path = get_fs_and_path(target)
        fs.create_dir(path, recursive=True)
        return target

    def persist_dir(self, local_dir: str, *parts: str) -> str:
        """Upload (or copy) a local directory under the run path;
        returns its storage path/URI."""
        dest = join(self.run_path, *parts)
        if self.is_remote:
            upload_dir(local_dir, dest)
        else:
            import shutil
            if os.path.abspath(local_dir) != os.path.abspath(dest):
                shutil.copytree(local_dir, dest, dirs_exist_ok=True)
        return dest

    def fetch_dir(self, storage_dir: str, local_dest: str) -> str:
        if is_uri(storage_dir):
            return download_dir(storage_dir, local_dest)
        import shutil
        if os.path.abspath(storage_dir) != os.path.abspath(local_dest):
            shutil.copytree(storage_dir, local_dest, dirs_exist_ok=True)
        return local_dest

    def delete(self, storage_dir: str) -> None:
        if is_uri(storage_dir):
            delete_dir(storage_dir)
        else:
            import shutil
            shutil.rmtree(storage_dir, ignore_errors=True)
