"""ray_tpu.tune: hyperparameter search over trial actors.

Reference parity: python/ray/tune — Tuner.fit (tuner.py:312) driving
Trainable actors through a TuneController (execution/tune_controller.py:68)
with pluggable Searchers and TrialSchedulers (ASHA/HyperBand/PBT/median).
TPU-first: trials are gang-schedulable (a trial's trainable can itself be
a JaxTrainer spanning a pod slice via placement groups).
"""

from .._private.usage import record_library_usage as _rlu
_rlu("tune")
del _rlu


from .search.sample import (uniform, quniform, loguniform, qloguniform,
                            randint, qrandint, lograndint, choice,
                            sample_from, grid_search)
from .search.searcher import (Searcher, BasicVariantGenerator, RandomSearch,
                              ConcurrencyLimiter)
from .search.tpe import TPESearch
from .search.bohb import BOHBSearch
from .search.adapters import HyperOptSearch, OptunaSearch
from .schedulers import (TrialScheduler, FIFOScheduler, MedianStoppingRule,
                         AsyncHyperBandScheduler, ASHAScheduler,
                         HyperBandScheduler, PopulationBasedTraining, PB2)
from .trainable import Trainable, report, get_checkpoint
from .trial import Trial
from .tuner import ResultGrid, TuneConfig, TuneResult, Tuner, run

__all__ = [
    "uniform", "quniform", "loguniform", "qloguniform", "randint",
    "qrandint", "lograndint", "choice", "sample_from", "grid_search",
    "Searcher", "BasicVariantGenerator", "RandomSearch", "TPESearch",
    "BOHBSearch", "OptunaSearch", "HyperOptSearch",
    "ConcurrencyLimiter", "TrialScheduler", "FIFOScheduler",
    "MedianStoppingRule", "AsyncHyperBandScheduler", "ASHAScheduler",
    "HyperBandScheduler", "PopulationBasedTraining", "PB2", "Trainable", "report",
    "get_checkpoint", "Trial", "ResultGrid", "TuneConfig", "TuneResult",
    "Tuner", "run",
]
