"""Trainable: the unit a Tune trial actor runs.

Reference parity: python/ray/tune/trainable/trainable.py (Trainable.train
:289, save :467, restore :507) and function_trainable.py (user function in
a thread, reports bridged over a queue). Class trainables implement
step/save_checkpoint/load_checkpoint; function trainables call
tune.report() and are driven one-report-per-train() for scheduler
decisions (ASHA/PBT need per-iteration control).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Dict, Optional

DONE = "done"
TRAINING_ITERATION = "training_iteration"


class Trainable:
    """Subclass API: setup(config), step() -> metrics dict,
    save_checkpoint() -> state, load_checkpoint(state)."""

    def __init__(self, config: Optional[Dict[str, Any]] = None):
        self.config = dict(config or {})
        self._iteration = 0
        self._start = time.time()
        self.setup(self.config)

    # -- subclass hooks -----------------------------------------------------
    def setup(self, config: Dict[str, Any]) -> None:
        pass

    def step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def save_checkpoint(self) -> Any:
        return None

    def load_checkpoint(self, state: Any) -> None:
        pass

    def reset_config(self, new_config: Dict[str, Any]) -> bool:
        """Return True if the trainable supports in-place config swap
        (lets PBT exploit without restarting the actor)."""
        return False

    def cleanup(self) -> None:
        pass

    # -- driver-facing API (called remotely by the controller) --------------
    def train(self) -> Dict[str, Any]:
        result = self.step() or {}
        self._iteration += 1
        result.setdefault(TRAINING_ITERATION, self._iteration)
        result.setdefault("time_total_s", time.time() - self._start)
        result.setdefault(DONE, False)
        return result

    def save(self) -> Any:
        return {"iteration": self._iteration,
                "state": self.save_checkpoint()}

    def restore(self, payload: Any) -> None:
        self._iteration = payload["iteration"]
        self.load_checkpoint(payload["state"])

    def reset(self, new_config: Dict[str, Any]) -> bool:
        ok = self.reset_config(new_config)
        if ok:
            self.config = dict(new_config)
        return ok

    def stop(self) -> None:
        self.cleanup()


class _FnReporter:
    def __init__(self):
        self.queue: "queue.Queue" = queue.Queue()
        self.continue_event = threading.Event()

    def report(self, metrics: Dict[str, Any],
               checkpoint: Any = None) -> None:
        self.queue.put(("report", dict(metrics), checkpoint))
        # Block the user loop until the controller consumes the report —
        # gives schedulers per-iteration pause/stop control.
        self.continue_event.wait()
        self.continue_event.clear()


_fn_reporter: Optional[_FnReporter] = None


def report(metrics: Dict[str, Any], checkpoint: Any = None) -> None:
    """tune.report from inside a function trainable."""
    if _fn_reporter is None:
        raise RuntimeError("tune.report() called outside a Tune trial")
    _fn_reporter.report(metrics, checkpoint)


def get_checkpoint() -> Any:
    return getattr(_fn_reporter, "starting_checkpoint", None)


class FunctionTrainable(Trainable):
    """Wraps fn(config) into the Trainable step protocol.

    Pause/resume contract (same as the reference's function trainables):
    on resume the user function restarts from its beginning in a fresh
    actor — it must call tune.get_checkpoint() and fast-forward from the
    restored state, reporting checkpoints via tune.report(..., checkpoint=)
    at rung-relevant milestones. A function that ignores checkpoints will
    redo its pre-pause work."""

    _fn: Callable[[Dict[str, Any]], Any] = None  # set by wrap_function

    def setup(self, config: Dict[str, Any]) -> None:
        self._reporter = _FnReporter()
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._last_checkpoint: Any = None

    def _runner(self) -> None:
        global _fn_reporter
        _fn_reporter = self._reporter
        try:
            type(self)._fn(self.config)
        except BaseException as exc:  # surfaced from step()
            self._error = exc
        finally:
            self._reporter.queue.put(("finished", None, None))

    def step(self) -> Dict[str, Any]:
        if self._thread is None:
            self._thread = threading.Thread(target=self._runner, daemon=True)
            self._thread.start()
        kind, metrics, checkpoint = self._reporter.queue.get()
        if kind == "finished":
            if self._error is not None:
                raise self._error
            return {DONE: True}
        if checkpoint is not None:
            self._last_checkpoint = checkpoint
        self._reporter.continue_event.set()
        return metrics

    def save_checkpoint(self) -> Any:
        return self._last_checkpoint

    def load_checkpoint(self, state: Any) -> None:
        self._reporter.starting_checkpoint = state


def wrap_function(fn: Callable[[Dict[str, Any]], Any]) -> type:
    return type(f"fn_{getattr(fn, '__name__', 'trainable')}",
                (FunctionTrainable,), {"_fn": staticmethod(fn)})
