"""Trial: driver-side record of one hyperparameter configuration.

Reference parity: python/ray/tune/experiment/trial.py (status machine
PENDING/RUNNING/PAUSED/TERMINATED/ERROR, last_result, checkpoint
bookkeeping).
"""

from __future__ import annotations

import itertools
from typing import Any, Dict, List, Optional

PENDING = "PENDING"
RUNNING = "RUNNING"
PAUSED = "PAUSED"
TERMINATED = "TERMINATED"
ERROR = "ERROR"

_counter = itertools.count()


class Trial:
    def __init__(self, trial_id: str, config: Dict[str, Any],
                 experiment_name: str = "exp"):
        self.trial_id = trial_id
        self.config = config
        self.experiment_name = experiment_name
        self.status = PENDING
        self.actor = None
        self.inflight = None            # outstanding train() ObjectRef
        self.last_result: Dict[str, Any] = {}
        self.results: List[Dict[str, Any]] = []
        self.checkpoint: Any = None     # payload from Trainable.save()
        self.error: Optional[str] = None
        self.iteration = 0
        self.restore_payload: Any = None

    @property
    def is_finished(self) -> bool:
        return self.status in (TERMINATED, ERROR)

    def metric_value(self, metric: str) -> Optional[float]:
        value = self.last_result.get(metric)
        return None if value is None else float(value)

    def __repr__(self):
        return f"Trial({self.trial_id}, {self.status}, it={self.iteration})"


def new_trial_id() -> str:
    return f"trial_{next(_counter):05d}"


def advance_trial_counter_past(trial_ids) -> None:
    """Experiment restore in a FRESH process: the global counter starts
    at 0 again, which would reissue restored trial ids and merge two
    trials' scheduler/searcher state. Fast-forward past the max."""
    global _counter
    import itertools
    top = -1
    for tid in trial_ids:
        try:
            top = max(top, int(str(tid).rsplit("_", 1)[-1]))
        except ValueError:
            continue
    current = next(_counter)
    _counter = itertools.count(max(current, top + 1))
