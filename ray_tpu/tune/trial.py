"""Trial: driver-side record of one hyperparameter configuration.

Reference parity: python/ray/tune/experiment/trial.py (status machine
PENDING/RUNNING/PAUSED/TERMINATED/ERROR, last_result, checkpoint
bookkeeping).
"""

from __future__ import annotations

import itertools
from typing import Any, Dict, List, Optional

PENDING = "PENDING"
RUNNING = "RUNNING"
PAUSED = "PAUSED"
TERMINATED = "TERMINATED"
ERROR = "ERROR"

_counter = itertools.count()


class Trial:
    def __init__(self, trial_id: str, config: Dict[str, Any],
                 experiment_name: str = "exp"):
        self.trial_id = trial_id
        self.config = config
        self.experiment_name = experiment_name
        self.status = PENDING
        self.actor = None
        self.inflight = None            # outstanding train() ObjectRef
        self.last_result: Dict[str, Any] = {}
        self.results: List[Dict[str, Any]] = []
        self.checkpoint: Any = None     # payload from Trainable.save()
        self.error: Optional[str] = None
        self.iteration = 0
        self.restore_payload: Any = None

    @property
    def is_finished(self) -> bool:
        return self.status in (TERMINATED, ERROR)

    def metric_value(self, metric: str) -> Optional[float]:
        value = self.last_result.get(metric)
        return None if value is None else float(value)

    def __repr__(self):
        return f"Trial({self.trial_id}, {self.status}, it={self.iteration})"


def new_trial_id() -> str:
    return f"trial_{next(_counter):05d}"
