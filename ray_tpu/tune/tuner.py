"""Tuner: the user-facing experiment API.

Reference parity: python/ray/tune/tuner.py (Tuner.fit :312) +
tune/result_grid.py (ResultGrid/get_best_result). Trainables may be a
function(config), a Trainable subclass, or a ray_tpu.train trainer
instance (wrapped the way base_trainer.py:808 wraps trainers into
trainables).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Union

from .execution.tune_controller import TuneController
from .schedulers.trial_scheduler import TrialScheduler
from .search.searcher import BasicVariantGenerator, Searcher
from .trainable import Trainable, wrap_function
from .trial import Trial


@dataclasses.dataclass
class TuneConfig:
    metric: Optional[str] = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: int = 4
    search_alg: Optional[Searcher] = None
    scheduler: Optional[TrialScheduler] = None
    time_budget_s: Optional[float] = None
    seed: Optional[int] = None
    stop: Optional[Dict[str, float]] = None
    # experiment-level durability: snapshot searcher/scheduler/trials
    # here every checkpoint_period_s; Tuner.restore(path, trainable)
    # resumes the sweep
    experiment_path: Optional[str] = None
    checkpoint_period_s: float = 10.0


@dataclasses.dataclass
class TuneResult:
    """One trial's outcome (the reference's tune/result.py Result)."""
    config: Dict[str, Any]
    metrics: Dict[str, Any]
    checkpoint: Any
    error: Optional[str]
    trial_id: str

    @property
    def metrics_dataframe(self) -> List[Dict[str, Any]]:
        return self._history

    def __repr__(self):
        return f"Result({self.trial_id}, metrics={self.metrics})"


class ResultGrid:
    def __init__(self, trials: List[Trial], metric: Optional[str],
                 mode: str):
        self._trials = trials
        self._metric, self._mode = metric, mode
        self.results = []
        for trial in trials:
            result = TuneResult(config=trial.config,
                                metrics=trial.last_result,
                                checkpoint=trial.checkpoint,
                                error=trial.error,
                                trial_id=trial.trial_id)
            result._history = trial.results
            self.results.append(result)

    def __len__(self):
        return len(self.results)

    def __getitem__(self, index: int) -> TuneResult:
        return self.results[index]

    @property
    def errors(self) -> List[str]:
        return [r.error for r in self.results if r.error]

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> TuneResult:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise ValueError("metric required (set TuneConfig.metric)")
        scored = [r for r in self.results if metric in (r.metrics or {})]
        if not scored:
            raise ValueError(f"no trial reported metric {metric!r}")
        return (max if mode == "max" else min)(
            scored, key=lambda r: r.metrics[metric])

    def get_dataframe(self) -> List[Dict[str, Any]]:
        rows = []
        for r in self.results:
            row = dict(r.metrics or {})
            row["trial_id"] = r.trial_id
            row.update({f"config/{k}": v for k, v in r.config.items()})
            rows.append(row)
        return rows


def _as_trainable_cls(trainable: Any) -> type:
    if isinstance(trainable, type) and issubclass(trainable, Trainable):
        return trainable
    if callable(trainable) and not isinstance(trainable, type):
        return wrap_function(trainable)
    # Train-library trainer instance → function trainable running fit()
    # (reference: base_trainer.py:808 as_trainable).
    if hasattr(trainable, "as_trainable"):
        return trainable.as_trainable()
    raise TypeError(f"cannot tune over {trainable!r}")


class Tuner:
    def __init__(self, trainable: Any,
                 *, param_space: Optional[Dict[str, Any]] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config: Any = None):
        self._trainable = trainable
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config
        self._restore_path: Optional[str] = None

    def fit(self) -> ResultGrid:
        cfg = self.tune_config
        searcher = cfg.search_alg
        if searcher is None:
            searcher = BasicVariantGenerator(
                self.param_space, num_samples=cfg.num_samples,
                seed=cfg.seed, metric=cfg.metric, mode=cfg.mode)
        else:
            searcher.set_search_properties(cfg.metric, cfg.mode,
                                           self.param_space)
        resources = None
        max_failures = 0
        if self.run_config is not None:
            failure = getattr(self.run_config, "failure_config", None)
            if failure is not None:
                max_failures = failure.max_failures
        trainable_cls = _as_trainable_cls(self._trainable)
        if hasattr(self._trainable, "tune_resources_per_trial"):
            resources = self._trainable.tune_resources_per_trial()
        controller = TuneController(
            trainable_cls, searcher, cfg.scheduler,
            max_concurrent=cfg.max_concurrent_trials,
            resources_per_trial=resources,
            max_failures=max_failures,
            time_budget_s=cfg.time_budget_s,
            stop=cfg.stop,
            experiment_path=cfg.experiment_path,
            checkpoint_period_s=cfg.checkpoint_period_s)
        metric, mode = cfg.metric, cfg.mode
        if self._restore_path:
            controller.restore_experiment(self._restore_path)
            # a bare restore (no tune_config) recovers metric/mode from
            # the pickled searcher so get_best_result just works
            if metric is None:
                metric = controller.searcher.metric
                mode = controller.searcher.mode or mode
            controller.scheduler.set_search_properties(
                controller.searcher.metric, controller.searcher.mode)
        trials = controller.run()
        return ResultGrid(trials, metric, mode)

    @classmethod
    def restore(cls, path: str, trainable: Any,
                *, tune_config: Optional[TuneConfig] = None,
                run_config: Any = None) -> "Tuner":
        """Resume an interrupted sweep from an experiment snapshot
        (reference parity: Tuner.restore — searcher observation
        history, scheduler state, finished-trial results all carry
        over; interrupted trials re-launch from their last
        checkpoint). Call .fit() on the returned Tuner."""
        tuner = cls(trainable, tune_config=tune_config,
                    run_config=run_config)
        if tuner.tune_config.experiment_path is None:
            # copy, don't mutate: the caller may reuse its TuneConfig
            tuner.tune_config = dataclasses.replace(
                tuner.tune_config, experiment_path=path)
        tuner._restore_path = path
        return tuner


def run(trainable: Any, *, config: Optional[Dict[str, Any]] = None,
        num_samples: int = 1, metric: Optional[str] = None,
        mode: str = "max", scheduler: Optional[TrialScheduler] = None,
        search_alg: Optional[Searcher] = None,
        max_concurrent_trials: int = 4,
        stop: Optional[Dict[str, float]] = None, **_ignored) -> ResultGrid:
    """tune.run-style convenience wrapper over Tuner."""
    tuner = Tuner(trainable, param_space=config,
                  tune_config=TuneConfig(
                      metric=metric, mode=mode, num_samples=num_samples,
                      scheduler=scheduler, search_alg=search_alg,
                      max_concurrent_trials=max_concurrent_trials,
                      stop=stop))
    return tuner.fit()
