"""TuneController: the experiment event loop.

Reference parity: python/ray/tune/execution/tune_controller.py
(TuneController :68 — step :666 pattern: ask searcher for configs, start
trial actors up to the concurrency cap, wait on in-flight train() futures,
route each result through scheduler+searcher, apply CONTINUE/PAUSE/STOP,
save/restore for pause and PBT exploit :1691-1791). Trials run as
ray_tpu actors; pause/exploit moves Trainable.save() payloads through the
object store.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.exceptions import RayTpuError

from ..schedulers.trial_scheduler import (CONTINUE, PAUSE, STOP,
                                          FIFOScheduler, TrialScheduler)
from ..search.searcher import Searcher
from ..trainable import DONE, Trainable
from ..trial import (ERROR, PAUSED, PENDING, RUNNING, TERMINATED, Trial,
                     new_trial_id)

logger = logging.getLogger(__name__)


class TuneController:
    def __init__(self, trainable_cls: type, searcher: Searcher,
                 scheduler: Optional[TrialScheduler] = None,
                 max_concurrent: int = 4,
                 resources_per_trial: Optional[Dict[str, float]] = None,
                 max_failures: int = 0,
                 time_budget_s: Optional[float] = None,
                 stop: Optional[Dict[str, float]] = None,
                 experiment_path: Optional[str] = None,
                 checkpoint_period_s: float = 10.0):
        self.trainable_cls = trainable_cls
        self.searcher = searcher
        self.scheduler = scheduler or FIFOScheduler(searcher.metric,
                                                    searcher.mode)
        self.scheduler.set_search_properties(searcher.metric, searcher.mode)
        self.max_concurrent = max_concurrent
        self.resources = resources_per_trial or {}
        self.max_failures = max_failures
        self.time_budget_s = time_budget_s
        self.stop_criteria = stop or {}
        self.trials: List[Trial] = []
        self._failures: Dict[str, int] = {}
        self._searcher_done = False
        # Experiment-level durability (reference parity:
        # tune_controller.py:351 save_to_dir / :424 restore_from_dir —
        # searcher + scheduler + trial table persist so a killed driver
        # resumes the SWEEP, not just individual trials).
        self.experiment_path = experiment_path
        self.checkpoint_period_s = checkpoint_period_s
        self._last_experiment_save = 0.0

    # -- trial lifecycle ----------------------------------------------------

    def _make_actor_class(self):
        opts = {}
        if self.resources:
            resources = dict(self.resources)
            opts["num_cpus"] = resources.pop("cpu", resources.pop("CPU", 0.1))
            tpu = resources.pop("tpu", resources.pop("TPU", None))
            if tpu:
                opts["num_tpus"] = tpu
            if resources:
                opts["resources"] = resources
        else:
            opts["num_cpus"] = 0.1
        return ray_tpu.remote(**opts)(self.trainable_cls)

    def _request_trial(self) -> Optional[Trial]:
        if self._searcher_done:
            return None
        trial_id = new_trial_id()
        config = self.searcher.suggest(trial_id)
        if config is Searcher.FINISHED:
            self._searcher_done = True
            return None
        if config is None:     # transient: retry on a later step
            return None
        trial = Trial(trial_id, config)
        self.trials.append(trial)
        self.scheduler.on_trial_add(trial)
        return trial

    def _start_trial(self, trial: Trial) -> None:
        actor_cls = self._make_actor_class()
        trial.actor = actor_cls.remote(trial.config)
        if trial.restore_payload is not None:
            ray_tpu.get(trial.actor.restore.remote(trial.restore_payload))
            trial.restore_payload = None
        elif trial.checkpoint is not None:
            ray_tpu.get(trial.actor.restore.remote(trial.checkpoint))
        trial.status = RUNNING
        trial.inflight = trial.actor.train.remote()

    def _stop_trial(self, trial: Trial, status: str,
                    save_first: bool = False) -> None:
        if trial.actor is not None:
            try:
                if save_first:
                    trial.checkpoint = ray_tpu.get(trial.actor.save.remote())
                trial.actor.stop.remote()
                ray_tpu.kill(trial.actor)
            except RayTpuError:
                pass
            trial.actor = None
        trial.inflight = None
        trial.status = status

    # -- result handling ----------------------------------------------------

    def _complete_trial(self, trial: Trial, result: Optional[Dict[str, Any]],
                        save_first: bool = True) -> None:
        """Terminate + notify scheduler/searcher (the one place completion
        bookkeeping lives)."""
        self._stop_trial(trial, TERMINATED, save_first=save_first)
        self.scheduler.on_trial_complete(trial, result)
        self.searcher.on_trial_complete(trial.trial_id, result)

    def _handle_result(self, trial: Trial, result: Dict[str, Any]) -> None:
        trial.last_result = {**trial.last_result, **result}
        trial.results.append(result)
        trial.iteration = int(result.get("training_iteration",
                                         trial.iteration + 1))
        hit_stop = any(result.get(key, float("-inf")) >= threshold
                       for key, threshold in self.stop_criteria.items())
        if result.get(DONE) or hit_stop:
            self._complete_trial(trial, result)
            return
        trial.tune_trials = self.trials  # PBT reads the population
        decision = self.scheduler.on_trial_result(trial, result)
        self.searcher.on_trial_result(trial.trial_id, result)
        exploit = getattr(self.scheduler, "pending_exploits", {}) \
            .pop(trial.trial_id, None)
        if exploit is not None:
            self._exploit(trial, *exploit)
            return
        if decision == STOP:
            self._complete_trial(trial, result)
        elif decision == PAUSE:
            self._stop_trial(trial, PAUSED, save_first=True)
        else:
            trial.inflight = trial.actor.train.remote()

    def _exploit(self, trial: Trial, source_trial_id: str,
                 new_config: Dict[str, Any]) -> None:
        """PBT: clone a top trial's weights+config into this one."""
        source = next((t for t in self.trials
                       if t.trial_id == source_trial_id), None)
        if source is None:
            trial.inflight = trial.actor.train.remote()
            return
        if source.actor is not None:
            payload = ray_tpu.get(source.actor.save.remote())
        else:
            payload = source.checkpoint
        trial.config = new_config
        reset_ok = False
        if trial.actor is not None:
            try:
                reset_ok = ray_tpu.get(trial.actor.reset.remote(new_config))
            except RayTpuError:
                reset_ok = False
        if not reset_ok:
            self._stop_trial(trial, PENDING)
            trial.restore_payload = payload
            self._start_trial(trial)
            return
        if payload is not None:
            ray_tpu.get(trial.actor.restore.remote(payload))
        trial.status = RUNNING
        trial.inflight = trial.actor.train.remote()

    def _handle_error(self, trial: Trial, exc: Exception) -> None:
        count = self._failures.get(trial.trial_id, 0) + 1
        self._failures[trial.trial_id] = count
        if self.max_failures < 0 or count <= self.max_failures:
            logger.warning("trial %s failed (attempt %d), retrying: %s",
                           trial.trial_id, count, exc)
            self._stop_trial(trial, PENDING)
            return
        trial.error = repr(exc)
        self._stop_trial(trial, ERROR)
        self.scheduler.on_trial_complete(trial, None)
        self.searcher.on_trial_complete(trial.trial_id, error=True)

    # -- main loop ----------------------------------------------------------

    def _live(self) -> List[Trial]:
        return [t for t in self.trials if not t.is_finished]

    def step(self) -> bool:
        """One controller iteration; False when the experiment is over."""
        # Schedulers (sync HyperBand) may decide to stop trials that are
        # currently PAUSED at a rung barrier — apply before refilling.
        pop_stops = getattr(self.scheduler, "pop_trials_to_stop", None)
        if pop_stops is not None:
            for tid in pop_stops():
                trial = next((t for t in self._live()
                              if t.trial_id == tid), None)
                if trial is not None:
                    self._complete_trial(trial, trial.last_result or None,
                                         save_first=False)
        running = [t for t in self._live() if t.status == RUNNING]
        # Fill capacity: scheduler picks among PENDING/PAUSED, searcher
        # supplies fresh configs.
        while len(running) < self.max_concurrent:
            candidate = self.scheduler.choose_trial_to_run(self._live())
            if candidate is None:
                candidate = self._request_trial()
            if candidate is None:
                break
            self._start_trial(candidate)
            running.append(candidate)
        if not running:
            return bool(self._live()) and not self._searcher_done
        refs = [t.inflight for t in running if t.inflight is not None]
        ready, _ = ray_tpu.wait(refs, num_returns=1, timeout=30.0)
        for ref in ready:
            trial = next(t for t in running if t.inflight == ref)
            trial.inflight = None
            try:
                result = ray_tpu.get(ref)
            except RayTpuError as exc:
                self._handle_error(trial, exc)
                continue
            self._handle_result(trial, result)
        return True

    # -- experiment-level save/restore -------------------------------------

    def save_experiment(self, path: Optional[str] = None) -> str:
        """Snapshot searcher + scheduler + trial table to one file.
        In-flight trials are recorded as PAUSED at their last
        checkpoint (their running actors cannot persist); a restore
        re-launches them from that checkpoint."""
        import os

        import cloudpickle
        path = path or self.experiment_path
        assert path, "no experiment_path configured"
        snap = []
        for t in self.trials:
            status = t.status
            if status not in (TERMINATED, ERROR):
                status = PAUSED if t.checkpoint is not None else PENDING
            snap.append({
                "trial_id": t.trial_id, "config": t.config,
                "status": status, "last_result": t.last_result,
                "results": t.results, "checkpoint": t.checkpoint,
                "error": t.error, "iteration": t.iteration,
            })
        state = {"searcher": self.searcher, "scheduler": self.scheduler,
                 "trials": snap, "failures": dict(self._failures),
                 "searcher_done": self._searcher_done}
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            cloudpickle.dump(state, f)
        os.replace(tmp, path)
        self._last_experiment_save = time.time()
        return path

    def restore_experiment(self, path: Optional[str] = None) -> None:
        """Load a save_experiment snapshot: finished trials keep their
        results, interrupted ones re-enter the queue from their last
        checkpoint, and the searcher/scheduler continue exactly where
        the sweep stopped (TPE's observation history, HyperBand rungs,
        PBT populations all survive)."""
        import cloudpickle
        path = path or self.experiment_path
        with open(path, "rb") as f:
            state = cloudpickle.load(f)
        self.searcher = state["searcher"]
        self.scheduler = state["scheduler"]
        self._failures = dict(state["failures"])
        self._searcher_done = state["searcher_done"]
        self.trials = []
        for s in state["trials"]:
            t = Trial(s["trial_id"], s["config"])
            t.status = s["status"]
            t.last_result = s["last_result"]
            t.results = s["results"]
            t.checkpoint = s["checkpoint"]
            t.error = s["error"]
            t.iteration = s["iteration"]
            if t.status == PAUSED:
                t.restore_payload = t.checkpoint
            self.trials.append(t)
        from ..trial import advance_trial_counter_past
        advance_trial_counter_past(t2.trial_id for t2 in self.trials)

    def _maybe_save_experiment(self) -> None:
        if (self.experiment_path
                and time.time() - self._last_experiment_save
                >= self.checkpoint_period_s):
            try:
                self.save_experiment()
            except Exception:
                logger.exception("experiment checkpoint failed")

    def run(self) -> List[Trial]:
        start = time.time()
        while self.step():
            self._maybe_save_experiment()
            if self.time_budget_s and time.time() - start > self.time_budget_s:
                break
        for trial in self._live():
            self._stop_trial(trial, TERMINATED, save_first=True)
            self.searcher.on_trial_complete(trial.trial_id,
                                            trial.last_result or None)
        if self.experiment_path:
            try:
                self.save_experiment()
            except Exception:
                logger.exception("final experiment checkpoint failed")
        return self.trials
