"""Population Based Training.

Reference parity: python/ray/tune/schedulers/pbt.py (PopulationBasedTraining
:221 — at each perturbation_interval, bottom-quantile trials _exploit the
checkpoint+config of a top-quantile peer then _explore :54 by perturbing
hyperparams: continuous values x0.8/x1.2 or resample, categoricals shift
or resample).
"""

from __future__ import annotations

import random
from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np

from ..search.sample import Domain
from ..trial import RUNNING, Trial
from .trial_scheduler import CONTINUE, TrialScheduler


def explore(config: Dict[str, Any],
            mutations: Dict[str, Any],
            resample_probability: float,
            rng: random.Random) -> Dict[str, Any]:
    new_config = dict(config)
    for key, spec in mutations.items():
        if isinstance(spec, Domain):
            if rng.random() < resample_probability or key not in new_config:
                new_config[key] = spec.sample(np.random.default_rng(
                    rng.randrange(2**31)))
            elif isinstance(new_config[key], (int, float)):
                factor = 1.2 if rng.random() > 0.5 else 0.8
                new_config[key] = type(new_config[key])(
                    new_config[key] * factor)
        elif isinstance(spec, list):
            if rng.random() < resample_probability or \
                    new_config.get(key) not in spec:
                new_config[key] = rng.choice(spec)
            else:
                index = spec.index(new_config[key])
                shift = rng.choice([-1, 1])
                new_config[key] = spec[max(0, min(len(spec) - 1,
                                                  index + shift))]
        elif callable(spec):
            new_config[key] = spec()
    return new_config


class PopulationBasedTraining(TrialScheduler):
    def __init__(self, metric: Optional[str] = None, mode: str = "max",
                 perturbation_interval: int = 5,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 time_attr: str = "training_iteration",
                 seed: Optional[int] = None):
        super().__init__(metric, mode)
        self.perturbation_interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile_fraction = quantile_fraction
        self.resample_probability = resample_probability
        self.time_attr = time_attr
        self.rng = random.Random(seed)
        self._last_perturb: Dict[str, int] = {}
        self._scores: Dict[str, float] = {}
        # exploit requests consumed by the controller:
        # trial_id -> (source_trial_id, new_config)
        self.pending_exploits: Dict[str, Any] = {}
        self.num_perturbations = 0

    def _quantiles(self, trials: List[Trial]):
        scored = [t for t in trials
                  if t.trial_id in self._scores and not t.is_finished]
        if len(scored) < 2:
            return [], []
        scored.sort(key=lambda t: self._scores[t.trial_id])
        count = max(1, int(len(scored) * self.quantile_fraction))
        if count > len(scored) / 2:
            count = int(len(scored) / 2)
        return scored[:count], scored[-count:]   # bottom, top

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        score = self._score(result)
        if score is not None:
            self._scores[trial.trial_id] = score
        step = int(result.get(self.time_attr, 0))
        last = self._last_perturb.get(trial.trial_id, 0)
        if step - last < self.perturbation_interval:
            return CONTINUE
        self._last_perturb[trial.trial_id] = step
        bottom, top = self._quantiles(trial.tune_trials
                                      if hasattr(trial, "tune_trials") else [])
        if trial in bottom and top:
            source = self.rng.choice(top)
            new_config = self._explore_config(source.config, step)
            self.pending_exploits[trial.trial_id] = (source.trial_id,
                                                     new_config)
            self.num_perturbations += 1
        return CONTINUE

    def _explore_config(self, config: Dict[str, Any],
                        step: int) -> Dict[str, Any]:
        """Subclass hook: PBT perturbs randomly; PB2 fits a GP bandit."""
        return explore(config, self.mutations,
                       self.resample_probability, self.rng)
