"""PB2: Population Based Bandits (Parker-Holder et al. 2020).

Reference parity: python/ray/tune/schedulers/pb2.py (which wraps GPy).
Nothing external is vendored: the time-varying GP (RBF kernel over
[t, hyperparams], Cholesky solve) and the UCB acquisition are
implemented directly on numpy. PBT picks new hyperparams by random
perturbation; PB2 instead fits a GP to the population's observed
(time, config) -> reward-improvement data and selects the UCB argmax
over the bounded search box — provably efficient for small
populations, where random perturbation wastes trials.

Only bounded continuous hyperparams (`hyperparam_bounds`) ride the GP;
anything in `hyperparam_mutations` keeps PBT-style exploration.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .pbt import PopulationBasedTraining, explore


def _rbf(a: np.ndarray, b: np.ndarray, ls: float) -> np.ndarray:
    d = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
    return np.exp(-0.5 * d / (ls * ls))


class _GP:
    """Minimal GP regressor: RBF kernel, fixed noise, Cholesky solve."""

    def __init__(self, lengthscale: float = 0.3, noise: float = 1e-2):
        self.ls = lengthscale
        self.noise = noise
        self.x: Optional[np.ndarray] = None
        self.alpha: Optional[np.ndarray] = None
        self.chol: Optional[np.ndarray] = None

    def fit(self, x: np.ndarray, y: np.ndarray) -> None:
        k = _rbf(x, x, self.ls) + self.noise * np.eye(len(x))
        self.chol = np.linalg.cholesky(k)
        self.alpha = np.linalg.solve(
            self.chol.T, np.linalg.solve(self.chol, y))
        self.x = x

    def predict(self, q: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        ks = _rbf(q, self.x, self.ls)
        mu = ks @ self.alpha
        v = np.linalg.solve(self.chol, ks.T)
        var = np.clip(1.0 - (v * v).sum(0), 1e-9, None)
        return mu, np.sqrt(var)


class PB2(PopulationBasedTraining):
    def __init__(self, metric: Optional[str] = None, mode: str = "max",
                 perturbation_interval: int = 5,
                 hyperparam_bounds: Optional[
                     Dict[str, Tuple[float, float]]] = None,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 time_attr: str = "training_iteration",
                 ucb_kappa: float = 2.0,
                 n_candidates: int = 256,
                 seed: Optional[int] = None):
        super().__init__(metric, mode,
                         perturbation_interval=perturbation_interval,
                         hyperparam_mutations=hyperparam_mutations,
                         quantile_fraction=quantile_fraction,
                         time_attr=time_attr, seed=seed)
        if not hyperparam_bounds:
            raise ValueError("PB2 needs hyperparam_bounds={name: (lo, hi)}")
        self.bounds = {k: (float(lo), float(hi))
                       for k, (lo, hi) in hyperparam_bounds.items()}
        self.kappa = ucb_kappa
        self.n_candidates = n_candidates
        self._np_rng = np.random.default_rng(seed)
        # observation log: (t, config values at t, score at t) per trial
        self._obs: Dict[str, List[Tuple[float, Dict[str, float], float]]] = {}

    # ------------------------------------------------------------- observe

    def on_trial_result(self, trial, result):
        score = self._score(result)
        if score is not None:
            t = float(result.get(self.time_attr, 0))
            vals = {k: float(trial.config.get(k, 0.0))
                    for k in self.bounds}
            rows = self._obs.setdefault(trial.trial_id, [])
            rows.append((t, vals, score))
            if len(rows) > 64:        # only consecutive deltas are used
                del rows[:-64]
        return super().on_trial_result(trial, result)

    # ------------------------------------------------------------- explore

    def _training_data(self):
        """(X=[t, *hyperparams] normalized, y=reward deltas normalized)."""
        keys = sorted(self.bounds)
        xs, ys = [], []
        for rows in self._obs.values():
            for (t0, v0, s0), (t1, v1, s1) in zip(rows, rows[1:]):
                xs.append([t1] + [v1[k] for k in keys])
                ys.append(s1 - s0)
        if not xs:
            return keys, None, None
        x = np.asarray(xs, np.float64)
        y = np.asarray(ys, np.float64)
        # normalize: t and each hyperparam into [0,1]; y standardized
        lo = np.array([x[:, 0].min()] + [self.bounds[k][0] for k in keys])
        hi = np.array([x[:, 0].max()] + [self.bounds[k][1] for k in keys])
        span = np.where(hi > lo, hi - lo, 1.0)
        x = (x - lo) / span
        y = (y - y.mean()) / (y.std() + 1e-9)
        return keys, x, y

    def _explore_config(self, config: Dict[str, Any],
                        step: int) -> Dict[str, Any]:
        # non-GP params keep PBT exploration
        new_config = explore(config, self.mutations,
                             self.resample_probability, self.rng)
        keys, x, y = self._training_data()
        if x is None or len(x) < 4:
            # cold start: uniform sample in bounds
            for k in keys:
                lo, hi = self.bounds[k]
                new_config[k] = float(self._np_rng.uniform(lo, hi))
            return new_config
        gp = _GP()
        # cap the dataset so the Cholesky stays cheap
        if len(x) > 512:
            sel = self._np_rng.choice(len(x), 512, replace=False)
            x, y = x[sel], y[sel]
        gp.fit(x, y)
        # candidates at the CURRENT (latest) normalized time, sweeping
        # the hyperparam box: pick the UCB argmax
        q = self._np_rng.uniform(
            size=(self.n_candidates, 1 + len(keys)))
        q[:, 0] = x[:, 0].max()           # "what helps going forward"
        mu, sd = gp.predict(q)
        best = q[int(np.argmax(mu + self.kappa * sd))]
        for i, k in enumerate(keys):
            lo, hi = self.bounds[k]
            new_config[k] = float(lo + best[1 + i] * (hi - lo))
        return new_config
