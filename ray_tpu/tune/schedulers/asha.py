"""ASHA: asynchronous successive halving.

Reference parity: python/ray/tune/schedulers/async_hyperband.py
(AsyncHyperBandScheduler with brackets of rungs; a trial reaching a rung
milestone continues only if it is in the top 1/reduction_factor of
recorded scores at that rung).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ..trial import Trial
from .trial_scheduler import CONTINUE, STOP, TrialScheduler


class _Bracket:
    def __init__(self, min_t: int, max_t: int, reduction_factor: float,
                 stop_last_trials: bool):
        self.rf = reduction_factor
        self.stop_last_trials = stop_last_trials
        self.rungs: List[Dict[str, Any]] = []
        milestone = min_t
        while milestone < max_t:
            self.rungs.append({"milestone": milestone, "recorded": {}})
            milestone = int(milestone * reduction_factor)
        self.rungs.reverse()  # highest milestone first, like the reference

    def on_result(self, trial_id: str, cur_iter: int,
                  score: Optional[float]) -> str:
        decision = CONTINUE
        for rung in self.rungs:
            milestone, recorded = rung["milestone"], rung["recorded"]
            if cur_iter < milestone or trial_id in recorded:
                continue
            if score is not None:
                values = list(recorded.values())
                if values:
                    values.sort()
                    cutoff = values[
                        max(0, len(values) - max(1, int(len(values) / self.rf)))]
                    if score < cutoff:
                        decision = STOP
                recorded[trial_id] = score
            break
        return decision


class AsyncHyperBandScheduler(TrialScheduler):
    def __init__(self, metric: Optional[str] = None, mode: str = "max",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: float = 4, brackets: int = 1,
                 time_attr: str = "training_iteration",
                 stop_last_trials: bool = True):
        super().__init__(metric, mode)
        self.time_attr = time_attr
        self.max_t = max_t
        self._brackets = [
            _Bracket(grace_period * int(reduction_factor ** s), max_t,
                     reduction_factor, stop_last_trials)
            for s in range(brackets)
        ]
        self._trial_bracket: Dict[str, _Bracket] = {}
        self._rr = 0

    def on_trial_add(self, trial: Trial) -> None:
        self._trial_bracket[trial.trial_id] = \
            self._brackets[self._rr % len(self._brackets)]
        self._rr += 1

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        cur_iter = int(result.get(self.time_attr, 0))
        if cur_iter >= self.max_t:
            return STOP
        bracket = self._trial_bracket.get(trial.trial_id)
        if bracket is None:
            return CONTINUE
        return bracket.on_result(trial.trial_id, cur_iter,
                                 self._score(result))


ASHAScheduler = AsyncHyperBandScheduler
