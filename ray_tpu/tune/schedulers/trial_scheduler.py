"""TrialScheduler interface + FIFO and MedianStopping.

Reference parity: python/ray/tune/schedulers/trial_scheduler.py (decision
enum CONTINUE/PAUSE/STOP) and median_stopping_rule.py.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ..trial import Trial

CONTINUE = "CONTINUE"
PAUSE = "PAUSE"
STOP = "STOP"


class TrialScheduler:
    def __init__(self, metric: Optional[str] = None, mode: str = "max"):
        self.metric, self.mode = metric, mode

    def set_search_properties(self, metric: Optional[str],
                              mode: Optional[str]) -> None:
        if metric:
            self.metric = metric
        if mode:
            self.mode = mode

    def _score(self, result: Dict[str, Any]) -> Optional[float]:
        value = result.get(self.metric) if self.metric else None
        if value is None:
            return None
        return float(value) if self.mode == "max" else -float(value)

    def on_trial_add(self, trial: Trial) -> None:
        pass

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        return CONTINUE

    def on_trial_complete(self, trial: Trial,
                          result: Optional[Dict[str, Any]]) -> None:
        pass

    def choose_trial_to_run(self, trials: List[Trial]) -> Optional[Trial]:
        """Pick the next PENDING/PAUSED trial to (re)start; FIFO default."""
        from ..trial import PAUSED, PENDING
        for trial in trials:
            if trial.status == PENDING:
                return trial
        for trial in trials:
            if trial.status == PAUSED:
                return trial
        return None


class FIFOScheduler(TrialScheduler):
    """Run every trial to completion in submission order."""


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose running-average metric falls below the median of
    completed averages at the same step (reference:
    schedulers/median_stopping_rule.py)."""

    def __init__(self, metric: Optional[str] = None, mode: str = "max",
                 grace_period: int = 1, min_samples_required: int = 3):
        super().__init__(metric, mode)
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        self._history: Dict[str, List[float]] = {}

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        score = self._score(result)
        if score is None:
            return CONTINUE
        history = self._history.setdefault(trial.trial_id, [])
        history.append(score)
        step = len(history)
        if step <= self.grace_period:
            return CONTINUE
        peers = [sum(h[:step]) / step
                 for tid, h in self._history.items()
                 if tid != trial.trial_id and len(h) >= step]
        if len(peers) < self.min_samples:
            return CONTINUE
        peers.sort()
        median = peers[len(peers) // 2]
        mine = sum(history) / step
        return STOP if mine < median else CONTINUE
