"""Synchronous HyperBand.

Reference parity: python/ray/tune/schedulers/hyperband.py — trials are
grouped into brackets; at each rung boundary the bracket PAUSES until all
members report, then the bottom (1 - 1/eta) fraction is stopped and the
rest resume with a larger budget.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional

from ..trial import PAUSED, Trial
from .trial_scheduler import CONTINUE, PAUSE, STOP, TrialScheduler


class _SyncBracket:
    def __init__(self, milestones: List[int], eta: float):
        self.milestones = milestones   # increasing rung budgets
        self.eta = eta
        self.members: List[str] = []
        self.rung_of: Dict[str, int] = {}
        self.waiting: Dict[str, float] = {}   # trial_id -> score at rung
        self.stopped: set = set()
        self.newly_stopped: set = set()       # drained by the scheduler

    def add(self, trial_id: str) -> None:
        self.members.append(trial_id)
        self.rung_of[trial_id] = 0

    def live_members(self) -> List[str]:
        return [t for t in self.members if t not in self.stopped]

    def on_result(self, trial_id: str, cur_iter: int,
                  score: Optional[float]) -> str:
        rung = self.rung_of[trial_id]
        if rung >= len(self.milestones):
            return STOP
        if cur_iter < self.milestones[rung]:
            return CONTINUE
        self.waiting[trial_id] = -math.inf if score is None else score
        if set(self.waiting) >= set(self.live_members()):
            self._promote()
            return CONTINUE if trial_id not in self.stopped else STOP
        return PAUSE

    def _promote(self) -> None:
        ranked = sorted(self.waiting.items(), key=lambda kv: kv[1],
                        reverse=True)
        keep = max(1, int(len(ranked) / self.eta))
        for i, (tid, _) in enumerate(ranked):
            if i < keep:
                self.rung_of[tid] += 1
            else:
                self.stopped.add(tid)
                self.newly_stopped.add(tid)
        self.waiting.clear()


class HyperBandScheduler(TrialScheduler):
    def __init__(self, metric: Optional[str] = None, mode: str = "max",
                 max_t: int = 81, reduction_factor: float = 3,
                 time_attr: str = "training_iteration"):
        super().__init__(metric, mode)
        self.time_attr = time_attr
        self.max_t = max_t
        self.eta = reduction_factor
        s_max = int(math.log(max_t) / math.log(reduction_factor))
        # Bracket spec pairs (capacity, halving exponent s): the bracket
        # with the MOST trials starts at the SMALLEST budget (most rungs,
        # aggressive halving) and vice versa.
        self._bracket_specs = [
            (max(1, int(math.ceil((s_max + 1) / (s + 1)
                                  * reduction_factor ** s))), s)
            for s in reversed(range(s_max + 1))]
        self._brackets: List[_SyncBracket] = []
        self._next_bracket = 0
        self._trial_bracket: Dict[str, _SyncBracket] = {}

    def _open_bracket(self) -> _SyncBracket:
        capacity, s = self._bracket_specs[
            self._next_bracket % len(self._bracket_specs)]
        rungs = []
        budget = max(1, int(self.max_t / self.eta ** s))
        while budget <= self.max_t:
            rungs.append(budget)
            budget = int(budget * self.eta)
        bracket = _SyncBracket(rungs or [self.max_t], self.eta)
        bracket.capacity = capacity
        self._brackets.append(bracket)
        self._next_bracket += 1
        return bracket

    def on_trial_add(self, trial: Trial) -> None:
        for bracket in self._brackets:
            if len(bracket.members) < bracket.capacity:
                bracket.add(trial.trial_id)
                self._trial_bracket[trial.trial_id] = bracket
                return
        bracket = self._open_bracket()
        bracket.add(trial.trial_id)
        self._trial_bracket[trial.trial_id] = bracket

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        if int(result.get(self.time_attr, 0)) >= self.max_t:
            return STOP
        bracket = self._trial_bracket.get(trial.trial_id)
        if bracket is None:
            return CONTINUE
        return bracket.on_result(trial.trial_id,
                                 int(result.get(self.time_attr, 0)),
                                 self._score(result))

    def on_trial_complete(self, trial: Trial,
                          result: Optional[Dict[str, Any]]) -> None:
        bracket = self._trial_bracket.get(trial.trial_id)
        if bracket is None:
            return
        bracket.stopped.add(trial.trial_id)
        bracket.newly_stopped.discard(trial.trial_id)
        bracket.waiting.pop(trial.trial_id, None)
        if bracket.waiting and set(bracket.waiting) >= set(bracket.live_members()):
            bracket._promote()

    def pop_trials_to_stop(self) -> List[str]:
        out: List[str] = []
        for bracket in self._brackets:
            out.extend(bracket.newly_stopped)
            bracket.newly_stopped.clear()
        return out

    def choose_trial_to_run(self, trials: List[Trial]) -> Optional[Trial]:
        """Hold the synchronous rung barrier: a PAUSED trial is resumable
        only once its bracket promoted it (it is no longer parked in
        `waiting` and was not halved away)."""
        from ..trial import PENDING
        for trial in trials:
            if trial.status == PENDING:
                return trial
        for trial in trials:
            if trial.status != PAUSED:
                continue
            bracket = self._trial_bracket.get(trial.trial_id)
            if bracket is None:
                return trial
            if trial.trial_id in bracket.waiting or \
                    trial.trial_id in bracket.stopped:
                continue
            return trial
        return None
