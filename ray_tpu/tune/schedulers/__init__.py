from .trial_scheduler import (TrialScheduler, FIFOScheduler,
                              MedianStoppingRule, CONTINUE, PAUSE, STOP)
from .asha import AsyncHyperBandScheduler, ASHAScheduler
from .hyperband import HyperBandScheduler
from .pbt import PopulationBasedTraining

__all__ = [
    "TrialScheduler", "FIFOScheduler", "MedianStoppingRule",
    "AsyncHyperBandScheduler", "ASHAScheduler", "HyperBandScheduler",
    "PopulationBasedTraining", "CONTINUE", "PAUSE", "STOP",
]
