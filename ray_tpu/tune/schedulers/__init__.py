from .trial_scheduler import (TrialScheduler, FIFOScheduler,
                              MedianStoppingRule, CONTINUE, PAUSE, STOP)
from .asha import AsyncHyperBandScheduler, ASHAScheduler
from .hyperband import HyperBandScheduler
from .pbt import PopulationBasedTraining
from .pb2 import PB2

__all__ = [
    "TrialScheduler", "FIFOScheduler", "MedianStoppingRule",
    "AsyncHyperBandScheduler", "ASHAScheduler", "HyperBandScheduler",
    "PopulationBasedTraining", "PB2", "CONTINUE", "PAUSE", "STOP",
]
