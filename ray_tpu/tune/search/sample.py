"""Search-space sampling primitives.

Reference parity: python/ray/tune/search/sample.py (Domain/Float/Integer/
Categorical with uniform/loguniform/quantized samplers) — rebuilt on
numpy.random.Generator; every domain is picklable so search spaces travel
to trial actors.
"""

from __future__ import annotations

import math
from typing import Any, Callable, List, Optional, Sequence

import numpy as np


class Domain:
    """A sampleable dimension of a search space."""

    def sample(self, rng: np.random.Generator) -> Any:
        raise NotImplementedError

    # Grid support: domains that can enumerate values override this.
    def grid_values(self) -> Optional[List[Any]]:
        return None


class Float(Domain):
    def __init__(self, lower: float, upper: float, log: bool = False,
                 q: Optional[float] = None):
        if log and lower <= 0:
            raise ValueError("loguniform requires lower > 0")
        if upper <= lower:
            raise ValueError(f"empty range [{lower}, {upper})")
        self.lower, self.upper, self.log, self.q = lower, upper, log, q

    def sample(self, rng: np.random.Generator) -> float:
        if self.log:
            value = math.exp(rng.uniform(math.log(self.lower),
                                         math.log(self.upper)))
        else:
            value = rng.uniform(self.lower, self.upper)
        if self.q is not None:
            value = round(round(value / self.q) * self.q, 10)
        return float(value)

    def __repr__(self):
        kind = "loguniform" if self.log else "uniform"
        return f"{kind}({self.lower}, {self.upper})"


class Integer(Domain):
    def __init__(self, lower: int, upper: int, log: bool = False,
                 q: Optional[int] = None):
        if upper <= lower:
            raise ValueError(f"empty range [{lower}, {upper})")
        self.lower, self.upper, self.log, self.q = lower, upper, log, q

    def sample(self, rng: np.random.Generator) -> int:
        if self.log:
            value = int(math.exp(rng.uniform(math.log(self.lower),
                                             math.log(self.upper))))
        else:
            value = int(rng.integers(self.lower, self.upper))
        if self.q is not None:
            value = int(round(value / self.q) * self.q)
            hi = (self.upper // self.q) * self.q
            return int(min(max(value, self.lower), hi))
        return int(min(max(value, self.lower), self.upper - 1))

    def __repr__(self):
        return f"randint({self.lower}, {self.upper})"


class Categorical(Domain):
    def __init__(self, categories: Sequence[Any]):
        if not categories:
            raise ValueError("empty choice()")
        self.categories = list(categories)

    def sample(self, rng: np.random.Generator) -> Any:
        return self.categories[int(rng.integers(len(self.categories)))]

    def grid_values(self) -> List[Any]:
        return list(self.categories)

    def __repr__(self):
        return f"choice({self.categories})"


class Function(Domain):
    """sample_from: arbitrary callable, optionally taking the resolved spec."""

    def __init__(self, fn: Callable):
        self.fn = fn

    def sample(self, rng: np.random.Generator, spec: Any = None) -> Any:
        try:
            return self.fn(spec)
        except TypeError:
            return self.fn()

    def __repr__(self):
        return f"sample_from({self.fn})"


class GridSearch:
    """Marker for exhaustive enumeration of the given values."""

    def __init__(self, values: Sequence[Any]):
        if not values:
            raise ValueError("empty grid_search()")
        self.values = list(values)

    def __repr__(self):
        return f"grid_search({self.values})"


# -- public constructors (mirror ray.tune's names) --------------------------

def uniform(lower: float, upper: float) -> Float:
    return Float(lower, upper)


def quniform(lower: float, upper: float, q: float) -> Float:
    return Float(lower, upper, q=q)


def loguniform(lower: float, upper: float) -> Float:
    return Float(lower, upper, log=True)


def qloguniform(lower: float, upper: float, q: float) -> Float:
    return Float(lower, upper, log=True, q=q)


def randint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper)


def qrandint(lower: int, upper: int, q: int) -> Integer:
    return Integer(lower, upper, q=q)


def lograndint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper, log=True)


def choice(categories: Sequence[Any]) -> Categorical:
    return Categorical(categories)


def sample_from(fn: Callable) -> Function:
    return Function(fn)


def grid_search(values: Sequence[Any]) -> GridSearch:
    return GridSearch(values)
