from .sample import (uniform, quniform, loguniform, qloguniform, randint,
                     qrandint, lograndint, choice, sample_from, grid_search,
                     Domain, GridSearch)
from .searcher import (Searcher, BasicVariantGenerator, RandomSearch,
                       ConcurrencyLimiter)
from .variant_generator import generate_variants, count_grid_variants

__all__ = [
    "uniform", "quniform", "loguniform", "qloguniform", "randint",
    "qrandint", "lograndint", "choice", "sample_from", "grid_search",
    "Domain", "GridSearch", "Searcher", "BasicVariantGenerator",
    "RandomSearch", "ConcurrencyLimiter", "generate_variants",
    "count_grid_variants",
]
