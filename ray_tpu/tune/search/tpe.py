"""TPE: a native model-based searcher.

Reference parity: the role of tune/search/optuna/optuna_search.py and
hyperopt/hyperopt_search.py — both wrap external TPE implementations;
this environment vendors none, so the Tree-structured Parzen Estimator
is implemented directly (Bergstra et al. 2011) over the tune sample
Domains: observed trials are split into good/bad by metric quantile,
candidates are drawn from a KDE over the good set and ranked by the
density ratio l(x)/g(x).

Supports Float (linear + log), Integer, and Categorical dimensions;
grid_search keys are rejected (use BasicVariantGenerator for grids).
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .sample import Categorical, Domain, Float, GridSearch, Integer
from .searcher import Searcher


class TPESearch(Searcher):
    def __init__(self, space: Dict[str, Any], metric: str, mode: str = "max",
                 num_samples: int = 64, n_startup_trials: int = 10,
                 n_candidates: int = 24, gamma: float = 0.25,
                 seed: Optional[int] = None):
        super().__init__(metric, mode)
        self.space: Dict[str, Any] = {}
        self.fixed: Dict[str, Any] = {}
        for k, v in space.items():
            if isinstance(v, GridSearch):
                raise ValueError(
                    "TPESearch does not take grid_search dimensions; "
                    "use BasicVariantGenerator for grids")
            if isinstance(v, Domain):
                self.space[k] = v
            else:
                self.fixed[k] = v
        self.num_samples = num_samples
        self.n_startup = n_startup_trials
        self.n_candidates = n_candidates
        self.gamma = gamma
        self.rng = np.random.default_rng(seed)
        self.total = num_samples
        self._suggested = 0
        self._trials: Dict[str, Dict[str, Any]] = {}   # id -> config
        self._latest: Dict[str, Dict[str, Any]] = {}   # id -> last result
        self._scores: List[Tuple[Dict[str, Any], float]] = []

    # ------------------------------------------------------------ observe

    def on_trial_result(self, trial_id: str,
                        result: Dict[str, Any]) -> None:
        # intermediate reports carry the metric; completion may not (a
        # function trainable's terminal result can be empty)
        if result and self.metric in result:
            self._latest[trial_id] = result

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False) -> None:
        config = self._trials.pop(trial_id, None)
        if not (result and self.metric in result):
            result = self._latest.pop(trial_id, None)
        else:
            self._latest.pop(trial_id, None)
        if config is None or error or not result \
                or self.metric not in result:
            return
        score = float(result[self.metric])
        if self.mode == "min":
            score = -score
        self._scores.append((config, score))

    # ------------------------------------------------------------ suggest

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._suggested >= self.num_samples:
            return Searcher.FINISHED
        self._suggested += 1
        if len(self._scores) < self.n_startup:
            config = {k: d.sample(self.rng) for k, d in self.space.items()}
        else:
            config = {k: self._suggest_dim(k, d)
                      for k, d in self.space.items()}
        config.update(self.fixed)
        self._trials[trial_id] = config
        return dict(config)

    def _split(self) -> Tuple[list, list]:
        ranked = sorted(self._scores, key=lambda cs: -cs[1])
        # good set grows as gamma*sqrt(n), capped (hyperopt's rule, not
        # gamma*n): the model trusts only the FEW best points, whose
        # adaptive bandwidths then span their isolation — a linear-
        # fraction good set dilutes l(x) with mediocre points and
        # measurably loses to random search on Branin/quadratics
        n_good = max(1, min(
            int(math.ceil(self.gamma * math.sqrt(len(ranked)))), 25))
        return ranked[:n_good], ranked[n_good:]

    def _suggest_dim(self, key: str, dom: Domain) -> Any:
        good, bad = self._split()
        gvals = [c[key] for c, _ in good]
        bvals = [c[key] for c, _ in bad] or gvals
        if isinstance(dom, Categorical):
            return self._categorical(dom, gvals, bvals)
        if isinstance(dom, (Float, Integer)):
            return self._numeric(dom, gvals, bvals)
        return dom.sample(self.rng)

    def _categorical(self, dom: Categorical, gvals, bvals) -> Any:
        cats = list(dom.categories)
        prior = 1.0

        def weights(vals):
            w = np.array([prior + sum(v == c for v in vals)
                          for c in cats], float)
            return w / w.sum()

        wl, wg = weights(gvals), weights(bvals)
        # sample candidates from l, keep the best l/g ratio
        idx = self.rng.choice(len(cats), size=self.n_candidates, p=wl)
        best = max(idx, key=lambda i: wl[i] / wg[i])
        return cats[best]

    def _numeric(self, dom, gvals, bvals) -> Any:
        log = bool(getattr(dom, "log", False))
        lo, hi = float(dom.lower), float(dom.upper)
        if log:
            lo, hi = math.log(lo), math.log(hi)
            to_x = math.log
            from_x = math.exp
        else:
            to_x = from_x = float
        g = np.array([to_x(float(v)) for v in gvals])
        b = np.array([to_x(float(v)) for v in bvals])
        span = hi - lo

        def adaptive_bw(data):
            # Bergstra's adaptive Parzen: each point's bandwidth is its
            # max gap to the adjacent SORTED neighbors (domain bounds at
            # the edges). Isolated points get wide kernels — built-in
            # exploration around lone good points; clustered points get
            # narrow ones — refinement where evidence concentrates. A
            # single global Scott bandwidth (the old code) collapses as
            # the cluster tightens and the search drills whatever
            # mediocre region the startup found, measurably WORSE than
            # random on Branin.
            order = np.argsort(data)
            srt = data[order]
            left = np.diff(srt, prepend=lo)
            right = np.diff(srt, append=hi)
            bw_sorted = np.maximum(np.maximum(left, right), span * 1e-2)
            bw = np.empty_like(bw_sorted)
            bw[order] = np.minimum(bw_sorted, span)
            return bw

        def kde_logpdf(xs, data, bw):
            # mixture of per-point gaussians + the uniform prior as one
            # extra component (each weighted 1/(n+1))
            d = (xs[:, None] - data[None, :]) / bw[None, :]
            comp = (-0.5 * d * d
                    - np.log(bw * math.sqrt(2 * math.pi))[None, :])
            log_prior = -math.log(span)
            m = np.maximum(comp.max(axis=1), log_prior)
            kde = np.exp(comp - m[:, None]).sum(axis=1)
            prior = np.exp(log_prior - m)
            return m + np.log((kde + prior) / (len(data) + 1))

        g_bw = adaptive_bw(g)
        b_bw = adaptive_bw(b)
        # candidates drawn FROM the good mixture: prior share uniform,
        # the rest perturbed good points with their own bandwidths (the
        # incumbent g[0] always a center — good set is rank-sorted)
        idx = self.rng.integers(0, len(g), size=self.n_candidates)
        idx[0] = 0
        cand = np.clip(g[idx] + self.rng.normal(0, 1, len(idx)) * g_bw[idx],
                       lo, hi)
        n_prior = max(1, self.n_candidates // (len(g) + 1))
        cand[-n_prior:] = self.rng.uniform(lo, hi, n_prior)
        score = kde_logpdf(cand, g, g_bw) - kde_logpdf(cand, b, b_bw)
        x = from_x(float(cand[int(np.argmax(score))]))
        if isinstance(dom, Integer):
            return int(np.clip(round(x), dom.lower, dom.upper))
        return float(np.clip(x, dom.lower, dom.upper))
