"""BOHB: Bayesian Optimization + HyperBand (Falkner et al. 2018).

Reference parity: python/ray/tune/search/bohb/bohb_search.py (TuneBOHB,
which wraps the external hpbandster lib) paired with HyperBandForBOHB.
Nothing external is vendored here: the model is the native TPE
implementation (tpe.py), kept PER BUDGET — results observed at deeper
training_iteration milestones build separate, more-trustworthy models,
and suggestions come from the deepest budget that has enough
observations (BOHB's core rule). Pair with HyperBandScheduler, whose
successive-halving milestones produce exactly the budget strata this
searcher feeds on.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from .tpe import TPESearch


class BOHBSearch(TPESearch):
    def __init__(self, space: Dict[str, Any], metric: str, mode: str = "max",
                 num_samples: int = 64, n_startup_trials: int = 8,
                 n_candidates: int = 24, gamma: float = 0.25,
                 budget_key: str = "training_iteration",
                 min_points_in_model: Optional[int] = None,
                 seed: Optional[int] = None):
        super().__init__(space, metric, mode, num_samples=num_samples,
                         n_startup_trials=n_startup_trials,
                         n_candidates=n_candidates, gamma=gamma, seed=seed)
        self.budget_key = budget_key
        # BOHB default: dim+1 points before a budget's model is usable
        self.min_points = (min_points_in_model
                           if min_points_in_model is not None
                           else len(self.space) + 1)
        # budget level -> [(config, score)] observed AT that budget
        self._budget_scores: Dict[int, List[Tuple[dict, float]]] = {}
        self._trial_budget: Dict[str, int] = {}

    # ------------------------------------------------------------ observe

    def on_trial_result(self, trial_id: str,
                        result: Dict[str, Any]) -> None:
        super().on_trial_result(trial_id, result)
        if not result or self.metric not in result:
            return
        budget = int(result.get(self.budget_key, 0) or 0)
        self._trial_budget[trial_id] = max(
            budget, self._trial_budget.get(trial_id, 0))
        config = self._trials.get(trial_id)
        if config is None:
            return
        score = float(result[self.metric])
        if self.mode == "min":
            score = -score
        # keep only the LATEST observation of this trial at this budget
        pool = self._budget_scores.setdefault(budget, [])
        pool[:] = [(c, s) for c, s in pool if c is not config]
        pool.append((config, score))

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False) -> None:
        self._trial_budget.pop(trial_id, None)
        super().on_trial_complete(trial_id, result, error)

    # ------------------------------------------------------------ model

    def _split(self):
        """Rank/split from the deepest budget with enough points —
        observations that survived more halvings are worth more. Falls
        back to the global pool (TPE behavior) before any budget
        matures."""
        for budget in sorted(self._budget_scores, reverse=True):
            pool = self._budget_scores[budget]
            if len(pool) >= self.min_points:
                saved, self._scores = self._scores, pool
                try:
                    return super()._split()
                finally:
                    self._scores = saved
        return super()._split()
