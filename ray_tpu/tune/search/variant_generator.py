"""Resolve search-space dicts into concrete trial configs.

Reference parity: python/ray/tune/search/variant_generator.py
(generate_variants — cartesian product of grid_search values crossed with
sampled Domains, nested-dict aware).
"""

from __future__ import annotations

import copy
import itertools
from typing import Any, Dict, Iterator, List, Tuple

import numpy as np

from .sample import Domain, Function, GridSearch

Path = Tuple[str, ...]


def _walk(spec: Dict[str, Any], prefix: Path = ()) -> Iterator[Tuple[Path, Any]]:
    for key, value in spec.items():
        path = prefix + (key,)
        if isinstance(value, dict):
            yield from _walk(value, path)
        else:
            yield path, value


def _set_path(spec: Dict[str, Any], path: Path, value: Any) -> None:
    node = spec
    for key in path[:-1]:
        node = node[key]
    node[path[-1]] = value


def count_grid_variants(spec: Dict[str, Any]) -> int:
    total = 1
    for _, value in _walk(spec):
        if isinstance(value, GridSearch):
            total *= len(value.values)
    return total


def generate_variants(spec: Dict[str, Any],
                      rng: np.random.Generator) -> Iterator[Dict[str, Any]]:
    """Yield one resolved config per grid-product element; Domain values are
    re-sampled per variant. Called repeatedly for num_samples > 1."""
    grid_paths: List[Path] = []
    grid_values: List[List[Any]] = []
    for path, value in _walk(spec):
        if isinstance(value, GridSearch):
            grid_paths.append(path)
            grid_values.append(value.values)

    for combo in itertools.product(*grid_values) if grid_paths else [()]:
        resolved = copy.deepcopy(spec)
        for path, value in zip(grid_paths, combo):
            _set_path(resolved, path, value)
        # Two passes so sample_from(spec) can read already-resolved values.
        deferred: List[Tuple[Path, Function]] = []
        for path, value in _walk(resolved):
            if isinstance(value, Function):
                deferred.append((path, value))
            elif isinstance(value, Domain):
                _set_path(resolved, path, value.sample(rng))
        for path, fn_domain in deferred:
            _set_path(resolved, path, fn_domain.sample(rng, _Spec(resolved)))
        yield resolved


class _Spec:
    """Attribute view over the resolved config for sample_from callables
    (mirrors the reference's spec.config access pattern)."""

    def __init__(self, config: Dict[str, Any]):
        self.config = _AttrDict(config)


class _AttrDict(dict):
    def __init__(self, data: Dict[str, Any]):
        super().__init__(data)

    def __getattr__(self, name: str) -> Any:
        try:
            value = self[name]
        except KeyError as exc:
            raise AttributeError(name) from exc
        return _AttrDict(value) if isinstance(value, dict) else value
