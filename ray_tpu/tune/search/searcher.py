"""Searcher interface + built-in implementations.

Reference parity: python/ray/tune/search/searcher.py (Searcher ABC),
search/basic_variant.py (BasicVariantGenerator — grid x random), and
search/concurrency_limiter.py. External-library adapters (Optuna/HyperOpt/
...) are out of scope: the Searcher ABC is the plugin point they'd use.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from .variant_generator import count_grid_variants, generate_variants


class Searcher:
    """Suggest configs; observe results. Subclass to plug in external
    optimizers (the reference's OptunaSearch etc. implement this shape)."""

    #: Sentinel return of suggest(): the search space is exhausted.
    #: Plain None means "no suggestion available right now, retry later"
    #: (e.g. under a ConcurrencyLimiter or an async optimizer backend).
    FINISHED = "FINISHED"

    def __init__(self, metric: Optional[str] = None, mode: str = "max"):
        self.metric, self.mode = metric, mode

    def set_search_properties(self, metric: Optional[str], mode: Optional[str],
                              config: Dict[str, Any]) -> bool:
        if metric:
            self.metric = metric
        if mode:
            self.mode = mode
        return True

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        """Next config; Searcher.FINISHED when exhausted; None to retry."""
        raise NotImplementedError

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]) -> None:
        pass

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False) -> None:
        pass


class BasicVariantGenerator(Searcher):
    """Grid search crossed with random sampling: each of `num_samples`
    repetitions emits the full grid product with Domains re-sampled."""

    def __init__(self, space: Dict[str, Any], num_samples: int = 1,
                 seed: Optional[int] = None, metric: Optional[str] = None,
                 mode: str = "max"):
        super().__init__(metric, mode)
        self.space = space
        self.num_samples = num_samples
        self.rng = np.random.default_rng(seed)
        # initial RNG state: experiment restore replays the emitted
        # prefix from here (the live rng has advanced past it)
        self._rng_init_state = self.rng.bit_generator.state
        self._iter = self._generate()
        self.total = num_samples * count_grid_variants(space)

    def _generate(self):
        for _ in range(self.num_samples):
            yield from generate_variants(self.space, self.rng)

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        try:
            config = next(self._iter)
        except StopIteration:
            return Searcher.FINISHED
        self._emitted = getattr(self, "_emitted", 0) + 1
        return config

    # Experiment snapshots pickle the searcher; a generator can't
    # pickle, but the stream is deterministic given (space, rng seed,
    # emitted count) — rebuild and fast-forward on restore.
    def __getstate__(self):
        state = dict(self.__dict__)
        state.pop("_iter", None)
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self.rng.bit_generator.state = self._rng_init_state
        self._iter = self._generate()
        for _ in range(getattr(self, "_emitted", 0)):
            try:
                next(self._iter)
            except StopIteration:
                break


class RandomSearch(BasicVariantGenerator):
    """Alias emphasizing pure random sampling (no grid keys)."""


class ConcurrencyLimiter(Searcher):
    """Caps in-flight suggestions from a wrapped searcher.

    Reference parity: tune/search/concurrency_limiter.py.
    """

    def __init__(self, searcher: Searcher, max_concurrent: int):
        super().__init__(searcher.metric, searcher.mode)
        self.searcher = searcher
        self.max_concurrent = max_concurrent
        self.live: List[str] = []

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if len(self.live) >= self.max_concurrent:
            return None  # controller retries later
        config = self.searcher.suggest(trial_id)
        if config is not None and config is not Searcher.FINISHED:
            self.live.append(trial_id)
        return config

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]) -> None:
        self.searcher.on_trial_result(trial_id, result)

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False) -> None:
        if trial_id in self.live:
            self.live.remove(trial_id)
        self.searcher.on_trial_complete(trial_id, result, error)
