"""External-optimizer searcher adapters (Optuna, HyperOpt).

Reference parity: python/ray/tune/search/optuna/optuna_search.py and
search/hyperopt/hyperopt_search.py — thin Searcher implementations that
translate tune Domains into the external library's space and delegate
suggest/observe. The libraries are NOT vendored: constructing an
adapter without its library installed raises ImportError with install
guidance (same behavior as the reference). The Domain translation is a
standalone pure function so it stays testable without the libraries.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from .sample import Categorical, Domain, Float, GridSearch, Integer
from .searcher import Searcher


def domain_spec(dom: Domain) -> Tuple:
    """Neutral description of a Domain: the adapter materializes it in
    its library's vocabulary. ("float", lo, hi, log, q) |
    ("int", lo, hi, log, q) | ("cat", [choices])."""
    if isinstance(dom, Float):
        return ("float", float(dom.lower), float(dom.upper),
                bool(dom.log), dom.q)
    if isinstance(dom, Integer):
        return ("int", int(dom.lower), int(dom.upper),
                bool(dom.log), dom.q)
    if isinstance(dom, Categorical):
        return ("cat", list(dom.categories))
    raise ValueError(f"unsupported domain type {type(dom).__name__}")


def split_space(space: Dict[str, Any]):
    """(domains, fixed) — grid_search keys are rejected like TPE does."""
    domains: Dict[str, Tuple] = {}
    fixed: Dict[str, Any] = {}
    for key, val in space.items():
        if isinstance(val, GridSearch):
            raise ValueError(
                "external searchers do not take grid_search dimensions; "
                "use BasicVariantGenerator for grids")
        if isinstance(val, Domain):
            domains[key] = domain_spec(val)
        else:
            fixed[key] = val
    return domains, fixed


class OptunaSearch(Searcher):
    """Suggestions from an optuna.Study (TPE/CMA-ES/NSGA per sampler).

    Reference: tune/search/optuna/optuna_search.py:OptunaSearch.
    """

    def __init__(self, space: Dict[str, Any], metric: str, mode: str = "max",
                 num_samples: int = 64, sampler=None,
                 seed: Optional[int] = None):
        super().__init__(metric, mode)
        try:
            import optuna
        except ImportError as e:
            raise ImportError(
                "OptunaSearch requires optuna (`pip install optuna`); "
                "TPESearch/BOHBSearch are the no-dependency equivalents"
            ) from e
        self._optuna = optuna
        self.num_samples = num_samples
        self._suggested = 0
        self.domains, self.fixed = split_space(space)
        self._study = optuna.create_study(
            direction="maximize" if mode == "max" else "minimize",
            sampler=sampler or optuna.samplers.TPESampler(seed=seed))
        self._ot_trials: Dict[str, Any] = {}

    def _distributions(self):
        d = self._optuna.distributions
        out = {}
        for key, spec in self.domains.items():
            if spec[0] == "float":
                _, lo, hi, log, q = spec
                out[key] = d.FloatDistribution(lo, hi, log=log,
                                               step=None if log else q)
            elif spec[0] == "int":
                _, lo, hi, log, q = spec
                out[key] = d.IntDistribution(lo, hi, log=log,
                                             step=(q or 1) if not log else 1)
            else:
                out[key] = d.CategoricalDistribution(spec[1])
        return out

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._suggested >= self.num_samples:
            return Searcher.FINISHED
        self._suggested += 1
        ot = self._study.ask(self._distributions())
        self._ot_trials[trial_id] = ot
        config = dict(ot.params)
        config.update(self.fixed)
        return config

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False) -> None:
        ot = self._ot_trials.pop(trial_id, None)
        if ot is None:
            return
        state = self._optuna.trial.TrialState
        if error or not result or self.metric not in result:
            self._study.tell(ot, state=state.FAIL)
        else:
            self._study.tell(ot, float(result[self.metric]))


class HyperOptSearch(Searcher):
    """Suggestions from hyperopt's TPE over a translated space.

    Reference: tune/search/hyperopt/hyperopt_search.py:HyperOptSearch.
    """

    def __init__(self, space: Dict[str, Any], metric: str, mode: str = "max",
                 num_samples: int = 64, seed: Optional[int] = None):
        super().__init__(metric, mode)
        try:
            import hyperopt
        except ImportError as e:
            raise ImportError(
                "HyperOptSearch requires hyperopt (`pip install "
                "hyperopt`); TPESearch/BOHBSearch are the no-dependency "
                "equivalents") from e
        import numpy as np
        self._hpo = hyperopt
        self.num_samples = num_samples
        self._suggested = 0
        self.domains, self.fixed = split_space(space)
        self._hp_space = self._build_space()
        self._hp_trials = hyperopt.Trials()
        self._rng = np.random.default_rng(seed)
        self._tid_by_trial: Dict[str, int] = {}

    def _build_space(self):
        hp = self._hpo.hp
        import math
        out = {}
        for key, spec in self.domains.items():
            if spec[0] == "float":
                _, lo, hi, log, q = spec
                if log:
                    out[key] = hp.loguniform(key, math.log(lo), math.log(hi))
                elif q:
                    out[key] = hp.quniform(key, lo, hi, q)
                else:
                    out[key] = hp.uniform(key, lo, hi)
            elif spec[0] == "int":
                _, lo, hi, log, q = spec
                out[key] = hp.uniformint(key, lo, hi)
            else:
                out[key] = hp.choice(key, spec[1])
        return out

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._suggested >= self.num_samples:
            return Searcher.FINISHED
        self._suggested += 1
        hpo = self._hpo
        tid = len(self._hp_trials.trials)
        seed = int(self._rng.integers(2 ** 31 - 1))
        new = hpo.tpe.suggest(
            [tid], hpo.base.Domain(lambda c: 0, self._hp_space),
            self._hp_trials, seed)
        self._hp_trials.insert_trial_docs(new)
        self._hp_trials.refresh()
        self._tid_by_trial[trial_id] = tid
        vals = {k: v[0] for k, v in
                self._hp_trials.trials[tid]["misc"]["vals"].items() if v}
        config = self._decode(vals)
        config.update(self.fixed)
        return config

    def _decode(self, vals: Dict[str, Any]) -> Dict[str, Any]:
        out = {}
        for key, spec in self.domains.items():
            v = vals[key]
            if spec[0] == "cat":
                out[key] = spec[1][int(v)]      # hp.choice gives an index
            elif spec[0] == "int":
                out[key] = int(v)
            else:
                out[key] = float(v)
        return out

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False) -> None:
        tid = self._tid_by_trial.pop(trial_id, None)
        if tid is None:
            return
        trial = self._hp_trials.trials[tid]
        if error or not result or self.metric not in result:
            trial["state"] = self._hpo.JOB_STATE_ERROR
        else:
            score = float(result[self.metric])
            loss = -score if self.mode == "max" else score
            trial["state"] = self._hpo.JOB_STATE_DONE
            trial["result"] = {"loss": loss, "status": "ok"}
        self._hp_trials.refresh()
