"""@ray_tpu.remote for classes: ActorClass / ActorHandle / ActorMethod.

Reference parity: python/ray/actor.py (ActorClass._remote :324,
ActorHandle :900+). Actor method calls are direct RPCs to the hosting
worker (one round trip, result inline) — see core.py submit_actor_task.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

from ._private import state
from .remote_function import normalize_scheduling, validate_options


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str,
                 opts: Optional[Dict[str, Any]] = None):
        self._handle = handle
        self._name = name
        self._opts = opts or {}

    def remote(self, *args, **kwargs):
        client = state.current_client()
        return client.submit_actor_task(
            self._handle._actor_id, self._name, args, kwargs, self._opts)

    def options(self, **opts):
        """Per-call options; num_returns="streaming" returns an
        ObjectRefGenerator of the method's yields."""
        merged = dict(self._opts)
        merged.update(validate_options(opts))
        return ActorMethod(self._handle, self._name, merged)

    def bind(self, *args):
        """Build a compiled-graph node from this method (reference:
        python/ray/dag class_node.py — actor_method.bind)."""
        from .dag.dag_node import ClassMethodNode
        return ClassMethodNode(self._handle, self._name, args)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"actor method {self._name!r} cannot be called directly; "
            f"use .remote().")


class ActorHandle:
    def __init__(self, actor_id: str, class_name: str = "Actor"):
        self._actor_id = actor_id
        self._class_name = class_name

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        return ActorMethod(self, name)

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._class_name))

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id[:12]})"

    def __eq__(self, other):
        return (isinstance(other, ActorHandle)
                and other._actor_id == self._actor_id)

    def __hash__(self):
        return hash(self._actor_id)


class ActorClass:
    def __init__(self, cls, opts: Optional[Dict[str, Any]] = None):
        self._cls = cls
        self._opts = validate_options(opts or {})
        self._cls_blob: Optional[bytes] = None   # cached cloudpickle of cls
        self._cls_hash: Optional[str] = None     # sha1, computed with blob
        functools.update_wrapper(self, cls, updated=[])

    def remote(self, *args, **kwargs) -> ActorHandle:
        client = state.current_client()
        if self._cls_blob is None and not getattr(client, "is_local_mode", False):
            import hashlib
            from ._private.serialization import serialize_code
            self._cls_blob = serialize_code(self._cls)
            self._cls_hash = hashlib.sha1(self._cls_blob).hexdigest()
        actor_id, creation_ref = client.create_actor(
            self._cls, args, kwargs, normalize_scheduling(self._opts),
            cls_blob=self._cls_blob, cls_hash=self._cls_hash)
        handle = ActorHandle(actor_id, self._cls.__name__)
        handle._creation_ref = creation_ref  # keeps creation errors reachable
        return handle

    def options(self, **opts) -> "ActorClass":
        merged = dict(self._opts)
        merged.update(validate_options(opts))
        return ActorClass(self._cls, merged)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"actor class {self._cls.__name__!r} cannot be instantiated "
            f"directly; use .remote().")

    @property
    def cls(self):
        return self._cls
