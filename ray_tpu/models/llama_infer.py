"""Cache-aware Llama forward passes for inference.

Net-new (reference inference = external vLLM; SURVEY.md §7 hard part #1).
Two entry points, both designed to jit once and stay compiled:

- prefill: full-prompt forward that also emits every layer's K/V and
  scatters them into the shared page pool (ops/paged_attention.py layout:
  [n_layers, num_pages, n_kv_heads, page_size, head_dim]).
- decode_step: one token per active sequence, paged attention over the
  pool, new KV scattered in-place (donate the pools for true in-place
  HBM updates under jit).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

from ..ops.attention import attention as attention_op
# shard_map version shim: ONE shared implementation (ops/jax_compat)
# so the compat logic cannot drift between consumers
from ..ops.jax_compat import shard_map_compat as _shard_map
from ..ops.paged_attention import (gather_kv, gather_kv_quant,
                                   paged_attention_on_gathered,
                                   paged_decode_with_new_token, scatter_kv,
                                   scatter_kv_quant)
from .llama import LlamaConfig, rms_norm, rope_frequencies


def _rope_single(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """x: (B, H, D) one token per sequence; cos/sin: (B, D//2)."""
    d = x.shape[-1]
    x1, x2 = x[..., : d // 2], x[..., d // 2:]
    cos = cos[:, None, :]
    sin = sin[:, None, :]
    xf1, xf2 = x1.astype(jnp.float32), x2.astype(jnp.float32)
    return jnp.concatenate(
        [xf1 * cos - xf2 * sin, xf2 * cos + xf1 * sin],
        axis=-1).astype(x.dtype)


def _rope_seq(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """x: (B, S, H, D); cos/sin: (S, D//2) (shared positions)."""
    d = x.shape[-1]
    x1, x2 = x[..., : d // 2], x[..., d // 2:]
    cos = cos[None, :, None, :]
    sin = sin[None, :, None, :]
    xf1, xf2 = x1.astype(jnp.float32), x2.astype(jnp.float32)
    return jnp.concatenate(
        [xf1 * cos - xf2 * sin, xf2 * cos + xf1 * sin],
        axis=-1).astype(x.dtype)




# ---------------------------------------------------------------- layer body

def _layer_body(cfg: LlamaConfig, dt, x, layer, lora_l, lora_idx,
                lead_shape: tuple, rope_fn, attn_fn,
                psum_axis: Optional[str] = None):
    """ONE transformer layer, shared by every inference path (prefill,
    chunked prefill, ragged step, decode) — the paths differ only in
    the leading activation shape, the rope application, and the
    attention call. Returns (x, (k, v)) with k/v rope'd, ready for the
    KV scatter.

    psum_axis: inside an explicit-tp shard_map (Megatron layout:
    wq/wk/wv/wg/wi column-parallel, wo/wd row-parallel, cfg a shard-
    local view with n_heads/n_kv_heads divided by tp) the two residual
    projections produce PARTIAL sums — all-reduce them over the named
    axis before the residual add so activations stay replicated."""
    y = rms_norm(x, layer["ln1"], cfg.norm_eps)
    q = _proj(y, layer["wq"], lora_l, "wq", lora_idx, dt).reshape(
        *lead_shape, cfg.n_heads, cfg.head_dim)
    k = _proj(y, layer["wk"], lora_l, "wk", lora_idx, dt).reshape(
        *lead_shape, cfg.n_kv_heads, cfg.head_dim)
    v = _proj(y, layer["wv"], lora_l, "wv", lora_idx, dt).reshape(
        *lead_shape, cfg.n_kv_heads, cfg.head_dim)
    q = rope_fn(q)
    k = rope_fn(k)
    attn = attn_fn(q, k, v)
    attn_out = _proj(attn.reshape(*lead_shape, cfg.q_dim), layer["wo"],
                     lora_l, "wo", lora_idx, dt)
    if psum_axis is not None:
        attn_out = jax.lax.psum(attn_out, psum_axis)
    x = x + attn_out
    y = rms_norm(x, layer["ln2"], cfg.norm_eps)
    gate = jax.nn.silu(y @ layer["wg"].astype(dt))
    up = y @ layer["wi"].astype(dt)
    mlp_out = (gate * up) @ layer["wd"].astype(dt)
    if psum_axis is not None:
        mlp_out = jax.lax.psum(mlp_out, psum_axis)
    x = x + mlp_out
    return x, (k, v)


# ----------------------------------------------------- explicit tp (shard_map)

def tp_local_config(cfg: LlamaConfig, tp: int) -> LlamaConfig:
    """Shard-local view of *cfg* for the explicit-tp forwards: inside
    the engine's shard_map each shard sees 1/tp of the heads, so the
    reshape arithmetic in _layer_body must use divided head counts
    (q_dim follows automatically — it is a property of n_heads)."""
    if tp <= 1:
        return cfg
    if cfg.n_experts:
        raise ValueError("explicit tp (mesh_shape) does not support MoE "
                         "models; use the GSPMD mesh= path")
    for name, dim in (("n_heads", cfg.n_heads),
                      ("n_kv_heads", cfg.n_kv_heads),
                      ("hidden", cfg.hidden), ("ffn", cfg.ffn)):
        if dim % tp:
            raise ValueError(
                f"model {name}={dim} not divisible by tp={tp}")
    return dataclasses.replace(cfg, n_heads=cfg.n_heads // tp,
                               n_kv_heads=cfg.n_kv_heads // tp)


def tp_param_specs(cfg: LlamaConfig, tp_axis: str = "tp"):
    """PartitionSpec tree for init_params' dense llama tree under the
    Megatron layout: column-parallel wq/wk/wv/wg/wi (shard the output
    feature dim), row-parallel wo/wd (shard the input dim, psum in
    _layer_body), lm_head row-parallel over hidden (psum'd logits in
    _tp_head_logits), everything norm/embed replicated. Used both for
    device placement and as shard_map in_specs so dispatch never
    reshards."""
    if cfg.n_experts:
        raise ValueError("explicit tp (mesh_shape) does not support MoE "
                         "models; use the GSPMD mesh= path")
    P = PartitionSpec
    col = P(None, None, tp_axis)
    row = P(None, tp_axis, None)
    return {
        "embed": P(),
        "layers": {"wq": col, "wk": col, "wv": col, "wg": col,
                   "wi": col, "wo": row, "wd": row,
                   "ln1": P(), "ln2": P()},
        "final_norm": P(),
        "lm_head": P(tp_axis, None),
    }


def _tp_head_logits(last, lm_head, psum_axis, logits_psum=None):
    """Row-parallel lm_head: each shard holds an (H/tp, V) slice over
    hidden. Slice the replicated activations down to the shard's rows,
    take the partial product, and all-reduce. logits_psum lets the
    engine route the reduction through ops/quantized_collectives when
    EngineConfig.quantized_collectives is armed."""
    h_loc = lm_head.shape[0]
    shard = jax.lax.axis_index(psum_axis)
    loc = jax.lax.dynamic_slice_in_dim(last, shard * h_loc, h_loc,
                                       axis=-1)
    part = loc.astype(jnp.float32) @ lm_head.astype(jnp.float32)
    if logits_psum is None:
        return jax.lax.psum(part, psum_axis)
    return logits_psum(part, psum_axis)


# ------------------------------------------------------------------- prefill

def prefill(cfg: LlamaConfig, params: Dict[str, Any], tokens: jax.Array,
            true_lens: jax.Array, k_pages: jax.Array, v_pages: jax.Array,
            page_tables: jax.Array, lora: Optional[dict] = None,
            lora_idx: Optional[jax.Array] = None,
            hidden: Optional[jax.Array] = None, emit: str = "logits"
            ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """tokens: (B, S) padded prompts; true_lens: (B); page_tables:
    (B, max_pages). Returns (last_logits (B, V) f32, k_pages, v_pages).

    Pipeline-parallel serving (engine pp>1) runs this per STAGE:
    `params["layers"]` holds only the stage's slice of the stack (the
    KV pools likewise), `hidden` carries the previous stage's (B, S, H)
    activations in place of embedding (params then needs no "embed"),
    and emit="hidden" returns the full activations for the next stage
    instead of head logits (no "final_norm"/"lm_head" needed).
    """
    b, s = tokens.shape
    dt = cfg.dtype
    x = (params["embed"].astype(dt)[tokens] if hidden is None
         else hidden.astype(dt))
    cos, sin = rope_frequencies(cfg, jnp.arange(s))

    impl = "xla" if cfg.attention_impl in ("auto", "ring") \
        else cfg.attention_impl

    def layer_fn(x, inp):
        layer, lora_l = inp
        return _layer_body(
            cfg, dt, x, layer, lora_l, lora_idx, (b, s),
            lambda t: _rope_seq(t, cos, sin),
            lambda q, k, v: attention_op(q, k, v, causal=True,
                                         impl=impl))

    x, (ks, vs) = jax.lax.scan(
        layer_fn, x, (params["layers"], lora_scan_xs(lora)))
    # ks/vs: (L, B, S, KVH, D) -> token-major (B*S, L, KVH, D);
    # L from the stack itself (a pp stage carries n_layers // pp)
    n_l = ks.shape[0]
    k_rows = jnp.transpose(ks, (1, 2, 0, 3, 4)).reshape(
        b * s, n_l, cfg.n_kv_heads, cfg.head_dim)
    v_rows = jnp.transpose(vs, (1, 2, 0, 3, 4)).reshape(
        b * s, n_l, cfg.n_kv_heads, cfg.head_dim)
    positions = jnp.tile(jnp.arange(s), b)
    valid = positions < jnp.repeat(true_lens, s)
    tables = jnp.repeat(page_tables, s, axis=0)
    k_pages, v_pages = scatter_kv(k_pages, v_pages, k_rows, v_rows,
                                  tables, positions, valid)

    if emit == "hidden":
        return x, k_pages, v_pages
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    last = jnp.take_along_axis(
        x, (true_lens - 1)[:, None, None].astype(jnp.int32), axis=1)[:, 0]
    logits = last.astype(jnp.float32) @ params["lm_head"].astype(jnp.float32)
    return logits, k_pages, v_pages


def prefill_chunk(cfg: LlamaConfig, params: Dict[str, Any],
                  tokens: jax.Array, start_pos: jax.Array,
                  chunk_lens: jax.Array, k_pages: jax.Array,
                  v_pages: jax.Array, page_tables: jax.Array,
                  ctx_pages: int = -1, lora: Optional[dict] = None,
                  lora_idx: Optional[jax.Array] = None,
                  hidden: Optional[jax.Array] = None, emit: str = "logits"
                  ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Prefill a CHUNK of each prompt against already-cached context.

    Powers chunked prefill (long prompts advance max_prefill_tokens per
    engine step so decode ticks never stall behind them) and prefix-
    cache hits (the un-matched suffix prefills against the shared
    pages). tokens: (B, C) padded chunk; start_pos: (B,) tokens already
    in the pool; chunk_lens: (B,) valid tokens in this chunk.

    Returns (last_logits (B, V) f32 — logits at the chunk's final valid
    token, k_pages, v_pages) with the chunk's KV scattered in at
    positions start_pos + [0, chunk_lens).

    ctx_pages (static): gather/attend only the first ctx_pages table
    entries — the caller buckets ceil(max(start_pos)/page_size) so the
    dense context cost scales with the context that EXISTS, not
    max_seq (-1 = the whole table). Scatter always uses the full table.
    """
    from ..ops.paged_attention import chunk_attention_on_gathered

    b, c = tokens.shape
    dt = cfg.dtype
    x = (params["embed"].astype(dt)[tokens] if hidden is None
         else hidden.astype(dt))
    positions = start_pos[:, None] + jnp.arange(c)[None, :]      # (B, C)
    cos, sin = rope_frequencies(cfg, positions.reshape(-1))
    cos = cos.reshape(b, c, -1)
    sin = sin.reshape(b, c, -1)

    def rope(t):
        d = t.shape[-1]
        t1, t2 = t[..., : d // 2], t[..., d // 2:]
        c_, s_ = cos[:, :, None, :], sin[:, :, None, :]
        tf1, tf2 = t1.astype(jnp.float32), t2.astype(jnp.float32)
        return jnp.concatenate(
            [tf1 * c_ - tf2 * s_, tf2 * c_ + tf1 * s_],
            axis=-1).astype(t.dtype)

    # one dense gather of the cached context for all layers (layer-major)
    ctx_tables = (page_tables if ctx_pages < 0
                  else page_tables[:, :ctx_pages])
    k_ctx_all, v_ctx_all = gather_kv(k_pages, v_pages, ctx_tables)

    def layer_fn(x, inp):
        layer, k_ctx, v_ctx, lora_l = inp
        return _layer_body(
            cfg, dt, x, layer, lora_l, lora_idx, (b, c), rope,
            lambda q, k, v: chunk_attention_on_gathered(
                q, k_ctx, v_ctx, k, v, start_pos, chunk_lens))

    x, (ks, vs) = jax.lax.scan(
        layer_fn, x,
        (params["layers"], k_ctx_all, v_ctx_all, lora_scan_xs(lora)))
    # ks/vs: (L, B, C, KVH, D) -> token-major (B*C, L, KVH, D);
    # L from the stack itself (a pp stage carries n_layers // pp)
    n_l = ks.shape[0]
    k_rows = jnp.transpose(ks, (1, 2, 0, 3, 4)).reshape(
        b * c, n_l, cfg.n_kv_heads, cfg.head_dim)
    v_rows = jnp.transpose(vs, (1, 2, 0, 3, 4)).reshape(
        b * c, n_l, cfg.n_kv_heads, cfg.head_dim)
    flat_pos = positions.reshape(-1)
    valid = (jnp.arange(c)[None, :] < chunk_lens[:, None]).reshape(-1)
    tables = jnp.repeat(page_tables, c, axis=0)
    k_pages, v_pages = scatter_kv(k_pages, v_pages, k_rows, v_rows,
                                  tables, flat_pos, valid)

    if emit == "hidden":
        return x, k_pages, v_pages
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    if emit == "logits_all":
        # per-position logits over the whole chunk — speculative
        # decoding's verify step greedy-checks every candidate token
        logits_all = (x.astype(jnp.float32)
                      @ params["lm_head"].astype(jnp.float32))
        return logits_all, k_pages, v_pages
    last = jnp.take_along_axis(
        x, jnp.maximum(chunk_lens - 1, 0)[:, None, None].astype(jnp.int32),
        axis=1)[:, 0]
    logits = last.astype(jnp.float32) @ params["lm_head"].astype(jnp.float32)
    return logits, k_pages, v_pages


# ---------------------------------------------------------------------- lora

def lora_delta(y, stack, idx):
    """Per-slot low-rank delta for one projection at one layer.

    y: (B, H) or (B, S, H) activations; stack: (A, Ha, r) down / up pair
    packed as {"a": (Adapters, H, r), "b": (Adapters, r, O)} already
    sliced to this layer; idx: (B,) adapter index per slot (0 = the
    zero adapter -> exact no-op). Multi-LoRA batching the vLLM way:
    gather each slot's adapter then two tiny einsums.
    """
    a = stack["a"][idx]          # (B, H, r)
    b = stack["b"][idx]          # (B, r, O)
    if y.ndim == 2:
        mid = jnp.einsum("bh,bhr->br", y, a)
        return jnp.einsum("br,bro->bo", mid, b)
    mid = jnp.einsum("bsh,bhr->bsr", y, a)
    return jnp.einsum("bsr,bro->bso", mid, b)


def _proj(y, w, lora_layer, key, idx, dt):
    """y @ w (+ the slot's LoRA delta for projection `key`, if any).

    lora_layer: THIS layer's slice of the adapter stacks (rides the
    layer scan as xs): {key: {"a": (A, H, r), "b": (A, r, O)}},
    already in compute dtype."""
    out = y @ w.astype(dt)
    if lora_layer is not None and key in lora_layer:
        out = out + lora_delta(y, lora_layer[key], idx).astype(out.dtype)
    return out


def lora_scan_xs(lora: Optional[dict]):
    """Adapter stacks are stored LAYER-MAJOR ((L, A, ...)) in compute
    dtype at registration — they ride the layer scan as xs directly
    (the old form relayouted + cast inside every compiled step)."""
    return lora if lora else None


# -------------------------------------------------------------- ragged step

def ragged_forward(cfg: LlamaConfig, params: Dict[str, Any],
                   tokens: jax.Array, slot_ids: jax.Array,
                   positions: jax.Array, valid: jax.Array,
                   start: jax.Array, last_idx: jax.Array,
                   k_pages: jax.Array, v_pages: jax.Array,
                   page_tables: jax.Array, ctx_pages: int = -1,
                   lora: Optional[dict] = None,
                   lora_idx: Optional[jax.Array] = None,
                   impl: str = "gather", mesh=None,
                   max_seg_len: int = -1, kv_kind: str = "f32",
                   k_scales: Optional[jax.Array] = None,
                   v_scales: Optional[jax.Array] = None,
                   psum_axis: Optional[str] = None,
                   logits_psum=None) -> Tuple[jax.Array, ...]:
    """Unified ragged prefill+decode forward: ONE program per engine
    tick consumes a FLAT token batch where each active slot contributes
    between 1 token (decoding) and C tokens (prefilling), packed by the
    engine's token-budget scheduler. Decode is the n_tokens == 1 case
    of chunked prefill, so this replaces the per-tick pair of
    prefill_chunk + decode_step dispatches with one program.

    tokens: (T,) flat ragged batch (slot segments contiguous, position
    order); slot_ids: (T,) owning slot; positions: (T,) absolute
    position per token; valid: (T,) bool (padding excluded from
    attention, KV scatter, and seen updates); start: (B,) tokens
    already cached per slot; last_idx: (B,) flat index of each slot's
    last valid token (logits source; 0 for slots with no tokens this
    tick — callers mask); lora_idx: per-TOKEN adapter index (T,).

    impl (mirrors decode_step's kernel selection):
      "gather"            dense XLA fallback — gathers each token's
                          [ctx] context up front; O(T*ctx*KVH*D)
                          transient per layer.
      "pallas"            Pallas ragged kernel: stream each slot's KV
                          pages through VMEM with online softmax, no
                          gathered-context transient.
      "pallas_interpret"  same kernel, interpreter mode (CPU tests).

    mesh: optional tp Mesh — the gather impl partitions via GSPMD as
    before; the kernel impl is wrapped in shard_map over 'tp'
    (attention is per-head: no collectives inside). max_seg_len
    (static) bounds any one slot's token count this tick (the engine
    passes its chunk cap so the kernel's per-slot staging doesn't pad
    decode-heavy batches to T); -1 = no bound.

    kv_kind/k_scales/v_scales: quantized pools (ISSUE 16). With
    kv_kind in ("int8", "fp8") the pools hold narrow values and
    k_scales/v_scales carry the [L, P, page, KVH] f32 row scales; the
    gather impl dequantizes up front (gather_kv_quant), the kernel impl
    streams scale blocks beside the pages and fuses the dequant
    multiply, and the return grows to (logits, k_pages, v_pages,
    k_scales, v_scales) with the tick's fresh KV quantized at append.

    psum_axis/logits_psum: explicit-tp mode (ISSUE 17) — the CALLER is
    already inside a shard_map over psum_axis, cfg is the shard-local
    view (tp_local_config), params/pools are the local shards
    (tp_param_specs layout), and mesh must be None (no nested
    shard_map). _layer_body all-reduces the row-parallel residual
    projections and the lm_head goes row-parallel over hidden with the
    partial logits reduced via logits_psum (default lax.psum).

    Returns (last-token logits per slot (B, V) f32, k_pages, v_pages)
    with every valid token's KV scattered into the pool at its
    position.
    """
    from ..ops.ragged_paged_attention import (
        ragged_paged_attention_pallas, ragged_prefill_decode_attention)

    (t,) = tokens.shape
    dt = cfg.dtype
    quantized = kv_kind != "f32"
    x = params["embed"].astype(dt)[tokens]              # (T, H)
    cos, sin = rope_frequencies(cfg, positions)         # (T, D/2)
    use_kernel = impl in ("pallas", "pallas_interpret")
    kernel_quant = use_kernel and quantized
    if use_kernel:
        # pool stays layer-major in HBM: the scan slices one layer's
        # [pages, page, KVH, D] and the kernel streams pages from it
        k_by_layer, v_by_layer = k_pages, v_pages
    else:
        ctx_tables = (page_tables if ctx_pages < 0
                      else page_tables[:, :ctx_pages])
        if quantized:
            k_by_layer, v_by_layer = gather_kv_quant(
                k_pages, v_pages, k_scales, v_scales, ctx_tables)
        else:
            k_by_layer, v_by_layer = gather_kv(k_pages, v_pages,
                                               ctx_tables)

    def layer_fn(x, inp):
        if kernel_quant:
            layer, k_l, v_l, ks_l, vs_l, lora_l = inp
        else:
            layer, k_l, v_l, lora_l = inp
            ks_l = vs_l = None

        def attn_fn(q, k, v):
            if not use_kernel:
                return ragged_prefill_decode_attention(
                    q, k_l, v_l, k, v, slot_ids, positions, valid,
                    start)
            base = functools.partial(
                ragged_paged_attention_pallas, ctx_pages=ctx_pages,
                max_seg_len=max_seg_len,
                interpret=(impl == "pallas_interpret"))
            if kernel_quant:
                # positional wrapper so shard_map's in_specs line up
                def kernel(q_, kp, vp, tb, si, po, va, st, kn, vn,
                           ksl, vsl):
                    return base(q_, kp, vp, tb, si, po, va, st, kn, vn,
                                k_scales=ksl, v_scales=vsl)
            else:
                kernel = base
            if mesh is not None and mesh.shape.get("tp", 1) > 1:
                # per-head attention: each tp shard streams pages for
                # its local kv heads, no cross-shard comms
                from jax.sharding import PartitionSpec as P
                in_specs = [P(None, "tp", None),          # q (T,H,D)
                            P(None, None, "tp", None),    # k pool
                            P(None, None, "tp", None),    # v pool
                            P(None, None),                # tables
                            P(None),                      # slot_ids
                            P(None),                      # positions
                            P(None),                      # valid
                            P(None),                      # start
                            P(None, "tp", None),          # new k
                            P(None, "tp", None)]          # new v
                if kernel_quant:
                    # scale blocks shard on kv heads like their pages
                    in_specs += [P(None, None, "tp"),     # k scales
                                 P(None, None, "tp")]     # v scales
                kernel = _shard_map(
                    kernel, mesh,
                    in_specs=tuple(in_specs),
                    out_specs=P(None, "tp", None))
            args = (q, k_l, v_l, page_tables, slot_ids,
                    positions, valid, start, k, v)
            if kernel_quant:
                args += (ks_l, vs_l)
            return kernel(*args)

        return _layer_body(
            cfg, dt, x, layer, lora_l, lora_idx, (t,),
            lambda a: _rope_single(a, cos, sin), attn_fn,
            psum_axis=psum_axis)

    scan_xs = (params["layers"], k_by_layer, v_by_layer)
    if kernel_quant:
        scan_xs += (k_scales, v_scales)
    scan_xs += (lora_scan_xs(lora),)
    x, (ks, vs) = jax.lax.scan(layer_fn, x, scan_xs)
    # ks/vs: (L, T, KVH, D) -> token-major (T, L, KVH, D)
    k_rows = jnp.swapaxes(ks, 0, 1)
    v_rows = jnp.swapaxes(vs, 0, 1)
    if quantized:
        k_pages, v_pages, k_scales, v_scales = scatter_kv_quant(
            k_pages, v_pages, k_scales, v_scales, k_rows, v_rows,
            page_tables[slot_ids], positions, valid, kv_kind)
    else:
        k_pages, v_pages = scatter_kv(k_pages, v_pages, k_rows, v_rows,
                                      page_tables[slot_ids], positions,
                                      valid)
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    last = x[last_idx]                                  # (B, H)
    if psum_axis is not None:
        logits = _tp_head_logits(last, params["lm_head"], psum_axis,
                                 logits_psum)
    else:
        logits = last.astype(jnp.float32) @ params["lm_head"].astype(
            jnp.float32)
    if quantized:
        return logits, k_pages, v_pages, k_scales, v_scales
    return logits, k_pages, v_pages


# -------------------------------------------------------------------- decode

def decode_step(cfg: LlamaConfig, params: Dict[str, Any],
                tokens: jax.Array, positions: jax.Array,
                k_pages: jax.Array, v_pages: jax.Array,
                page_tables: jax.Array, active: jax.Array,
                impl: str = "gather", mesh=None,
                lora: Optional[dict] = None,
                lora_idx: Optional[jax.Array] = None,
                hidden: Optional[jax.Array] = None, emit: str = "logits",
                kv_kind: str = "f32",
                k_scales: Optional[jax.Array] = None,
                v_scales: Optional[jax.Array] = None,
                psum_axis: Optional[str] = None,
                logits_psum=None) -> Tuple[jax.Array, ...]:
    """One decode step for the whole running batch.

    tokens: (B,) last sampled token per slot; positions: (B,) its
    absolute position (== number of cached tokens); active: (B,) bool.
    Returns (logits (B, V) f32, k_pages, v_pages) with the new token's KV
    scattered in.

    impl:
      "gather"            dense XLA fallback — gathers [B, max_ctx] KV up
                          front; cost scales with max_pages.
      "pallas"            stream pages through the Pallas decode kernel;
                          cost scales with each sequence's actual length.
      "pallas_interpret"  same kernel, interpreter mode (CPU tests).

    mesh: a jax Mesh with a 'tp' axis for tensor-parallel serving
    (params sharded on heads/mlp/vocab, KV pool on kv_heads — the
    reference places external vLLM TP workers via PGs,
    vllm_models.py:123-159; here TP is in-program GSPMD). The gather
    impl partitions end-to-end via GSPMD; the Pallas kernel is wrapped
    in shard_map over 'tp' (attention is per-head: no collectives
    inside, psum on the projections happens in the surrounding GSPMD
    program).

    kv_kind/k_scales/v_scales: quantized pools (ISSUE 16) — same
    contract as ragged_forward: dequant-on-gather or fused-dequant
    kernel on the read side, quantize-at-append on the write side, and
    a (logits, k_pages, v_pages, k_scales, v_scales) return.

    psum_axis/logits_psum: explicit-tp mode — same contract as
    ragged_forward (caller already inside the shard_map, shard-local
    cfg/params/pools, mesh=None).
    """
    b = tokens.shape[0]
    dt = cfg.dtype
    quantized = kv_kind != "f32"
    x = (params["embed"].astype(dt)[tokens] if hidden is None
         else hidden.astype(dt))                    # (B, H)
    cos, sin = rope_frequencies(cfg, positions)     # (B, D/2)

    use_kernel = impl in ("pallas", "pallas_interpret")
    kernel_quant = use_kernel and quantized
    if use_kernel:
        # Pool is layer-major already: scan slices (pages, page, KVH, D).
        k_by_layer, v_by_layer = k_pages, v_pages
    else:
        # One gather of the whole context for all layers, layer-major.
        if quantized:
            k_by_layer, v_by_layer = gather_kv_quant(
                k_pages, v_pages, k_scales, v_scales, page_tables)
        else:
            k_by_layer, v_by_layer = gather_kv(k_pages, v_pages,
                                               page_tables)

    def layer_fn(x, inp):
        if kernel_quant:
            layer, k_l, v_l, ks_l, vs_l, lora_l = inp
        else:
            layer, k_l, v_l, lora_l = inp
            ks_l = vs_l = None

        def attn_fn(q, k, v):
            # The just-computed token's KV is not yet in the pages: the
            # kernel path merges it with one extra online-softmax step,
            # the gather path appends it to the dense context
            # (append_len=1).
            if not use_kernel:
                k_full = jnp.concatenate([k_l, k[:, None]], axis=1)
                v_full = jnp.concatenate([v_l, v[:, None]], axis=1)
                return paged_attention_on_gathered(
                    q, k_full, v_full, positions, append_len=1)
            base = functools.partial(
                paged_decode_with_new_token,
                interpret=(impl == "pallas_interpret"))
            if kernel_quant:
                # positional wrapper so shard_map's in_specs line up
                def kernel(q_, kp, vp, tb, po, kn, vn, ksl, vsl):
                    return base(q_, kp, vp, tb, po, kn, vn,
                                k_scales=ksl, v_scales=vsl)
            else:
                kernel = base
            if mesh is not None and mesh.shape.get("tp", 1) > 1:
                # per-head attention: each tp shard runs the kernel on
                # its local heads/kv-heads, no cross-shard comms
                from jax.sharding import PartitionSpec as P
                in_specs = [P(None, "tp", None),          # q (B,H,D)
                            P(None, None, "tp", None),    # k pool
                            P(None, None, "tp", None),    # v pool
                            P(None, None),                # tables
                            P(None),                      # positions
                            P(None, "tp", None),          # new k
                            P(None, "tp", None)]          # new v
                if kernel_quant:
                    # scale blocks shard on kv heads like their pages
                    in_specs += [P(None, None, "tp"),     # k scales
                                 P(None, None, "tp")]     # v scales
                kernel = _shard_map(
                    kernel, mesh,
                    in_specs=tuple(in_specs),
                    out_specs=P(None, "tp", None))
            args = (q, k_l, v_l, page_tables, positions, k, v)
            if kernel_quant:
                args += (ks_l, vs_l)
            return kernel(*args)

        return _layer_body(cfg, dt, x, layer, lora_l, lora_idx, (b,),
                           lambda a: _rope_single(a, cos, sin),
                           attn_fn, psum_axis=psum_axis)

    scan_xs = (params["layers"], k_by_layer, v_by_layer)
    if kernel_quant:
        scan_xs += (k_scales, v_scales)
    scan_xs += (lora_scan_xs(lora),)
    x, (ks, vs) = jax.lax.scan(layer_fn, x, scan_xs)
    k_rows = jnp.transpose(ks, (1, 0, 2, 3))        # (B, L, KVH, D)
    v_rows = jnp.transpose(vs, (1, 0, 2, 3))
    if quantized:
        k_pages, v_pages, k_scales, v_scales = scatter_kv_quant(
            k_pages, v_pages, k_scales, v_scales, k_rows, v_rows,
            page_tables, positions, active, kv_kind)
    else:
        k_pages, v_pages = scatter_kv(k_pages, v_pages, k_rows, v_rows,
                                      page_tables, positions, active)
    if emit == "hidden":
        if quantized:
            return x, k_pages, v_pages, k_scales, v_scales
        return x, k_pages, v_pages
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    if psum_axis is not None:
        logits = _tp_head_logits(x, params["lm_head"], psum_axis,
                                 logits_psum)
    else:
        logits = x.astype(jnp.float32) @ params["lm_head"].astype(
            jnp.float32)
    if quantized:
        return logits, k_pages, v_pages, k_scales, v_scales
    return logits, k_pages, v_pages
