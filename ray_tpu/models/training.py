"""Sharded train-step factory: params + optax state on the mesh, one jit.

Reference parity: this replaces the reference's torch DDP/FSDP wrap +
NCCL allreduce (train/torch/train_loop_utils.py:163, torch/config.py:66)
with a single pjit program — gradients are reduced by XLA collectives the
sharding implies (psum over dp, reduce-scatter over fsdp), and optimizer
state is sharded like its parameters (ZeRO by construction).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from . import llama
from ..ops.jax_compat import set_mesh_compat
from ..parallel.mesh import BATCH_AXES, AXIS_SP, AXIS_PP, mesh_shape
from ..parallel.sharding import spec_for, tree_shardings


def _path_key(entry) -> str:
    for attr in ("key", "name", "idx"):
        if hasattr(entry, attr):
            return str(getattr(entry, attr))
    return str(entry)


def opt_state_shardings(opt_state_shapes, param_shardings, mesh: Mesh):
    """Shard optimizer-state leaves like the parameters they mirror.

    optax states (adam mu/nu etc.) embed subtrees with the params' structure;
    we match each state leaf to a param by path suffix, falling back to
    replication for scalars/counters.
    """
    param_by_path = {}
    for path, sh in jax.tree_util.tree_flatten_with_path(param_shardings)[0]:
        key = tuple(_path_key(p) for p in path)
        param_by_path[key] = sh
    replicated = NamedSharding(mesh, PartitionSpec())

    def assign(path, leaf):
        key = tuple(_path_key(p) for p in path)
        for start in range(len(key)):
            sh = param_by_path.get(key[start:])
            if sh is not None:
                return sh
        return replicated

    return jax.tree_util.tree_map_with_path(assign, opt_state_shapes)


def default_optimizer(learning_rate=3e-4, weight_decay=0.1,
                      warmup_steps=100, total_steps=10000,
                      b1=0.9, b2=0.95, grad_clip=1.0,
                      mu_dtype=None) -> optax.GradientTransformation:
    """mu_dtype=jnp.bfloat16 halves first-moment memory (the second moment
    stays f32); the standard trade on HBM-bound single-chip runs."""
    schedule = optax.warmup_cosine_decay_schedule(
        0.0, learning_rate, warmup_steps, max(total_steps, warmup_steps + 1))
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(schedule, b1=b1, b2=b2, weight_decay=weight_decay,
                    mu_dtype=mu_dtype),
    )


class TrainStepBundle:
    """Everything needed to run sharded training of a Llama config."""

    def __init__(self, cfg: llama.LlamaConfig, mesh: Mesh,
                 optimizer: Optional[optax.GradientTransformation] = None,
                 rules: Optional[Dict] = None,
                 donate_state: bool = True):
        self.cfg = cfg
        self.mesh = mesh
        self.optimizer = optimizer or default_optimizer()
        axes = llama.param_logical_axes(cfg)
        self.param_shardings = tree_shardings(axes, mesh, rules)
        self.batch_sharding = NamedSharding(
            mesh, PartitionSpec(BATCH_AXES, AXIS_SP))

        params_shape = jax.eval_shape(
            lambda: llama.init_params(cfg, jax.random.PRNGKey(0)))
        opt_shape = jax.eval_shape(self.optimizer.init, params_shape)
        self.opt_shardings = opt_state_shardings(
            opt_shape, self.param_shardings, mesh)
        self.state_shardings = (self.param_shardings, self.opt_shardings)

        self._init = jax.jit(
            self._init_impl, out_shardings=self.state_shardings)
        self._step = jax.jit(
            self._step_impl,
            in_shardings=(self.state_shardings, self.batch_sharding),
            out_shardings=(self.state_shardings, None),
            donate_argnums=(0,) if donate_state else ())
        self._eval = jax.jit(
            lambda p, t: llama.loss_fn(self.cfg, p, t, self.mesh)[1])

    def _init_impl(self, key):
        params = llama.init_params(self.cfg, key)
        return params, self.optimizer.init(params)

    def _step_impl(self, state, tokens):
        params, opt_state = state
        if (self.cfg.pp_schedule == "1f1b"
                and mesh_shape(self.mesh).get(AXIS_PP, 1) > 1):
            from . import pipeline_1f1b
            loss, metrics, grads = pipeline_1f1b.loss_and_grads(
                self.cfg, params, tokens, self.mesh)
        else:
            grad_fn = jax.value_and_grad(
                lambda p: llama.loss_fn(self.cfg, p, tokens, self.mesh),
                has_aux=True)
            (loss, metrics), grads = grad_fn(params)
        updates, opt_state = self.optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        metrics = dict(metrics)
        metrics["grad_norm"] = optax.global_norm(grads)
        return (params, opt_state), metrics

    # public API -----------------------------------------------------------
    #
    # Each call runs under `jax.set_mesh` (via the version shim) so the model's logical-axis
    # sharding constraints (with_logical_constraint) resolve against this
    # bundle's mesh at trace time — without the context they silently
    # no-op, which both loses the intended activation shardings and (for
    # MoE-inside-pipeline programs) trips an XLA SPMD partitioner
    # check-fail ("Invalid binary instruction opcode copy").

    def init_state(self, seed: int = 0):
        with set_mesh_compat(self.mesh):
            return self._init(jax.random.PRNGKey(seed))

    def init_state_from_checkpoint(self, ckpt_dir: str):
        """Init train state from an HF-layout safetensors checkpoint:
        params stream in pre-sharded (checkpoint_io windowed per-shard
        reads onto this bundle's mesh), optimizer state inits jitted
        under the same shardings."""
        from . import checkpoint_io
        params = checkpoint_io.load_llama_params(
            self.cfg, ckpt_dir, mesh=self.mesh)
        with set_mesh_compat(self.mesh):
            opt_state = jax.jit(
                self.optimizer.init,
                out_shardings=self.opt_shardings)(params)
        return params, opt_state

    def step(self, state, tokens):
        with set_mesh_compat(self.mesh):
            return self._step(state, tokens)

    def eval_loss(self, state, tokens):
        with set_mesh_compat(self.mesh):
            return self._eval(state[0], tokens)

    def shard_batch(self, tokens):
        return jax.device_put(tokens, self.batch_sharding)
