"""Llama-family transformer LM, TPU-first.

Net-new compute path: the reference delegates all modeling to external
torch/vLLM; here the flagship LM is native JAX — functional (pytree params,
no framework), layers stacked on a leading axis and executed with lax.scan
(single layer compile + clean rematerialization), GQA + RoPE + SwiGLU +
RMSNorm, logical-axis sharding annotations throughout so the same code runs
DP/FSDP/TP/SP by changing the mesh (parallel/sharding.py), and attention
dispatched to the Pallas flash kernel on TPU or the ring kernel when the
sequence axis is sharded.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from ..ops.attention import attention as attention_op
from ..ops.moe import moe_ffn
from ..ops.ring_attention import ring_attention_sharded
from ..ops.ulysses import ulysses_attention
from ..parallel.mesh import AXIS_SP
from ..parallel.sharding import with_logical_constraint as wlc


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    head_dim: int = 128
    ffn: int = 14336
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    max_seq: int = 8192
    dtype: Any = jnp.bfloat16          # activation/compute dtype
    param_dtype: Any = jnp.float32     # storage dtype
    attention_impl: str = "auto"       # auto | xla | pallas | ring | ulysses
    # MoE (Mixtral-style): n_experts=0 -> dense SwiGLU; >0 -> every layer's
    # MLP is a top-k expert mixture (ops/moe.py), experts sharded over ep.
    n_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01
    # Dropless dispatch (ops/moe.py): sort + ragged_dot grouped matmuls,
    # ragged_all_to_all over the ep axis — no capacity drops; the
    # capacity_factor knob is ignored when True.
    moe_dropless: bool = False
    # Pipeline parallelism: microbatch count used when the mesh has pp>1
    # (models/pipeline.py). Must divide the per-step batch.
    pp_microbatches: int = 4
    # Schedule for the pp training step: "gpipe" (models/pipeline.py,
    # autodiff backward, supports MoE) or "1f1b" (models/pipeline_1f1b.py
    # hand-scheduled interleaved 1F1B: O(stages) activation stash,
    # fill/drain bubble shrunk by pp_interleave; dense layers only).
    pp_schedule: str = "gpipe"
    pp_interleave: int = 2          # model chunks per device under 1f1b
    remat: bool = True
    # "dots_no_batch" saves matmul outputs (fastest when HBM allows);
    # "nothing" fully rematerializes each layer in backward (~1B params on
    # a 16 GiB chip needs this).
    remat_policy: str = "dots_no_batch"
    # Cross-entropy sequence chunk: bounds logits to (B, chunk, vocab) per
    # step instead of materializing (B, S, vocab). 0 = unchunked.
    loss_chunk: int = 512

    @property
    def q_dim(self) -> int:
        return self.n_heads * self.head_dim

    @property
    def kv_dim(self) -> int:
        return self.n_kv_heads * self.head_dim

    def num_params(self) -> int:
        ffn_mult = max(self.n_experts, 1)
        per_layer = (self.hidden * (self.q_dim + 2 * self.kv_dim)
                     + self.q_dim * self.hidden
                     + 3 * self.hidden * self.ffn * ffn_mult
                     + (self.hidden * self.n_experts if self.n_experts else 0)
                     + 2 * self.hidden)
        return (self.vocab_size * self.hidden * 2
                + self.n_layers * per_layer + self.hidden)


# Model-size presets (Llama-3 family shapes).
PRESETS: Dict[str, LlamaConfig] = {
    "debug": LlamaConfig(vocab_size=256, hidden=128, n_layers=2, n_heads=4,
                         n_kv_heads=2, head_dim=32, ffn=256, max_seq=256),
    "tiny": LlamaConfig(vocab_size=2048, hidden=512, n_layers=4, n_heads=8,
                        n_kv_heads=4, head_dim=64, ffn=1536, max_seq=2048),
    # Mixtral-shaped MoE variants for tests/dryruns.
    "debug_moe": LlamaConfig(vocab_size=256, hidden=128, n_layers=2,
                             n_heads=4, n_kv_heads=2, head_dim=32, ffn=256,
                             max_seq=256, n_experts=4, moe_top_k=2),
    "8x7b": LlamaConfig(vocab_size=32000, hidden=4096, n_layers=32,
                        n_heads=32, n_kv_heads=8, head_dim=128, ffn=14336,
                        n_experts=8, moe_top_k=2),
    "1b": LlamaConfig(vocab_size=128256, hidden=2048, n_layers=16,
                      n_heads=32, n_kv_heads=8, head_dim=64, ffn=8192),
    "3b": LlamaConfig(vocab_size=128256, hidden=3072, n_layers=28,
                      n_heads=24, n_kv_heads=8, head_dim=128, ffn=8192),
    "8b": LlamaConfig(vocab_size=128256, hidden=4096, n_layers=32,
                      n_heads=32, n_kv_heads=8, head_dim=128, ffn=14336),
    "70b": LlamaConfig(vocab_size=128256, hidden=8192, n_layers=80,
                       n_heads=64, n_kv_heads=8, head_dim=128, ffn=28672),
}


def config(name_or_cfg, **overrides) -> LlamaConfig:
    cfg = PRESETS[name_or_cfg] if isinstance(name_or_cfg, str) else name_or_cfg
    return dataclasses.replace(cfg, **overrides) if overrides else cfg


# --------------------------------------------------------------------- params

def param_logical_axes(cfg: LlamaConfig) -> Dict[str, Any]:
    """Pytree of logical-axis tuples mirroring init_params' structure."""
    if cfg.n_experts:
        mlp_axes = {
            "router": ("layers", "embed", None),
            "wi": ("layers", "experts", "embed", "mlp"),
            "wg": ("layers", "experts", "embed", "mlp"),
            "wd": ("layers", "experts", "mlp", "embed"),
        }
    else:
        mlp_axes = {
            "wi": ("layers", "embed", "mlp"),
            "wg": ("layers", "embed", "mlp"),
            "wd": ("layers", "mlp", "embed"),
        }
    return {
        "embed": ("vocab", "embed"),
        "layers": {
            "wq": ("layers", "embed", "heads"),
            "wk": ("layers", "embed", "kv_heads"),
            "wv": ("layers", "embed", "kv_heads"),
            "wo": ("layers", "heads", "embed"),
            **mlp_axes,
            "ln1": ("layers", None),
            "ln2": ("layers", None),
        },
        "final_norm": (None,),
        "lm_head": ("embed", "vocab"),
    }


def init_params(cfg: LlamaConfig, key: jax.Array) -> Dict[str, Any]:
    """Initialize parameters (layers stacked on the leading axis)."""
    keys = jax.random.split(key, 10)
    h, L = cfg.hidden, cfg.n_layers
    pd = cfg.param_dtype

    def dense(k, shape, fan_in):
        return (jax.random.normal(k, shape, jnp.float32)
                * (1.0 / math.sqrt(fan_in))).astype(pd)

    if cfg.n_experts:
        E = cfg.n_experts
        mlp = {
            "router": dense(keys[9], (L, h, E), h),
            "wi": dense(keys[5], (L, E, h, cfg.ffn), h),
            "wg": dense(keys[6], (L, E, h, cfg.ffn), h),
            "wd": dense(keys[7], (L, E, cfg.ffn, h), cfg.ffn),
        }
    else:
        mlp = {
            "wi": dense(keys[5], (L, h, cfg.ffn), h),
            "wg": dense(keys[6], (L, h, cfg.ffn), h),
            "wd": dense(keys[7], (L, cfg.ffn, h), cfg.ffn),
        }
    return {
        "embed": dense(keys[0], (cfg.vocab_size, h), h),
        "layers": {
            "wq": dense(keys[1], (L, h, cfg.q_dim), h),
            "wk": dense(keys[2], (L, h, cfg.kv_dim), h),
            "wv": dense(keys[3], (L, h, cfg.kv_dim), h),
            "wo": dense(keys[4], (L, cfg.q_dim, h), cfg.q_dim),
            **mlp,
            "ln1": jnp.ones((L, h), pd),
            "ln2": jnp.ones((L, h), pd),
        },
        "final_norm": jnp.ones((h,), pd),
        "lm_head": dense(keys[8], (h, cfg.vocab_size), h),
    }


# -------------------------------------------------------------------- modules

def rms_norm(x: jax.Array, weight: jax.Array, eps: float) -> jax.Array:
    dtype = x.dtype
    x = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    x = x * jax.lax.rsqrt(var + eps)
    return (x * weight.astype(jnp.float32)).astype(dtype)


def rope_frequencies(cfg: LlamaConfig, positions: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """positions: (S,) -> cos/sin of shape (S, head_dim//2), float32."""
    half = cfg.head_dim // 2
    inv_freq = 1.0 / (cfg.rope_theta
                      ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    angles = positions.astype(jnp.float32)[:, None] * inv_freq[None, :]
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """x: (B, S, H, D); rotate-half RoPE."""
    d = x.shape[-1]
    x1, x2 = x[..., : d // 2], x[..., d // 2:]
    cos = cos[None, :, None, :]
    sin = sin[None, :, None, :]
    xf1, xf2 = x1.astype(jnp.float32), x2.astype(jnp.float32)
    return jnp.concatenate(
        [xf1 * cos - xf2 * sin, xf2 * cos + xf1 * sin],
        axis=-1).astype(x.dtype)


def _attend(cfg: LlamaConfig, q, k, v, mesh: Optional[Mesh]):
    impl = cfg.attention_impl
    if impl == "auto" and mesh is not None \
            and dict(zip(mesh.axis_names, mesh.devices.shape)).get(AXIS_SP, 1) > 1:
        impl = "ring"
    if impl == "ring":
        if mesh is None:
            raise ValueError("ring attention requires a mesh")
        return ring_attention_sharded(q, k, v, mesh, causal=True)
    if impl == "ulysses":
        return ulysses_attention(q, k, v, causal=True)
    return attention_op(q, k, v, causal=True, impl=impl)


def decoder_layer(cfg: LlamaConfig, x: jax.Array, layer: Dict[str, jax.Array],
                  cos: jax.Array, sin: jax.Array,
                  mesh: Optional[Mesh]) -> Tuple[jax.Array, jax.Array]:
    """Returns (x, aux) — aux is the MoE load-balance loss (0 when dense)."""
    b, s, h = x.shape
    dt = cfg.dtype

    # Attention block
    y = rms_norm(x, layer["ln1"], cfg.norm_eps)
    q = (y @ layer["wq"].astype(dt)).reshape(b, s, cfg.n_heads, cfg.head_dim)
    k = (y @ layer["wk"].astype(dt)).reshape(b, s, cfg.n_kv_heads, cfg.head_dim)
    v = (y @ layer["wv"].astype(dt)).reshape(b, s, cfg.n_kv_heads, cfg.head_dim)
    q = wlc(apply_rope(q, cos, sin), "batch", "seq", "heads", "head_dim")
    k = wlc(apply_rope(k, cos, sin), "batch", "seq", "kv_heads", "head_dim")
    v = wlc(v, "batch", "seq", "kv_heads", "head_dim")
    attn = _attend(cfg, q, k, v, mesh).reshape(b, s, cfg.q_dim)
    x = x + wlc(attn @ layer["wo"].astype(dt), "batch", "seq", "act_embed")

    # MLP block: dense SwiGLU or top-k expert mixture
    y = rms_norm(x, layer["ln2"], cfg.norm_eps)
    if cfg.n_experts:
        out, aux = moe_ffn(
            y, layer["router"], layer["wi"], layer["wg"], layer["wd"],
            top_k=cfg.moe_top_k, capacity_factor=cfg.moe_capacity_factor,
            dropless=cfg.moe_dropless, mesh=mesh)
        x = x + wlc(out, "batch", "seq", "act_embed")
        return x, aux
    gate = jax.nn.silu(y @ layer["wg"].astype(dt))
    up = y @ layer["wi"].astype(dt)
    mlp = wlc(gate * up, "batch", "seq", "mlp")
    x = x + wlc(mlp @ layer["wd"].astype(dt), "batch", "seq", "act_embed")
    return x, jnp.zeros((), jnp.float32)


_REMAT_POLICIES = {
    "dots_no_batch":
        lambda: jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
    "nothing": lambda: None,
}


def hidden_states_with_aux(cfg: LlamaConfig, params: Dict[str, Any],
                           tokens: jax.Array,
                           mesh: Optional[Mesh] = None
                           ) -> Tuple[jax.Array, jax.Array]:
    """tokens: (B, S) int32 -> (final-norm hidden states (B, S, hidden),
    summed MoE aux loss). Dispatches to the GPipe pipeline when the mesh
    has a pp axis > 1 (models/pipeline.py)."""
    if mesh is not None and dict(
            zip(mesh.axis_names, mesh.devices.shape)).get("pp", 1) > 1:
        from .pipeline import pipelined_hidden_states
        return pipelined_hidden_states(cfg, params, tokens, mesh)

    b, s = tokens.shape
    dt = cfg.dtype
    # constrain BOTH gather operands: tokens to the activation layout,
    # and the table's feature dim to replicated (act_embed) just for the
    # lookup. Leaving the table's fsdp-sharded feature dim in place makes
    # the partitioner emit the gather feature-sharded and then
    # "involuntarily rematerialize" (replicate + repartition) it into the
    # batch/seq activation layout the next constraint demands.
    tokens = wlc(tokens, "batch", "seq")
    table = wlc(params["embed"].astype(dt), "vocab", "act_embed")
    x = table[tokens]
    x = wlc(x, "batch", "seq", "act_embed")
    positions = jnp.arange(s)
    cos, sin = rope_frequencies(cfg, positions)

    layer_fn = lambda x, layer: decoder_layer(cfg, x, layer, cos, sin, mesh)
    if cfg.remat:
        layer_fn = jax.checkpoint(
            layer_fn, policy=_REMAT_POLICIES[cfg.remat_policy]())
    x, aux = jax.lax.scan(layer_fn, x, params["layers"])

    return rms_norm(x, params["final_norm"], cfg.norm_eps), jnp.sum(aux)


def hidden_states(cfg: LlamaConfig, params: Dict[str, Any],
                  tokens: jax.Array,
                  mesh: Optional[Mesh] = None) -> jax.Array:
    """tokens: (B, S) int32 -> final-norm hidden states (B, S, hidden)."""
    return hidden_states_with_aux(cfg, params, tokens, mesh)[0]


def _head_logits(cfg: LlamaConfig, x: jax.Array, lm_head: jax.Array):
    # bf16 operands + f32 accumulation: full-f32 operands would run the
    # largest matmul in the model off the MXU fast path
    logits = jnp.einsum("bsh,hv->bsv", x.astype(cfg.dtype),
                        lm_head.astype(cfg.dtype),
                        preferred_element_type=jnp.float32)
    return wlc(logits, "batch", "seq", "vocab")


def forward(cfg: LlamaConfig, params: Dict[str, Any], tokens: jax.Array,
            mesh: Optional[Mesh] = None) -> jax.Array:
    """tokens: (B, S) int32 -> logits (B, S, vocab) float32."""
    x = hidden_states(cfg, params, tokens, mesh)
    return _head_logits(cfg, x, params["lm_head"])


def loss_fn(cfg: LlamaConfig, params: Dict[str, Any], tokens: jax.Array,
            mesh: Optional[Mesh] = None,
            mask: Optional[jax.Array] = None) -> Tuple[jax.Array, Dict]:
    """Next-token cross entropy. tokens: (B, S); mask: (B, S) or None.

    The head matmul + softmax run in sequence chunks (cfg.loss_chunk) under
    remat, so the (B, S, vocab) logits tensor never materializes — at Llama
    vocab sizes it would dwarf every other activation.
    """
    b, s = tokens.shape
    x, moe_aux = hidden_states_with_aux(cfg, params, tokens, mesh)  # (B,S,h)
    # shift: position i predicts token i+1; last position is masked out.
    # The weight for position i is the TARGET's mask (mask[i+1]), so
    # predictions of padding tokens never contribute.
    targets = jnp.concatenate(
        [tokens[:, 1:], jnp.zeros((b, 1), tokens.dtype)], axis=1)
    if mask is not None:
        m = jnp.concatenate(
            [mask[:, 1:].astype(jnp.float32),
             jnp.zeros((b, 1), jnp.float32)], axis=1)
    else:
        m = jnp.ones((b, s), jnp.float32).at[:, -1].set(0.0)

    # Largest divisor of s within the configured chunk bound, so chunking
    # never silently disables on awkward sequence lengths (a full-vocab
    # (B, S, V) logits tensor is an OOM cliff, not a fallback).
    chunk = 0
    if cfg.loss_chunk:
        c = min(cfg.loss_chunk, s)
        while c > 1 and s % c:
            c -= 1
        chunk = c
    if chunk and s > chunk:
        n = s // chunk

        def chunk_nll(x_c, t_c):
            logits = _head_logits(cfg, x_c, params["lm_head"])
            lse = jax.nn.logsumexp(logits, axis=-1)
            tgt = jnp.take_along_axis(logits, t_c[..., None], axis=-1)[..., 0]
            return lse - tgt                               # (B, chunk)

        chunk_nll = jax.checkpoint(chunk_nll)              # drop chunk logits

        def body(_, xc_tc):
            return None, chunk_nll(*xc_tc)

        xs = x.reshape(b, n, chunk, -1).transpose(1, 0, 2, 3)
        ts = targets.reshape(b, n, chunk).transpose(1, 0, 2)
        _, nll = jax.lax.scan(body, None, (xs, ts))
        nll = nll.transpose(1, 0, 2).reshape(b, s)
    else:
        logits = _head_logits(cfg, x, params["lm_head"])
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]

    total = jnp.sum(nll * m)
    count = jnp.maximum(jnp.sum(m), 1.0)
    ce = total / count
    loss = ce
    metrics = {"loss": ce, "tokens": count,
               "ppl_proxy": jnp.exp(jnp.minimum(ce, 20.0))}
    if cfg.n_experts:
        aux = moe_aux / cfg.n_layers
        loss = ce + cfg.moe_aux_weight * aux
        metrics["moe_aux"] = aux
    return loss, metrics


def flops_per_token(cfg: LlamaConfig, seq_len: int) -> float:
    """Approximate training FLOPs/token (fwd+bwd = 6*N_active + attention).

    For MoE, only top_k of n_experts FFNs touch each token, so inactive
    expert params are excluded from the 6N term.
    """
    n = cfg.num_params()
    if cfg.n_experts:
        n -= (3 * cfg.hidden * cfg.ffn * cfg.n_layers
              * max(cfg.n_experts - cfg.moe_top_k, 0))
    attn = 12 * cfg.n_layers * cfg.hidden * seq_len  # causal attn matmuls
    return 6.0 * n + attn
