"""Interleaved 1F1B pipeline schedule as ONE SPMD program.

GPipe (models/pipeline.py) runs all M forwards then lets jax autodiff
replay the reverse — activation stash grows with M and the fwd+bwd
bubble is 2(S-1) full-stage ticks. This module hand-writes the
fwd+bwd pipeline instead (PipeDream-flush / Megatron-style), with two
upgrades the SPMD formulation makes natural:

- **1F1B ordering**: a microbatch's backward starts as soon as its
  forward reaches the last stage, so at most ~S microbatches are ever
  in flight (activation stash O(S), not O(M)).
- **Interleaving**: each device holds V model CHUNKS (virtual stages
  sv = v*pp + d cover layers [sv*Lc, (sv+1)*Lc)); the fill/drain
  bubble shrinks by V because a chunk is 1/V of a stage's work
  (Megatron interleaved schedule, re-derived for one-pjit SPMD).

The schedule is built host-side by a list scheduler (`build_schedule`)
into dense [T, pp] tick tables; on device, every tick each core looks
up ITS row (data-dependent `lax.cond` branches are per-core control
flow on TPU — no collective sits inside a branch, so cores may
diverge), runs at most one forward chunk and one backward chunk
(`jax.vjp` recomputes the forward from the stashed input — remat is
the 1F1B memory profile), then two uniform `ppermute`s rotate
activations (+1) and gradients (-1) around the pp ring.

Reference role: torch pipeline engines schedule 1F1B with per-stage
processes + NCCL p2p; here the whole train step (embed -> V*pp virtual
stages -> head+loss -> full backward -> psum'd grads) is one compiled
program. Exactness gate: grads bit-match the dense single-device
autodiff path (tests/test_pipeline_1f1b.py).

MoE layers are not supported inside the hand-written backward (dense
SwiGLU only); use the GPipe path for pp+MoE.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from . import llama
from ..parallel.mesh import AXIS_PP, mesh_shape

F_COST = 1.0      # relative tick cost of a forward chunk
B_COST = 2.0      # backward chunk (recompute + vjp)


@dataclasses.dataclass
class Schedule:
    """Dense tick tables, one row per tick, one column per device.

    f_valid/b_valid: does device d run a forward/backward this tick;
    f_mb/f_chunk (b_*): which microbatch / local chunk. r_f_*/r_b_*:
    receiver routing — what lands on device d at the END of tick t
    (forward activation from d-1, backward gradient from d+1).
    """
    pp: int
    n_chunks: int
    n_micro: int
    stash: int                      # in-flight slots per (device, chunk)
    f_valid: np.ndarray             # [T, pp] bool
    f_mb: np.ndarray                # [T, pp] int
    f_chunk: np.ndarray
    b_valid: np.ndarray
    b_mb: np.ndarray
    b_chunk: np.ndarray
    r_f_valid: np.ndarray
    r_f_mb: np.ndarray
    r_f_chunk: np.ndarray
    r_b_valid: np.ndarray
    r_b_mb: np.ndarray
    r_b_chunk: np.ndarray

    @property
    def ticks(self) -> int:
        return self.f_valid.shape[0]

    def bubble_fraction(self) -> float:
        """Idle fraction under the F=1/B=2 cost model: 1 - busy/total
        where total = ticks * per-tick max cost across devices (the
        ring advances in lockstep, so the slowest device sets the tick
        length)."""
        per_tick = (self.f_valid * F_COST + self.b_valid * B_COST)
        tick_len = per_tick.max(axis=1)           # [T]
        busy = per_tick.sum()
        return 1.0 - busy / (tick_len.sum() * self.pp)

    def async_bubble_fraction(self, f_cost: float = F_COST,
                              b_cost: float = B_COST) -> float:
        """Bubble under ASYNC execution: each device runs its op
        sequence in schedule order, an op starts when the device is
        free AND its dependency has finished (F(m,sv) after F(m,sv-1);
        B(m,sv) after B(m,sv+1); the first backward after the last
        forward) — p2p neighbor sync only, no global barrier. This is
        the timing model of per-stage processes exchanging activations
        over channels (dag/compiled_dag.py stages), and of XLA's
        collective-permute pipelining on real ICI; the lockstep
        `bubble_fraction` above is the conservative bound. The
        interleaved (n_chunks>1) schedule beats GPipe only under this
        model — fill/drain hops cost a chunk (1/V of a stage), not a
        full stage.
        """
        sv_count = self.n_chunks * self.pp
        f_end = np.full((self.n_micro, sv_count), -1.0)
        b_end = np.full((self.n_micro, sv_count), -1.0)
        free = np.zeros(self.pp)
        busy = 0.0
        for t in range(self.ticks):
            for d in range(self.pp):
                if self.f_valid[t, d]:
                    m, c = int(self.f_mb[t, d]), int(self.f_chunk[t, d])
                    sv = c * self.pp + d
                    dep = 0.0 if sv == 0 else f_end[m, sv - 1]
                    assert sv == 0 or dep >= 0, (m, sv)
                    start = max(free[d], dep)
                    free[d] = start + f_cost
                    f_end[m, sv] = free[d]
                    busy += f_cost
                if self.b_valid[t, d]:
                    m, c = int(self.b_mb[t, d]), int(self.b_chunk[t, d])
                    sv = c * self.pp + d
                    dep = (f_end[m, sv] if sv == sv_count - 1
                           else b_end[m, sv + 1])
                    assert dep >= 0, (m, sv)
                    start = max(free[d], dep)
                    free[d] = start + b_cost
                    b_end[m, sv] = free[d]
                    busy += b_cost
        makespan = free.max()
        return 1.0 - busy / (makespan * self.pp)


def gpipe_bubble_fraction(n_micro: int, pp: int) -> float:
    """Same cost model applied to GPipe fwd+bwd: (M+S-1) forward ticks
    + (M+S-1) backward ticks, M of each busy per device."""
    ticks = n_micro + pp - 1
    busy = n_micro * (F_COST + B_COST) * pp
    total = ticks * (F_COST + B_COST) * pp
    return 1.0 - busy / total


def _interleaved_order(n_micro: int, pp: int, v: int,
                       d: int) -> List[Tuple[str, int, int]]:
    """Per-device op order of the Megatron-LM interleaved 1F1B
    schedule (re-derived): forwards run in rounds of pp microbatches
    per chunk (breadth-first across microbatches, chunks cycling), the
    warmup depth 2(pp-d-1) + (v-1)*pp covers the fill, then strict
    1F1B alternation, then the backward drain. Requires
    n_micro % pp == 0."""
    total = n_micro * v
    group = pp * v

    def f_op(i):
        chunk = (i % group) // pp
        mb = (i // group) * pp + (i % pp)
        return ("f", mb, chunk * pp + d)

    def b_op(j):
        chunk = v - 1 - ((j % group) // pp)
        mb = (j // group) * pp + (j % pp)
        return ("b", mb, chunk * pp + d)

    warmup = min(2 * (pp - d - 1) + (v - 1) * pp, total)
    seq: List[Tuple[str, int, int]] = []
    fi = bi = 0
    for _ in range(warmup):
        seq.append(f_op(fi))
        fi += 1
    while fi < total:
        seq.append(f_op(fi))
        fi += 1
        seq.append(b_op(bi))
        bi += 1
    while bi < total:
        seq.append(b_op(bi))
        bi += 1
    return seq


def _schedule_from_orders(n_micro: int, pp: int, n_chunks: int,
                          orders: List[List[Tuple[str, int, int]]]
                          ) -> Optional[List[Dict[str, Any]]]:
    """Lockstep-simulate fixed per-device op orders into tick rows.
    Returns None if the order deadlocks (infeasible)."""
    sv_count = n_chunks * pp
    f_done = [[-1] * sv_count for _ in range(n_micro)]   # finish tick
    b_done = [[-1] * sv_count for _ in range(n_micro)]
    ptr = [0] * pp
    rows: List[Dict[str, Any]] = []
    total = sum(len(o) for o in orders)
    done = 0
    t = 0
    while done < total:
        if t > 8 * (total + sv_count) + 64:
            return None
        row = {"f": [None] * pp, "b": [None] * pp}
        fired = []
        for d in range(pp):
            if ptr[d] >= len(orders[d]):
                continue
            kind, m, sv = orders[d][ptr[d]]
            if kind == "f":
                ready = (sv == 0 and True) or (
                    f_done[m][sv - 1] >= 0 and f_done[m][sv - 1] < t)
                if ready:
                    row["f"][d] = (m, sv)
                    fired.append(("f", m, sv))
                    ptr[d] += 1
            else:
                if sv == sv_count - 1:
                    ready = f_done[m][sv] >= 0 and f_done[m][sv] < t
                else:
                    ready = (b_done[m][sv + 1] >= 0
                             and b_done[m][sv + 1] < t)
                if ready:
                    row["b"][d] = (m, sv)
                    fired.append(("b", m, sv))
                    ptr[d] += 1
        for kind, m, sv in fired:
            (f_done if kind == "f" else b_done)[m][sv] = t
            done += 1
        rows.append(row)
        t += 1
    # trim trailing all-idle rows
    while rows and all(rows[-1][k][d] is None
                       for k in ("f", "b") for d in range(pp)):
        rows.pop()
    return rows


def build_schedule(n_micro: int, pp: int, n_chunks: int = 1) -> Schedule:
    """Build the interleaved 1F1B tick tables.

    When n_micro % pp == 0 the deterministic Megatron-style interleaved
    order is used (bubble ~ (pp-1)/(v*m + pp-1) under the async timing
    model); otherwise, or if that order is infeasible, a greedy list
    scheduler (backward-first) provides a valid fallback. Any valid
    order is correct — the executor follows whatever tables this
    emits; the bubble assertion in the tests pins the quality.
    """
    if n_micro % pp == 0:
        orders = [_interleaved_order(n_micro, pp, n_chunks, d)
                  for d in range(pp)]
        rows = _schedule_from_orders(n_micro, pp, n_chunks, orders)
        if rows is not None:
            return _emit(n_micro, pp, n_chunks, rows)
    return _greedy_schedule(n_micro, pp, n_chunks)


def _greedy_schedule(n_micro: int, pp: int, n_chunks: int) -> Schedule:
    sv_count = n_chunks * pp

    def dev(sv):
        return sv % pp

    # ready_at[m][sv] for F; b_ready_at[m][sv] for B. None = not ready.
    f_ready = [[None] * sv_count for _ in range(n_micro)]
    b_ready = [[None] * sv_count for _ in range(n_micro)]
    for m in range(n_micro):
        f_ready[m][0] = 0
    f_done = [[False] * sv_count for _ in range(n_micro)]
    b_done = [[False] * sv_count for _ in range(n_micro)]

    rows: List[Dict[str, Any]] = []
    t = 0
    total_ops = 2 * n_micro * sv_count
    done_ops = 0
    max_ticks = 16 * (total_ops + sv_count) + 64      # safety margin
    while done_ops < total_ops:
        assert t < max_ticks, "scheduler wedged"
        row = {"f": [None] * pp, "b": [None] * pp}
        for d in range(pp):
            # backward first
            cand_b = [(m, sv) for m in range(n_micro)
                      for sv in range(sv_count)
                      if dev(sv) == d and not b_done[m][sv]
                      and b_ready[m][sv] is not None
                      and b_ready[m][sv] <= t]
            if cand_b:
                m, sv = min(cand_b, key=lambda x: (x[1], x[0]))
                row["b"][d] = (m, sv)
                continue
            cand_f = [(m, sv) for m in range(n_micro)
                      for sv in range(sv_count)
                      if dev(sv) == d and not f_done[m][sv]
                      and f_ready[m][sv] is not None
                      and f_ready[m][sv] <= t]
            if cand_f:
                # highest virtual stage first; FIFO within a stage
                m, sv = min(cand_f, key=lambda x: (-x[1], x[0]))
                row["f"][d] = (m, sv)
        # commit the tick
        for d in range(pp):
            if row["f"][d] is not None:
                m, sv = row["f"][d]
                f_done[m][sv] = True
                done_ops += 1
                if sv + 1 < sv_count:
                    f_ready[m][sv + 1] = t + 1
                else:                       # loss grad available at once
                    b_ready[m][sv] = t + 1
            if row["b"][d] is not None:
                m, sv = row["b"][d]
                b_done[m][sv] = True
                done_ops += 1
                if sv > 0:
                    b_ready[m][sv - 1] = t + 1
        rows.append(row)
        t += 1
    return _emit(n_micro, pp, n_chunks, rows)


def _emit(n_micro: int, pp: int, n_chunks: int,
          rows: List[Dict[str, Any]]) -> Schedule:
    """Dense tick tables + receiver routing + minimal stash size from a
    list of per-tick rows."""
    sv_count = n_chunks * pp
    T = len(rows)
    z_i = lambda: np.zeros((T, pp), np.int32)
    z_b = lambda: np.zeros((T, pp), bool)
    sched = Schedule(pp=pp, n_chunks=n_chunks, n_micro=n_micro,
                     stash=1,
                     f_valid=z_b(), f_mb=z_i(), f_chunk=z_i(),
                     b_valid=z_b(), b_mb=z_i(), b_chunk=z_i(),
                     r_f_valid=z_b(), r_f_mb=z_i(), r_f_chunk=z_i(),
                     r_b_valid=z_b(), r_b_mb=z_i(), r_b_chunk=z_i())
    for t, row in enumerate(rows):
        for d in range(pp):
            if row["f"][d] is not None:
                m, sv = row["f"][d]
                sched.f_valid[t, d] = True
                sched.f_mb[t, d] = m
                sched.f_chunk[t, d] = sv // pp
                if sv + 1 < sv_count:       # activation lands on d+1
                    nd = (d + 1) % pp
                    sched.r_f_valid[t, nd] = True
                    sched.r_f_mb[t, nd] = m
                    sched.r_f_chunk[t, nd] = (sv + 1) // pp
            if row["b"][d] is not None:
                m, sv = row["b"][d]
                sched.b_valid[t, d] = True
                sched.b_mb[t, d] = m
                sched.b_chunk[t, d] = sv // pp
                if sv > 0:                  # gradient lands on d-1
                    nd = (d - 1) % pp
                    sched.r_b_valid[t, nd] = True
                    sched.r_b_mb[t, nd] = m
                    sched.r_b_chunk[t, nd] = (sv - 1) // pp
    # Stash slots are addressed m % stash: grow stash until that map is
    # injective over every concurrently-live microbatch set per
    # (device, chunk), else a late microbatch would overwrite a stashed
    # activation (or banked gradient) an earlier one's backward still
    # needs. An activation's life starts when it LANDS in the stash —
    # at receive time (r_f), or at forward time for the entry stage's
    # embed write and the exit stage's loss-grad write — and ends when
    # the backward consumes it.
    live_sets: List[set] = []
    act_live: Dict[Tuple[int, int], set] = {}
    grad_live: Dict[Tuple[int, int], set] = {}
    last_sv = sv_count - 1
    for t in range(T):
        for d in range(pp):
            if sched.r_f_valid[t, d]:
                key = (d, int(sched.r_f_chunk[t, d]))
                cur = act_live.setdefault(key, set())
                cur.add(int(sched.r_f_mb[t, d]))
                live_sets.append(set(cur))
            if sched.r_b_valid[t, d]:
                key = (d, int(sched.r_b_chunk[t, d]))
                cur = grad_live.setdefault(key, set())
                cur.add(int(sched.r_b_mb[t, d]))
                live_sets.append(set(cur))
            if sched.f_valid[t, d]:
                m = int(sched.f_mb[t, d])
                c = int(sched.f_chunk[t, d])
                if d == 0 and c == 0:           # embed write (sv=0)
                    cur = act_live.setdefault((0, 0), set())
                    cur.add(m)
                    live_sets.append(set(cur))
                if c * pp + d == last_sv:       # loss-grad write
                    cur = grad_live.setdefault((d, c), set())
                    cur.add(m)
                    live_sets.append(set(cur))
            if sched.b_valid[t, d]:
                m = int(sched.b_mb[t, d])
                key = (d, int(sched.b_chunk[t, d]))
                act_live.get(key, set()).discard(m)
                grad_live.get(key, set()).discard(m)
    w = sched.stash
    while any(len({m % w for m in s_}) < len(s_) for s_ in live_sets):
        w += 1
    sched.stash = w
    return sched


# ===================================================================== SPMD
# executor: the schedule tables drive one scanned tick program per device.

def _choose_microbatches(cfg: "llama.LlamaConfig", b: int, pp: int) -> int:
    """Microbatch count: cfg.pp_microbatches clipped to the batch, then
    nudged down to a divisor of b, preferring multiples of pp (the
    deterministic interleaved order needs m % pp == 0)."""
    m = min(cfg.pp_microbatches or pp, b)
    while b % m:
        m -= 1
    cand = m
    while cand > 0 and (b % cand or cand % pp):
        cand -= 1
    return cand if cand > 0 else m


def loss_and_grads(cfg: "llama.LlamaConfig", params: Dict[str, Any],
                   tokens: jax.Array, mesh: Mesh
                   ) -> Tuple[jax.Array, Dict[str, jax.Array],
                              Dict[str, Any]]:
    """Hand-scheduled interleaved-1F1B train step: returns
    (loss, metrics, grads) with grads matching the dense autodiff path
    (mask-free next-token CE; dense SwiGLU layers only).
    """
    pp = mesh_shape(mesh).get(AXIS_PP, 1)
    if cfg.n_experts:
        raise ValueError(
            "pp_schedule='1f1b' supports dense layers only (the "
            "hand-written backward drops the MoE aux loss) — use "
            "pp_schedule='gpipe' for pp+MoE")
    v = max(int(getattr(cfg, "pp_interleave", 1)), 1)
    L = cfg.n_layers
    assert L % (pp * v) == 0, (L, pp, v)
    lc = L // (pp * v)
    b, s = tokens.shape
    m = _choose_microbatches(cfg, b, pp)
    bmb = b // m
    sched = build_schedule(m, pp, v)
    w = sched.stash
    h = cfg.hidden
    denom = float(m * bmb * (s - 1))    # mask-free token count (static)

    # layers [L, ...] -> [v, pp, lc, ...]; f32 activations/weights in the
    # manual region (see models/pipeline.py bf16 partitioner note)
    def stage_view(a):
        a = a.reshape(v, pp, lc, *a.shape[1:])
        return a.astype(jnp.float32) if a.dtype == jnp.bfloat16 else a

    staged = jax.tree.map(stage_view, params["layers"])
    f32 = lambda a: (a.astype(jnp.float32)
                     if a.dtype == jnp.bfloat16 else a)
    embed = f32(params["embed"])
    fnorm = f32(params["final_norm"])
    head = f32(params["lm_head"])
    tokens_mb = tokens.reshape(m, bmb, s)

    tables = dict(
        fv=sched.f_valid, fm=sched.f_mb, fc=sched.f_chunk,
        bv=sched.b_valid, bm=sched.b_mb, bc=sched.b_chunk,
        rfv=sched.r_f_valid, rfm=sched.r_f_mb, rfc=sched.r_f_chunk,
        rbv=sched.r_b_valid, rbm=sched.r_b_mb, rbc=sched.r_b_chunk)
    tables = {k: jnp.asarray(val) for k, val in tables.items()}

    positions = jnp.arange(s)
    cos, sin = llama.rope_frequencies(cfg, positions)
    dt = cfg.dtype

    def stage_fn(layers_c, x):
        """One chunk of lc decoder layers (dense only; MoE aux ignored)."""
        def layer_fn(xx, layer):
            y, _ = llama.decoder_layer(cfg, xx, layer, cos, sin, mesh)
            return y, None
        if cfg.remat:
            layer_fn = jax.checkpoint(
                layer_fn, policy=llama._REMAT_POLICIES[cfg.remat_policy]())
        y, _ = jax.lax.scan(layer_fn, x, layers_c)
        return y

    def head_fn(fnorm_w, head_w, x, tgt):
        """Microbatch loss CONTRIBUTION: sum(nll * shift-mask) / denom,
        so summing over microbatches reproduces the dense mean CE."""
        xn = llama.rms_norm(x, fnorm_w, cfg.norm_eps)
        logits = jnp.einsum("bsh,hv->bsv", xn.astype(dt),
                            head_w.astype(dt),
                            preferred_element_type=jnp.float32)
        tgt_s = jnp.concatenate(
            [tgt[:, 1:], jnp.zeros((bmb, 1), tgt.dtype)], axis=1)
        msk = jnp.ones((bmb, s), jnp.float32).at[:, -1].set(0.0)
        lse = jax.nn.logsumexp(logits, axis=-1)
        picked = jnp.take_along_axis(
            logits, tgt_s[..., None], axis=-1)[..., 0]
        return jnp.sum((lse - picked) * msk) / denom

    last_sv = v * pp - 1

    def body(staged_local, embed, fnorm, head, tokens_mb, tabs):
        stage = jax.lax.axis_index(AXIS_PP)
        layers_local = jax.tree.map(lambda a: a[:, 0], staged_local)

        def vary(a):
            """pp-varying view; no-op for values already varying (e.g.
            zeros_like of the pp-sharded layer params)."""
            try:
                return jax.lax.pcast(a, (AXIS_PP,), to="varying")
            except ValueError:
                return a

        # Differentiating w.r.t. a pp-INVARIANT value inside a varying
        # region makes jax insert a psum(pp) in the vjp to keep the
        # cotangent invariant — a collective inside the per-device
        # lax.cond branches, which deadlocks (devices reach different
        # collectives). Cast every differentiated input to pp-varying
        # up front; the grads are psum'd manually after the tick loop.
        embed = vary(embed)
        fnorm = vary(fnorm)
        head = vary(head)
        tokens_mb = vary(tokens_mb)
        zeros_act = lambda: vary(jnp.zeros((bmb, s, h), jnp.float32))
        carry0 = dict(
            fwd_in=vary(jnp.zeros((v, w, bmb, s, h), jnp.float32)),
            grad_in=vary(jnp.zeros((v, w, bmb, s, h), jnp.float32)),
            g_layers=jax.tree.map(
                lambda a: vary(jnp.zeros_like(a)), layers_local),
            g_embed=vary(jnp.zeros_like(embed)),
            g_fnorm=vary(jnp.zeros_like(fnorm)),
            g_head=vary(jnp.zeros_like(head)),
            loss=vary(jnp.zeros((), jnp.float32)))

        def tick(carry, row):
            my = {k: jnp.take(val, stage, axis=0)
                  for k, val in row.items()}

            # ---------------- forward ----------------
            def do_f(c):
                fm, fc = my["fm"], my["fc"]
                slot = fm % w
                sv = fc * pp + stage
                tok = jax.lax.dynamic_index_in_dim(
                    tokens_mb, fm, keepdims=False)
                x_entry = embed.astype(jnp.float32)[tok]
                x_stash = c["fwd_in"][fc, slot]
                x_in = jnp.where(sv == 0, x_entry, x_stash)
                # the entry stage banks its embed output for backward
                fwd_in = jax.lax.cond(
                    sv == 0,
                    lambda fi: fi.at[fc, slot].set(x_in),
                    lambda fi: fi, c["fwd_in"])
                layers_c = jax.tree.map(
                    lambda a: jax.lax.dynamic_index_in_dim(
                        a, fc, keepdims=False), layers_local)
                y = stage_fn(layers_c, x_in)

                def exit_head(c):
                    lossv, grads_h = jax.value_and_grad(
                        head_fn, argnums=(0, 1, 2))(fnorm, head, y, tok)
                    dfn, dhd, dx = grads_h
                    return dict(
                        c,
                        fwd_in=fwd_in,
                        grad_in=c["grad_in"].at[fc, slot].set(dx),
                        g_fnorm=c["g_fnorm"] + dfn,
                        g_head=c["g_head"] + dhd,
                        loss=c["loss"] + lossv), zeros_act()

                def mid(c):
                    return dict(c, fwd_in=fwd_in), y

                return jax.lax.cond(sv == last_sv, exit_head, mid, c)

            carry, act_out = jax.lax.cond(
                my["fv"], do_f, lambda c: (c, zeros_act()), carry)

            # ---------------- backward ----------------
            def do_b(c):
                bm, bc = my["bm"], my["bc"]
                slot = bm % w
                sv = bc * pp + stage
                x_in = c["fwd_in"][bc, slot]
                g = c["grad_in"][bc, slot]
                layers_c = jax.tree.map(
                    lambda a: jax.lax.dynamic_index_in_dim(
                        a, bc, keepdims=False), layers_local)
                _, vjp_fn = jax.vjp(stage_fn, layers_c, x_in)
                dlayers, dx = vjp_fn(g)
                g_layers = jax.tree.map(
                    lambda G, dl: G.at[bc].add(dl),
                    c["g_layers"], dlayers)

                def entry_embed(c):
                    tok = jax.lax.dynamic_index_in_dim(
                        tokens_mb, bm, keepdims=False)
                    ge = c["g_embed"].at[tok.reshape(-1)].add(
                        dx.reshape(-1, h))
                    return dict(c, g_layers=g_layers,
                                g_embed=ge), zeros_act()

                def mid(c):
                    return dict(c, g_layers=g_layers), dx

                return jax.lax.cond(sv == 0, entry_embed, mid, c)

            carry, grad_out = jax.lax.cond(
                my["bv"], do_b, lambda c: (c, zeros_act()), carry)

            # ---------------- uniform ring rotation ----------------
            fwd_perm = [(i, (i + 1) % pp) for i in range(pp)]
            bwd_perm = [(i, (i - 1) % pp) for i in range(pp)]
            act_recv = jax.lax.ppermute(act_out, AXIS_PP, fwd_perm)
            grad_recv = jax.lax.ppermute(grad_out, AXIS_PP, bwd_perm)

            carry = jax.lax.cond(
                my["rfv"],
                lambda c: dict(c, fwd_in=c["fwd_in"].at[
                    my["rfc"], my["rfm"] % w].set(act_recv)),
                lambda c: c, carry)
            carry = jax.lax.cond(
                my["rbv"],
                lambda c: dict(c, grad_in=c["grad_in"].at[
                    my["rbc"], my["rbm"] % w].set(grad_recv)),
                lambda c: c, carry)
            return carry, None

        carry, _ = jax.lax.scan(tick, carry0, tabs)

        loss = jax.lax.psum(carry["loss"], AXIS_PP)
        g_embed = jax.lax.psum(carry["g_embed"], AXIS_PP)
        g_fnorm = jax.lax.psum(carry["g_fnorm"], AXIS_PP)
        g_head = jax.lax.psum(carry["g_head"], AXIS_PP)
        g_layers = jax.tree.map(lambda a: a[:, None], carry["g_layers"])
        return loss, g_embed, g_fnorm, g_head, g_layers

    fn = jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(None, AXIS_PP), P(), P(), P(), P(), P()),
        out_specs=(P(), P(), P(), P(), P(None, AXIS_PP)),
        axis_names={AXIS_PP})
    loss, g_embed, g_fnorm, g_head, g_staged = fn(
        staged, embed, fnorm, head, tokens_mb, tables)

    g_layers = jax.tree.map(
        lambda a, ref: a.reshape(L, *a.shape[3:]).astype(ref.dtype),
        g_staged, params["layers"])
    grads = {
        "embed": g_embed.astype(params["embed"].dtype),
        "layers": g_layers,
        "final_norm": g_fnorm.astype(params["final_norm"].dtype),
        "lm_head": g_head.astype(params["lm_head"].dtype),
    }
    metrics = {"loss": loss, "tokens": jnp.asarray(denom),
               "ppl_proxy": jnp.exp(jnp.minimum(loss, 20.0))}
    return loss, metrics, grads
