"""Pipeline parallelism: GPipe microbatch schedule inside one pjit program.

Net-new TPU design (SURVEY.md §2.4 lists PP as a first-class strategy; the
reference realizes it with per-stage worker processes and NCCL p2p — here
the whole pipeline is ONE SPMD program). Layers are stacked [L, ...] and
re-viewed as [pp, L/pp, ...] with the stage dim sharded over the `pp` mesh
axis; `jax.shard_map(..., axis_names={'pp'})` makes only that axis manual,
so dp/fsdp/sp/tp/ep sharding inside each stage is still GSPMD-automatic.

Schedule: classic GPipe. M microbatches flow through S stages over
M + S - 1 ticks; every tick each stage applies its layer block to the
activation it holds, then `ppermute` rotates activations one stage
forward. Bubble ticks compute garbage on non-active stages (static
shapes — the SPMD price for zero host control flow); outputs are
collected at the last stage and psum-broadcast at the end. The backward
schedule is the exact transpose: jax autodiff of scan+ppermute *is* the
reverse pipeline, no hand-written backward pass.

Throughput: bubble fraction = (S-1)/(M+S-1); pick cfg.pp_microbatches >=
4*pp for <20% bubble, standard GPipe guidance.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from . import llama
from ..parallel.mesh import AXIS_PP, mesh_shape


def _stage_params(params_layers: Dict[str, jax.Array], pp: int):
    """[L, ...] stacked layer params -> [pp, L/pp, ...]."""
    def reshape(a):
        L = a.shape[0]
        assert L % pp == 0, f"n_layers={L} not divisible by pp={pp}"
        return a.reshape(pp, L // pp, *a.shape[1:])
    return jax.tree.map(reshape, params_layers)


def pipelined_hidden_states(cfg: "llama.LlamaConfig", params: Dict[str, Any],
                            tokens: jax.Array, mesh: Mesh
                            ) -> Tuple[jax.Array, jax.Array]:
    """Drop-in replacement for the lax.scan layer stack in
    llama.hidden_states_with_aux when mesh pp > 1.

    tokens: (B, S) -> ((B, S, hidden) final-norm hidden states, aux).
    Embedding, final norm and the LM head stay outside the pipeline
    (replicated over pp, sharded over the other axes as usual).
    """
    pp = mesh_shape(mesh).get(AXIS_PP, 1)
    b, s = tokens.shape
    m = min(cfg.pp_microbatches or pp, b)
    while b % m:
        m -= 1
    dt = cfg.dtype

    x = params["embed"].astype(dt)[tokens]                # (B, S, h)
    positions = jnp.arange(s)
    cos, sin = llama.rope_frequencies(cfg, positions)

    staged = _stage_params(params["layers"], pp)
    # bf16 tensors in (or crossing into) a partial-manual shard_map region
    # check-fail both XLA SPMD partitioners on current jaxlib ("Invalid
    # binary instruction opcode copy"; order-dependent, verified down to a
    # 20-line repro). Workaround: carry activations through the pipeline
    # in f32 — weights are still cast to cfg.dtype inside each matmul, so
    # the MXU-side operand dtype survives; activations pay an f32 tax
    # until the upstream bug is fixed, at which point `_act_dtype` can
    # return cfg.dtype again.
    staged = jax.tree.map(
        lambda a: a.astype(jnp.float32) if a.dtype == jnp.bfloat16 else a,
        staged)
    h = x.shape[-1]
    x_mb = x.astype(jnp.float32).reshape(m, b // m, s, h)  # (M, Bmb, S, h)

    def stage_apply(layers_local, x_in):
        layer_fn = lambda x, layer: llama.decoder_layer(
            cfg, x, layer, cos, sin, mesh)
        if cfg.remat:
            layer_fn = jax.checkpoint(
                layer_fn,
                policy=llama._REMAT_POLICIES[cfg.remat_policy]())
        x_out, aux = jax.lax.scan(layer_fn, x_in, layers_local)
        return x_out, jnp.sum(aux)

    def pipeline_body(staged_local, x_mb):
        # staged_local: [1, L/pp, ...] (this stage's block); x_mb replicated
        # over pp. Manual only over pp — everything inside is still GSPMD.
        layers_local = jax.tree.map(lambda a: a[0], staged_local)
        stage = jax.lax.axis_index(AXIS_PP)
        nstages = jax.lax.axis_size(AXIS_PP)
        last = nstages - 1
        ticks = m + nstages - 1

        if dt != jnp.bfloat16:      # see the f32-activations note above
            x_mb = x_mb.astype(dt)

        # Initial carries must already be pp-varying (each stage's loop
        # state diverges immediately) or the scan carry types mismatch.
        vary = lambda a: jax.lax.pcast(a, (AXIS_PP,), to="varying")
        outputs = vary(jnp.zeros_like(x_mb))
        carry = vary(jnp.zeros_like(x_mb[0]))
        aux_total = vary(jnp.zeros((), jnp.float32))

        def tick(state, t):
            carry, outputs, aux_total = state
            # Stage 0 ingests microbatch t (clamped during drain ticks);
            # later stages consume what rotated in from the previous stage.
            fresh = jax.lax.dynamic_index_in_dim(
                x_mb, jnp.clip(t, 0, m - 1), keepdims=False)
            x_in = jnp.where(stage == 0, fresh, carry)
            x_out, aux = stage_apply(layers_local, x_in)
            # This tick was real work iff microbatch t-stage is in range.
            mb = t - stage
            valid = jnp.logical_and(mb >= 0, mb < m)
            aux_total = aux_total + jnp.where(valid, aux, 0.0)
            # Last stage banks finished microbatch t-last.
            out_idx = jnp.clip(t - last, 0, m - 1)
            banked = jax.lax.dynamic_update_index_in_dim(
                outputs, x_out, out_idx, axis=0)
            bank = jnp.logical_and(stage == last,
                                   jnp.logical_and(t - last >= 0,
                                                   t - last < m))
            outputs = jnp.where(bank, banked, outputs)
            # Rotate forward one stage.
            perm = [(i, (i + 1) % nstages) for i in range(nstages)]
            carry = jax.lax.ppermute(x_out, AXIS_PP, perm)
            return (carry, outputs, aux_total), None

        (carry, outputs, aux_total), _ = jax.lax.scan(
            tick, (carry, outputs, aux_total), jnp.arange(ticks))

        # Outputs live on the last stage, aux on every stage for its own
        # layers; psum makes both pp-invariant (replicated) again.
        outputs = jax.lax.psum(
            jnp.where(stage == last, outputs,
                      jnp.zeros_like(outputs)).astype(jnp.float32),
            AXIS_PP)                # f32 out: bf16 can't cross the boundary
        # Mean over microbatches: each microbatch's aux is a statistic over
        # B/M tokens; averaging matches the dense path's full-batch scale.
        aux_total = jax.lax.psum(aux_total, AXIS_PP) / m
        return outputs, aux_total

    fn = jax.shard_map(
        pipeline_body, mesh=mesh,
        in_specs=(P(AXIS_PP), P()), out_specs=(P(), P()),
        axis_names={AXIS_PP})
    outputs, aux = fn(staged, x_mb)

    x = outputs.astype(dt).reshape(b, s, h)
    return llama.rms_norm(x, params["final_norm"], cfg.norm_eps), aux
