"""Cross-host pipeline parallelism over compiled-DAG channels.

The second PP story (VERDICT r3 #10). `models/pipeline.py` is GPipe
INSIDE one pjit program — right for one slice, where stage hops ride
ICI. ACROSS hosts/slices there is no shared XLA program: each stage is
an actor owning its layer shard, and activations hop between them over
the compiled-DAG channel layer (reference parity: the compiled-graph PP
role, python/ray/dag/compiled_dag_node.py:805 with NCCL channels; here
shm/DCN channels + jax arrays).

    stages = build_pipeline_stages(cfg, n_stages=2, seed=0)
    pipe = CompiledPipeline(stages)
    logits = pipe.forward_batches([tok0, tok1, tok2])   # pipelined
    pipe.teardown()

Microbatch k+1 enters stage 0 while microbatch k is still inside stage
1 — the channel write/read is the hand-off, so stage computes overlap
(test_pipeline_adag asserts the wall-clock overlap and that logits
match the single-process forward bit-for-bit).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from ..dag.dag_node import InputNode

__all__ = ["PipelineStage", "build_pipeline_stages", "CompiledPipeline"]


class PipelineStage:
    """One actor-hosted stage: a contiguous slice of decoder layers.

    Stage 0 also owns the embedding; the last stage owns the final norm
    + lm_head. Params are materialized INSIDE the actor and only the
    stage's slice is kept live/device_put. (Init transiently draws the
    full tree on host so stage weights match llama.init_params' seeds
    exactly; for models beyond host RAM, swap the init for a per-stage
    checkpoint load — the pipeline itself never moves non-stage
    weights.)"""

    def __init__(self, cfg_dict: Dict[str, Any], stage: int,
                 n_stages: int, seed: int,
                 compute_delay_s: float = 0.0):
        import jax
        import jax.numpy as jnp

        from . import llama

        self.cfg = llama.config(llama.LlamaConfig(**cfg_dict))
        self.stage = stage
        self.n_stages = n_stages
        self.delay = compute_delay_s
        L = self.cfg.n_layers
        lo = (L * stage) // n_stages
        hi = (L * (stage + 1)) // n_stages
        full = llama.init_params(self.cfg, jax.random.PRNGKey(seed))
        params: Dict[str, Any] = {
            "layers": jax.tree.map(lambda a: np.array(a[lo:hi]),
                                   full["layers"])}
        if stage == 0:
            params["embed"] = full["embed"]
        if stage == n_stages - 1:
            params["final_norm"] = full["final_norm"]
            params["lm_head"] = full["lm_head"]
        del full                       # only the slice stays live
        self.params = jax.device_put(params)

        cfg = self.cfg

        def run(params, x):
            from .llama import (_head_logits, rms_norm, rope_frequencies,
                                decoder_layer)
            if stage == 0:
                x = params["embed"].astype(cfg.dtype)[x]
            else:
                x = x.astype(cfg.dtype)
            s = x.shape[1]
            cos, sin = rope_frequencies(cfg, jnp.arange(s))
            fn = lambda h, layer: decoder_layer(cfg, h, layer, cos, sin,
                                                None)
            x, _ = jax.lax.scan(fn, x, params["layers"])
            if stage == n_stages - 1:
                x = rms_norm(x, params["final_norm"], cfg.norm_eps)
                return _head_logits(cfg, x, params["lm_head"])
            return x

        self._fn = jax.jit(run)

    def forward(self, x):
        import time
        out = np.asarray(self._fn(self.params, np.asarray(x)))  # jaxlint: disable=JL005 -- actor boundary: the stage output crosses a process hop as numpy, there is nothing to overlap with
        if self.delay:
            time.sleep(self.delay)   # stands in for a bigger stage on
        return out                   # a 1-core test box (overlap proof)

    def ping(self) -> int:
        return self.stage


def build_pipeline_stages(cfg, n_stages: int = 2, seed: int = 0,
                          compute_delay_s: float = 0.0) -> List[Any]:
    """Spawn one PipelineStage actor per stage (own process each)."""
    import dataclasses

    from . import llama
    cfg = llama.config(cfg)
    cls = ray_tpu.remote(num_cpus=0)(PipelineStage)
    stages = [cls.remote(dataclasses.asdict(cfg), i, n_stages, seed,
                         compute_delay_s)
              for i in range(n_stages)]
    ray_tpu.get([s.ping.remote() for s in stages])   # constructed
    return stages


class CompiledPipeline:
    """The stage chain compiled onto channels; execute() per microbatch."""

    def __init__(self, stages: List[Any], buffer_size: int = 16 << 20,
                 cfg=None):
        self.stages = stages
        self._buffer_size = buffer_size
        self._cfg = cfg
        with InputNode() as inp:
            node = inp
            for s in stages:
                node = s.forward.bind(node)
        self._cd = node.experimental_compile(buffer_size=buffer_size)

    def _check_fits(self, tok: np.ndarray) -> None:
        """Fail fast with the real cause: an oversized stage payload
        otherwise dies inside the actor loop and surfaces 120s later as
        an opaque read timeout."""
        if self._cfg is None:
            return
        b, s = tok.shape
        logits = b * s * self._cfg.vocab_size * 4
        hidden = b * s * self._cfg.hidden * 4
        need = max(logits, hidden) + (1 << 16)
        if need > self._buffer_size:
            raise ValueError(
                f"stage payload up to ~{need} bytes exceeds channel "
                f"buffer_size={self._buffer_size}; pass "
                f"CompiledPipeline(..., buffer_size={need})")

    def forward_batches(self, token_batches: List[np.ndarray],
                        timeout: Optional[float] = 120.0
                        ) -> List[np.ndarray]:
        """Pipelined forward: microbatch i+1 enters stage 0 while i is
        inside stage 1. In-flight submissions are capped at the
        pipeline's buffering depth (one unacked value per channel slot)
        — submitting deeper than that can't add overlap and deadlocks
        the single-threaded driver against the full input channel."""
        token_batches = [np.asarray(t) for t in token_batches]
        if token_batches:
            self._check_fits(max(token_batches, key=lambda t: t.size))
        depth = len(self.stages) + 1
        out: List[np.ndarray] = []
        refs: List[Any] = []
        for t in token_batches:
            if len(refs) >= depth:
                out.append(refs.pop(0).get(timeout=timeout))
            refs.append(self._cd.execute(t))
        for r in refs:
            out.append(r.get(timeout=timeout))
        return out

    def teardown(self) -> None:
        self._cd.teardown()
