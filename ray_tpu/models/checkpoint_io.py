"""Sharding-aware safetensors checkpoint IO for the Llama family.

Role of the reference's vLLM weight loading
(/root/reference/python/ray/llm/_internal/serve/deployments/llm/vllm/
vllm_engine.py:57-63 delegates to vLLM; vLLM reads HF safetensors into
torch), rebuilt TPU-native:

- a self-contained safetensors parser (the format is 8-byte little-endian
  header length + JSON header + raw tensor bytes) over ``np.memmap`` so
  slice reads touch only the bytes a shard needs — no torch, no
  full-tensor materialization;
- an HF-Llama name/layout mapping onto this repo's stacked-layer pytree
  (``models/llama.py`` ``init_params``: per-layer weights stacked on a
  leading ``layers`` axis, matmul weights stored input-major, i.e. the
  TRANSPOSE of HF's (out, in) torch linear layout);
- per-shard loading onto a ``jax.sharding.Mesh`` via
  ``jax.make_array_from_callback``: each device's addressable shard
  triggers one windowed read of exactly its slice (per-host shard reads
  on an fsdp×tp mesh — the multi-host case reads only the host's
  shards), cast to the target dtype shard-by-shard so host memory stays
  bounded at the largest single shard;
- a writer (HF layout, size-sharded files + ``model.safetensors.index
  .json``) so tests round-trip and trained params export back to the
  ecosystem format.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
import ml_dtypes

from . import llama
from ..parallel.sharding import named_sharding

_DTYPES: Dict[str, Any] = {
    "F64": np.float64, "F32": np.float32, "F16": np.float16,
    "BF16": ml_dtypes.bfloat16, "I64": np.int64, "I32": np.int32,
    "I16": np.int16, "I8": np.int8, "U8": np.uint8, "BOOL": np.bool_,
    "U16": np.uint16, "U32": np.uint32, "U64": np.uint64,
}
_DTYPE_NAMES = {np.dtype(v): k for k, v in _DTYPES.items()}


class SafeTensorsFile:
    """Zero-copy reader: tensors are memory-mapped views; ``read`` with a
    numpy index touches only the pages the slice spans."""

    def __init__(self, path: str):
        self.path = path
        with open(path, "rb") as f:
            hlen = int.from_bytes(f.read(8), "little")
            header = json.loads(f.read(hlen).decode("utf-8"))
        self.metadata = header.pop("__metadata__", {})
        self.tensors: Dict[str, dict] = header
        self._base = 8 + hlen
        self._mm = np.memmap(path, np.uint8, mode="r")

    def keys(self) -> List[str]:
        return list(self.tensors)

    def info(self, name: str) -> Tuple[Tuple[int, ...], np.dtype]:
        ent = self.tensors[name]
        return tuple(ent["shape"]), np.dtype(_DTYPES[ent["dtype"]])

    def read(self, name: str, index: Any = None) -> np.ndarray:
        ent = self.tensors[name]
        dt = np.dtype(_DTYPES[ent["dtype"]])
        a, b = ent["data_offsets"]
        arr = self._mm[self._base + a:self._base + b].view(dt)
        arr = arr.reshape(tuple(ent["shape"]))
        return arr if index is None else arr[index]


def write_safetensors(path: str, tensors: Dict[str, np.ndarray],
                      metadata: Optional[Dict[str, str]] = None) -> None:
    header: Dict[str, Any] = {}
    if metadata:
        header["__metadata__"] = {k: str(v) for k, v in metadata.items()}
    arrays = []
    off = 0
    for name, t in tensors.items():
        t = np.ascontiguousarray(t)
        arrays.append(t)
        header[name] = {"dtype": _DTYPE_NAMES[t.dtype],
                        "shape": list(t.shape),
                        "data_offsets": [off, off + t.nbytes]}
        off += t.nbytes
    hjson = json.dumps(header).encode("utf-8")
    hjson += b" " * (-len(hjson) % 8)          # HF pads headers with spaces
    with open(path, "wb") as f:
        f.write(len(hjson).to_bytes(8, "little"))
        f.write(hjson)
        for t in arrays:
            f.write(t.tobytes())


class _FileSet:
    """Resolves tensor names across a single- or index-sharded checkpoint
    directory; files open lazily and stay cached (mmap is cheap)."""

    def __init__(self, ckpt_dir: str):
        self.dir = ckpt_dir
        self._open: Dict[str, SafeTensorsFile] = {}
        index = os.path.join(ckpt_dir, "model.safetensors.index.json")
        if os.path.exists(index):
            with open(index) as f:
                self.weight_map: Dict[str, str] = json.load(f)["weight_map"]
        else:
            single = os.path.join(ckpt_dir, "model.safetensors")
            if os.path.isfile(ckpt_dir):           # direct file path
                single, self.dir = ckpt_dir, os.path.dirname(ckpt_dir)
            st = SafeTensorsFile(single)
            self._open[os.path.basename(single)] = st
            self.weight_map = {k: os.path.basename(single)
                               for k in st.keys()}

    def __contains__(self, name: str) -> bool:
        return name in self.weight_map

    def file(self, name: str) -> SafeTensorsFile:
        fname = self.weight_map[name]
        if fname not in self._open:
            self._open[fname] = SafeTensorsFile(
                os.path.join(self.dir, fname))
        return self._open[fname]

    def read(self, name: str, index: Any = None) -> np.ndarray:
        return self.file(name).read(name, index)

    def info(self, name: str) -> Tuple[Tuple[int, ...], np.dtype]:
        return self.file(name).info(name)


# ------------------------------------------------------------------ HF naming

_L = "model.layers.{l}."


def _norm_index(index, shape) -> Tuple[slice, ...]:
    """make_array_from_callback hands a tuple of slices (possibly with
    None bounds); normalize to concrete per-dim slices."""
    if index is None:
        index = (slice(None),) * len(shape)
    out = []
    for dim, sl in zip(shape, index):
        start = 0 if sl.start is None else sl.start
        stop = dim if sl.stop is None else sl.stop
        out.append(slice(start, stop))
    return tuple(out)


class _Leaf:
    """One target-pytree leaf: target shape + a slice reader."""

    def __init__(self, shape: Tuple[int, ...],
                 read: Callable[[Tuple[slice, ...]], np.ndarray]):
        self.shape = shape
        self._read = read

    def read(self, index) -> np.ndarray:
        return self._read(_norm_index(index, self.shape))


def _direct(files: _FileSet, name: str, shape) -> _Leaf:
    return _Leaf(tuple(shape), lambda idx: files.read(name, idx))


def _transposed(files: _FileSet, name: str, shape) -> _Leaf:
    """Target = HF tensor transposed: read the swapped slice, then .T —
    only the requested window crosses the mmap."""
    def read(idx):
        r, c = idx
        return files.read(name, (c, r)).T
    return _Leaf(tuple(shape), read)


def _stacked(files: _FileSet, fmt: str, shape,
             transpose: bool) -> _Leaf:
    """Target (L, *rest) stacking per-layer HF tensors on a new leading
    axis; per-layer windows read independently so a layer-sharded (pp)
    load touches only its layers."""
    def read(idx):
        lsl, rest = idx[0], idx[1:]
        per = []
        for l in range(lsl.start, lsl.stop):
            name = fmt.format(l=l)
            if transpose:
                r, c = rest
                per.append(files.read(name, (c, r)).T)
            else:
                per.append(files.read(name, rest))
        return np.stack(per)
    return _Leaf(tuple(shape), read)


def _stacked_experts(files: _FileSet, fmt: str, shape) -> _Leaf:
    """Target (L, E, a, b) from per-layer-per-expert HF tensors stored
    (b, a) (Mixtral block_sparse_moe layout)."""
    def read(idx):
        lsl, esl, a, b = idx
        layers = []
        for l in range(lsl.start, lsl.stop):
            experts = [files.read(fmt.format(l=l, e=e), (b, a)).T
                       for e in range(esl.start, esl.stop)]
            layers.append(np.stack(experts))
        return np.stack(layers)
    return _Leaf(tuple(shape), read)


def _llama_leaf_specs(cfg: llama.LlamaConfig,
                      files: _FileSet) -> Dict[str, Any]:
    """Pytree of _Leaf readers mirroring init_params' structure."""
    h, L, v = cfg.hidden, cfg.n_layers, cfg.vocab_size
    if cfg.n_experts:
        E, F = cfg.n_experts, cfg.ffn
        mlp = {
            "router": _stacked(
                files, _L + "block_sparse_moe.gate.weight",
                (L, h, E), transpose=True),
            # Mixtral: w1=gate, w3=up(in), w2=down
            "wg": _stacked_experts(
                files, _L + "block_sparse_moe.experts.{e}.w1.weight",
                (L, E, h, F)),
            "wi": _stacked_experts(
                files, _L + "block_sparse_moe.experts.{e}.w3.weight",
                (L, E, h, F)),
            "wd": _stacked_experts(
                files, _L + "block_sparse_moe.experts.{e}.w2.weight",
                (L, E, F, h)),
        }
    else:
        mlp = {
            "wg": _stacked(files, _L + "mlp.gate_proj.weight",
                           (L, h, cfg.ffn), transpose=True),
            "wi": _stacked(files, _L + "mlp.up_proj.weight",
                           (L, h, cfg.ffn), transpose=True),
            "wd": _stacked(files, _L + "mlp.down_proj.weight",
                           (L, cfg.ffn, h), transpose=True),
        }
    if "lm_head.weight" in files:
        lm_head = _transposed(files, "lm_head.weight", (h, v))
    else:   # tied embeddings (Llama-3.2 1B/3B ship no lm_head tensor)
        lm_head = _transposed(files, "model.embed_tokens.weight", (h, v))
    return {
        "embed": _direct(files, "model.embed_tokens.weight", (v, h)),
        "layers": {
            "wq": _stacked(files, _L + "self_attn.q_proj.weight",
                           (L, h, cfg.q_dim), transpose=True),
            "wk": _stacked(files, _L + "self_attn.k_proj.weight",
                           (L, h, cfg.kv_dim), transpose=True),
            "wv": _stacked(files, _L + "self_attn.v_proj.weight",
                           (L, h, cfg.kv_dim), transpose=True),
            "wo": _stacked(files, _L + "self_attn.o_proj.weight",
                           (L, cfg.q_dim, h), transpose=True),
            **mlp,
            "ln1": _stacked(files, _L + "input_layernorm.weight",
                            (L, h), transpose=False),
            "ln2": _stacked(files, _L + "post_attention_layernorm.weight",
                            (L, h), transpose=False),
        },
        "final_norm": _direct(files, "model.norm.weight", (h,)),
        "lm_head": lm_head,
    }


def load_llama_params(cfg: llama.LlamaConfig, ckpt_dir: str,
                      mesh: Optional[jax.sharding.Mesh] = None,
                      dtype: Any = None,
                      rules: Optional[Dict] = None) -> Dict[str, Any]:
    """Load an HF-layout Llama safetensors checkpoint into this repo's
    param pytree.

    With ``mesh``, every leaf is built with
    ``jax.make_array_from_callback`` under its logical sharding
    (``param_logical_axes`` + the repo's sharding rules): each
    addressable device's shard is one windowed mmap read + dtype cast —
    a host never materializes more than its own shards.
    """
    dtype = dtype or cfg.param_dtype
    files = _FileSet(ckpt_dir)
    specs = _llama_leaf_specs(cfg, files)
    axes = llama.param_logical_axes(cfg)

    def build(leaf: _Leaf, leaf_axes):
        if mesh is None:
            return jnp.asarray(
                np.asarray(leaf.read(None), dtype=np.dtype(dtype)))
        sharding = named_sharding(mesh, *leaf_axes, rules=rules)
        return jax.make_array_from_callback(
            leaf.shape, sharding,
            lambda idx: np.asarray(leaf.read(idx), dtype=np.dtype(dtype)))

    return jax.tree.map(
        build, specs, axes,
        is_leaf=lambda x: isinstance(x, _Leaf))


def save_llama_checkpoint(cfg: llama.LlamaConfig, params: Dict[str, Any],
                          out_dir: str,
                          max_shard_bytes: int = 4 << 30) -> None:
    """Write params back out in HF Llama safetensors layout (per-layer
    tensors, torch (out, in) orientation, size-sharded files + index)."""
    os.makedirs(out_dir, exist_ok=True)
    layers = params["layers"]

    def np_(x) -> np.ndarray:
        x = np.asarray(x)
        if x.dtype == np.dtype(ml_dtypes.bfloat16):
            return x            # keep BF16 storage
        return x

    tensors: Dict[str, np.ndarray] = {
        "model.embed_tokens.weight": np_(params["embed"]),
        "model.norm.weight": np_(params["final_norm"]),
        "lm_head.weight": np_(params["lm_head"]).T,
    }
    per_layer = {
        "self_attn.q_proj.weight": ("wq", True),
        "self_attn.k_proj.weight": ("wk", True),
        "self_attn.v_proj.weight": ("wv", True),
        "self_attn.o_proj.weight": ("wo", True),
        "input_layernorm.weight": ("ln1", False),
        "post_attention_layernorm.weight": ("ln2", False),
    }
    if cfg.n_experts:
        for l in range(cfg.n_layers):
            tensors[_L.format(l=l) + "block_sparse_moe.gate.weight"] = (
                np_(layers["router"][l]).T)
            for e in range(cfg.n_experts):
                base = _L.format(l=l) + f"block_sparse_moe.experts.{e}."
                tensors[base + "w1.weight"] = np_(layers["wg"][l, e]).T
                tensors[base + "w3.weight"] = np_(layers["wi"][l, e]).T
                tensors[base + "w2.weight"] = np_(layers["wd"][l, e]).T
    else:
        per_layer.update({
            "mlp.gate_proj.weight": ("wg", True),
            "mlp.up_proj.weight": ("wi", True),
            "mlp.down_proj.weight": ("wd", True),
        })
    for l in range(cfg.n_layers):
        for hf_name, (ours, transpose) in per_layer.items():
            t = np_(layers[ours][l])
            tensors[_L.format(l=l) + hf_name] = t.T if transpose else t

    # size-sharded emission
    shards: List[Dict[str, np.ndarray]] = [{}]
    sizes = [0]
    for name, t in tensors.items():
        if sizes[-1] and sizes[-1] + t.nbytes > max_shard_bytes:
            shards.append({})
            sizes.append(0)
        shards[-1][name] = t
        sizes[-1] += t.nbytes
    if len(shards) == 1:
        write_safetensors(
            os.path.join(out_dir, "model.safetensors"), shards[0],
            metadata={"format": "pt"})
        return
    weight_map: Dict[str, str] = {}
    n = len(shards)
    for i, shard in enumerate(shards):
        fname = f"model-{i + 1:05d}-of-{n:05d}.safetensors"
        write_safetensors(os.path.join(out_dir, fname), shard,
                          metadata={"format": "pt"})
        weight_map.update({k: fname for k in shard})
    with open(os.path.join(out_dir, "model.safetensors.index.json"),
              "w") as f:
        json.dump({"metadata": {"total_size": sum(sizes)},
                   "weight_map": weight_map}, f)


def load_config(ckpt_dir: str) -> llama.LlamaConfig:
    """Build a LlamaConfig from an HF ``config.json`` next to the
    checkpoint (hidden/heads/ffn/rope names translated)."""
    with open(os.path.join(ckpt_dir, "config.json")) as f:
        hc = json.load(f)
    n_heads = hc["num_attention_heads"]
    head_dim = hc.get("head_dim") or hc["hidden_size"] // n_heads
    return llama.LlamaConfig(
        vocab_size=hc["vocab_size"], hidden=hc["hidden_size"],
        n_layers=hc["num_hidden_layers"], n_heads=n_heads,
        n_kv_heads=hc.get("num_key_value_heads", n_heads),
        head_dim=head_dim, ffn=hc["intermediate_size"],
        rope_theta=float(hc.get("rope_theta", 500000.0)),
        norm_eps=float(hc.get("rms_norm_eps", 1e-5)),
        max_seq=int(hc.get("max_position_embeddings", 8192)),
        n_experts=int(hc.get("num_local_experts", 0)),
        moe_top_k=int(hc.get("num_experts_per_tok", 2)),
    )


def save_config(cfg: llama.LlamaConfig, out_dir: str) -> None:
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, "config.json"), "w") as f:
        json.dump({
            "architectures": ["LlamaForCausalLM"]
            if not cfg.n_experts else ["MixtralForCausalLM"],
            "vocab_size": cfg.vocab_size, "hidden_size": cfg.hidden,
            "num_hidden_layers": cfg.n_layers,
            "num_attention_heads": cfg.n_heads,
            "num_key_value_heads": cfg.n_kv_heads,
            "head_dim": cfg.head_dim,
            "intermediate_size": cfg.ffn, "rope_theta": cfg.rope_theta,
            "rms_norm_eps": cfg.norm_eps,
            "max_position_embeddings": cfg.max_seq,
            "num_local_experts": cfg.n_experts,
            "num_experts_per_tok": cfg.moe_top_k,
        }, f)
