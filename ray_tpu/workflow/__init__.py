"""Workflows: durable task-DAG execution with checkpoint/resume.

Reference parity: python/ray/workflow (workflow_executor.py:32
WorkflowExecutor, workflow_state_from_dag.py, workflow_storage.py) — a
task DAG built with `fn.bind(...)` runs with every step's result
checkpointed, so a crashed/killed run resumes from the last completed
step instead of recomputing.

    @ray_tpu.remote
    def add(a, b): return a + b

    dag = add.bind(add.bind(1, 2), 10)
    workflow.run(dag, workflow_id="my-flow")      # -> 13
    workflow.resume("my-flow")                    # no-op: already done

Dynamic workflows (reference: workflow/api.py:776 `continuation`): a
step may return `workflow.continuation(another.bind(...))`; the
returned sub-DAG is spliced into the run and its output becomes the
step's result — recursion (factorial-style) expresses loops whose
length is only known at runtime. Continuation checkpoints are
namespaced under the parent step, so resume works mid-expansion.

Per-step options (reference: step options) via
`fn.bind(...).options(max_retries=2, catch_exceptions=True)`:
max_retries re-runs a crashed/failed step; catch_exceptions makes the
step's value `(result, None)` / `(None, exception)` instead of failing
the workflow.

Step identity is structural (function name + position in the DAG), so a
resumed run maps checkpoints back to the same steps. Steps with all
dependencies ready execute in parallel as normal ray_tpu tasks.

Storage rides the train.storage pyarrow-fs layer, so
RAY_TPU_WORKFLOW_STORAGE may be a local dir, gs://bucket/prefix, or
mock:// (tests).
"""

from __future__ import annotations

from .._private.usage import record_library_usage as _rlu
_rlu("workflow")
del _rlu

import json
import os
import posixpath
import time

import cloudpickle
from typing import Any, Dict, List, Optional

from ..dag.dag_node import DAGNode, FunctionNode

__all__ = ["run", "resume", "get_output", "get_status", "list_all",
           "delete", "storage_dir", "continuation", "Continuation",
           "wait_for_event", "EventListener", "HTTPEventListener",
           "start_http_event_provider"]

from .events import (EventListener, HTTPEventListener,  # noqa: E402
                     start_http_event_provider, wait_for_event)

_STATUS = ("RUNNING", "SUCCESSFUL", "FAILED", "NOT_FOUND")


def storage_dir(workflow_id: Optional[str] = None) -> str:
    # env read per call: tests and tools point storage at temp dirs
    from ..train.storage import join
    base = os.environ.get(
        "RAY_TPU_WORKFLOW_STORAGE",
        None) or "/tmp/ray_tpu/workflows"
    return join(base, workflow_id) if workflow_id else base


class Continuation:
    """Marker a step returns to splice a sub-DAG into the workflow."""

    def __init__(self, dag: FunctionNode):
        if not isinstance(dag, FunctionNode):
            raise TypeError("continuation expects a fn.bind(...) node")
        self.dag = dag


def continuation(dag: FunctionNode) -> Continuation:
    """Return this from a workflow step to continue with a sub-DAG
    (reference parity: python/ray/workflow/api.py:776)."""
    return Continuation(dag)


# ---------------------------------------------------------------- planning

def _topo_steps(dag: FunctionNode) -> List[FunctionNode]:
    """Deterministic post-order of the DAG (dedup by identity): children
    before parents, stable across runs of the same DAG shape."""
    seen: Dict[int, FunctionNode] = {}
    order: List[FunctionNode] = []

    def visit(node: FunctionNode) -> None:
        if id(node) in seen:
            return
        seen[id(node)] = node
        for up in node._upstream():
            if not isinstance(up, FunctionNode):
                raise TypeError(
                    f"workflow DAGs are built from fn.bind(...) nodes; "
                    f"got {type(up).__name__}")
            visit(up)
        order.append(node)

    visit(dag)
    return order


def _step_ids(steps: List[FunctionNode], prefix: str = "") -> Dict[int, str]:
    counts: Dict[str, int] = {}
    ids: Dict[int, str] = {}
    for s in steps:
        n = counts.get(s.name, 0)
        counts[s.name] = n + 1
        ids[id(s)] = f"{prefix}{s.name}_{n}"
    return ids


# ---------------------------------------------------------------- storage
# All IO goes through the pyarrow-fs layer (train/storage.py) so the
# base may be a cloud URI; local writes stay rename-atomic.

def _fs_and(path: str):
    from ..train.storage import get_fs_and_path
    return get_fs_and_path(path)


_ENSURED_DIRS: set = set()


def _write_bytes(path: str, data: bytes) -> None:
    from ..train.storage import is_uri
    if is_uri(path):
        fs, p = _fs_and(path)
        parent = posixpath.dirname(p)
        # one create_dir per workflow dir per process, not per write
        if parent and (id(fs), parent) not in _ENSURED_DIRS:
            fs.create_dir(parent, recursive=True)
            _ENSURED_DIRS.add((id(fs), parent))
        with fs.open_output_stream(p) as f:
            f.write(data)
        return
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)


def _read_bytes(path: str) -> Optional[bytes]:
    from ..train.storage import is_uri
    if is_uri(path):
        fs, p = _fs_and(path)
        if fs.get_file_info([p])[0].type.name == "NotFound":
            return None
        with fs.open_input_stream(p) as f:
            return f.read()
    if not os.path.exists(path):
        return None
    with open(path, "rb") as f:
        return f.read()


def _join(*parts: str) -> str:
    from ..train.storage import join
    return join(*parts)


def _write_json(path: str, data: dict) -> None:
    _write_bytes(path, json.dumps(data).encode())


def _checkpoint(wf_dir: str, step_id: str, value: Any) -> None:
    _write_bytes(_join(wf_dir, f"{step_id}.pkl"), cloudpickle.dumps(value))


def _load_checkpoint(wf_dir: str, step_id: str):
    blob = _read_bytes(_join(wf_dir, f"{step_id}.pkl"))
    if blob is None:
        return False, None
    import pickle
    return True, pickle.loads(blob)


# --------------------------------------------------------------- execution

class _StepFailed(Exception):
    def __init__(self, step_id: str, cause: Exception):
        super().__init__(f"workflow step {step_id!r} failed: {cause!r}")
        self.step_id = step_id
        self.cause = cause


def _execute(wf_dir: str, dag: FunctionNode) -> Any:
    """Event-driven scheduler over the DAG, with dynamic expansion.

    State:
      results[sid]  materialized step outputs (satisfy dependents)
      pending[sid]  in-flight object refs
      remaining     not yet launched
      expansions    sid -> sub-root sid whose result resolves sid
    """
    import ray_tpu

    steps = _topo_steps(dag)
    ids = _step_ids(steps)
    root_id = ids[id(dag)]
    results: Dict[str, Any] = {}
    pending: Dict[str, Any] = {}
    retries_left: Dict[str, int] = {}
    nodes: Dict[str, FunctionNode] = {}
    remaining: Dict[str, FunctionNode] = {}
    dep_ids: Dict[str, List[str]] = {}
    expansions: Dict[str, str] = {}      # parent sid -> sub-root sid
    cont_counts: Dict[str, int] = {}
    progress = 0     # monotonic: launches + finishes + expansions

    def add_steps(new_steps, new_ids):
        for s in new_steps:
            sid = new_ids[id(s)]
            if sid in nodes:
                continue
            nodes[sid] = s
            remaining[sid] = s
            dep_ids[sid] = [new_ids[id(u)] for u in s._upstream()]
            retries_left[sid] = int(
                s.workflow_options.get("max_retries", 0))

    add_steps(steps, ids)

    def finish(sid: str, value: Any, error: Exception = None,
               from_checkpoint: bool = False) -> None:
        """A step's outcome arrived (raw value, continuation, or error):
        expand, or materialize the (possibly catch-wrapped) result.

        Owns ALL catch_exceptions handling so the option follows a step
        through continuations: a sub-DAG failure walks up `expansions`
        until a catching ancestor absorbs it (or fails the workflow),
        and a sub-DAG success is wrapped at the catching parent — not
        bypassed (checkpointed values are already final: no re-wrap)."""
        nonlocal progress
        progress += 1
        if sid in results:
            # Already resolved — e.g. two failing sibling sub-steps both
            # routing to the same catching ancestor: the first outcome
            # won and was checkpointed; a second finish would overwrite
            # it and diverge live-run vs resume.
            return
        node = nodes.get(sid)
        catching = bool(node is not None
                        and node.workflow_options.get("catch_exceptions"))
        if error is not None and not (catching and not from_checkpoint):
            parent = expansions.pop(sid, None)
            if parent is None and "+" in sid:
                # `expansions` only maps sub-DAG ROOTS to their parent;
                # a failing NON-root sub-step still has a catching
                # chance at the expanding ancestor. Ids are namespaced
                # `{parent}+{n}.{name}_{k}`, so the innermost expanding
                # parent is everything before the last '+'.
                parent = sid[:sid.rfind("+")]
            if parent is not None:
                finish(parent, None, error)
                return
            raise _StepFailed(sid, error)
        if error is None and isinstance(value, Continuation):
            n = cont_counts.get(sid, 0)
            cont_counts[sid] = n + 1
            # deterministic namespace so resume maps sub-checkpoints
            prefix = f"{sid}+{n}."
            sub_steps = _topo_steps(value.dag)
            sub_ids = _step_ids(sub_steps, prefix=prefix)
            # checkpoint the continuation itself: a resumed run
            # re-expands without re-running the parent step
            if not from_checkpoint:
                _checkpoint(wf_dir, sid, value)
            add_steps(sub_steps, sub_ids)
            expansions[sub_ids[id(value.dag)]] = sid
            return
        if catching and not from_checkpoint:
            final = (None, error) if error is not None else (value, None)
        else:
            final = value
        if not from_checkpoint:
            _checkpoint(wf_dir, sid, final)
        results[sid] = final
        # a completed sub-root resolves its parent: a catching sub-root
        # absorbed/wrapped the outcome, so its FINAL is what the parent
        # sees; otherwise the raw value flows up for the parent to apply
        # its own policy
        parent = expansions.pop(sid, None)
        if parent is not None:
            if catching and not from_checkpoint:
                finish(parent, final)
            else:
                finish(parent, value)

    def launch_ready() -> None:
        for sid, node in list(remaining.items()):
            if any(d not in results for d in dep_ids[sid]):
                continue
            del remaining[sid]
            done, value = _load_checkpoint(wf_dir, sid)
            if done:
                finish(sid, value, from_checkpoint=True)
                continue
            fn = node.remote_fn

            def resolve(v):
                return results[ids_of(v)] if isinstance(v, FunctionNode) \
                    else v

            def ids_of(n):
                # dep ids were precomputed; find via nodes mapping
                for d in dep_ids[sid]:
                    if nodes[d] is n:
                        return d
                raise KeyError(repr(n))

            nonlocal progress
            progress += 1
            pending[sid] = fn.remote(
                *[resolve(a) for a in node.args],
                **{k: resolve(v) for k, v in node.kwargs.items()})

    while True:
        state = progress
        launch_ready()
        if root_id in results:
            break
        if not pending:
            if not remaining:
                break
            if progress == state:
                # no launch, no expansion, nothing in flight: the
                # remaining steps' dependencies can never materialize —
                # fail loudly instead of busy-spinning
                raise RuntimeError(
                    f"workflow deadlocked: steps {sorted(remaining)} "
                    f"have unsatisfiable dependencies")
            # resuming a chain of checkpointed continuations expands new
            # steps inside launch_ready — give them a pass
            continue
        by_oid = {ref.id: sid for sid, ref in pending.items()}
        ready, _ = ray_tpu.wait(list(pending.values()), num_returns=1)
        for r in ready:
            sid = by_oid[r.id]
            node = nodes[sid]
            del pending[sid]
            try:
                value = ray_tpu.get(r)
            except Exception as e:
                # step-level retries cover application exceptions AND
                # worker crashes (reference: WorkflowStepRuntimeOptions
                # max_retries)
                if retries_left.get(sid, 0) > 0:
                    retries_left[sid] -= 1
                    remaining[sid] = node     # relaunch next pass
                    continue
                finish(sid, None, e)
                continue
            finish(sid, value)

    return results[root_id]


def run(dag: FunctionNode, workflow_id: Optional[str] = None) -> Any:
    """Execute the DAG durably; returns the root step's result. Re-running
    an existing workflow_id resumes it (completed steps are not re-run)."""
    if not isinstance(dag, FunctionNode):
        raise TypeError("workflow.run expects a fn.bind(...) DAG node")
    workflow_id = workflow_id or f"wf-{int(time.time())}-{os.getpid()}"
    wf_dir = storage_dir(workflow_id)
    from ..train.storage import is_uri
    if not is_uri(wf_dir):
        os.makedirs(wf_dir, exist_ok=True)

    _write_json(_join(wf_dir, "status.json"), {
        "workflow_id": workflow_id, "status": "RUNNING",
        "start_time": time.time(),
    })
    # the DAG itself is persisted so resume() can re-execute it
    # (cloudpickle: DAGs routinely close over locally-defined functions)
    _write_bytes(_join(wf_dir, "dag.pkl"), cloudpickle.dumps(dag))

    try:
        output = _execute(wf_dir, dag)
    except Exception as e:
        _write_json(_join(wf_dir, "status.json"), {
            "workflow_id": workflow_id, "status": "FAILED",
            "error": repr(e), "end_time": time.time(),
        })
        raise

    _checkpoint(wf_dir, "__output__", output)
    _write_json(_join(wf_dir, "status.json"), {
        "workflow_id": workflow_id, "status": "SUCCESSFUL",
        "end_time": time.time(),
    })
    return output


def resume(workflow_id: str) -> Any:
    """Resume a previously started workflow from its checkpoints."""
    wf_dir = storage_dir(workflow_id)
    dag_blob = _read_bytes(_join(wf_dir, "dag.pkl"))
    if dag_blob is None:
        raise ValueError(f"workflow {workflow_id!r} not found")
    done, output = _load_checkpoint(wf_dir, "__output__")
    if done:
        return output
    import pickle
    return run(pickle.loads(dag_blob), workflow_id=workflow_id)


def get_output(workflow_id: str) -> Any:
    done, output = _load_checkpoint(storage_dir(workflow_id), "__output__")
    if not done:
        raise ValueError(f"workflow {workflow_id!r} has no output "
                         f"(status: {get_status(workflow_id)})")
    return output


def get_status(workflow_id: str) -> str:
    blob = _read_bytes(_join(storage_dir(workflow_id), "status.json"))
    if blob is None:
        return "NOT_FOUND"
    return json.loads(blob)["status"]


def list_all(status_filter: Optional[str] = None) -> List[tuple]:
    from ..train.storage import is_uri
    base = storage_dir()
    out = []
    if is_uri(base):
        from pyarrow.fs import FileSelector
        fs, p = _fs_and(base)
        if fs.get_file_info([p])[0].type.name == "NotFound":
            return out
        wids = sorted(i.base_name for i in
                      fs.get_file_info(FileSelector(p))
                      if i.type.name == "Directory")
    else:
        if not os.path.isdir(base):
            return out
        wids = sorted(os.listdir(base))
    for wid in wids:
        status = get_status(wid)
        if status == "NOT_FOUND":
            continue
        if status_filter is None or status == status_filter:
            out.append((wid, status))
    return out


def delete(workflow_id: str) -> None:
    from ..train.storage import delete_dir, is_uri
    path = storage_dir(workflow_id)
    if is_uri(path):
        delete_dir(path)
    else:
        import shutil
        shutil.rmtree(path, ignore_errors=True)
