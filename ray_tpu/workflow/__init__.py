"""Workflows: durable task-DAG execution with checkpoint/resume.

Reference parity: python/ray/workflow (workflow_executor.py:32
WorkflowExecutor, workflow_state_from_dag.py, storage layer) — a task DAG
built with `fn.bind(...)` runs with every step's result checkpointed, so
a crashed/killed run resumes from the last completed step instead of
recomputing.

    @ray_tpu.remote
    def add(a, b): return a + b

    dag = add.bind(add.bind(1, 2), 10)
    workflow.run(dag, workflow_id="my-flow")      # -> 13
    workflow.resume("my-flow")                    # no-op: already done

Step identity is structural (function name + position in the DAG), so a
resumed run maps checkpoints back to the same steps. Steps with all
dependencies ready execute in parallel as normal ray_tpu tasks.
"""

from __future__ import annotations

import json
import os
import pickle
import time

import cloudpickle
from typing import Any, Dict, List, Optional

from ..dag.dag_node import DAGNode, FunctionNode

__all__ = ["run", "resume", "get_output", "get_status", "list_all",
           "delete", "storage_dir"]

_STATUS = ("RUNNING", "SUCCESSFUL", "FAILED", "NOT_FOUND")


def storage_dir(workflow_id: Optional[str] = None) -> str:
    # env read per call: tests and tools point storage at temp dirs
    base = os.environ.get(
        "RAY_TPU_WORKFLOW_STORAGE",
        None) or "/tmp/ray_tpu/workflows"
    return os.path.join(base, workflow_id) if workflow_id else base


# ---------------------------------------------------------------- planning

def _topo_steps(dag: FunctionNode) -> List[FunctionNode]:
    """Deterministic post-order of the DAG (dedup by identity): children
    before parents, stable across runs of the same DAG shape."""
    seen: Dict[int, FunctionNode] = {}
    order: List[FunctionNode] = []

    def visit(node: FunctionNode) -> None:
        if id(node) in seen:
            return
        seen[id(node)] = node
        for up in node._upstream():
            if not isinstance(up, FunctionNode):
                raise TypeError(
                    f"workflow DAGs are built from fn.bind(...) nodes; "
                    f"got {type(up).__name__}")
            visit(up)
        order.append(node)

    visit(dag)
    return order


def _step_ids(steps: List[FunctionNode]) -> Dict[int, str]:
    counts: Dict[str, int] = {}
    ids: Dict[int, str] = {}
    for s in steps:
        n = counts.get(s.name, 0)
        counts[s.name] = n + 1
        ids[id(s)] = f"{s.name}_{n}"
    return ids


# ---------------------------------------------------------------- storage

def _write_json(path: str, data: dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(data, f)
    os.replace(tmp, path)


def _checkpoint(wf_dir: str, step_id: str, value: Any) -> None:
    tmp = os.path.join(wf_dir, f"{step_id}.pkl.tmp")
    with open(tmp, "wb") as f:
        pickle.dump(value, f)
    os.replace(tmp, os.path.join(wf_dir, f"{step_id}.pkl"))


def _load_checkpoint(wf_dir: str, step_id: str):
    path = os.path.join(wf_dir, f"{step_id}.pkl")
    if not os.path.exists(path):
        return False, None
    with open(path, "rb") as f:
        return True, pickle.load(f)


# --------------------------------------------------------------- execution

def run(dag: FunctionNode, workflow_id: Optional[str] = None) -> Any:
    """Execute the DAG durably; returns the root step's result. Re-running
    an existing workflow_id resumes it (completed steps are not re-run)."""
    import ray_tpu

    if not isinstance(dag, FunctionNode):
        raise TypeError("workflow.run expects a fn.bind(...) DAG node")
    workflow_id = workflow_id or f"wf-{int(time.time())}-{os.getpid()}"
    wf_dir = storage_dir(workflow_id)
    os.makedirs(wf_dir, exist_ok=True)

    steps = _topo_steps(dag)
    ids = _step_ids(steps)
    _write_json(os.path.join(wf_dir, "status.json"), {
        "workflow_id": workflow_id, "status": "RUNNING",
        "num_steps": len(steps), "start_time": time.time(),
    })
    # the DAG itself is persisted so resume() can re-execute it
    # (cloudpickle: DAGs routinely close over locally-defined functions)
    with open(os.path.join(wf_dir, "dag.pkl"), "wb") as f:
        cloudpickle.dump(dag, f)

    results: Dict[str, Any] = {}
    pending: Dict[str, Any] = {}        # step_id -> (ref, node)
    remaining = {ids[id(s)]: s for s in steps}

    def resolve(v):
        if isinstance(v, FunctionNode):
            return results[ids[id(v)]]
        return v

    try:
        while remaining or pending:
            # launch every step whose deps are all materialized
            for sid, node in list(remaining.items()):
                deps = [ids[id(u)] for u in node._upstream()]
                if any(d not in results for d in deps):
                    continue
                del remaining[sid]
                done, value = _load_checkpoint(wf_dir, sid)
                if done:
                    results[sid] = value
                    continue
                ref = node.remote_fn.remote(
                    *[resolve(a) for a in node.args],
                    **{k: resolve(v) for k, v in node.kwargs.items()})
                pending[sid] = ref
            if not pending:
                continue
            by_oid = {ref.id: sid for sid, ref in pending.items()}
            ready, _ = ray_tpu.wait(list(pending.values()), num_returns=1)
            for r in ready:
                sid = by_oid[r.id]
                value = ray_tpu.get(r)
                _checkpoint(wf_dir, sid, value)
                results[sid] = value
                del pending[sid]
    except Exception as e:
        _write_json(os.path.join(wf_dir, "status.json"), {
            "workflow_id": workflow_id, "status": "FAILED",
            "num_steps": len(steps), "num_done": len(results),
            "error": repr(e), "end_time": time.time(),
        })
        raise

    output = results[ids[id(dag)]]
    _checkpoint(wf_dir, "__output__", output)
    _write_json(os.path.join(wf_dir, "status.json"), {
        "workflow_id": workflow_id, "status": "SUCCESSFUL",
        "num_steps": len(steps), "num_done": len(results),
        "end_time": time.time(),
    })
    return output


def resume(workflow_id: str) -> Any:
    """Resume a previously started workflow from its checkpoints."""
    wf_dir = storage_dir(workflow_id)
    dag_path = os.path.join(wf_dir, "dag.pkl")
    if not os.path.exists(dag_path):
        raise ValueError(f"workflow {workflow_id!r} not found")
    done, output = _load_checkpoint(wf_dir, "__output__")
    if done:
        return output
    with open(dag_path, "rb") as f:
        dag = pickle.load(f)
    return run(dag, workflow_id=workflow_id)


def get_output(workflow_id: str) -> Any:
    done, output = _load_checkpoint(storage_dir(workflow_id), "__output__")
    if not done:
        raise ValueError(f"workflow {workflow_id!r} has no output "
                         f"(status: {get_status(workflow_id)})")
    return output


def get_status(workflow_id: str) -> str:
    path = os.path.join(storage_dir(workflow_id), "status.json")
    if not os.path.exists(path):
        return "NOT_FOUND"
    with open(path) as f:
        return json.load(f)["status"]


def list_all(status_filter: Optional[str] = None) -> List[tuple]:
    base = storage_dir()
    out = []
    if not os.path.isdir(base):
        return out
    for wid in sorted(os.listdir(base)):
        status = get_status(wid)
        if status == "NOT_FOUND":
            continue
        if status_filter is None or status == status_filter:
            out.append((wid, status))
    return out


def delete(workflow_id: str) -> None:
    import shutil
    shutil.rmtree(storage_dir(workflow_id), ignore_errors=True)
