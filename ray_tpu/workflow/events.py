"""Workflow events: durable waits on external signals.

Reference parity: python/ray/workflow/event_listener.py (EventListener,
wait_for_event) and http_event_provider.py:33 (HTTPEventProvider — a
Serve deployment accepting POSTs that resolve waiting workflow steps).
Rebuilt on this stack's primitives: the provider is a NAMED async actor
hosting a minimal asyncio HTTP endpoint (no web framework dependency),
and an event step is an ordinary checkpointed workflow step — once the
event arrives its payload is durably recorded, so a later resume does
not wait again; a run that crashes BEFORE the event re-arms the wait on
resume, and the event bank inside the provider survives it.

    from ray_tpu import workflow

    provider = workflow.start_http_event_provider()     # named actor
    dag = handle.bind(workflow.wait_for_event("key-1"))
    workflow.run(dag, workflow_id="wf-ev")              # blocks on event

    # elsewhere:  POST /event/send_event/key-1  {"by": "external"}
"""

from __future__ import annotations

import json
from typing import Any, Optional

PROVIDER_NAME = "__workflow_http_event_provider__"


class EventListener:
    """Subclass and implement ``poll_for_event`` (async) to integrate a
    custom event source (reference: event_listener.py EventListener).
    """

    async def poll_for_event(self, *args, **kwargs) -> Any:
        raise NotImplementedError

    async def event_checkpointed(self, event: Any) -> None:
        """Optional ack hook. When overridden, it runs as a FOLLOW-ON
        workflow step — i.e. strictly after the wait step's result is
        checkpointed — so deleting the event from its source here never
        loses it to a crash."""


class HTTPEventListener(EventListener):
    """Waits on the named HTTPEventProvider actor for ``event_key``
    (sync block inside the event step's worker — the provider parks the
    call on a future until the POST arrives)."""

    def wait(self, event_key: str,
             timeout: Optional[float] = None) -> Any:
        import ray_tpu
        provider = ray_tpu.get_actor(PROVIDER_NAME)
        ref = provider.get_event.remote(event_key, timeout=timeout)
        client_timeout = None if timeout is None else timeout + 15.0
        return ray_tpu.get(ref, timeout=client_timeout)


def _make_provider_class():
    import ray_tpu

    @ray_tpu.remote(num_cpus=0, max_concurrency=256)
    class HTTPEventProvider:
        """Bank of events keyed by event_key + a tiny HTTP POST
        endpoint. Async actor: many ``get_event`` calls park on
        futures concurrently.

        Binds 127.0.0.1 by default (reference parity: Serve's
    DEFAULT_HTTP_HOST — the endpoint accepts UNAUTHENTICATED event
    injection, so it must be opt-in to expose; ADVICE.md flagged the
    old 0.0.0.0 default). Set RAY_TPU_EVENT_HTTP_HOST=0.0.0.0 for
    cluster-external signaling.

    HTTP contract (reference http_event_provider.py): POST
        ``/event/send_event/<event_key>`` with a JSON body resolves
        every waiting ``get_event(<event_key>)`` with that payload and
        banks it for late/repeat waiters.
        """

        def __init__(self, port: int = 0):
            # __init__ runs OFF the actor's event loop (sync executor):
            # the HTTP server lazy-starts on the first async method call
            self._events = {}
            self._waiters = {}
            self._want_port = port
            self._port = None
            self._server = None

        async def _ensure_started(self) -> None:
            if self._server is None:
                await self._serve(self._want_port)

        async def _serve(self, port: int) -> None:
            import asyncio

            async def handle(reader, writer):
                try:
                    request = await reader.readline()
                    parts = request.decode("latin-1").split()
                    method, path = (parts + ["", ""])[:2]
                    length = 0
                    while True:
                        line = await reader.readline()
                        if line in (b"\r\n", b"\n", b""):
                            break
                        if line.lower().startswith(b"content-length:"):
                            length = int(line.split(b":")[1])
                    body = await reader.readexactly(length) if length \
                        else b"{}"
                    prefix = "/event/send_event/"
                    if method == "POST" and path.startswith(prefix):
                        key = path[len(prefix):]
                        try:
                            payload = json.loads(body.decode() or "{}")
                        except ValueError:
                            payload = {"raw": body.decode("latin-1")}
                        await self.send_event(key, payload)
                        out = json.dumps({"status": "ok",
                                          "event_key": key}).encode()
                        code = b"200 OK"
                    else:
                        out = b'{"error": "not found"}'
                        code = b"404 Not Found"
                    writer.write(
                        b"HTTP/1.1 " + code
                        + b"\r\nContent-Type: application/json"
                        + b"\r\nContent-Length: "
                        + str(len(out)).encode()
                        + b"\r\nConnection: close\r\n\r\n" + out)
                    await writer.drain()
                except Exception:
                    pass
                finally:
                    try:
                        writer.close()
                    except Exception:
                        pass

            import os
            self._server = await asyncio.start_server(
                handle,
                host=os.environ.get("RAY_TPU_EVENT_HTTP_HOST",
                                    "127.0.0.1"),
                port=port)
            self._port = self._server.sockets[0].getsockname()[1]

        async def get_port(self) -> int:
            await self._ensure_started()
            return self._port

        async def get_bound_host(self) -> str:
            """The address the HTTP listener actually bound
            (introspection for the loopback-by-default contract)."""
            await self._ensure_started()
            return self._server.sockets[0].getsockname()[0]

        async def send_event(self, event_key: str, payload) -> bool:
            """Bank + deliver (also callable directly, without HTTP)."""
            await self._ensure_started()
            self._events[event_key] = payload
            for fut in self._waiters.pop(event_key, []):
                if not fut.done():
                    fut.set_result(payload)
            return True

        async def get_event(self, event_key: str,
                            timeout: float = None):
            import asyncio
            await self._ensure_started()
            if event_key in self._events:
                return self._events[event_key]
            fut = asyncio.get_event_loop().create_future()
            self._waiters.setdefault(event_key, []).append(fut)
            try:
                return await asyncio.wait_for(fut, timeout=timeout)
            finally:
                # timed-out/cancelled waiters must not park an actor
                # concurrency slot (and its future) forever
                waiters = self._waiters.get(event_key)
                if waiters and fut in waiters:
                    waiters.remove(fut)
                    if not waiters:
                        self._waiters.pop(event_key, None)

        async def pop_event(self, event_key: str):
            """Consume a banked event (repeatable-key workflows)."""
            return self._events.pop(event_key, None)

    return HTTPEventProvider


def start_http_event_provider(port: int = 0):
    """Start (or fetch) the named provider actor. Returns its handle;
    ``get_port()`` yields the bound HTTP port."""
    import time as _time

    import ray_tpu
    try:
        return ray_tpu.get_actor(PROVIDER_NAME)
    except Exception:
        pass
    cls = _make_provider_class()
    handle = cls.options(name=PROVIDER_NAME,
                         lifetime="detached").remote(port)
    # concurrent creators race on the name: the loser's creation fails,
    # but the named lookup converges on the winner either way
    try:
        ray_tpu.get(handle.get_port.remote(), timeout=30)
        return handle
    except Exception:
        for _ in range(20):
            try:
                return ray_tpu.get_actor(PROVIDER_NAME)
            except Exception:
                _time.sleep(0.25)
        raise


def wait_for_event(event_key_or_listener, *args,
                   timeout: Optional[float] = None, **kwargs):
    """A DAG node that completes when the event arrives; its payload is
    the step result (checkpointed — resumes never re-wait once the
    event landed). Pass an event-key string for the HTTP provider, or
    an EventListener subclass for custom sources (reference:
    api.py wait_for_event)."""
    import asyncio

    import ray_tpu

    if isinstance(event_key_or_listener, str):
        listener_cls = HTTPEventListener
        args = (event_key_or_listener,) + args
        kwargs.setdefault("timeout", timeout)
    else:
        listener_cls = event_key_or_listener

    @ray_tpu.remote(num_cpus=0)
    def __wait_for_event__(*a, **kw):
        listener = listener_cls()
        if isinstance(listener, HTTPEventListener):
            return listener.wait(*a, **kw)
        to = kw.pop("timeout", timeout)

        async def go():
            coro = listener.poll_for_event(*a, **kw)
            if to is not None:
                coro = asyncio.wait_for(coro, timeout=to)
            return await coro

        return asyncio.run(go())

    node = __wait_for_event__.bind(*args, **kwargs)
    if listener_cls.event_checkpointed is not             EventListener.event_checkpointed:
        # ack in a SEPARATE step: a step only starts after its
        # dependency's result is durably checkpointed, so the listener
        # may safely delete the event from its source here (crash
        # between poll and checkpoint re-polls; crash after checkpoint
        # re-runs only this idempotent ack)
        @ray_tpu.remote(num_cpus=0)
        def __event_checkpointed__(event):
            asyncio.run(listener_cls().event_checkpointed(event))
            return event

        node = __event_checkpointed__.bind(node)
    return node
