"""Native (C++) components, loaded via ctypes.

The .so builds on demand from src/ (g++ is in the base image); failures
degrade gracefully — callers fall back to pure-Python paths.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_lock = threading.Lock()
_libs = {}

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _build_dir() -> str:
    d = os.environ.get("RAY_TPU_BUILD_DIR")
    if d:
        return d
    d = os.path.join(_REPO_ROOT, "build")
    if os.access(os.path.dirname(d), os.W_OK):
        return d
    return os.path.join("/tmp", "ray_tpu_build")


def load_library(name: str, source: str) -> Optional[ctypes.CDLL]:
    """Load build/<name>.so, compiling src/<source> first if needed."""
    with _lock:
        if name in _libs:
            return _libs[name]
        lib = None
        build = _build_dir()
        so_path = os.path.join(build, f"{name}.so")
        src_path = os.path.join(_REPO_ROOT, "src", source)
        try:
            have_src = os.path.exists(src_path)
            if not os.path.exists(so_path) or (
                    have_src and os.path.getmtime(so_path)
                    < os.path.getmtime(src_path)):
                if not have_src:
                    raise FileNotFoundError(src_path)
                os.makedirs(build, exist_ok=True)
                tmp = so_path + f".tmp{os.getpid()}"
                # -lrt: shm_open/shm_unlink live in librt on older
                # glibc (no-op on newer where they moved into libc)
                subprocess.run(
                    ["g++", "-O2", "-std=c++17", "-fPIC", "-Wall",
                     "-shared", "-pthread", "-o", tmp, src_path,
                     "-lrt"],
                    check=True, capture_output=True, timeout=120)
                os.replace(tmp, so_path)   # atomic: racing builders OK
            lib = ctypes.CDLL(so_path)
        except Exception:
            lib = None
        _libs[name] = lib
        return lib
