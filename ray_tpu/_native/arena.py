"""ctypes binding for the C++ shared-memory arena store.

Reference parity: the plasma client's create/seal/get/release surface
(src/ray/object_manager/plasma/client.h) over the native arena in
src/arena_store.cc. Buffers returned are zero-copy memoryviews into the
mapped segment; Ref keeps the refcount held until closed/GC'd.
"""

from __future__ import annotations

import ctypes
from typing import Optional, Tuple

from . import load_library

ID_LEN = 32


def _lib():
    lib = load_library("libarena", "arena_store.cc")
    if lib is None:
        return None
    if not getattr(lib, "_arena_configured", False):
        u64, i64, vp, cp = (ctypes.c_uint64, ctypes.c_int64,
                            ctypes.c_void_p, ctypes.c_char_p)
        lib.arena_create.restype = vp
        lib.arena_create.argtypes = [cp, u64, u64]
        lib.arena_attach.restype = vp
        lib.arena_attach.argtypes = [cp]
        lib.arena_alloc.restype = i64
        lib.arena_alloc.argtypes = [vp, cp, u64]
        lib.arena_seal.argtypes = [vp, cp]
        lib.arena_get.argtypes = [vp, cp, ctypes.POINTER(u64),
                                  ctypes.POINTER(u64)]
        lib.arena_release.argtypes = [vp, cp]
        lib.arena_delete.argtypes = [vp, cp]
        lib.arena_contains.argtypes = [vp, cp]
        lib.arena_evict.restype = u64
        lib.arena_evict.argtypes = [vp, u64, ctypes.c_char_p, u64,
                                    ctypes.POINTER(u64)]
        lib.arena_stats.argtypes = [vp, ctypes.POINTER(u64),
                                    ctypes.POINTER(u64),
                                    ctypes.POINTER(u64)]
        lib.arena_stats_ext.argtypes = [vp, ctypes.POINTER(u64)]
        lib.arena_base.restype = vp
        lib.arena_base.argtypes = [vp]
        lib.arena_detach.argtypes = [vp]
        lib.arena_unlink.argtypes = [cp]
        lib._arena_configured = True
    return lib


def available() -> bool:
    import os
    if os.environ.get("RAY_TPU_DISABLE_NATIVE_ARENA"):
        return False
    return _lib() is not None


def _id_bytes(object_id: str) -> bytes:
    b = object_id.encode()[:ID_LEN]
    return b.ljust(ID_LEN, b"0")


class Ref:
    """Held reference to a sealed object; zero-copy view into the arena."""

    def __init__(self, arena: "Arena", object_id: str, offset: int,
                 size: int):
        self._arena = arena
        self._id = object_id
        self.size = size
        addr = arena._base + offset
        self._view = memoryview(
            (ctypes.c_char * size).from_address(addr)).cast("B")
        self._released = False

    @property
    def buf(self) -> memoryview:
        return self._view

    def release(self) -> None:
        if not self._released:
            self._released = True
            self._view.release()
            self._arena.release(self._id)

    # plasma-client parity: dropping the last Python ref releases the pin
    def __del__(self):
        try:
            self.release()
        except Exception:
            pass


class Arena:
    def __init__(self, handle, name: str):
        lib = _lib()
        self._h = handle
        self.name = name
        self._base = lib.arena_base(handle)

    # -- lifecycle ----------------------------------------------------------
    @classmethod
    def create(cls, name: str, size: int,
               capacity: Optional[int] = None) -> Optional["Arena"]:
        lib = _lib()
        if lib is None:
            return None
        if capacity is None:
            # ~1 slot per 16KiB of heap, clamped — the table must stay a
            # small fraction of the segment (each slot is ~72 bytes)
            capacity = max(1024, min(262144, size // 16384))
        h = lib.arena_create(name.encode(), size, capacity)
        return cls(h, name) if h else None

    @classmethod
    def attach(cls, name: str) -> Optional["Arena"]:
        lib = _lib()
        if lib is None:
            return None
        h = lib.arena_attach(name.encode())
        return cls(h, name) if h else None

    @classmethod
    def create_or_attach(cls, name: str,
                         size: int) -> Tuple[Optional["Arena"], bool]:
        """Returns (arena, created_by_us)."""
        a = cls.create(name, size)
        if a is not None:
            return a, True
        return cls.attach(name), False

    def detach(self) -> None:
        if self._h:
            _lib().arena_detach(self._h)
            self._h = None

    def unlink(self) -> None:
        _lib().arena_unlink(self.name.encode())

    # -- object ops ---------------------------------------------------------
    def create_buffer(self, object_id: str,
                      size: int) -> Optional[memoryview]:
        off = _lib().arena_alloc(self._h, _id_bytes(object_id), size)
        if off < 0:
            return None
        return memoryview(
            (ctypes.c_char * size).from_address(self._base + off)).cast("B")

    def seal(self, object_id: str) -> None:
        _lib().arena_seal(self._h, _id_bytes(object_id))

    def get(self, object_id: str) -> Optional[Ref]:
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        rc = _lib().arena_get(self._h, _id_bytes(object_id),
                              ctypes.byref(off), ctypes.byref(size))
        if rc != 0:
            return None
        return Ref(self, object_id, off.value, size.value)

    def release(self, object_id: str) -> None:
        _lib().arena_release(self._h, _id_bytes(object_id))

    def delete(self, object_id: str) -> bool:
        return _lib().arena_delete(self._h, _id_bytes(object_id)) == 0

    def contains(self, object_id: str) -> bool:
        return bool(_lib().arena_contains(self._h, _id_bytes(object_id)))

    def evict(self, needed: int, max_ids: int = 1024) -> Tuple[int, list]:
        out = ctypes.create_string_buffer(max_ids * ID_LEN)
        n = ctypes.c_uint64()
        reclaimed = _lib().arena_evict(self._h, needed, out, max_ids,
                                       ctypes.byref(n))
        ids = [out.raw[i * ID_LEN:(i + 1) * ID_LEN].decode()
               for i in range(min(n.value, max_ids))]
        return reclaimed, ids

    def stats(self) -> dict:
        """Native counters maintained inside the C++ arena (reference
        parity role: src/ray/stats/metric_defs.h — the native stats
        source feeding the per-node metrics pipeline)."""
        out = (ctypes.c_uint64 * 8)()
        _lib().arena_stats_ext(self._h, out)
        return {"bytes_allocated": out[0], "heap_capacity": out[1],
                "num_objects": out[2], "allocs": out[3],
                "alloc_fails": out[4], "frees": out[5],
                "coalesces": out[6], "crash_sweeps": out[7]}
