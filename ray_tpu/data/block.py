"""Blocks: the unit of data movement in ray_tpu.data.

A block is a pyarrow.Table. BlockAccessor wraps one block with
format-agnostic helpers (rows, batches, slicing, size accounting).

Reference parity: python/ray/data/block.py (BlockAccessor) — semantics
only; this implementation is Arrow-native with numpy-dict batch views
so batches hand off zero-copy into jnp.asarray where dtypes allow.
"""

from __future__ import annotations

import sys
from typing import Any, Callable, Dict, Iterator, List, Optional, Union

import numpy as np
import pyarrow as pa

Block = pa.Table
Row = Dict[str, Any]
Batch = Union[pa.Table, Dict[str, np.ndarray], "pandas.DataFrame"]

VALUE_COL = "value"  # column name used for simple (non-tabular) datasets


def _to_table(data: Any) -> pa.Table:
    """Coerce rows / dicts-of-arrays / tables / numpy into an Arrow table."""
    if isinstance(data, pa.Table):
        return data
    if isinstance(data, dict):
        cols = {k: _as_array(v) for k, v in data.items()}
        return pa.table(cols)
    if data.__class__.__module__.split(".")[0] == "pandas":
        return pa.Table.from_pandas(data, preserve_index=False)
    if isinstance(data, np.ndarray):
        return pa.table({VALUE_COL: _as_array(data)})
    if isinstance(data, list):
        if not data:
            return pa.table({})
        if isinstance(data[0], dict):
            keys = list(data[0].keys())
            return pa.table(
                {k: _as_array([row[k] for row in data]) for k in keys})
        return pa.table({VALUE_COL: _as_array(data)})
    raise TypeError(f"cannot convert {type(data)} to a block")


def _as_array(v: Any) -> pa.Array:
    if isinstance(v, (pa.Array, pa.ChunkedArray)):
        return v
    arr = np.asarray(v)
    if arr.ndim > 1:
        # Tensor column: store as fixed-size-list of flattened rows.
        flat = arr.reshape(arr.shape[0], -1)
        inner = pa.array(flat.ravel())
        return pa.FixedSizeListArray.from_arrays(inner, flat.shape[1])
    return pa.array(arr)


def from_pandas_df(df) -> pa.Table:
    return pa.Table.from_pandas(df, preserve_index=False)


class BlockAccessor:
    """Format-agnostic view over one Arrow block."""

    def __init__(self, block: Block):
        if not isinstance(block, pa.Table):
            block = _to_table(block)
        self._table = block

    @staticmethod
    def for_block(block: Any) -> "BlockAccessor":
        return BlockAccessor(block)

    def to_arrow(self) -> pa.Table:
        return self._table

    def num_rows(self) -> int:
        return self._table.num_rows

    def size_bytes(self) -> int:
        return self._table.nbytes

    def schema(self) -> Optional[pa.Schema]:
        return self._table.schema if self._table.num_columns else None

    def slice(self, start: int, end: int) -> Block:
        return self._table.slice(start, end - start)

    def take(self, indices: List[int]) -> Block:
        return self._table.take(pa.array(indices, type=pa.int64()))

    def iter_rows(self) -> Iterator[Row]:
        cols = self._table.column_names
        for i in range(self._table.num_rows):
            yield {c: self._table.column(c)[i].as_py() for c in cols}

    def to_numpy(self) -> Dict[str, np.ndarray]:
        out = {}
        for name in self._table.column_names:
            col = self._table.column(name).combine_chunks()
            if isinstance(col, pa.ChunkedArray):
                col = col.chunk(0) if col.num_chunks else pa.array([])
            if pa.types.is_fixed_size_list(col.type):
                width = col.type.list_size
                flat = col.flatten().to_numpy(zero_copy_only=False)
                out[name] = flat.reshape(len(col), width)
            else:
                out[name] = col.to_numpy(zero_copy_only=False)
        return out

    def to_pandas(self):
        return self._table.to_pandas()

    def to_batch(self, batch_format: str) -> Batch:
        if batch_format in ("numpy", "np"):
            return self.to_numpy()
        if batch_format == "pandas":
            return self.to_pandas()
        if batch_format in ("pyarrow", "arrow", None, "default"):
            return self._table
        raise ValueError(f"unknown batch_format {batch_format!r}")

    def sample(self, n: int, seed: Optional[int] = None) -> Block:
        rng = np.random.default_rng(seed)
        n = min(n, self.num_rows())
        idx = rng.choice(self.num_rows(), size=n, replace=False)
        return self.take([int(i) for i in idx])

    def sort_indices(self, key: str, descending: bool = False) -> np.ndarray:
        col = self._table.column(key).combine_chunks().to_numpy(
            zero_copy_only=False)
        order = np.argsort(col, kind="stable")
        return order[::-1] if descending else order


def batch_to_block(batch: Batch) -> Block:
    """Convert a user-returned batch back into an Arrow block."""
    return _to_table(batch)


def concat_blocks(blocks: List[Block]) -> Block:
    blocks = [b for b in blocks if b.num_rows > 0] or blocks[:1]
    if not blocks:
        return pa.table({})
    if len(blocks) == 1:
        return blocks[0]
    return pa.concat_tables(blocks, promote_options="default")


def block_from_rows(rows: List[Row]) -> Block:
    return _to_table(rows)
