"""Datasources: read_* / from_* / write helpers.

Reference parity: python/ray/data/read_api.py + data/datasource/ —
each read produces a list of read tasks (thunks returning blocks) so
the streaming executor parallelizes and the Read op can stop early
under a pushed-down limit.
"""

from __future__ import annotations

import builtins
import glob
import io
import json
import os
from typing import Any, Callable, Dict, Iterable, List, Optional

import numpy as np
import pyarrow as pa

from . import logical as L
from .block import VALUE_COL, Block, _to_table
from .dataset import Dataset, MaterializedDataset

DEFAULT_BLOCK_ROWS = 1000


# -- in-memory sources ------------------------------------------------------

def range(n: int, *, parallelism: int = -1) -> Dataset:  # noqa: A001
    k = _parallelism(parallelism, n)
    bounds = np.linspace(0, n, k + 1, dtype=np.int64)

    def make_task(lo: int, hi: int) -> Callable[[], Block]:
        return lambda: pa.table({"id": np.arange(lo, hi, dtype=np.int64)})

    tasks = [make_task(int(bounds[i]), int(bounds[i + 1]))
             for i in builtins.range(k)]
    return Dataset(L.Read(tasks, source_name=f"range({n})"))


def from_items(items: List[Any], *, parallelism: int = -1) -> Dataset:
    k = _parallelism(parallelism, len(items))
    blocks = []
    bounds = np.linspace(0, len(items), k + 1, dtype=np.int64)
    for i in builtins.range(k):
        chunk = items[int(bounds[i]):int(bounds[i + 1])]
        if chunk or i == 0:
            blocks.append(_to_table(
                chunk if chunk and isinstance(chunk[0], dict)
                else {"item": chunk}))
    return MaterializedDataset(blocks)


def from_numpy(arr: np.ndarray, column: str = "data") -> Dataset:
    return MaterializedDataset([_to_table({column: arr})])


def from_arrow(table: pa.Table) -> Dataset:
    return MaterializedDataset([table])


def from_pandas(df) -> Dataset:
    return MaterializedDataset([pa.Table.from_pandas(
        df, preserve_index=False)])


# -- file sources -----------------------------------------------------------

def _expand_paths(paths, suffix: str) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(glob.glob(os.path.join(p, f"*{suffix}"))))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(glob.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files match {paths}")
    return out


def _file_read(paths, suffix: str, reader: Callable[[str], Block],
               name: str) -> Dataset:
    files = _expand_paths(paths, suffix)

    def make_task(f: str) -> Callable[[], Block]:
        return lambda: reader(f)

    return Dataset(L.Read([make_task(f) for f in files], source_name=name))


def read_parquet(paths, **kw) -> Dataset:
    import pyarrow.parquet as pq
    return _file_read(paths, ".parquet",
                      lambda f: pq.read_table(f, **kw), "Parquet")


def read_csv(paths, **kw) -> Dataset:
    import pyarrow.csv as pcsv
    return _file_read(paths, ".csv",
                      lambda f: pcsv.read_csv(f, **kw), "CSV")


def read_json(paths, **kw) -> Dataset:
    def reader(f: str) -> Block:
        with open(f) as fh:
            rows = [json.loads(line) for line in fh if line.strip()]
        return _to_table(rows)
    return _file_read(paths, ".jsonl", reader, "JSON")


def read_text(paths, **kw) -> Dataset:
    def reader(f: str) -> Block:
        with open(f) as fh:
            lines = [ln.rstrip("\n") for ln in fh]
        return pa.table({"text": lines})
    return _file_read(paths, ".txt", reader, "Text")


def read_numpy(paths, **kw) -> Dataset:
    def reader(f: str) -> Block:
        return _to_table({"data": np.load(f)})
    return _file_read(paths, ".npy", reader, "Numpy")


def read_binary_files(paths, **kw) -> Dataset:
    def reader(f: str) -> Block:
        with open(f, "rb") as fh:
            return pa.table({"path": [f], "bytes": [fh.read()]})
    return _file_read(paths, "", reader, "Binary")


IMAGE_SUFFIXES = (".png", ".jpg", ".jpeg", ".bmp", ".gif", ".webp")


def _wds_decode(ext: str, raw: bytes) -> Any:
    """Standard WebDataset field decoding by extension. Arrays stay
    numpy here (compact); the nested-list form Arrow stores (same
    choice as read_images) is produced per-chunk at table build time —
    holding a whole image shard as Python lists was the memory
    blowup."""
    if ext in ("txt", "text"):
        return raw.decode()
    if ext in ("cls", "id", "index"):
        return int(raw.decode().strip())
    if ext == "json":
        return json.loads(raw.decode())
    if ext == "npy":
        return np.load(io.BytesIO(raw))
    if f".{ext.lower()}" in IMAGE_SUFFIXES:
        from PIL import Image
        return np.asarray(Image.open(io.BytesIO(raw)))
    return raw


def read_webdataset(paths, *, decode: bool = True) -> Dataset:
    """WebDataset tar shards (reference parity: data/read_api.py
    read_webdataset): one row per SAMPLE — files sharing a basename up
    to the first dot ("0001.jpg" + "0001.cls" + "0001.json" form the
    sample keyed "0001") — with one column per extension plus
    "__key__". decode=True applies the standard field decoders
    (txt->str, cls->int, json->dict, npy->array, images->HWC arrays);
    decode=False keeps raw bytes. One read task per shard so the
    streaming executor parallelizes across shards."""
    import tarfile

    # Samples accumulate as numpy/scalars for the WHOLE shard (so
    # fields of one sample merge even when its tar entries are not
    # adjacent), then convert to Arrow in CHUNK-row batches: the
    # nested-Python-list working set — the actual memory blowup on
    # image shards — stays bounded at CHUNK rows.
    CHUNK = 256

    def reader(f: str) -> Block:
        samples: Dict[str, Dict[str, Any]] = {}
        order: List[str] = []
        with tarfile.open(f) as tf:
            for m in tf:
                if not m.isfile():
                    continue
                # sample key = full path up to the first dot of the
                # BASENAME (webdataset semantics: "train/0001.jpg" and
                # "val/0001.jpg" are distinct samples)
                dirname, base = os.path.split(m.name)
                stem, _, ext = base.partition(".")
                key = os.path.join(dirname, stem) if dirname else stem
                if key not in samples:
                    samples[key] = {}
                    order.append(key)
                raw = tf.extractfile(m).read()
                samples[key][ext] = (_wds_decode(ext, raw)
                                     if decode else raw)
        if not order:
            return pa.table({"__key__": pa.array([], pa.string())})
        # column set = UNION across ALL samples (a field absent from
        # the first sample must not vanish from the shard)
        names: List[str] = ["__key__"]
        for k in order:
            for name in samples[k]:
                if name not in names:
                    names.append(name)

        def to_cell(v):
            return v.tolist() if isinstance(v, np.ndarray) else v

        tables: List[Any] = []
        for at in builtins.range(0, len(order), CHUNK):
            keys = order[at:at + CHUNK]
            # explicit pa.array per column: the generic tensor
            # conversion in _to_table flattens nested lists (decoded
            # images must stay list<list<list<uint8>>> — same choice
            # as read_images)
            cols = {}
            for name in names:
                if name == "__key__":
                    cols[name] = pa.array(keys)
                else:
                    cols[name] = pa.array(
                        [to_cell(samples[k].get(name)) for k in keys])
            tables.append(pa.table(cols))
            for k in keys:          # free converted rows eagerly
                samples[k] = {}
        if len(tables) == 1:
            return tables[0]
        # "permissive": per-chunk inference differences unify
        # (int64 + double -> double) and all-null chunks take the
        # typed column's type
        return pa.concat_tables(tables, promote_options="permissive")

    return _file_read(paths, ".tar", reader, "WebDataset")


def read_images(paths, size=None, mode=None,
                include_paths: bool = False) -> Dataset:
    """Decode an image directory/file list into blocks with an "image"
    column of HWC uint8 arrays (reference parity: data/read_api.py:956
    read_images — size/mode/include_paths options).

    size: (height, width) to resize every image to (required for
    batching mixed-size images into one numpy batch). mode: PIL mode
    conversion, e.g. "RGB"/"L". Row access yields the image as nested
    lists (Arrow list column semantics) — np.asarray(row["image"])
    restores the HWC array."""
    def reader(f: str) -> Block:
        from PIL import Image
        img = Image.open(f)
        if mode is not None:
            img = img.convert(mode)
        if size is not None:
            img = img.resize((size[1], size[0]))
        # explicit Arrow list<list<list<uint8>>>: the generic tensor
        # conversion flattens >1-D arrays to fixed-size lists, losing
        # the H/W/C structure
        arr = np.asarray(img)
        cols = {"image": pa.array([arr.tolist()])}
        if include_paths:
            cols["path"] = [f]
        return _to_table(cols)

    files = _expand_paths(paths, "")
    images = [f for f in files
              if f.lower().endswith(IMAGE_SUFFIXES)]
    if not images:
        raise ValueError(f"no image files found under {paths!r}")

    def make_task(f: str):
        return lambda: reader(f)

    return read_datasource([make_task(f) for f in images], name="Image")


def read_datasource(read_tasks: List[Callable[[], Block]],
                    name: str = "Custom") -> Dataset:
    """Escape hatch: bring your own read tasks."""
    return Dataset(L.Read(list(read_tasks), source_name=name))


# -- writes -----------------------------------------------------------------

def write_blocks(blocks: Iterable[Block], path: str, fmt: str) -> None:
    os.makedirs(path, exist_ok=True)
    for i, block in enumerate(blocks):
        f = os.path.join(path, f"part-{i:05d}.{fmt}")
        if fmt == "parquet":
            import pyarrow.parquet as pq
            pq.write_table(block, f)
        elif fmt == "csv":
            import pyarrow.csv as pcsv
            pcsv.write_csv(block, f)
        elif fmt == "json":
            from .block import BlockAccessor
            with open(f, "w") as fh:
                for row in BlockAccessor(block).iter_rows():
                    fh.write(json.dumps(row, default=_json_default) + "\n")
        else:
            raise ValueError(fmt)


def _json_default(o):
    if isinstance(o, (np.integer,)):
        return int(o)
    if isinstance(o, (np.floating,)):
        return float(o)
    if isinstance(o, np.ndarray):
        return o.tolist()
    raise TypeError(type(o))


def _parallelism(parallelism: int, n: int) -> int:
    if parallelism and parallelism > 0:
        return min(parallelism, max(n, 1))
    return max(1, min(8, (n + DEFAULT_BLOCK_ROWS - 1) // DEFAULT_BLOCK_ROWS))
