"""ray_tpu.data.llm: batch LLM inference over Datasets.

Reference parity: python/ray/llm/_internal/batch/processor/
vllm_engine_proc.py (build_llm_processor + vLLMEngineProcessorConfig) —
the external vLLM engine replaced by the in-repo paged-KV continuous
batching engine (llm/_internal/engine.py). Each processor replica is a
map_batches actor holding one engine; prompts in a batch run through the
engine's continuous batching loop together.

    config = LLMEngineProcessorConfig(model_source="debug",
                                      batch_size=16, concurrency=1)
    processor = build_llm_processor(
        config,
        preprocess=lambda row: {"prompt": f"Q: {row['question']}"},
        postprocess=lambda row: {"answer": row["generated_text"]})
    ds = processor(ds)
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional

from .dataset import Dataset


@dataclasses.dataclass
class LLMEngineProcessorConfig:
    """Reference: vLLMEngineProcessorConfig (pydantic there)."""

    model_source: Any = "debug"          # preset name or LlamaConfig
    tokenizer_source: Optional[str] = None
    engine_kwargs: Dict[str, Any] = dataclasses.field(default_factory=dict)
    sampling_params: Dict[str, Any] = dataclasses.field(
        default_factory=lambda: {"max_tokens": 32})
    batch_size: int = 16
    concurrency: int = 1
    num_tpus: Optional[float] = None     # per engine replica
    # {adapter_name: {proj: (A, B)}} registered on every engine replica;
    # rows may select one via a "lora" column (multi-LoRA batch
    # inference over the base model)
    lora_adapters: Dict[str, Any] = dataclasses.field(default_factory=dict)


class _LLMBatchPredictor:
    """One engine per map_batches replica; called with numpy batches."""

    def __init__(self, config: LLMEngineProcessorConfig):
        from ..llm._internal.engine import (EngineConfig, InferenceEngine,
                                            SamplingParams)
        from ..llm._internal.tokenizer import load_tokenizer
        from ..models import llama

        model = (llama.config(config.model_source)
                 if isinstance(config.model_source, str)
                 else config.model_source)
        kwargs = dict(config.engine_kwargs)
        kwargs.setdefault("max_batch_size", min(config.batch_size, 16))
        self.engine = InferenceEngine(EngineConfig(model=model, **kwargs))
        if config.lora_adapters:
            self.engine.register_loras(dict(config.lora_adapters))
        self.tokenizer = load_tokenizer(config.tokenizer_source,
                                        vocab_size=model.vocab_size)
        self.params = SamplingParams(**config.sampling_params)

    def __call__(self, batch: Dict[str, Any]) -> Dict[str, Any]:
        if "prompt" in batch:
            prompts = [self.tokenizer.encode(str(p))
                       for p in batch["prompt"]]
        elif "prompt_tokens" in batch:
            prompts = [list(map(int, p)) for p in batch["prompt_tokens"]]
        else:
            raise ValueError(
                "LLM processor batches need a 'prompt' (text) or "
                "'prompt_tokens' column")
        def _lora_of(x):
            # None / "" / NaN -> base model; anything else by value
            if x is None:
                return None
            if isinstance(x, float) and x != x:
                return None
            s = str(x)
            return s if s else None

        loras = ([_lora_of(x) for x in batch["lora"]]
                 if "lora" in batch else None)
        reqs = self.engine.generate(prompts, self.params, loras=loras)
        batch = dict(batch)
        batch["generated_tokens"] = [list(r.output_tokens) for r in reqs]
        batch["generated_text"] = [
            self.tokenizer.decode(r.output_tokens) for r in reqs]
        return batch


def build_llm_processor(
        config: LLMEngineProcessorConfig,
        preprocess: Optional[Callable[[dict], dict]] = None,
        postprocess: Optional[Callable[[dict], dict]] = None,
) -> Callable[[Dataset], Dataset]:
    """Dataset -> Dataset stage running batch inference.

    preprocess maps each input row to a row with a 'prompt' column;
    postprocess maps each output row (input columns + generated_text /
    generated_tokens) to the final row.
    """

    def apply(ds: Dataset) -> Dataset:
        if preprocess is not None:
            ds = ds.map(lambda row, _f=preprocess: {**row, **_f(row)})
        ds = ds.map_batches(
            _LLMBatchPredictor,
            fn_constructor_args=(config,),
            batch_size=config.batch_size,
            concurrency=config.concurrency,
            num_tpus=config.num_tpus)
        if postprocess is not None:
            ds = ds.map(lambda row, _f=postprocess: _f(row))
        return ds

    return apply
