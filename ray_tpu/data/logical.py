"""Logical plan: operators + optimizer rules.

Reference parity: python/ray/data/_internal/logical/ (operators and
rules operator_fusion / limit_pushdown — semantics only). A plan is a
linear chain (Union/Zip hold extra inputs); optimization rewrites the
chain before the streaming executor plans physical operators.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple


class LogicalOp:
    """Base logical operator. `input_op` forms the chain."""
    name = "Op"

    def __init__(self, input_op: Optional["LogicalOp"] = None):
        self.input_op = input_op

    def chain(self) -> List["LogicalOp"]:
        ops: List[LogicalOp] = []
        op: Optional[LogicalOp] = self
        while op is not None:
            ops.append(op)
            op = op.input_op
        return list(reversed(ops))

    def __repr__(self):
        return self.name


class Read(LogicalOp):
    """Leaf: a datasource producing read tasks (thunks -> blocks)."""
    name = "Read"

    def __init__(self, read_tasks: List[Callable[[], Any]],
                 source_name: str = "Read", row_limit: Optional[int] = None):
        super().__init__(None)
        self.read_tasks = read_tasks
        self.source_name = source_name
        self.row_limit = row_limit  # set by limit pushdown

    def __repr__(self):
        return f"Read[{self.source_name}]"


class InputData(LogicalOp):
    """Leaf: pre-materialized blocks (from_items / from_arrow / splits)."""
    name = "InputData"

    def __init__(self, blocks: List[Any]):
        super().__init__(None)
        self.blocks = blocks


# -- map-like ops (fusable) -------------------------------------------------

class AbstractMap(LogicalOp):
    """A row/batch transform. `transform(block) -> block` composed lazily."""

    def __init__(self, input_op, fn, *, fn_kind: str,
                 batch_size: Optional[int] = None,
                 batch_format: str = "numpy",
                 fn_constructor: Optional[Tuple[Any, tuple, dict]] = None,
                 compute: Optional[Any] = None,
                 num_cpus: Optional[float] = None,
                 num_tpus: Optional[float] = None,
                 concurrency: Optional[Any] = None):
        super().__init__(input_op)
        self.fn = fn
        self.fn_kind = fn_kind          # map_rows|map_batches|filter|flat_map
        self.batch_size = batch_size
        self.batch_format = batch_format
        self.fn_constructor = fn_constructor  # (cls, args, kwargs) actor pool
        self.compute = compute
        self.num_cpus = num_cpus
        self.num_tpus = num_tpus
        self.concurrency = concurrency

    @property
    def uses_actors(self) -> bool:
        return self.fn_constructor is not None

    def __repr__(self):
        return f"{self.name}({getattr(self.fn, '__name__', 'fn')})"


class MapRows(AbstractMap):
    name = "MapRows"

    def __init__(self, input_op, fn, **kw):
        super().__init__(input_op, fn, fn_kind="map_rows", **kw)


class MapBatches(AbstractMap):
    name = "MapBatches"

    def __init__(self, input_op, fn, **kw):
        super().__init__(input_op, fn, fn_kind="map_batches", **kw)


class Filter(AbstractMap):
    name = "Filter"

    def __init__(self, input_op, fn, **kw):
        super().__init__(input_op, fn, fn_kind="filter", **kw)


class FlatMap(AbstractMap):
    name = "FlatMap"

    def __init__(self, input_op, fn, **kw):
        super().__init__(input_op, fn, fn_kind="flat_map", **kw)


class FusedMap(AbstractMap):
    """Several adjacent map-like ops fused into one task per block."""
    name = "FusedMap"

    def __init__(self, input_op, stages: List[AbstractMap]):
        first = stages[0]
        super().__init__(
            input_op, None, fn_kind="fused",
            fn_constructor=next(
                (s.fn_constructor for s in stages if s.fn_constructor), None),
            num_cpus=max((s.num_cpus or 0) for s in stages) or None,
            num_tpus=max((s.num_tpus or 0) for s in stages) or None,
            concurrency=next(
                (s.concurrency for s in stages if s.concurrency), None))
        self.stages = stages

    def __repr__(self):
        return "Fused[" + "->".join(repr(s) for s in self.stages) + "]"


# -- all-to-all + misc ------------------------------------------------------

class Limit(LogicalOp):
    name = "Limit"

    def __init__(self, input_op, limit: int):
        super().__init__(input_op)
        self.limit = limit


class RandomShuffle(LogicalOp):
    name = "RandomShuffle"

    def __init__(self, input_op, seed: Optional[int] = None):
        super().__init__(input_op)
        self.seed = seed


class Repartition(LogicalOp):
    name = "Repartition"

    def __init__(self, input_op, num_blocks: int):
        super().__init__(input_op)
        self.num_blocks = num_blocks


class Sort(LogicalOp):
    name = "Sort"

    def __init__(self, input_op, key: str, descending: bool = False):
        super().__init__(input_op)
        self.key = key
        self.descending = descending


class GroupByAggregate(LogicalOp):
    name = "GroupByAggregate"

    def __init__(self, input_op, key: Optional[str], aggs: List[Any]):
        super().__init__(input_op)
        self.key = key
        self.aggs = aggs


class Union(LogicalOp):
    name = "Union"

    def __init__(self, input_op, others: List[LogicalOp]):
        super().__init__(input_op)
        self.others = others


class Zip(LogicalOp):
    name = "Zip"

    def __init__(self, input_op, other: LogicalOp):
        super().__init__(input_op)
        self.other = other


# -- optimizer --------------------------------------------------------------

_FUSABLE = (MapRows, MapBatches, Filter, FlatMap)


def optimize(plan: LogicalOp) -> LogicalOp:
    """Apply rules: limit pushdown, then map fusion.

    Operates on shallow copies of the chain — plans are shared between
    Dataset objects (ds.limit(5) wraps ds's plan), so rules must never
    write through to the originals.
    """
    ops = [copy.copy(op) for op in plan.chain()]
    ops = _push_limit(ops)
    ops = _fuse_maps(ops)
    # Relink the (copied) chain.
    prev: Optional[LogicalOp] = None
    for op in ops:
        op.input_op = prev if not isinstance(op, (Read, InputData)) else None
        prev = op
    return prev


def _push_limit(ops: List[LogicalOp]) -> List[LogicalOp]:
    """Annotate the Read with a row limit when every op between it and
    the first Limit is row-preserving (MapRows), so the datasource stops
    producing early. The Limit op itself always stays in the plan.

    Reference: limit_pushdown rule.
    """
    acc: Optional[int] = None
    for op in ops:
        if isinstance(op, (Read, InputData, MapRows)):
            continue
        if isinstance(op, Limit):
            acc = op.limit
        break
    if acc is not None and isinstance(ops[0], Read):
        ops[0].row_limit = acc
    return ops


def _fuse_maps(ops: List[LogicalOp]) -> List[LogicalOp]:
    out: List[LogicalOp] = []
    run: List[AbstractMap] = []

    def flush():
        if not run:
            return
        if len(run) == 1:
            out.append(run[0])
        else:
            out.append(FusedMap(None, list(run)))
        run.clear()

    for op in ops:
        if isinstance(op, _FUSABLE):
            # Actor-pool stages only fuse with stages sharing the same pool.
            if run and (run[-1].uses_actors or op.uses_actors):
                flush()
            run.append(op)
        else:
            flush()
            out.append(op)
    flush()
    return out
