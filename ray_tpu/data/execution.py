"""Streaming execution of logical plans.

Reference parity: python/ray/data/_internal/execution/ (StreamingExecutor,
TaskPoolMapOperator, ActorPoolMapOperator, backpressure) — semantics only.

Design: each operator is a generator transform over an iterator of block
refs. Map operators keep a bounded window of in-flight tasks (backpressure)
and yield blocks in order, overlapping upstream production with task
execution. All-to-all ops (shuffle/sort/groupby/repartition) are
map+reduce over tasks. Two compute backends:

- ClusterBackend: blocks flow as ObjectRefs through ray_tpu tasks/actors.
- InlineBackend: thread pool in-process (no cluster needed) — also the
  path Train/RL data loading uses on a single host, where the GIL is
  released inside Arrow/numpy.
"""

from __future__ import annotations

import collections
import concurrent.futures
import threading
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np
import pyarrow as pa

from . import logical as L
from .block import (Block, BlockAccessor, batch_to_block, block_from_rows,
                    concat_blocks)

DEFAULT_MAX_IN_FLIGHT = 8


# ---------------------------------------------------------------------------
# block transforms compiled from map-like logical ops
# ---------------------------------------------------------------------------

def _apply_stage(stage_kind: str, fn: Callable, blocks: List[Block],
                 batch_size: Optional[int], batch_format: str) -> List[Block]:
    out: List[Block] = []
    for block in blocks:
        acc = BlockAccessor(block)
        if stage_kind == "map_rows":
            out.append(block_from_rows([fn(r) for r in acc.iter_rows()]))
        elif stage_kind == "filter":
            mask = [bool(fn(r)) for r in acc.iter_rows()]
            idx = [i for i, m in enumerate(mask) if m]
            out.append(acc.take(idx) if idx else block.slice(0, 0))
        elif stage_kind == "flat_map":
            rows: List[dict] = []
            for r in acc.iter_rows():
                rows.extend(fn(r))
            out.append(block_from_rows(rows))
        elif stage_kind == "map_batches":
            n = acc.num_rows()
            bs = batch_size or n or 1
            for start in range(0, max(n, 1), bs):
                if n == 0 and start > 0:
                    break
                piece = BlockAccessor(acc.slice(start, min(start + bs, n))) \
                    if n else acc
                result = fn(piece.to_batch(batch_format))
                out.append(batch_to_block(result))
        else:
            raise ValueError(stage_kind)
    return out


def compile_map_transform(op: L.AbstractMap) -> Callable[[Block], List[Block]]:
    """Build a picklable block->blocks function for a (possibly fused) op."""
    stages: List[Tuple[str, Callable, Optional[int], str]] = []
    for s in (op.stages if isinstance(op, L.FusedMap) else [op]):
        stages.append((s.fn_kind, s.fn, s.batch_size, s.batch_format))

    def transform(block: Block, _stages=tuple(stages)) -> List[Block]:
        blocks = [block]
        for kind, fn, bs, fmt in _stages:
            blocks = _apply_stage(kind, fn, blocks, bs, fmt)
        return blocks

    return transform


class _ActorTransform:
    """Stateful transform: constructs the callable-class stages once per
    worker, then applies the stage chain to each block."""

    def __init__(self, stage_specs: List[tuple]):
        self._stages = []
        for kind, fn_or_ctor, bs, fmt, is_ctor in stage_specs:
            if is_ctor:
                cls, args, kwargs = fn_or_ctor
                fn = cls(*args, **kwargs)
            else:
                fn = fn_or_ctor
            self._stages.append((kind, fn, bs, fmt))

    def __call__(self, block: Block) -> List[Block]:
        blocks = [block]
        for kind, fn, bs, fmt in self._stages:
            blocks = _apply_stage(kind, fn, blocks, bs, fmt)
        return blocks


def actor_stage_specs(op: L.AbstractMap) -> List[tuple]:
    specs = []
    for s in (op.stages if isinstance(op, L.FusedMap) else [op]):
        if s.fn_constructor is not None:
            specs.append((s.fn_kind, s.fn_constructor, s.batch_size,
                          s.batch_format, True))
        else:
            specs.append((s.fn_kind, s.fn, s.batch_size, s.batch_format,
                          False))
    return specs


# ---------------------------------------------------------------------------
# compute backends
# ---------------------------------------------------------------------------

class InlineBackend:
    """Thread-pool execution in-process."""

    name = "inline"

    def __init__(self, max_workers: int = 8):
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=max_workers, thread_name_prefix="ray_tpu_data")

    def submit(self, fn: Callable, *args, **resources) -> Any:
        return self._pool.submit(fn, *args)

    def get(self, ref: Any) -> Any:
        return ref.result() if isinstance(
            ref, concurrent.futures.Future) else ref

    def make_pool(self, stage_specs: List[tuple], size: int) -> "_InlinePool":
        return _InlinePool(self, stage_specs, size)

    def shutdown(self):
        self._pool.shutdown(wait=False)


class _InlinePool:
    def __init__(self, backend: InlineBackend, stage_specs, size):
        if isinstance(size, tuple):
            size = size[1]          # inline threads are cheap: use max
        self._backend = backend
        self._workers = [_ActorTransform(stage_specs) for _ in range(size)]
        self._rr = 0

    def submit(self, block_ref) -> Any:
        w = self._workers[self._rr % len(self._workers)]
        self._rr += 1
        block = self._backend.get(block_ref)
        return self._backend.submit(w, block)

    def resolve(self, token, get) -> Any:
        return get(token)

    def shutdown(self):
        pass


def _run_data_task(payload: bytes, *args) -> Any:
    """Module-level task body (picklable by reference on workers): unpickle
    the function and apply it to the (runtime-resolved) args."""
    import cloudpickle
    fn = cloudpickle.loads(payload)
    return fn(*args)


class _MapWorkerActor:
    """Actor holding a constructed stateful transform (cluster mode)."""

    def __init__(self, specs_payload: bytes):
        import cloudpickle
        self._transform = _ActorTransform(cloudpickle.loads(specs_payload))

    def apply(self, block: Block) -> List[Block]:
        return self._transform(block)


class ClusterBackend:
    """Executes block transforms as ray_tpu tasks / actors."""

    name = "cluster"

    def __init__(self):
        import ray_tpu
        self._ray = ray_tpu

    def submit(self, fn: Callable, *args, num_cpus=None, num_tpus=None):
        import cloudpickle
        from ray_tpu.remote_function import RemoteFunction
        opts: Dict[str, Any] = {"num_cpus": num_cpus or 1}
        if num_tpus:
            opts["num_tpus"] = num_tpus
        task = RemoteFunction(_run_data_task, opts)
        return task.remote(cloudpickle.dumps(fn), *args)

    def get(self, ref: Any) -> Any:
        from ray_tpu._private.object_ref import ObjectRef
        if isinstance(ref, ObjectRef):
            return self._ray.get(ref)
        return ref

    def make_pool(self, stage_specs: List[tuple], size: int) -> "_ActorPool":
        return _ActorPool(self, stage_specs, size)


class _ActorPool:
    """Map-actor pool that autoscales between (min_size, max_size)
    (reference parity: data/_internal/execution/autoscaler/
    autoscaling_actor_pool.py): a new actor is added when every actor
    already has PER_ACTOR_BACKLOG submissions outstanding, and idle
    surplus actors are released once their work drains."""

    PER_ACTOR_BACKLOG = 2

    def __init__(self, backend: ClusterBackend, stage_specs, size):
        import cloudpickle
        import ray_tpu
        self._ray = ray_tpu
        if isinstance(size, tuple):
            self.min_size, self.max_size = size
        else:
            self.min_size = self.max_size = int(size)
        self._payload = cloudpickle.dumps(stage_specs)
        self._cls = ray_tpu.remote(_MapWorkerActor)
        self._actors: List[Any] = []
        self._inflight: Dict[int, int] = {}   # idx -> outstanding
        self._idle_since: Dict[int, float] = {}
        for _ in range(self.min_size):
            self._add_actor()
        self._rr = 0

    def _add_actor(self):
        self._actors.append(self._cls.remote(self._payload))
        self._inflight[len(self._actors) - 1] = 0

    @property
    def size(self) -> int:
        return sum(1 for a in self._actors if a is not None)

    def submit(self, block_ref) -> Any:
        live = [i for i, a in enumerate(self._actors) if a is not None]
        if (all(self._inflight[i] >= self.PER_ACTOR_BACKLOG
                for i in live)
                and self.size < self.max_size):
            self._add_actor()
            live.append(len(self._actors) - 1)
        idx = min((i for i in live),
                  key=lambda i: (self._inflight[i], (i - self._rr) % max(
                      len(self._actors), 1)))
        self._rr = idx + 1
        self._inflight[idx] += 1
        ref = self._actors[idx].apply.remote(block_ref)
        return (idx, ref)

    IDLE_SHRINK_S = 2.0

    def resolve(self, token, get) -> Any:
        import time
        idx, ref = token
        try:
            return get(ref)
        finally:
            self._inflight[idx] -= 1
            if self._inflight[idx] == 0:
                self._idle_since[idx] = time.monotonic()
            self._maybe_shrink()

    def _maybe_shrink(self):
        """Release surplus actors idle longer than IDLE_SHRINK_S."""
        import time
        now = time.monotonic()
        while self.size > self.min_size:
            idle = [i for i, a in enumerate(self._actors)
                    if a is not None and self._inflight[i] == 0
                    and now - self._idle_since.get(i, now) >=
                    self.IDLE_SHRINK_S]
            if not idle:
                return
            idx = idle[-1]
            try:
                self._ray.kill(self._actors[idx])
            except Exception:
                pass
            self._actors[idx] = None

    def shutdown(self):
        for a in self._actors:
            if a is None:
                continue
            try:
                self._ray.kill(a)
            except Exception:
                pass


def pick_backend() -> Any:
    import ray_tpu
    if ray_tpu.is_initialized():
        client = None
        try:
            from ray_tpu._private import state
            client = state.current_client()
        except Exception:
            pass
        if client is not None and not getattr(client, "is_local_mode", False):
            return ClusterBackend()
    return InlineBackend()


# ---------------------------------------------------------------------------
# streaming operator iterators
# ---------------------------------------------------------------------------

class MemoryBackpressure:
    """Memory-keyed dynamic in-flight window (reference parity:
    data/_internal/execution/backpressure_policy/
    concurrency_cap_backpressure_policy.py + resource_manager.py).

    The window is the fixed cap while the cluster's shm arenas are
    comfortable, then shrinks linearly to 1 as the worst node's arena
    fills past the low watermark — streaming a larger-than-arena dataset
    throttles submission instead of drowning the store (the arena's own
    spill loop drains what's already sealed)."""

    LOW = 0.5
    HIGH = 0.85
    POLL_S = 0.25

    def __init__(self, max_in_flight: int):
        self.max_in_flight = max_in_flight
        self._last_poll = 0.0
        self._last_pressure = 0.0

    def _pressure(self) -> float:
        import time
        now = time.monotonic()
        if now - self._last_poll < self.POLL_S:
            return self._last_pressure
        self._last_poll = now
        try:
            from ray_tpu.util.state import list_nodes
            self._last_pressure = max(
                (n.get("stats", {}).get("arena_pressure", 0.0)
                 for n in list_nodes() if n.get("alive")), default=0.0)
        except Exception:
            pass   # keep the last known value: a transient RPC failure
                   # must not fling the window open under real pressure
        return self._last_pressure

    def window(self) -> int:
        p = self._pressure()
        if p <= self.LOW:
            return self.max_in_flight
        if p >= self.HIGH:
            return 1
        frac = (self.HIGH - p) / (self.HIGH - self.LOW)
        return max(1, int(round(frac * self.max_in_flight)))


def _windowed(upstream: Iterator[Any], submit: Callable[[Any], Any],
              resolve: Callable[[Any], Any],
              max_in_flight: int,
              policy: Optional[MemoryBackpressure] = None
              ) -> Iterator[Block]:
    """Submit one task per upstream ref with a bounded in-flight window
    (memory-shrunk when a policy is given); yield each task's resulting
    blocks in order."""
    pending: "collections.deque[Any]" = collections.deque()
    for ref in upstream:
        cap = policy.window() if policy is not None else max_in_flight
        while len(pending) >= cap:
            for blk in resolve(pending.popleft()):
                yield blk
            cap = policy.window() if policy is not None else max_in_flight
        pending.append(submit(ref))
    while pending:
        for blk in resolve(pending.popleft()):
            yield blk


def execute_plan(plan: L.LogicalOp, backend, *,
                 max_in_flight: int = DEFAULT_MAX_IN_FLIGHT
                 ) -> Iterator[Block]:
    """Yield materialized output blocks of the optimized plan."""
    plan = L.optimize(plan)
    it = _build_iter(plan, backend, max_in_flight)
    for ref in it:
        blk = backend.get(ref)
        if isinstance(blk, list):
            for b in blk:
                yield b
        else:
            yield blk


def _build_iter(op: L.LogicalOp, backend, max_in_flight) -> Iterator[Any]:
    """Returns an iterator of block refs (or blocks) for `op`'s output."""
    if isinstance(op, L.InputData):
        return iter(op.blocks)
    if isinstance(op, L.Read):
        return _read_iter(op, backend, max_in_flight)
    upstream = _build_iter(op.input_op, backend, max_in_flight) \
        if op.input_op is not None else iter(())
    if isinstance(op, L.AbstractMap):
        return _map_iter(op, upstream, backend, max_in_flight)
    if isinstance(op, L.Limit):
        return _limit_iter(op, upstream, backend)
    if isinstance(op, L.Union):
        extra = [_build_iter(o, backend, max_in_flight) for o in op.others]

        def chain():
            yield from upstream
            for e in extra:
                yield from e
        return chain()
    if isinstance(op, L.Zip):
        other = _build_iter(op.other, backend, max_in_flight)
        return _zip_iter(upstream, other, backend)
    if isinstance(op, L.RandomShuffle):
        return _shuffle_iter(op, upstream, backend, max_in_flight)
    if isinstance(op, L.Repartition):
        return _repartition_iter(op, upstream, backend)
    if isinstance(op, L.Sort):
        return _sort_iter(op, upstream, backend, max_in_flight)
    if isinstance(op, L.GroupByAggregate):
        from .aggregate import groupby_execute
        return groupby_execute(op, upstream, backend, max_in_flight)
    raise NotImplementedError(f"no physical op for {op!r}")


def _read_iter(op: L.Read, backend, max_in_flight) -> Iterator[Any]:
    tasks = list(op.read_tasks)
    row_limit = op.row_limit

    if row_limit is None:
        yield from _windowed(
            iter(tasks), lambda t: backend.submit(_call_thunk, t),
            lambda ref: _as_blocks(backend.get(ref)), max_in_flight)
        return
    # With a pushed-down limit, read sequentially until satisfied.
    produced = 0
    for t in tasks:
        if produced >= row_limit:
            break
        for blk in _as_blocks(backend.get(backend.submit(_call_thunk, t))):
            yield blk
            produced += BlockAccessor(blk).num_rows()
            if produced >= row_limit:
                break


def _call_thunk(t):
    return t()


def _as_blocks(result) -> List[Block]:
    if isinstance(result, list):
        return result
    return [result]


def _map_iter(op: L.AbstractMap, upstream, backend, max_in_flight):
    policy = (MemoryBackpressure(max_in_flight)
              if getattr(backend, "name", "") == "cluster" else None)
    if op.uses_actors:
        # concurrency: int = fixed pool, (min, max) tuple = autoscaling
        # actor pool (reference parity: ActorPoolStrategy min/max)
        size = op.concurrency if isinstance(op.concurrency,
                                            (int, tuple)) else 2
        pool = backend.make_pool(actor_stage_specs(op), size)
        try:
            yield from _windowed(
                upstream, pool.submit,
                lambda tok: _as_blocks(pool.resolve(tok, backend.get)),
                max_in_flight, policy)
        finally:
            pool.shutdown()
        return
    transform = compile_map_transform(op)
    yield from _windowed(
        upstream,
        lambda block: backend.submit(
            transform, block, num_cpus=op.num_cpus, num_tpus=op.num_tpus),
        lambda ref: _as_blocks(backend.get(ref)), max_in_flight, policy)


def _limit_iter(op: L.Limit, upstream, backend):
    remaining = op.limit
    for ref in upstream:
        if remaining <= 0:
            return
        for blk in _as_blocks(backend.get(ref)):
            acc = BlockAccessor(blk)
            if acc.num_rows() <= remaining:
                remaining -= acc.num_rows()
                yield blk
            else:
                yield acc.slice(0, remaining)
                remaining = 0
            if remaining <= 0:
                return


def _zip_iter(upstream, other, backend):
    left = concat_blocks([b for r in upstream
                          for b in _as_blocks(backend.get(r))])
    right = concat_blocks([b for r in other
                           for b in _as_blocks(backend.get(r))])
    if left.num_rows != right.num_rows:
        raise ValueError(
            f"zip requires equal row counts ({left.num_rows} vs "
            f"{right.num_rows})")
    cols = {name: left.column(name) for name in left.column_names}
    for name in right.column_names:
        out = name if name not in cols else f"{name}_1"
        cols[out] = right.column(name)
    yield pa.table(cols)


def _shuffle_iter(op: L.RandomShuffle, upstream, backend, max_in_flight):
    """2-phase map/reduce shuffle: each block randomly partitioned into k
    parts; reducer i concatenates part i of every block and permutes."""
    refs = list(upstream)
    k = max(len(refs), 1)
    seed = op.seed

    def partition(block: Block, i: int) -> List[Block]:
        rng = np.random.default_rng(
            None if seed is None else seed + 17 * i)
        n = block.num_rows
        assign = rng.integers(0, k, size=n)
        acc = BlockAccessor(block)
        return [acc.take(list(np.nonzero(assign == j)[0])) for j in range(k)]

    parts: List[List[Block]] = []
    for i, ref in enumerate(refs):
        for blk in _as_blocks(backend.get(ref)):
            parts.append(partition(blk, i))

    def reduce_part(j: int) -> Block:
        merged = concat_blocks([p[j] for p in parts]) if parts \
            else pa.table({})
        rng = np.random.default_rng(None if seed is None else seed + j)
        order = rng.permutation(merged.num_rows)
        return BlockAccessor(merged).take([int(x) for x in order])

    for j in range(k):
        if parts:
            yield reduce_part(j)


def _repartition_iter(op: L.Repartition, upstream, backend):
    merged = concat_blocks(
        [b for r in upstream for b in _as_blocks(backend.get(r))])
    n = merged.num_rows
    k = op.num_blocks
    base, rem = divmod(n, k)
    start = 0
    for i in range(k):
        size = base + (1 if i < rem else 0)
        yield merged.slice(start, size)
        start += size


def _sort_iter(op: L.Sort, upstream, backend, max_in_flight):
    """Sample-based range partition sort (parallel-friendly semantics)."""
    blocks = [b for r in upstream for b in _as_blocks(backend.get(r))]
    blocks = [b for b in blocks if b.num_rows > 0]
    if not blocks:
        return
    k = len(blocks)
    # Sample boundaries.
    samples = np.concatenate([
        BlockAccessor(b).sample(min(20, b.num_rows), seed=0)
        .column(op.key).to_numpy(zero_copy_only=False) for b in blocks])
    samples = np.sort(samples)
    bounds = [samples[int(len(samples) * (i + 1) / k)]
              for i in range(k - 1)] if k > 1 else []

    def part_of(vals):
        return np.searchsorted(np.asarray(bounds), vals, side="right") \
            if bounds else np.zeros(len(vals), dtype=np.int64)

    parts: List[List[Block]] = [[] for _ in range(k)]
    for b in blocks:
        vals = b.column(op.key).to_numpy(zero_copy_only=False)
        pid = part_of(vals)
        acc = BlockAccessor(b)
        for j in range(k):
            idx = np.nonzero(pid == j)[0]
            if len(idx):
                parts[j].append(acc.take([int(x) for x in idx]))
    order = range(k - 1, -1, -1) if op.descending else range(k)
    for j in order:
        if not parts[j]:
            continue
        merged = concat_blocks(parts[j])
        idx = BlockAccessor(merged).sort_indices(op.key, op.descending)
        yield BlockAccessor(merged).take([int(x) for x in idx])
