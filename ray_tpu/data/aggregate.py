"""Groupby + aggregations.

Reference parity: python/ray/data/aggregate.py and grouped_data.py —
AggregateFn protocol (init/accumulate/merge/finalize) with built-ins
Count/Sum/Min/Max/Mean/Std; execution is hash-partition + per-partition
sorted aggregation.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterator, List, Optional

import numpy as np
import pyarrow as pa

from . import logical as L
from .block import Block, BlockAccessor, concat_blocks


class AggregateFn:
    def __init__(self, *, init: Callable[[], Any],
                 accumulate: Callable[[Any, np.ndarray], Any],
                 merge: Callable[[Any, Any], Any],
                 finalize: Callable[[Any], Any],
                 name: str, on: Optional[str] = None):
        self.init = init
        self.accumulate = accumulate
        self.merge = merge
        self.finalize = finalize
        self.name = name
        self.on = on


def Count() -> AggregateFn:
    return AggregateFn(
        init=lambda: 0,
        accumulate=lambda a, vals: a + len(vals),
        merge=lambda a, b: a + b,
        finalize=lambda a: a,
        name="count()")


def _col_agg(op_name: str, on: str, np_fn, merge_fn) -> AggregateFn:
    return AggregateFn(
        init=lambda: None,
        accumulate=lambda a, vals: (
            np_fn(vals) if a is None else merge_fn(a, np_fn(vals))
        ) if len(vals) else a,
        merge=lambda a, b: b if a is None else (a if b is None
                                                else merge_fn(a, b)),
        finalize=lambda a: a,
        name=f"{op_name}({on})", on=on)


def Sum(on: str) -> AggregateFn:
    return _col_agg("sum", on, np.sum, lambda a, b: a + b)


def Min(on: str) -> AggregateFn:
    return _col_agg("min", on, np.min, min)


def Max(on: str) -> AggregateFn:
    return _col_agg("max", on, np.max, max)


def Mean(on: str) -> AggregateFn:
    return AggregateFn(
        init=lambda: (0.0, 0),
        accumulate=lambda a, vals: (a[0] + float(np.sum(vals)),
                                    a[1] + len(vals)),
        merge=lambda a, b: (a[0] + b[0], a[1] + b[1]),
        finalize=lambda a: a[0] / a[1] if a[1] else None,
        name=f"mean({on})", on=on)


def Std(on: str, ddof: int = 1) -> AggregateFn:
    # Chan et al. parallel variance: track (count, mean, M2).
    def acc(a, vals):
        n0, mu0, m20 = a
        n1 = len(vals)
        if n1 == 0:
            return a
        mu1 = float(np.mean(vals))
        m21 = float(np.var(vals)) * n1
        return _merge((n0, mu0, m20), (n1, mu1, m21))

    def _merge(a, b):
        n0, mu0, m20 = a
        n1, mu1, m21 = b
        n = n0 + n1
        if n == 0:
            return a
        delta = mu1 - mu0
        mu = mu0 + delta * n1 / n
        m2 = m20 + m21 + delta * delta * n0 * n1 / n
        return (n, mu, m2)

    return AggregateFn(
        init=lambda: (0, 0.0, 0.0),
        accumulate=acc, merge=_merge,
        finalize=lambda a: float(np.sqrt(a[2] / (a[0] - ddof)))
        if a[0] > ddof else None,
        name=f"std({on})", on=on)


def groupby_execute(op: L.GroupByAggregate, upstream, backend,
                    max_in_flight) -> Iterator[Block]:
    """Hash-aggregate across all input blocks; one output block, sorted
    by key (global aggregate when key is None)."""
    from .execution import _as_blocks

    states: Dict[Any, List[Any]] = {}

    def touch(key):
        if key not in states:
            states[key] = [agg.init() for agg in op.aggs]
        return states[key]

    for ref in upstream:
        for block in _as_blocks(backend.get(ref)):
            if block.num_rows == 0:
                continue
            cols = {c: block.column(c).to_numpy(zero_copy_only=False)
                    for c in block.column_names}
            if op.key is None:
                st = touch(None)
                for i, agg in enumerate(op.aggs):
                    vals = cols[agg.on] if agg.on else cols[
                        next(iter(cols))]
                    st[i] = agg.accumulate(st[i], vals)
                continue
            keys = cols[op.key]
            order = np.argsort(keys, kind="stable")
            sorted_keys = keys[order]
            uniq, starts = np.unique(sorted_keys, return_index=True)
            starts = list(starts) + [len(sorted_keys)]
            for u_i, key in enumerate(uniq):
                sel = order[starts[u_i]:starts[u_i + 1]]
                st = touch(key.item() if hasattr(key, "item") else key)
                for i, agg in enumerate(op.aggs):
                    vals = cols[agg.on][sel] if agg.on else \
                        np.empty(len(sel))
                    st[i] = agg.accumulate(st[i], vals)

    if not states:
        return
    if op.key is None:
        row = {agg.name: agg.finalize(st_i)
               for agg, st_i in zip(op.aggs, states[None])}
        yield pa.table({k: [v] for k, v in row.items()})
        return
    keys_sorted = sorted(states.keys())
    out: Dict[str, list] = {op.key: list(keys_sorted)}
    for i, agg in enumerate(op.aggs):
        out[agg.name] = [agg.finalize(states[k][i]) for k in keys_sorted]
    yield pa.table(out)
