"""Dataset: lazy, logically-planned, streaming-executed.

Reference parity: python/ray/data/dataset.py (Dataset :154) — API
semantics only. TPU-first additions: iter_jax_batches places batches
onto a jax sharding with background prefetch (overlap host→device with
compute), and streaming_split feeds per-host Train workers.
"""

from __future__ import annotations

import itertools
import queue as _queue
import threading
from typing import Any, Callable, Dict, Iterator, List, Optional, Union

import numpy as np
import pyarrow as pa

from . import logical as L
from .aggregate import AggregateFn, Count, Max, Mean, Min, Std, Sum
from .block import Block, BlockAccessor, concat_blocks
from .execution import (DEFAULT_MAX_IN_FLIGHT, InlineBackend, execute_plan,
                        pick_backend)


class Dataset:
    def __init__(self, plan: L.LogicalOp):
        self._plan = plan

    # -- transforms (lazy) --------------------------------------------------

    def map(self, fn: Callable[[dict], dict], **opts) -> "Dataset":
        return Dataset(L.MapRows(self._plan, fn, **_map_opts(opts)))

    def map_batches(self, fn: Union[Callable, type], *,
                    batch_size: Optional[int] = None,
                    batch_format: str = "numpy",
                    compute: Any = None,
                    fn_constructor_args: tuple = (),
                    fn_constructor_kwargs: Optional[dict] = None,
                    concurrency: Optional[int] = None,
                    num_cpus: Optional[float] = None,
                    num_tpus: Optional[float] = None) -> "Dataset":
        ctor = None
        if isinstance(fn, type):
            ctor = (fn, fn_constructor_args, fn_constructor_kwargs or {})
            fn = None
        return Dataset(L.MapBatches(
            self._plan, fn, batch_size=batch_size, batch_format=batch_format,
            fn_constructor=ctor, compute=compute, concurrency=concurrency,
            num_cpus=num_cpus, num_tpus=num_tpus))

    def filter(self, fn: Callable[[dict], bool], **opts) -> "Dataset":
        return Dataset(L.Filter(self._plan, fn, **_map_opts(opts)))

    def flat_map(self, fn: Callable[[dict], List[dict]], **opts) -> "Dataset":
        return Dataset(L.FlatMap(self._plan, fn, **_map_opts(opts)))

    def add_column(self, name: str, fn: Callable[[dict], Any]) -> "Dataset":
        return self.map(lambda row, _fn=fn, _n=name: {**row, _n: _fn(row)})

    def drop_columns(self, cols: List[str]) -> "Dataset":
        cols = set(cols)
        return self.map_batches(
            lambda b, _c=cols: {k: v for k, v in b.items() if k not in _c})

    def select_columns(self, cols: List[str]) -> "Dataset":
        keep = list(cols)
        return self.map_batches(
            lambda b, _c=keep: {k: b[k] for k in _c})

    def rename_columns(self, mapping: Dict[str, str]) -> "Dataset":
        return self.map_batches(
            lambda b, _m=mapping: {_m.get(k, k): v for k, v in b.items()})

    def limit(self, n: int) -> "Dataset":
        return Dataset(L.Limit(self._plan, n))

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        return Dataset(L.RandomShuffle(self._plan, seed))

    def repartition(self, num_blocks: int) -> "Dataset":
        return Dataset(L.Repartition(self._plan, num_blocks))

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        return Dataset(L.Sort(self._plan, key, descending))

    def union(self, *others: "Dataset") -> "Dataset":
        return Dataset(L.Union(self._plan, [o._plan for o in others]))

    def zip(self, other: "Dataset") -> "Dataset":
        return Dataset(L.Zip(self._plan, other._plan))

    def groupby(self, key: Optional[str]) -> "GroupedData":
        return GroupedData(self, key)

    # -- aggregates (eager) -------------------------------------------------

    def aggregate(self, *aggs: AggregateFn) -> dict:
        ds = Dataset(L.GroupByAggregate(self._plan, None, list(aggs)))
        rows = ds.take_all()
        return rows[0] if rows else {}

    def count(self) -> int:
        total = 0
        for blk in self._execute():
            total += BlockAccessor(blk).num_rows()
        return total

    def sum(self, on: str):
        return self.aggregate(Sum(on)).get(f"sum({on})")

    def min(self, on: str):
        return self.aggregate(Min(on)).get(f"min({on})")

    def max(self, on: str):
        return self.aggregate(Max(on)).get(f"max({on})")

    def mean(self, on: str):
        return self.aggregate(Mean(on)).get(f"mean({on})")

    def std(self, on: str):
        return self.aggregate(Std(on)).get(f"std({on})")

    # -- consumption --------------------------------------------------------

    def _execute(self, **kw) -> Iterator[Block]:
        backend = pick_backend()
        yield from execute_plan(self._plan, backend, **kw)

    def take(self, n: int = 20) -> List[dict]:
        out: List[dict] = []
        for blk in self.limit(n)._execute():
            out.extend(BlockAccessor(blk).iter_rows())
            if len(out) >= n:
                break
        return out[:n]

    def take_all(self) -> List[dict]:
        out: List[dict] = []
        for blk in self._execute():
            out.extend(BlockAccessor(blk).iter_rows())
        return out

    def take_batch(self, batch_size: int = 20,
                   batch_format: str = "numpy"):
        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format=batch_format):
            return batch
        return {}

    def show(self, limit: int = 20) -> None:
        for row in self.take(limit):
            print(row)

    def schema(self) -> Optional[pa.Schema]:
        for blk in self.limit(1)._execute():
            return BlockAccessor(blk).schema()
        return None

    def columns(self) -> List[str]:
        s = self.schema()
        return list(s.names) if s is not None else []

    def materialize(self) -> "MaterializedDataset":
        blocks = list(self._execute())
        return MaterializedDataset(blocks)

    def iter_rows(self) -> Iterator[dict]:
        for blk in self._execute():
            yield from BlockAccessor(blk).iter_rows()

    def iter_batches(self, *, batch_size: int = 256,
                     batch_format: str = "numpy",
                     drop_last: bool = False,
                     prefetch_batches: int = 1) -> Iterator[Any]:
        """Re-batch the output block stream to exactly batch_size rows
        (batch_size=None: each output block is one batch, reference
        iter_batches semantics)."""
        def gen():
            carry: Optional[Block] = None
            if batch_size is None:
                for blk in self._execute():
                    if blk.num_rows:
                        yield BlockAccessor(blk).to_batch(batch_format)
                return
            for blk in self._execute():
                carry = blk if carry is None else concat_blocks(
                    [carry, blk])
                while carry.num_rows >= batch_size:
                    acc = BlockAccessor(carry)
                    yield BlockAccessor(
                        acc.slice(0, batch_size)).to_batch(batch_format)
                    carry = acc.slice(batch_size, carry.num_rows)
            if carry is not None and carry.num_rows and not drop_last:
                yield BlockAccessor(carry).to_batch(batch_format)

        if prefetch_batches and prefetch_batches > 0:
            yield from _prefetch(gen(), prefetch_batches)
        else:
            yield from gen()

    def iter_jax_batches(self, *, batch_size: int = 256,
                         sharding: Any = None,
                         dtypes: Optional[Dict[str, Any]] = None,
                         drop_last: bool = True,
                         prefetch_batches: int = 2) -> Iterator[Dict]:
        """Batches as jax.Arrays, optionally placed on a sharding.

        TPU path: host numpy → device_put onto `sharding` (a
        jax.sharding.Sharding or a dict col→Sharding); prefetch_batches
        overlaps the host pipeline + transfer with device compute.
        """
        import jax

        def convert(batch: Dict[str, np.ndarray]) -> Dict[str, Any]:
            out = {}
            for k, v in batch.items():
                if dtypes and k in dtypes:
                    v = v.astype(dtypes[k])
                if sharding is None:
                    out[k] = jax.numpy.asarray(v)
                else:
                    s = sharding[k] if isinstance(sharding, dict) \
                        else sharding
                    out[k] = jax.device_put(v, s)
            return out

        it = (convert(b) for b in self.iter_batches(
            batch_size=batch_size, batch_format="numpy",
            drop_last=drop_last, prefetch_batches=0))
        yield from _prefetch(it, prefetch_batches)

    def iter_torch_batches(self, *, batch_size: int = 256,
                           drop_last: bool = False,
                           prefetch_batches: int = 1) -> Iterator[Dict]:
        import torch
        for b in self.iter_batches(batch_size=batch_size,
                                   batch_format="numpy",
                                   drop_last=drop_last,
                                   prefetch_batches=prefetch_batches):
            yield {k: torch.as_tensor(v) for k, v in b.items()}

    # -- splits -------------------------------------------------------------

    def split(self, n: int, *, equal: bool = False
              ) -> List["MaterializedDataset"]:
        blocks = list(self._execute())
        merged = concat_blocks(blocks) if blocks else pa.table({})
        total = merged.num_rows
        if equal:
            total = (total // n) * n
        base, rem = divmod(total, n)
        out, start = [], 0
        for i in range(n):
            size = base + (0 if equal else (1 if i < rem else 0))
            out.append(MaterializedDataset([merged.slice(start, size)]))
            start += size
        return out

    def streaming_split(self, n: int) -> List["_SplitIterator"]:
        """n coordinated iterators over disjoint shards (round-robin by
        block) — the Train ingest path (one per training worker)."""
        q: List[_queue.Queue] = [_queue.Queue(maxsize=4) for _ in range(n)]
        done = object()

        def feeder():
            try:
                for i, blk in enumerate(self._execute()):
                    q[i % n].put(blk)
            finally:
                for qq in q:
                    qq.put(done)

        threading.Thread(target=feeder, daemon=True).start()
        return [_SplitIterator(qq, done) for qq in q]

    # -- writes -------------------------------------------------------------

    def write_parquet(self, path: str) -> None:
        from .datasource import write_blocks
        write_blocks(self._execute(), path, "parquet")

    def write_csv(self, path: str) -> None:
        from .datasource import write_blocks
        write_blocks(self._execute(), path, "csv")

    def write_json(self, path: str) -> None:
        from .datasource import write_blocks
        write_blocks(self._execute(), path, "json")

    def to_pandas(self):
        blocks = list(self._execute())
        return concat_blocks(blocks).to_pandas() if blocks else None

    def to_arrow(self) -> pa.Table:
        blocks = list(self._execute())
        return concat_blocks(blocks) if blocks else pa.table({})

    def stats(self) -> str:
        ops = [repr(o) for o in L.optimize(self._plan).chain()]
        return " -> ".join(ops)

    def __repr__(self):
        return f"Dataset(plan={self.stats()})"


class MaterializedDataset(Dataset):
    def __init__(self, blocks: List[Block]):
        super().__init__(L.InputData(blocks))
        self._blocks = blocks

    def num_blocks(self) -> int:
        return len(self._blocks)

    def size_bytes(self) -> int:
        return sum(BlockAccessor(b).size_bytes() for b in self._blocks)


class GroupedData:
    def __init__(self, ds: Dataset, key: Optional[str]):
        self._ds = ds
        self._key = key

    def aggregate(self, *aggs: AggregateFn) -> Dataset:
        return Dataset(L.GroupByAggregate(self._ds._plan, self._key,
                                          list(aggs)))

    def count(self) -> Dataset:
        return self.aggregate(Count())

    def sum(self, on: str) -> Dataset:
        return self.aggregate(Sum(on))

    def min(self, on: str) -> Dataset:
        return self.aggregate(Min(on))

    def max(self, on: str) -> Dataset:
        return self.aggregate(Max(on))

    def mean(self, on: str) -> Dataset:
        return self.aggregate(Mean(on))

    def map_groups(self, fn: Callable) -> Dataset:
        """Sort by key, then map each group's batch through fn."""
        key = self._key
        sorted_ds = self._ds.sort(key)

        def per_block(batch: Dict[str, np.ndarray]):
            keys = batch[key]
            uniq, starts = np.unique(keys, return_index=True)
            starts = list(starts) + [len(keys)]
            outs = []
            for i in range(len(uniq)):
                group = {k: v[starts[i]:starts[i + 1]]
                         for k, v in batch.items()}
                outs.append(fn(group))
            merged: Dict[str, list] = {}
            for o in outs:
                for k, v in o.items():
                    merged.setdefault(k, []).append(np.atleast_1d(v))
            return {k: np.concatenate(v) for k, v in merged.items()}

        # One batch per (whole) block keeps groups intact after the
        # range-partition sort.
        return sorted_ds.map_batches(per_block, batch_size=None)


class _SplitIterator:
    def __init__(self, q: _queue.Queue, done: Any):
        self._q = q
        self._done = done

    def iter_batches(self, *, batch_size: int = 256,
                     batch_format: str = "numpy",
                     drop_last: bool = False) -> Iterator[Any]:
        carry: Optional[Block] = None
        while True:
            item = self._q.get()
            if item is self._done:
                break
            carry = item if carry is None else concat_blocks([carry, item])
            while carry.num_rows >= batch_size:
                acc = BlockAccessor(carry)
                yield BlockAccessor(
                    acc.slice(0, batch_size)).to_batch(batch_format)
                carry = acc.slice(batch_size, carry.num_rows)
        if carry is not None and carry.num_rows and not drop_last:
            yield BlockAccessor(carry).to_batch(batch_format)

    def iter_rows(self) -> Iterator[dict]:
        for batch in self.iter_batches(batch_size=256,
                                       batch_format="pyarrow"):
            yield from BlockAccessor(batch).iter_rows()


def _prefetch(it: Iterator[Any], depth: int) -> Iterator[Any]:
    """Background-thread prefetch of up to `depth` items."""
    q: _queue.Queue = _queue.Queue(maxsize=max(depth, 1))
    done = object()
    err: List[BaseException] = []

    def worker():
        try:
            for item in it:
                q.put(item)
        except BaseException as e:  # propagate to consumer
            err.append(e)
        finally:
            q.put(done)

    threading.Thread(target=worker, daemon=True).start()
    while True:
        item = q.get()
        if item is done:
            if err:
                raise err[0]
            return
        yield item


def _map_opts(opts: dict) -> dict:
    allowed = {"num_cpus", "num_tpus", "concurrency"}
    bad = set(opts) - allowed
    if bad:
        raise ValueError(f"unknown option(s): {sorted(bad)}")
    return opts
