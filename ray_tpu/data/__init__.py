"""ray_tpu.data: lazy, streaming, Arrow-block datasets.

Reference parity: python/ray/data (Dataset dataset.py:154, logical plan
_internal/logical/, StreamingExecutor _internal/execution/
streaming_executor.py:48) — capability parity, TPU-first execution:
batches hand off to jax.Arrays placed on mesh shardings with
prefetch (`Dataset.iter_jax_batches`).
"""

from .._private.usage import record_library_usage as _rlu
_rlu("data")
del _rlu


from .aggregate import AggregateFn, Count, Max, Mean, Min, Std, Sum
from .block import Block, BlockAccessor
from .dataset import Dataset, GroupedData, MaterializedDataset
from .datasource import (from_arrow, from_items, from_numpy, from_pandas,
                         range, read_binary_files, read_csv, read_datasource,
                         read_images, read_json, read_numpy, read_parquet,
                         read_text, read_webdataset)

__all__ = [
    "Dataset", "MaterializedDataset", "GroupedData", "Block",
    "BlockAccessor", "AggregateFn", "Count", "Sum", "Min", "Max", "Mean",
    "Std", "range", "from_items", "from_numpy", "from_arrow", "from_pandas",
    "read_parquet", "read_csv", "read_json", "read_text", "read_numpy",
    "read_binary_files", "read_datasource", "read_images",
    "read_webdataset",
]
