"""Mixture-of-Experts: top-k routing + capacity-based dispatch.

Net-new for the TPU build (EP is absent in the reference — SURVEY.md
§2.4; vLLM-internal only). GShard/Switch-style formulation chosen FOR the
hardware: dispatch/combine are einsums against a [tokens, experts,
capacity] one-hot — static shapes, MXU-friendly, and when the expert
dimension is sharded over the `ep` mesh axis XLA lowers the dispatch
einsum to the all-to-all over ICI (no hand-written collective).

Two dispatch modes:

- capacity (default-off via ``dropless=False``): tokens over an
  expert's capacity are dropped (residual passes through), the standard
  Switch behavior — einsum one-hot dispatch, shapes static under jit.
- dropless (``dropless=True``): sort-by-expert + ``lax.ragged_dot``
  grouped matmuls — every routed token computes, no capacity knob. With
  an ``ep`` mesh axis the token shards exchange assignments with the
  expert owners through ``ops/ragged_exchange.py`` (TPU: the real
  ``ragged_all_to_all`` ICI collective; CPU tests: semantics-exact
  emulation). SURVEY §2.4's EP target (`ragged_all_to_all`-style,
  VERDICT r4 weak #7).
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..parallel.sharding import with_logical_constraint as wlc
from .ragged_exchange import exchange_offsets, ragged_all_to_all


def router_probs(x: jax.Array, router_w: jax.Array
                 ) -> jax.Array:
    """x: [T, H]; router_w: [H, E] → probs [T, E] (float32 softmax)."""
    logits = x.astype(jnp.float32) @ router_w.astype(jnp.float32)
    return jax.nn.softmax(logits, axis=-1)


def top_k_routing(probs: jax.Array, k: int
                  ) -> Tuple[jax.Array, jax.Array]:
    """probs: [T, E] → (gates [T, k] renormalized, indices [T, k])."""
    gates, idx = jax.lax.top_k(probs, k)
    gates = gates / jnp.maximum(
        jnp.sum(gates, axis=-1, keepdims=True), 1e-9)
    return gates, idx


def load_balancing_loss(probs: jax.Array, idx: jax.Array,
                        num_experts: int) -> jax.Array:
    """Switch aux loss: E * Σ_e fraction_e * mean_prob_e."""
    t = probs.shape[0]
    sel = jax.nn.one_hot(idx[:, 0], num_experts)      # top-1 assignment
    fraction = jnp.mean(sel, axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    return num_experts * jnp.sum(fraction * mean_prob)


def make_dispatch(probs: jax.Array, k: int, capacity: int
                  ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Build (dispatch [T, E, C] one-hot, combine [T, E, C] gate-weighted,
    aux_loss) for capacity C per expert."""
    t, num_experts = probs.shape
    gates, idx = top_k_routing(probs, k)
    aux = load_balancing_loss(probs, idx, num_experts)

    dispatch = jnp.zeros((t, num_experts, capacity), probs.dtype)
    combine = jnp.zeros((t, num_experts, capacity), probs.dtype)
    for slot in range(k):                      # k is tiny (1-2): unrolled
        e = idx[:, slot]                       # [T]
        onehot = jax.nn.one_hot(e, num_experts, dtype=probs.dtype)
        # position of each token within its expert's queue, counting
        # earlier slots' assignments too
        prior = dispatch.sum(axis=2)           # [T, E] taken so far
        pos_in_e = (jnp.cumsum(onehot, axis=0) - onehot
                    + prior.sum(axis=0, keepdims=True))  # [T, E]
        pos = jnp.sum(pos_in_e * onehot, axis=1)          # [T]
        keep = pos < capacity
        pos_oh = jax.nn.one_hot(pos, capacity, dtype=probs.dtype)
        contrib = (onehot * keep[:, None])[:, :, None] * pos_oh[:, None, :]
        dispatch = dispatch + contrib
        combine = combine + contrib * gates[:, slot, None, None]
    return dispatch, combine, aux


def _swiglu_ragged(xs: jax.Array, wg: jax.Array, wi: jax.Array,
                   wd: jax.Array, counts: jax.Array) -> jax.Array:
    """Grouped SwiGLU over expert-sorted rows: three ragged_dot calls
    (per-group matmuls tile onto the MXU without capacity padding)."""
    dt = xs.dtype
    gate = jax.nn.silu(lax.ragged_dot(xs, wg.astype(dt), counts))
    up = lax.ragged_dot(xs, wi.astype(dt), counts)
    return lax.ragged_dot(gate * up, wd.astype(dt), counts)


def _dropless_local(xt: jax.Array, gates: jax.Array, idx: jax.Array,
                    wi: jax.Array, wg: jax.Array, wd: jax.Array
                    ) -> jax.Array:
    """Single-shard dropless dispatch: stable-sort the T*k assignments
    by expert, grouped matmuls, gate-weighted scatter-add combine."""
    t, h = xt.shape
    k = idx.shape[1]
    num_experts = wi.shape[0]
    eid = idx.reshape(-1)                          # [T*k]
    tok = jnp.repeat(jnp.arange(t), k)
    order = jnp.argsort(eid, stable=True)
    xs = xt[tok[order]]
    counts = jnp.bincount(eid, length=num_experts).astype(jnp.int32)
    ys = _swiglu_ragged(xs, wg, wi, wd, counts)
    gat = gates.reshape(-1)[order].astype(jnp.float32)
    out = jnp.zeros((t, h), jnp.float32).at[tok[order]].add(
        ys.astype(jnp.float32) * gat[:, None])
    return out.astype(xt.dtype)


def _dropless_ep_shard(xt: jax.Array, router_w: jax.Array,
                       wi: jax.Array, wg: jax.Array, wd: jax.Array,
                       *, top_k: int, num_experts: int,
                       axis_name: str) -> jax.Array:
    """Per-shard body (inside shard_map over the ep axis).

    Tokens arrive replicated w.r.t. ep; this shard owns the STATIC
    slice [me*Tl, (me+1)*Tl) of tokens and the experts
    [me*El, (me+1)*El). Assignments travel to their expert's owner via
    the ragged exchange, compute in grouped ragged_dot matmuls, travel
    back, and combine on the token's home shard — zero drops, compute
    proportional to each shard's routed load.
    """
    P = lax.psum(1, axis_name)
    me = lax.axis_index(axis_name)
    t, h = xt.shape                    # t is pre-padded to a multiple of P
    tl = t // P
    el = wi.shape[0]                   # local experts (= num_experts / P)
    k = top_k

    xs_tok = lax.dynamic_slice_in_dim(xt, me * tl, tl)
    probs = router_probs(xs_tok, router_w)
    gates, idx = top_k_routing(probs, k)           # [Tl, k]

    # ---- forward exchange: my assignments -> expert owners ----
    a = tl * k
    eid = idx.reshape(-1)
    tok = jnp.repeat(jnp.arange(tl), k)
    order = jnp.argsort(eid, stable=True)          # dest-shard-major
    xs = xs_tok[tok[order]]
    counts_e = jnp.bincount(eid, length=num_experts).astype(jnp.int32)
    send_sizes = counts_e.reshape(P, el).sum(axis=1)
    in_off, out_off, recv_sizes = exchange_offsets(send_sizes, axis_name)

    buf_rows = t * k                   # worst case: every assignment here
    buf = jnp.zeros((buf_rows, h), xt.dtype)
    buf = ragged_all_to_all(xs, buf, in_off, send_sizes, out_off,
                            recv_sizes, axis_name=axis_name)
    # ship the expert ids alongside (padding marker -1 survives in
    # unwritten rows and routes to the zero-weight trash group below)
    ebuf = jnp.full((buf_rows, 1), -1, jnp.int32)
    ebuf = ragged_all_to_all(eid[order][:, None].astype(jnp.int32), ebuf,
                             in_off, send_sizes, out_off, recv_sizes,
                             axis_name=axis_name)
    local_e = jnp.where(ebuf[:, 0] >= 0, ebuf[:, 0] - me * el, el)

    # ---- local grouped compute (El real groups + 1 zero trash group) --
    order2 = jnp.argsort(local_e, stable=True)
    xs2 = buf[order2]
    counts2 = jnp.bincount(local_e, length=el + 1).astype(jnp.int32)
    zeros = jnp.zeros((1,) + wi.shape[1:], wi.dtype)
    ys2 = _swiglu_ragged(xs2, jnp.concatenate([wg, zeros]),
                         jnp.concatenate([wi, zeros]),
                         jnp.concatenate([wd, jnp.zeros(
                             (1,) + wd.shape[1:], wd.dtype)]), counts2)
    ys = jnp.zeros_like(buf).at[order2].set(ys2)   # undo local sort

    # ---- return exchange: computed rows -> token owners ----
    in_off_r = jnp.cumsum(recv_sizes) - recv_sizes
    out_off_r = lax.all_to_all(in_off, axis_name, 0, 0)
    back = jnp.zeros((a, h), xt.dtype)
    back = ragged_all_to_all(ys, back, in_off_r, recv_sizes, out_off_r,
                             send_sizes, axis_name=axis_name)

    gat = gates.reshape(-1)[order].astype(jnp.float32)
    out_l = jnp.zeros((tl, h), jnp.float32).at[tok[order]].add(
        back.astype(jnp.float32) * gat[:, None])
    # tokens are shard-disjoint: the P('ep') out_spec reassembles the
    # full token axis (no psum, no gather needed)
    return out_l.astype(xt.dtype)


def moe_ffn_dropless(x: jax.Array, router_w: jax.Array,
                     wi: jax.Array, wg: jax.Array, wd: jax.Array,
                     *, top_k: int = 2,
                     mesh: Optional[jax.sharding.Mesh] = None
                     ) -> Tuple[jax.Array, jax.Array]:
    """Dropless MoE SwiGLU feed-forward (same contract as moe_ffn)."""
    from jax.sharding import PartitionSpec as PSpec
    from ..parallel.mesh import AXIS_EP
    b, s, h = x.shape
    num_experts = router_w.shape[1]
    xt = x.reshape(b * s, h)
    t = xt.shape[0]
    # aux loss on the full token set (cheap: router matmul only)
    probs = router_probs(xt, router_w)
    gates, idx = top_k_routing(probs, top_k)
    aux = load_balancing_loss(probs, idx, num_experts)

    ep = 1
    if mesh is not None:
        ep = dict(zip(mesh.axis_names, mesh.devices.shape)).get(AXIS_EP, 1)
    if ep <= 1:
        out = _dropless_local(xt, gates, idx, wi, wg, wd)
        return out.reshape(b, s, h), aux

    pad = (-t) % ep
    if pad:
        xt = jnp.concatenate([xt, jnp.zeros((pad, h), xt.dtype)])
    fn = jax.shard_map(
        functools.partial(_dropless_ep_shard, top_k=top_k,
                          num_experts=num_experts, axis_name=AXIS_EP),
        mesh=mesh,
        in_specs=(PSpec(), PSpec(), PSpec(AXIS_EP), PSpec(AXIS_EP),
                  PSpec(AXIS_EP)),
        out_specs=PSpec(AXIS_EP),
        axis_names={AXIS_EP})
    # f32 across the manual-ep boundary is LOAD-BEARING: the bf16 grad
    # path through a partial-manual shard_map check-fails XLA's SPMD
    # partitioner ("Invalid binary instruction opcode copy" in
    # CloneAllReduce; re-verified on this jaxlib — forward-only bf16
    # works, jax.grad crashes). Same workaround as models/pipeline.py.
    out = fn(xt.astype(jnp.float32), router_w, wi, wg, wd)
    if pad:
        out = out[:t]
    out = out.reshape(b, s, h).astype(x.dtype)
    # pin the output back to the activation layout — without the
    # constraint the partitioner can pick a tiling that has no named
    # PartitionSpec (jit output-sharding inference then fails)
    return wlc(out, "batch", "seq", "act_embed"), aux


def moe_ffn(x: jax.Array, router_w: jax.Array,
            wi: jax.Array, wg: jax.Array, wd: jax.Array,
            *, top_k: int = 2, capacity_factor: float = 1.25,
            dropless: bool = False,
            mesh: Optional[jax.sharding.Mesh] = None
            ) -> Tuple[jax.Array, jax.Array]:
    """MoE SwiGLU feed-forward.

    x: [B, S, H]; router_w: [H, E]; wi/wg: [E, H, F]; wd: [E, F, H].
    Returns (out [B, S, H], aux_loss scalar). Shard wi/wg/wd with logical
    axes ("experts", ...) and the dispatched activations pick up the
    all-to-all over the ep mesh axis. ``dropless=True`` switches to the
    sort + ragged_dot path (no capacity drops; see module docstring).
    """
    if dropless:
        return moe_ffn_dropless(x, router_w, wi, wg, wd,
                                top_k=top_k, mesh=mesh)
    b, s, h = x.shape
    num_experts = router_w.shape[1]
    dt = x.dtype
    xt = x.reshape(b * s, h)
    t = xt.shape[0]
    capacity = max(int(t * top_k / num_experts * capacity_factor), 1)  # jaxlint: disable=JL001 -- t is a static shape; this int() runs at trace time on Python scalars

    probs = router_probs(xt, router_w)
    dispatch, combine, aux = make_dispatch(probs, top_k, capacity)
    # dispatch/combine einsums stay f32: they are one-hot gathers (tiny
    # FLOPs next to the expert matmuls), f32 keeps the gate weighting
    # exact, and bf16 one-hot contractions inside a partial-manual
    # shard_map (PP+MoE) check-fail both XLA SPMD partitioners
    # ("Invalid binary instruction opcode copy", jax 0.9/jaxlib).

    expert_in = jnp.einsum("tec,th->ech", dispatch,
                           xt.astype(jnp.float32)).astype(dt)
    expert_in = wlc(expert_in, "experts", None, "act_embed")
    gate = jax.nn.silu(jnp.einsum("ech,ehf->ecf", expert_in,
                                  wg.astype(dt)))
    up = jnp.einsum("ech,ehf->ecf", expert_in, wi.astype(dt))
    expert_out = jnp.einsum("ecf,efh->ech", gate * up, wd.astype(dt))
    expert_out = wlc(expert_out, "experts", None, "act_embed")
    out = jnp.einsum("tec,ech->th", combine,
                     expert_out.astype(jnp.float32))
    return out.reshape(b, s, h).astype(dt), aux
