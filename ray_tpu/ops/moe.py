"""Mixture-of-Experts: top-k routing + capacity-based dispatch.

Net-new for the TPU build (EP is absent in the reference — SURVEY.md
§2.4; vLLM-internal only). GShard/Switch-style formulation chosen FOR the
hardware: dispatch/combine are einsums against a [tokens, experts,
capacity] one-hot — static shapes, MXU-friendly, and when the expert
dimension is sharded over the `ep` mesh axis XLA lowers the dispatch
einsum to the all-to-all over ICI (no hand-written collective).

Tokens over an expert's capacity are dropped (residual passes through),
the standard Switch behavior that keeps shapes static under jit.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from ..parallel.sharding import with_logical_constraint as wlc


def router_probs(x: jax.Array, router_w: jax.Array
                 ) -> jax.Array:
    """x: [T, H]; router_w: [H, E] → probs [T, E] (float32 softmax)."""
    logits = x.astype(jnp.float32) @ router_w.astype(jnp.float32)
    return jax.nn.softmax(logits, axis=-1)


def top_k_routing(probs: jax.Array, k: int
                  ) -> Tuple[jax.Array, jax.Array]:
    """probs: [T, E] → (gates [T, k] renormalized, indices [T, k])."""
    gates, idx = jax.lax.top_k(probs, k)
    gates = gates / jnp.maximum(
        jnp.sum(gates, axis=-1, keepdims=True), 1e-9)
    return gates, idx


def load_balancing_loss(probs: jax.Array, idx: jax.Array,
                        num_experts: int) -> jax.Array:
    """Switch aux loss: E * Σ_e fraction_e * mean_prob_e."""
    t = probs.shape[0]
    sel = jax.nn.one_hot(idx[:, 0], num_experts)      # top-1 assignment
    fraction = jnp.mean(sel, axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    return num_experts * jnp.sum(fraction * mean_prob)


def make_dispatch(probs: jax.Array, k: int, capacity: int
                  ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Build (dispatch [T, E, C] one-hot, combine [T, E, C] gate-weighted,
    aux_loss) for capacity C per expert."""
    t, num_experts = probs.shape
    gates, idx = top_k_routing(probs, k)
    aux = load_balancing_loss(probs, idx, num_experts)

    dispatch = jnp.zeros((t, num_experts, capacity), probs.dtype)
    combine = jnp.zeros((t, num_experts, capacity), probs.dtype)
    for slot in range(k):                      # k is tiny (1-2): unrolled
        e = idx[:, slot]                       # [T]
        onehot = jax.nn.one_hot(e, num_experts, dtype=probs.dtype)
        # position of each token within its expert's queue, counting
        # earlier slots' assignments too
        prior = dispatch.sum(axis=2)           # [T, E] taken so far
        pos_in_e = (jnp.cumsum(onehot, axis=0) - onehot
                    + prior.sum(axis=0, keepdims=True))  # [T, E]
        pos = jnp.sum(pos_in_e * onehot, axis=1)          # [T]
        keep = pos < capacity
        pos_oh = jax.nn.one_hot(pos, capacity, dtype=probs.dtype)
        contrib = (onehot * keep[:, None])[:, :, None] * pos_oh[:, None, :]
        dispatch = dispatch + contrib
        combine = combine + contrib * gates[:, slot, None, None]
    return dispatch, combine, aux


def moe_ffn(x: jax.Array, router_w: jax.Array,
            wi: jax.Array, wg: jax.Array, wd: jax.Array,
            *, top_k: int = 2, capacity_factor: float = 1.25
            ) -> Tuple[jax.Array, jax.Array]:
    """MoE SwiGLU feed-forward.

    x: [B, S, H]; router_w: [H, E]; wi/wg: [E, H, F]; wd: [E, F, H].
    Returns (out [B, S, H], aux_loss scalar). Shard wi/wg/wd with logical
    axes ("experts", ...) and the dispatched activations pick up the
    all-to-all over the ep mesh axis.
    """
    b, s, h = x.shape
    num_experts = router_w.shape[1]
    dt = x.dtype
    xt = x.reshape(b * s, h)
    t = xt.shape[0]
    capacity = max(int(t * top_k / num_experts * capacity_factor), 1)

    probs = router_probs(xt, router_w)
    dispatch, combine, aux = make_dispatch(probs, top_k, capacity)
    # dispatch/combine einsums stay f32: they are one-hot gathers (tiny
    # FLOPs next to the expert matmuls), f32 keeps the gate weighting
    # exact, and bf16 one-hot contractions inside a partial-manual
    # shard_map (PP+MoE) check-fail both XLA SPMD partitioners
    # ("Invalid binary instruction opcode copy", jax 0.9/jaxlib).

    expert_in = jnp.einsum("tec,th->ech", dispatch,
                           xt.astype(jnp.float32)).astype(dt)
    expert_in = wlc(expert_in, "experts", None, "act_embed")
    gate = jax.nn.silu(jnp.einsum("ech,ehf->ecf", expert_in,
                                  wg.astype(dt)))
    up = jnp.einsum("ech,ehf->ecf", expert_in, wi.astype(dt))
    expert_out = jnp.einsum("ecf,efh->ech", gate * up, wd.astype(dt))
    expert_out = wlc(expert_out, "experts", None, "act_embed")
    out = jnp.einsum("tec,ech->th", combine,
                     expert_out.astype(jnp.float32))
    return out.reshape(b, s, h).astype(dt), aux
