"""Ragged paged attention: ONE attention program for mixed
prefill+decode batches over the paged KV cache.

"Ragged Paged Attention" (PAPERS.md) is the key TPU-serving kernel:
instead of dispatching a chunked-prefill program per prompt AND a
separate whole-batch decode program per engine tick, a single program
consumes a flat ("ragged") token batch where each active slot
contributes between 1 token (decoding) and C tokens (prefilling).
Decode is just the n_tokens == 1 degenerate case of chunked prefill, so
one causal-masking rule covers both:

    token t of slot s at absolute position p attends
      - cached context of s:   pool positions c with c < start[s]
      - batch tokens of s:     tokens u with positions[u] <= p

The flat packing (not a padded [B, C] grid) is the point: a tick with 7
decode slots and one 64-token chunk costs 71 token-positions of
compute, not 8 x 64. Pool layout matches ops/paged_attention.py
([n_layers, num_pages, page_size, n_kv_heads, head_dim]); the dense
gather path here is the CPU/XLA reference the engine runs today and the
oracle a future Pallas ragged kernel must match.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


def ragged_prefill_decode_attention(
        q: jax.Array, k_ctx: jax.Array, v_ctx: jax.Array,
        k_new: jax.Array, v_new: jax.Array, slot_ids: jax.Array,
        positions: jax.Array, valid: jax.Array, start: jax.Array
) -> jax.Array:
    """Core ragged attention over gathered context + the batch's own KV.

    q: [T, H, D] queries of the flat ragged token batch; k_ctx/v_ctx:
    [B, ctx, KVH, D] gathered pool context per slot (position-major —
    row c holds the KV cached at absolute position c); k_new/v_new:
    [T, KVH, D] the batch's own (not yet scattered) KV; slot_ids: [T]
    owning slot per token; positions: [T] absolute position per token;
    valid: [T] bool (padding rows excluded everywhere); start: [B]
    cached tokens per slot (the per-slot causal boundary).

    Token t attends its slot's context positions c < start[slot] plus
    batch tokens u of the same slot with positions[u] <= positions[t].
    GQA (H // KVH query heads per kv head), softmax in float32.
    Returns [T, H, D].

    Every token also attends ITSELF unconditionally — a no-op for
    valid tokens (the causal rule already includes them) that keeps
    padding rows finite: an all-masked row softmaxes to NaN, the NaN
    poisons that row's K/V projection at the next layer, and a
    0-probability x NaN-value product then contaminates every real
    row of the batch (IEEE 0*NaN=NaN).

    Memory note: k_ctx[slot_ids] duplicates each slot's gathered
    context per token — O(T * ctx * KVH * D) f32 transient per layer.
    Fine at the engine's default budgets; at Sarathi-scale budgets
    over multi-thousand-token contexts this is the term the future
    Pallas ragged kernel removes (it streams pages per slot instead).
    Size the token budget accordingly until then.
    """
    t, h, d = q.shape
    ctx, kvh = k_ctx.shape[1], k_ctx.shape[2]
    group = h // kvh
    scale = 1.0 / jnp.sqrt(d)
    qf = q.reshape(t, kvh, group, d).astype(jnp.float32)
    kc = k_ctx[slot_ids].astype(jnp.float32)          # [T, ctx, KVH, D]
    vc = v_ctx[slot_ids].astype(jnp.float32)
    s_ctx = jnp.einsum("tkgd,tckd->tkgc", qf, kc)
    s_new = jnp.einsum("tkgd,ukd->tkgu", qf, k_new.astype(jnp.float32))
    ctx_mask = (jnp.arange(ctx)[None, :]
                < start[slot_ids][:, None])            # [T, ctx]
    new_mask = ((slot_ids[:, None] == slot_ids[None, :])
                & (positions[None, :] <= positions[:, None])
                & valid[None, :]) | jnp.eye(t, dtype=bool)  # [T, T]
    s_ctx = jnp.where(ctx_mask[:, None, None, :], s_ctx * scale,
                      -jnp.inf)
    s_new = jnp.where(new_mask[:, None, None, :], s_new * scale,
                      -jnp.inf)
    scores = jnp.concatenate([s_ctx, s_new], axis=-1)  # [T,KVH,G,ctx+T]
    probs = jax.nn.softmax(scores, axis=-1)
    p_ctx, p_new = probs[..., :ctx], probs[..., ctx:]
    out = (jnp.einsum("tkgc,tckd->tkgd", p_ctx, vc)
           + jnp.einsum("tkgu,ukd->tkgd", p_new,
                        v_new.astype(jnp.float32)))
    return out.reshape(t, h, d).astype(q.dtype)


def ragged_paged_prefill_decode_attention(
        q: jax.Array, k_pages: jax.Array, v_pages: jax.Array,
        page_tables: jax.Array, slot_ids: jax.Array,
        positions: jax.Array, valid: jax.Array, start: jax.Array,
        k_new: jax.Array, v_new: jax.Array,
        ctx_pages: int = -1) -> jax.Array:
    """Single-layer convenience: gather each slot's pages then run the
    ragged attention (what the model forward does once for all layers).

    k_pages/v_pages: [num_pages, page_size, KVH, D] (already sliced to
    the layer); page_tables: [B, max_pages]; ctx_pages (static) bounds
    the gather to the context that exists (-1 = the whole table).
    """
    tables = (page_tables if ctx_pages < 0
              else page_tables[:, :ctx_pages])
    g_k = k_pages[tables]                   # [B, P, page, KVH, D]
    g_v = v_pages[tables]
    b, p, s, kvh, d = g_k.shape
    return ragged_prefill_decode_attention(
        q, g_k.reshape(b, p * s, kvh, d), g_v.reshape(b, p * s, kvh, d),
        k_new, v_new, slot_ids, positions, valid, start)


def ragged_attention_dense_oracle(
        q, dense_k, dense_v, k_new, v_new, slot_ids, positions, valid,
        start) -> np.ndarray:
    """CPU-exact dense reference for the ragged op (numpy, per-token
    loops — slow and obviously correct; the property tests' ground
    truth).

    dense_k/dense_v: [B, max_ctx, KVH, D] each slot's cached KV in
    position order (row p = the KV written at absolute position p);
    everything else as in ragged_prefill_decode_attention. Output rows
    for invalid tokens are zero.
    """
    q = np.asarray(q, np.float32)
    dense_k = np.asarray(dense_k, np.float32)
    dense_v = np.asarray(dense_v, np.float32)
    k_new = np.asarray(k_new, np.float32)
    v_new = np.asarray(v_new, np.float32)
    slot_ids = np.asarray(slot_ids)
    positions = np.asarray(positions)
    valid = np.asarray(valid)
    start = np.asarray(start)
    t, h, d = q.shape
    kvh = k_new.shape[1]
    group = h // kvh
    out = np.zeros_like(q)
    for i in range(t):
        if not valid[i]:
            continue
        s = int(slot_ids[i])
        keys = [dense_k[s, :start[s]]]                 # [n_ctx, KVH, D]
        vals = [dense_v[s, :start[s]]]
        mates = [j for j in range(t)
                 if valid[j] and slot_ids[j] == s
                 and positions[j] <= positions[i]]
        keys.append(k_new[mates])
        vals.append(v_new[mates])
        kk = np.repeat(np.concatenate(keys), group, axis=1)  # [n, H, D]
        vv = np.repeat(np.concatenate(vals), group, axis=1)
        sc = np.einsum("hd,nhd->hn", q[i], kk) / np.sqrt(d)
        p = np.exp(sc - sc.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        out[i] = np.einsum("hn,nhd->hd", p, vv)
    return out
