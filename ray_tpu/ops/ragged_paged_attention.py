"""Ragged paged attention: ONE attention program for mixed
prefill+decode batches over the paged KV cache.

"Ragged Paged Attention" (PAPERS.md) is the key TPU-serving kernel:
instead of dispatching a chunked-prefill program per prompt AND a
separate whole-batch decode program per engine tick, a single program
consumes a flat ("ragged") token batch where each active slot
contributes between 1 token (decoding) and C tokens (prefilling).
Decode is just the n_tokens == 1 degenerate case of chunked prefill, so
one causal-masking rule covers both:

    token t of slot s at absolute position p attends
      - cached context of s:   pool positions c with c < start[s]
      - batch tokens of s:     tokens u with positions[u] <= p

The flat packing (not a padded [B, C] grid) is the point: a tick with 7
decode slots and one 64-token chunk costs 71 token-positions of
compute, not 8 x 64. Pool layout matches ops/paged_attention.py
([n_layers, num_pages, page_size, n_kv_heads, head_dim]).

Two implementations:
- dense gather (`ragged_prefill_decode_attention` /
  `ragged_paged_prefill_decode_attention`): the CPU/XLA reference —
  materializes each token's gathered context, O(T * ctx * KVH * D)
  transient per layer.
- Pallas kernel (`ragged_paged_attention_pallas`): flash-style online
  softmax that STREAMS each slot's KV pages through VMEM (manual DMA
  off the scalar-prefetched page table, the
  `_paged_decode_kernel_mp` scaffolding) and applies the per-slot
  causal rule blockwise — no [T, ctx] score or gathered-context
  tensor ever exists. Decode rows (1 token) and prefill chunks
  (C tokens) share the one program.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# default flash block sizes for the Pallas ragged kernel (shared with
# the benches' analytic staging-size math — keep in one place)
DEFAULT_Q_BLOCK = 8
DEFAULT_PAGES_PER_BLOCK = 8


def ragged_prefill_decode_attention(
        q: jax.Array, k_ctx: jax.Array, v_ctx: jax.Array,
        k_new: jax.Array, v_new: jax.Array, slot_ids: jax.Array,
        positions: jax.Array, valid: jax.Array, start: jax.Array
) -> jax.Array:
    """Core ragged attention over gathered context + the batch's own KV.

    q: [T, H, D] queries of the flat ragged token batch; k_ctx/v_ctx:
    [B, ctx, KVH, D] gathered pool context per slot (position-major —
    row c holds the KV cached at absolute position c); k_new/v_new:
    [T, KVH, D] the batch's own (not yet scattered) KV; slot_ids: [T]
    owning slot per token; positions: [T] absolute position per token;
    valid: [T] bool (padding rows excluded everywhere); start: [B]
    cached tokens per slot (the per-slot causal boundary).

    Token t attends its slot's context positions c < start[slot] plus
    batch tokens u of the same slot with positions[u] <= positions[t].
    GQA (H // KVH query heads per kv head), softmax in float32.
    Returns [T, H, D].

    Every token also attends ITSELF unconditionally — a no-op for
    valid tokens (the causal rule already includes them) that keeps
    padding rows finite: an all-masked row softmaxes to NaN, the NaN
    poisons that row's K/V projection at the next layer, and a
    0-probability x NaN-value product then contaminates every real
    row of the batch (IEEE 0*NaN=NaN).

    Memory note: k_ctx[slot_ids] duplicates each slot's gathered
    context per token — O(T * ctx * KVH * D) f32 transient per layer.
    Fine at the engine's default budgets; at Sarathi-scale budgets
    over multi-thousand-token contexts this is the term the future
    Pallas ragged kernel removes (it streams pages per slot instead).
    Size the token budget accordingly until then.
    """
    t, h, d = q.shape
    ctx, kvh = k_ctx.shape[1], k_ctx.shape[2]
    group = h // kvh
    scale = 1.0 / jnp.sqrt(d)
    qf = q.reshape(t, kvh, group, d).astype(jnp.float32)
    kc = k_ctx[slot_ids].astype(jnp.float32)          # [T, ctx, KVH, D]
    vc = v_ctx[slot_ids].astype(jnp.float32)
    s_ctx = jnp.einsum("tkgd,tckd->tkgc", qf, kc)
    s_new = jnp.einsum("tkgd,ukd->tkgu", qf, k_new.astype(jnp.float32))
    ctx_mask = (jnp.arange(ctx)[None, :]
                < start[slot_ids][:, None])            # [T, ctx]
    new_mask = ((slot_ids[:, None] == slot_ids[None, :])
                & (positions[None, :] <= positions[:, None])
                & valid[None, :]) | jnp.eye(t, dtype=bool)  # [T, T]
    s_ctx = jnp.where(ctx_mask[:, None, None, :], s_ctx * scale,
                      -jnp.inf)
    s_new = jnp.where(new_mask[:, None, None, :], s_new * scale,
                      -jnp.inf)
    scores = jnp.concatenate([s_ctx, s_new], axis=-1)  # [T,KVH,G,ctx+T]
    probs = jax.nn.softmax(scores, axis=-1)
    p_ctx, p_new = probs[..., :ctx], probs[..., ctx:]
    out = (jnp.einsum("tkgc,tckd->tkgd", p_ctx, vc)
           + jnp.einsum("tkgu,ukd->tkgd", p_new,
                        v_new.astype(jnp.float32)))
    return out.reshape(t, h, d).astype(q.dtype)


def ragged_paged_prefill_decode_attention(
        q: jax.Array, k_pages: jax.Array, v_pages: jax.Array,
        page_tables: jax.Array, slot_ids: jax.Array,
        positions: jax.Array, valid: jax.Array, start: jax.Array,
        k_new: jax.Array, v_new: jax.Array,
        ctx_pages: int = -1) -> jax.Array:
    """Single-layer convenience: gather each slot's pages then run the
    ragged attention (what the model forward does once for all layers).

    k_pages/v_pages: [num_pages, page_size, KVH, D] (already sliced to
    the layer); page_tables: [B, max_pages]; ctx_pages (static) bounds
    the gather to the context that exists (-1 = the whole table).
    """
    tables = (page_tables if ctx_pages < 0
              else page_tables[:, :ctx_pages])
    g_k = k_pages[tables]                   # [B, P, page, KVH, D]
    g_v = v_pages[tables]
    b, p, s, kvh, d = g_k.shape
    return ragged_prefill_decode_attention(
        q, g_k.reshape(b, p * s, kvh, d), g_v.reshape(b, p * s, kvh, d),
        k_new, v_new, slot_ids, positions, valid, start)


def ragged_attention_dense_oracle(
        q, dense_k, dense_v, k_new, v_new, slot_ids, positions, valid,
        start) -> np.ndarray:
    """CPU-exact dense reference for the ragged op (numpy, per-token
    loops — slow and obviously correct; the property tests' ground
    truth).

    dense_k/dense_v: [B, max_ctx, KVH, D] each slot's cached KV in
    position order (row p = the KV written at absolute position p);
    everything else as in ragged_prefill_decode_attention. Output rows
    for invalid tokens are zero.
    """
    q = np.asarray(q, np.float32)
    dense_k = np.asarray(dense_k, np.float32)
    dense_v = np.asarray(dense_v, np.float32)
    k_new = np.asarray(k_new, np.float32)
    v_new = np.asarray(v_new, np.float32)
    slot_ids = np.asarray(slot_ids)
    positions = np.asarray(positions)
    valid = np.asarray(valid)
    start = np.asarray(start)
    t, h, d = q.shape
    kvh = k_new.shape[1]
    group = h // kvh
    out = np.zeros_like(q)
    for i in range(t):
        if not valid[i]:
            continue
        s = int(slot_ids[i])
        keys = [dense_k[s, :start[s]]]                 # [n_ctx, KVH, D]
        vals = [dense_v[s, :start[s]]]
        mates = [j for j in range(t)
                 if valid[j] and slot_ids[j] == s
                 and positions[j] <= positions[i]]
        keys.append(k_new[mates])
        vals.append(v_new[mates])
        kk = np.repeat(np.concatenate(keys), group, axis=1)  # [n, H, D]
        vv = np.repeat(np.concatenate(vals), group, axis=1)
        sc = np.einsum("hd,nhd->hn", q[i], kk) / np.sqrt(d)
        p = np.exp(sc - sc.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        out[i] = np.einsum("hn,nhd->hd", p, vv)
    return out


# ----------------------------------------------------- Pallas ragged kernel

def _ragged_paged_kernel(tables_ref, start_ref, qlen_ref, q_ref, k_hbm,
                         v_hbm, *rest, page_size: int,
                         ppb: int, n_ctx_blocks: int, q_blk: int,
                         scale: float, kvh: int, group: int,
                         quantized: bool = False):
    """Grid (B, NQ, NK): slot b x query block qb x kv block i.

    kv blocks [0, n_ctx_blocks) stream the slot's CACHED context pages
    (ppb pages manually DMA'd per step off the scalar-prefetched page
    table, exactly the `_paged_decode_kernel_mp` pattern); blocks
    [n_ctx_blocks, NK) are the slot's own IN-BATCH KV, block-diagonal
    causal (new block jb only feeds query blocks qb >= jb since both
    use the same q_blk tokens). Online-softmax state (m/l/acc) lives in
    scratch across the NK sweep of one (b, qb) block; compute for
    blocks past the slot's context/segment is skipped via pl.when, so
    per-slot cost scales with the KV that EXISTS — a decode row pays
    one q block over ceil(start/page_size) pages, never a [T, ctx]
    score tensor.

    Per-slot causal rule, blockwise: context position c attends iff
    c < start[b]; in-batch key offset j attends query offset i iff
    j <= i and j < q_len[b] (the engine packs each slot's tokens
    contiguously at positions start[b] + rank, so offset order IS
    position order).

    quantized=True (ISSUE 16): the pools hold int8/fp8 values and two
    extra HBM refs carry the per-(row, head) f32 scales
    ([num_pages, page, KVH], ops/kv_quant.py layout). Each context
    step DMAs the scale rows of its ppb pages alongside the pages
    themselves and folds the dequant — one broadcast multiply — into
    the existing f32 upcast of the VMEM block, so the quantized
    kernel streams ~1/4 the context bytes with no extra pass. The
    fresh in-batch KV (kn/vn) is never quantized.
    """
    if quantized:
        (ks_hbm, vs_hbm, kn_ref, vn_ref, o_ref, k_vmem, v_vmem,
         ks_vmem, vs_vmem, sem, m_scr, l_scr, acc_scr) = rest
    else:
        (kn_ref, vn_ref, o_ref, k_vmem, v_vmem,
         sem, m_scr, l_scr, acc_scr) = rest
    b = pl.program_id(0)
    qb = pl.program_id(1)
    i = pl.program_id(2)
    nk = pl.num_programs(2)
    bk = page_size * ppb
    r = q_blk * group                      # score rows per kv head

    @pl.when(i == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, -1e30)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    ctx_len = start_ref[b]
    qlen = qlen_ref[b]
    live_q = qb * q_blk < qlen
    d = q_ref.shape[3]

    def online_update(h, s, v):
        """One flash step for kv head h: s (r, n) masked scores,
        v (n, D) values."""
        rows = slice(h * r, (h + 1) * r)
        m_prev = m_scr[rows]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_scr[rows] = (l_scr[rows] * corr
                       + jnp.sum(p, axis=1, keepdims=True))
        acc_scr[rows] = acc_scr[rows] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[rows] = m_new

    @pl.when(live_q & (i < n_ctx_blocks) & (i * bk < ctx_len))
    def _ctx_step():
        last = jnp.maximum((ctx_len - 1) // page_size, 0)

        def copies():
            out = []
            for t in range(ppb):
                idx = tables_ref[b, jnp.minimum(i * ppb + t, last)]
                out.append(pltpu.make_async_copy(
                    k_hbm.at[idx], k_vmem.at[t], sem))
                out.append(pltpu.make_async_copy(
                    v_hbm.at[idx], v_vmem.at[t], sem))
                if quantized:
                    out.append(pltpu.make_async_copy(
                        ks_hbm.at[idx], ks_vmem.at[t], sem))
                    out.append(pltpu.make_async_copy(
                        vs_hbm.at[idx], vs_vmem.at[t], sem))
            return out

        for c in copies():
            c.start()
        for c in copies():
            c.wait()

        pos = i * bk + jax.lax.broadcasted_iota(jnp.int32, (1, bk), 1)
        keep = pos < ctx_len                           # (1, bk)
        kb = k_vmem[...].astype(jnp.float32)           # (ppb, page, kvh, D)
        vb = v_vmem[...].astype(jnp.float32)
        if quantized:
            # the fused dequant: one multiply against the scale rows
            # that rode the same DMA wave as their pages
            kb = kb * ks_vmem[...][..., None]
            vb = vb * vs_vmem[...][..., None]
        for h in range(kvh):
            q = q_ref[0, :, h * group:(h + 1) * group, :].reshape(
                r, d).astype(jnp.float32)
            k = kb[:, :, h].reshape(bk, d)
            v = vb[:, :, h].reshape(bk, d)
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale  # (r, bk)
            online_update(h, jnp.where(keep, s, -1e30), v)

    jb = i - n_ctx_blocks
    @pl.when(live_q & (i >= n_ctx_blocks) & (jb <= qb)
             & (jb * q_blk < qlen))
    def _new_step():
        # query offset per score row / key offset per column, in the
        # slot's segment (offset order == position order)
        i_tok = (qb * q_blk + jax.lax.broadcasted_iota(
            jnp.int32, (r, q_blk), 0) // group)
        j_tok = jb * q_blk + jax.lax.broadcasted_iota(
            jnp.int32, (r, q_blk), 1)
        keep = (j_tok <= i_tok) & (j_tok < qlen)       # (r, q_blk)
        for h in range(kvh):
            q = q_ref[0, :, h * group:(h + 1) * group, :].reshape(
                r, d).astype(jnp.float32)
            k = kn_ref[0, :, h].astype(jnp.float32)    # (q_blk, D)
            v = vn_ref[0, :, h].astype(jnp.float32)
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale  # (r, q_blk)
            online_update(h, jnp.where(keep, s, -1e30), v)

    @pl.when(i == nk - 1)
    def _finish():
        # all-masked rows (query padding / empty slots) have l == 0 and
        # acc == 0: the epsilon floor makes them exact zeros, keeping
        # every output row finite (the caller re-masks by `valid`)
        safe_l = jnp.maximum(l_scr[:], 1e-30)
        out = acc_scr[:] / safe_l                      # (kvh*r, D)
        for h in range(kvh):
            rows = slice(h * r, (h + 1) * r)
            o_ref[0, :, h * group:(h + 1) * group, :] = out[rows].reshape(
                q_blk, group, d).astype(o_ref.dtype)


def ragged_paged_attention_pallas(
        q: jax.Array, k_pages: jax.Array, v_pages: jax.Array,
        page_tables: jax.Array, slot_ids: jax.Array,
        positions: jax.Array, valid: jax.Array, start: jax.Array,
        k_new: jax.Array, v_new: jax.Array, *, ctx_pages: int = -1,
        max_seg_len: int = -1, q_block: int = DEFAULT_Q_BLOCK,
        pages_per_block: int = DEFAULT_PAGES_PER_BLOCK,
        k_scales: jax.Array = None, v_scales: jax.Array = None,
        interpret: bool = False) -> jax.Array:
    """TPU Pallas ragged paged attention: same contract as
    `ragged_paged_prefill_decode_attention`, but each slot's KV pages
    are STREAMED through VMEM with online softmax — no [T, ctx] score
    and no gathered [T, ctx, KVH, D] context is ever materialized.

    q: [T, H, D] flat ragged batch (kv-major head order);
    k_pages/v_pages: [num_pages, page_size, KVH, D] (layer slice,
    stays in HBM); page_tables: [B, max_pages]; slot_ids/positions/
    valid: [T]; start: [B]; k_new/v_new: [T, KVH, D].

    Packing contract (what the engine's `_ragged_step` produces, and
    what the kernel's segment formulation requires): each slot's valid
    tokens form ONE run in position order with
    positions[t] == start[slot_ids[t]] + rank-within-slot (flat order
    of the run is irrelevant — tokens are re-packed per slot here).
    Invalid rows are ignored on input and zero on output.

    Static knobs: ctx_pages bounds the context sweep (-1 = whole
    table); max_seg_len bounds any single slot's token count
    (-1 = T) — the engine passes its chunk cap so decode-heavy ticks
    don't pad to T; q_block / pages_per_block are the flash block
    sizes. The per-slot padded Q/O/new-KV staging arrays are
    [B, ceil(max_seg_len/q_block)*q_block, ...] — O(B * C * H * D),
    vs the gather path's O(T * ctx * KVH * D) context transient.

    Quantized KV (ISSUE 16): pass k_scales/v_scales
    ([num_pages, page_size, KVH] f32, ops/kv_quant.py layout) when
    the pools hold int8/fp8 values; the kernel DMAs the scale rows
    beside their pages and fuses the dequant multiply into the
    streaming loop. k_new/v_new stay full-precision either way.
    """
    t, h, d = q.shape
    _, page_size, kvh, _ = k_pages.shape
    b = page_tables.shape[0]
    group = h // kvh
    scale = d ** -0.5
    tables = (page_tables if ctx_pages < 0
              else page_tables[:, :max(ctx_pages, 1)])
    n_ctx_pages = tables.shape[1] if ctx_pages != 0 else 0
    ppb = max(min(pages_per_block, n_ctx_pages), 1)
    n_ctx_blocks = -(-n_ctx_pages // ppb) if n_ctx_pages else 0

    q_max = t if max_seg_len < 0 else max(min(max_seg_len, t), 1)
    q_blk = max(min(q_block, q_max), 1)
    nq = -(-q_max // q_blk)
    qp = nq * q_blk
    nk = n_ctx_blocks + nq

    # per-slot repack: token -> (slot, offset-within-segment); invalid
    # rows land in a dummy slot row b that the grid never reads
    off = jnp.clip(positions - start[slot_ids], 0, qp - 1)
    row = jnp.where(valid, slot_ids, b)
    q_pad = jnp.zeros((b + 1, qp, h, d), q.dtype).at[row, off].set(q)
    kn_pad = jnp.zeros((b + 1, qp, kvh, d),
                       k_new.dtype).at[row, off].set(k_new)
    vn_pad = jnp.zeros((b + 1, qp, kvh, d),
                       v_new.dtype).at[row, off].set(v_new)
    qlen = jnp.zeros((b,), jnp.int32).at[
        jnp.where(valid, slot_ids, 0)].add(valid.astype(jnp.int32))

    io_spec = pl.BlockSpec(
        (1, q_blk, h, d),
        lambda bi, qb, i, tables, start, qlen: (bi, qb, 0, 0))

    def new_kv_index(bi, qb, i, tables, start, qlen):
        # clamp to the causal diagonal: blocks past qb are fully
        # masked, re-mapping them to qb elides the DMA entirely
        jb = jnp.clip(i - n_ctx_blocks, 0, nq - 1)
        return (bi, jnp.minimum(jb, qb), 0, 0)

    new_spec = pl.BlockSpec((1, q_blk, kvh, d), new_kv_index)

    quantized = k_scales is not None
    if quantized and v_scales is None:
        raise ValueError("k_scales and v_scales must come together")
    in_specs = [
        io_spec,                             # padded queries
        pl.BlockSpec(memory_space=pl.ANY),   # k pool in HBM
        pl.BlockSpec(memory_space=pl.ANY),   # v pool in HBM
    ]
    scratch = [
        pltpu.VMEM((ppb, page_size, kvh, d), k_pages.dtype),
        pltpu.VMEM((ppb, page_size, kvh, d), v_pages.dtype),
    ]
    inputs = [q_pad, k_pages, v_pages]
    if quantized:
        # scale pools ride beside the page pools: HBM-resident, DMA'd
        # per context block into their own VMEM scratch rows
        in_specs += [pl.BlockSpec(memory_space=pl.ANY),
                     pl.BlockSpec(memory_space=pl.ANY)]
        scratch += [pltpu.VMEM((ppb, page_size, kvh), jnp.float32),
                    pltpu.VMEM((ppb, page_size, kvh), jnp.float32)]
        inputs += [k_scales.astype(jnp.float32),
                   v_scales.astype(jnp.float32)]
    in_specs += [new_spec, new_spec]         # padded new k / new v
    inputs += [kn_pad, vn_pad]

    out = pl.pallas_call(
        functools.partial(
            _ragged_paged_kernel, page_size=page_size, ppb=ppb,
            n_ctx_blocks=n_ctx_blocks, q_blk=q_blk, scale=scale,
            kvh=kvh, group=group, quantized=quantized),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(b, nq, nk),
            in_specs=in_specs,
            out_specs=io_spec,
            scratch_shapes=scratch + [
                pltpu.SemaphoreType.DMA,
                pltpu.VMEM((kvh * q_blk * group, 1), jnp.float32),
                pltpu.VMEM((kvh * q_blk * group, 1), jnp.float32),
                pltpu.VMEM((kvh * q_blk * group, d), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, qp, h, d), q.dtype),
        interpret=interpret,
    )(tables.astype(jnp.int32), start.astype(jnp.int32), qlen,
      *inputs)

    flat = out[jnp.where(valid, slot_ids, 0), off]     # [T, H, D]
    return jnp.where(valid[:, None, None], flat,
                     jnp.zeros_like(flat)).astype(q.dtype)
