"""Ring attention: exact causal attention over a sequence-sharded mesh axis.

Net-new TPU work (SURVEY.md §2.4: SP/context parallelism is absent from the
reference). Each device holds one sequence shard of Q and a rotating shard
of K/V; K/V blocks travel around the `sp` ring via jax.lax.ppermute while
each hop's partial attention is merged with an online-softmax (log-sum-exp)
accumulator, so the full sequence is never materialized on one chip and
communication overlaps compute (XLA schedules the ppermute ahead of the
block math).

Use inside shard_map over a mesh with an `sp` axis; `ring_attention_sharded`
wraps that for callers holding globally-sharded arrays.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .attention import NEG_INF, _repeat_kv
from .jax_compat import shard_map_compat as _shard_map
from ..parallel.mesh import AXIS_SP, BATCH_AXES


def _block_attend(q, k, v, scale, causal_mode, q_offset, kv_offset):
    """One block pair: returns (numerator, row max, row denominator).

    causal_mode: 0 = fully visible (kv chunk strictly before q chunk),
                 1 = diagonal (same chunk: in-chunk causal mask),
                 2 = fully masked (kv chunk after q chunk).
    """
    b, sq, h, d = q.shape
    sk = k.shape[1]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    q_pos = q_offset + jnp.arange(sq)[:, None]
    k_pos = kv_offset + jnp.arange(sk)[None, :]
    visible = jnp.where(causal_mode == 0, True,
                        jnp.where(causal_mode == 1, q_pos >= k_pos, False))
    logits = jnp.where(visible[None, None], logits, NEG_INF)
    m = jnp.max(logits, axis=-1, keepdims=True)                  # (b,h,q,1)
    # Guard fully-masked rows: exp(NEG_INF - NEG_INF) would be exp(0).
    m_safe = jnp.maximum(m, NEG_INF / 2)
    p = jnp.exp(logits - m_safe)
    p = jnp.where(visible[None, None], p, 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)                       # (b,h,q,1)
    num = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)
    return num.astype(jnp.float32), m_safe, l


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                   axis_name: str = AXIS_SP,
                   causal: bool = True,
                   scale: Optional[float] = None) -> jax.Array:
    """Per-shard ring attention. Call inside shard_map.

    q: (B, Sq_local, H, D); k/v: (B, Sk_local, KVH, D) — the local shards.
    """
    b, sq, h, d = q.shape
    scale_val = scale if scale is not None else d ** -0.5
    # jax.lax.axis_size is newer than 0.4.x; psum(1) over the axis is
    # the version-stable spelling of its size (compile-time constant)
    ax_size = getattr(jax.lax, "axis_size",
                      lambda name: jax.lax.psum(1, name))
    sp = ax_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    k = _repeat_kv(k, h)
    v = _repeat_kv(v, h)
    sk = k.shape[1]

    acc = jnp.zeros((b, sq, h, d), jnp.float32)
    m_run = jnp.full((b, h, sq, 1), NEG_INF / 2, jnp.float32)
    l_run = jnp.zeros((b, h, sq, 1), jnp.float32)

    def step(carry, s):
        acc, m_run, l_run, k_cur, v_cur = carry
        # At hop s this device holds the kv chunk originally on (my - s).
        src = (my - s) % sp
        if causal:
            mode = jnp.where(src == my, 1, jnp.where(src < my, 0, 2))
        else:
            mode = jnp.int32(0)
        num, m_blk, l_blk = _block_attend(
            q, k_cur, v_cur, scale_val, mode,
            q_offset=my * sq, kv_offset=src * sk)
        m_new = jnp.maximum(m_run, m_blk)
        c_run = jnp.exp(m_run - m_new)
        c_blk = jnp.exp(m_blk - m_new)
        l_new = l_run * c_run + l_blk * c_blk
        # (b,h,q,1) -> (b,q,h,1) to scale the (b,q,h,d) accumulators.
        acc = (acc * c_run.transpose(0, 2, 1, 3)
               + num * c_blk.transpose(0, 2, 1, 3))
        # Rotate kv to the next device (skip after the final hop).
        perm = [(i, (i + 1) % sp) for i in range(sp)]
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (acc, m_new, l_new, k_nxt, v_nxt), None

    (acc, m_run, l_run, _, _), _ = jax.lax.scan(
        step, (acc, m_run, l_run, k, v), jnp.arange(sp))
    out = acc / jnp.maximum(l_run.transpose(0, 2, 1, 3), 1e-30)
    return out.astype(q.dtype)


def ring_attention_sharded(q: jax.Array, k: jax.Array, v: jax.Array,
                           mesh: Mesh, *, causal: bool = True) -> jax.Array:
    """Ring attention on globally-sharded (B, S, H, D) arrays: shard_map
    over (batch -> dp/fsdp, seq -> sp)."""
    spec = PartitionSpec(BATCH_AXES, AXIS_SP, None, None)
    fn = _shard_map(
        functools.partial(ring_attention, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)
