"""Ulysses (DeepSpeed-style) sequence parallelism, GSPMD-native.

Net-new TPU work (SURVEY.md §2.4: SP is absent from the reference; ring
attention covers the ppermute formulation, this file covers the
all-to-all one). Ulysses trades the ring's O(sp) K/V hops for two
all-to-alls: activations arrive sequence-sharded over `sp`, attention
runs with *heads* sharded over `sp` (each device sees the full sequence
for its head slice), and the output is resharded back to
sequence-sharded.

Rather than hand-writing `lax.all_to_all`, we express both reshards as
sharding constraints and let GSPMD lower them to all-to-alls over ICI —
the idiomatic TPU formulation: the same attention kernel (XLA or Pallas
flash) runs unmodified between the two constraints, and XLA is free to
fuse/overlap the collectives.

Requires n_heads (and n_kv_heads, after GQA head repetition) divisible
by sp*tp for a balanced shard; XLA pads otherwise.
"""

from __future__ import annotations

import jax

from .attention import attention as attention_op, _repeat_kv
from ..parallel.mesh import AXIS_SP, AXIS_TP
from ..parallel.sharding import with_logical_constraint as wlc

# During the attention body, heads absorb the sp axis (alongside tp) and
# the sequence axis is gathered.
_UL_RULES = {
    "ul_heads": (AXIS_TP, AXIS_SP),
    "ul_seq": None,
}


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                      causal: bool = True,
                      impl: str = "auto") -> jax.Array:
    """q: (B, S, H, D); k/v: (B, S, KVH, D), sequence-sharded over sp.

    Returns (B, S, H, D) sequence-sharded. The two wlc pairs below are the
    entire Ulysses algorithm: seq-shard -> head-shard (all-to-all), local
    full-sequence attention, head-shard -> seq-shard (all-to-all).
    """
    h = q.shape[2]
    # GQA: repeat K/V up to the full head count first so the head axis is
    # divisible by sp*tp in the common configs (kv_heads alone usually
    # isn't once sp > 1).
    k = _repeat_kv(k, h)
    v = _repeat_kv(v, h)

    q = wlc(q, "batch", "ul_seq", "ul_heads", "head_dim", rules=_UL_RULES)
    k = wlc(k, "batch", "ul_seq", "ul_heads", "head_dim", rules=_UL_RULES)
    v = wlc(v, "batch", "ul_seq", "ul_heads", "head_dim", rules=_UL_RULES)

    out = attention_op(q, k, v, causal=causal, impl=impl)

    return wlc(out, "batch", "seq", "heads", "head_dim")
