"""Quantized KV-page primitives: int8/fp8 storage with per-row scales.

ISSUE 16 stores the paged KV pool in a narrow dtype (int8 or fp8
e4m3) and keeps a separate f32 scale array so every consumer of pages
— the fused-dequant ragged kernel, the dense gather paths, the
spill/restore d2h/h2d hierarchy, and the fleet KV transport — reads a
quarter of the value bytes and reconstructs f32 with one multiply.

Scale granularity is per TOKEN ROW per KV HEAD: for a pool shaped
``[L, P, page, KVH, D]`` the scales are ``[L, P, page, KVH]`` f32,
i.e. one scale over each row's D lane values. Two properties hang on
this choice:

- the write path stays WRITE-ONLY: a page fills one token row at a
  time (decode appends, chunked prefill), and a per-row scale means
  appending a row never has to re-read neighbours to recompute a
  shared page scale;
- the scale array shards exactly like the pool under tp
  (``P(None, None, None, "tp")``): each shard scales its own heads,
  no cross-shard max.

Symmetric absmax quantization: ``scale = max|x| / qmax`` over D,
``q = clip(round(x / scale))`` for int8 or a straight fp8 cast of
``x / scale * qmax``-free form (fp8 keeps its own mantissa; only the
range is normalized). Dequant is ``q.astype(f32) * scale`` — the one
fused multiply the kernel folds into its HBM->VMEM streaming loop.

All functions are jit-safe and run identically under CPU interpret
mode (the tolerance-oracle tests exercise them there).
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

# KV page storage kinds: name -> (jnp storage dtype, qmax used to
# normalize the absmax into the representable range, bytes per value).
# "f32" is the identity kind — no scale arrays exist, every quant
# helper below rejects it so a caller can never half-quantize.
_FP8 = jnp.float8_e4m3fn
KV_KINDS = ("f32", "int8", "fp8")
_STORE = {
    "int8": (jnp.int8, 127.0, 1),
    "fp8": (_FP8, 448.0, 1),
}
# f32 scale per (token row, kv head) — the fixed overhead every
# byte-accounting surface (perfmodel, telemetry, transport) adds on
# top of the narrow values
SCALE_BYTES = 4


def validate_kind(kind: str) -> str:
    if kind not in KV_KINDS:
        raise ValueError(
            f"kv_dtype must be one of {KV_KINDS}, got {kind!r}")
    return kind


def is_quantized(kind: str) -> bool:
    return validate_kind(kind) != "f32"


def storage_dtype(kind: str):
    """jnp dtype the KV pool is allocated in for `kind`."""
    if kind == "f32":
        return jnp.float32
    return _STORE[validate_kind(kind)][0]


def qmax(kind: str) -> float:
    return _STORE[validate_kind(kind)][1]


def value_bytes(kind: str) -> int:
    """Bytes per stored KV value (no scale overhead)."""
    if kind == "f32":
        return 4
    return _STORE[validate_kind(kind)][2]


def token_row_bytes(kind: str, n_kv_heads: int, head_dim: int) -> int:
    """Bytes ONE token row of ONE of k/v occupies in ONE layer:
    values plus the per-(row, head) scales. The atom perfmodel and
    telemetry build page/token byte math from."""
    vals = n_kv_heads * head_dim * value_bytes(kind)
    if kind == "f32":
        return vals
    return vals + n_kv_heads * SCALE_BYTES


def scale_shape(pool_shape: Tuple[int, ...]) -> Tuple[int, ...]:
    """Scale-array shape for a pool shaped [..., KVH, D]: drop D."""
    return tuple(pool_shape[:-1])


def quantize_rows(x: jnp.ndarray, kind: str
                  ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Quantize f32 rows [..., KVH, D] -> (q [..., KVH, D] narrow,
    scales [..., KVH] f32). Symmetric absmax over D; all-zero rows get
    scale 0 and dequantize back to exact zeros (fresh pool pages and
    masked scatter rows stay clean)."""
    if not is_quantized(kind):
        raise ValueError("quantize_rows: kind must be int8/fp8")
    dt, qm, _ = _STORE[kind]
    x = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(x), axis=-1)
    scales = amax / qm
    # zero rows: divide by 1 instead of 0; scale 0 zeroes the dequant
    safe = jnp.where(scales > 0.0, scales, 1.0)[..., None]
    y = x / safe          # row absmax lands exactly at qmax
    if kind == "int8":
        q = jnp.clip(jnp.round(y), -qm, qm).astype(dt)
    else:
        # fp8 e4m3fn: the row's absmax sits at the format's top of
        # range (448); fp8's own mantissa does the rounding
        q = y.astype(dt)
    return q, scales


def dequantize_rows(q: jnp.ndarray, scales: jnp.ndarray,
                    kind: str) -> jnp.ndarray:
    """Inverse of quantize_rows: q [..., KVH, D] + scales [..., KVH]
    -> f32 [..., KVH, D]. This multiply is exactly what the Pallas
    kernel fuses after its VMEM load."""
    if not is_quantized(kind):
        raise ValueError("dequantize_rows: kind must be int8/fp8")
    return q.astype(jnp.float32) * scales[..., None].astype(jnp.float32)
