"""Attention ops: XLA reference impl + Pallas TPU flash-attention kernel.

This is net-new TPU work: the reference has no in-repo attention (vLLM is
external; SURVEY.md §2.4 marks SP/long-context absent). Shapes follow
(batch, seq, heads, head_dim) with GQA (kv_heads divides heads).

The flash kernel uses the online-softmax accumulation pattern with a
3-D grid (batch*heads, q_blocks, kv_blocks): the kv grid dimension is
innermost and sequential on TPU, so the running max / denominator / output
accumulator live in VMEM scratch across kv steps. The forward also emits
the per-row logsumexp; backward is two Pallas kernels (FlashAttention-2
style): a dq kernel accumulating over kv blocks and a dk/dv kernel
accumulating over (grouped-query head, q block) pairs, with
delta = rowsum(dO * O) precomputed in XLA.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# 512-blocks measured ~1.7x faster than 128 end-to-end on v5e (the
# (512, 512) f32 logits tile still fits VMEM comfortably); _resolve_blocks
# clamps to the sequence length for short inputs.
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512
NEG_INF = -1e30


def _repeat_kv(k: jax.Array, num_heads: int) -> jax.Array:
    """(B, S, KVH, D) -> (B, S, H, D) by repeating each kv head.

    broadcast+reshape, NOT jnp.repeat: repeat lowers to a gather whose
    sharding the SPMD partitioner can't propagate through a
    head-sharded (tp) mesh — it falls back to full rematerialization
    (replicate + repartition) of K/V. The broadcast form stays an
    elementwise/layout op and partitions cleanly."""
    b, s, kvh, d = k.shape
    if kvh == num_heads:
        return k
    reps = num_heads // kvh
    return jnp.broadcast_to(
        k[:, :, :, None, :], (b, s, kvh, reps, d)
    ).reshape(b, s, num_heads, d)


def reference_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        causal: bool = True,
                        q_offset: int | jax.Array = 0,
                        kv_offset: int | jax.Array = 0,
                        scale: Optional[float] = None) -> jax.Array:
    """Plain XLA attention. q: (B, Sq, H, D); k/v: (B, Sk, KVH, D).

    q_offset/kv_offset are the global positions of the first query/key —
    used by ring attention where each device holds a rotating kv chunk.
    """
    b, sq, h, d = q.shape
    scale = scale if scale is not None else d ** -0.5
    k = _repeat_kv(k, h)
    v = _repeat_kv(v, h)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        q_pos = q_offset + jnp.arange(sq)[:, None]
        k_pos = kv_offset + jnp.arange(k.shape[1])[None, :]
        mask = q_pos >= k_pos
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


# --------------------------------------------------------------------------
# Pallas flash attention (forward)
# --------------------------------------------------------------------------

def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                  m_scr, l_scr, acc_scr,
                  *, causal: bool, scale: float,
                  block_q: int, block_k: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Causal: the kv block is live iff its first key position can be seen
    # by the last query of this q block.
    if causal:
        live = ki * block_k <= qi * block_q + (block_q - 1)
    else:
        live = ki >= 0

    @pl.when(live)
    def _step():
        q = q_ref[0].astype(jnp.float32)          # (block_q, d)
        k = k_ref[0].astype(jnp.float32)          # (block_k, d)
        v = v_ref[0].astype(jnp.float32)          # (block_k, d)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # (bq, bk)
        if causal:
            row = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            col = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(row >= col, s, NEG_INF)
        m_prev = m_scr[:]                          # (bq, 1)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)                     # (bq, bk)
        corr = jnp.exp(m_prev - m_new)             # (bq, 1)
        l_scr[:] = l_scr[:] * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = m_new

    @pl.when(ki == n_k - 1)
    def _finish():
        l_safe = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0] = m_scr[:] + jnp.log(l_safe)


def _flash_forward(q: jax.Array, k: jax.Array, v: jax.Array,
                   causal: bool, scale: float,
                   block_q: int, block_k: int,
                   interpret: bool = False):
    """q: (BH, Sq, D); k/v: (BKVH, Sk, D); grouped via index maps.

    Returns (out (BH, Sq, D), lse (BH, Sq, 1) float32)."""
    bh, sq, d = q.shape
    bkvh, sk, _ = k.shape
    group = bh // bkvh
    grid = (bh, pl.cdiv(sq, block_q), pl.cdiv(sk, block_k))

    return pl.pallas_call(
        functools.partial(_flash_kernel, causal=causal, scale=scale,
                          block_q=block_q, block_k=block_k),
        out_shape=(
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq, 1), jnp.float32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b // group, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b // group, j, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)


# --------------------------------------------------------------------------
# Pallas flash attention (backward)
# --------------------------------------------------------------------------

def _bwd_tile(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
              qi, ki, causal, scale, block_q, block_k):
    """Shared per-tile recompute: returns (p, ds, q, k, do) in f32.

    p = softmax probabilities from the saved logsumexp; ds = the softmax
    backward dS = P o (dP - delta)."""
    q = q_ref[0].astype(jnp.float32)          # (bq, d)
    k = k_ref[0].astype(jnp.float32)          # (bk, d)
    v = v_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)        # (bq, d)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale
    if causal:
        row = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        col = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s = jnp.where(row >= col, s, NEG_INF)
    p = jnp.exp(s - lse_ref[0])               # masked entries underflow to 0
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)   # (bq, bk)
    ds = p * (dp - delta_ref[0])
    return p, ds, q, k, do


def _flash_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                     dq_ref, dq_scr,
                     *, causal: bool, scale: float,
                     block_q: int, block_k: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    live = (qi + 1) * block_q > ki * block_k if causal else ki >= 0

    @pl.when(live)
    def _step():
        _, ds, _, k, _ = _bwd_tile(q_ref, k_ref, v_ref, do_ref, lse_ref,
                                   delta_ref, qi, ki, causal, scale,
                                   block_q, block_k)
        dq_scr[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == n_k - 1)
    def _finish():
        dq_ref[0] = (dq_scr[:] * scale).astype(dq_ref.dtype)


def _flash_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      dk_ref, dv_ref, dk_scr, dv_scr,
                      *, causal: bool, scale: float,
                      block_q: int, block_k: int, n_qb: int):
    kj = pl.program_id(1)
    t = pl.program_id(2)          # (group, q_block) pairs, q innermost
    n_t = pl.num_programs(2)
    qi = t % n_qb

    @pl.when(t == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    live = (qi + 1) * block_q > kj * block_k if causal else t >= 0

    @pl.when(live)
    def _step():
        p, ds, q, _, do = _bwd_tile(q_ref, k_ref, v_ref, do_ref, lse_ref,
                                    delta_ref, qi, kj, causal, scale,
                                    block_q, block_k)
        # contract the bq dim: p^T @ dO and ds^T @ q, both (bk, d)
        dv_scr[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_scr[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(t == n_t - 1)
    def _finish():
        dk_ref[0] = (dk_scr[:] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_backward(q, k, v, o, lse, do, causal, scale,
                    block_q, block_k, interpret=False):
    """All flat: q/o/do (BH, Sq, D); k/v (BKVH, Sk, D); lse (BH, Sq, 1)."""
    bh, sq, d = q.shape
    bkvh, sk, _ = k.shape
    group = bh // bkvh
    n_qb = pl.cdiv(sq, block_q)
    n_kb = pl.cdiv(sk, block_k)

    delta = jnp.sum(o.astype(jnp.float32) * do.astype(jnp.float32),
                    axis=-1, keepdims=True)            # (BH, Sq, 1)

    dq = pl.pallas_call(
        functools.partial(_flash_dq_kernel, causal=causal, scale=scale,
                          block_q=block_q, block_k=block_k),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        grid=(bh, n_qb, n_kb),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b // group, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b // group, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    # dk/dv: one pass per kv head; the innermost grid dim walks every
    # (q head in the GQA group, q block) pair so the accumulators cover
    # the whole group.
    dk, dv = pl.pallas_call(
        functools.partial(_flash_dkv_kernel, causal=causal, scale=scale,
                          block_q=block_q, block_k=block_k, n_qb=n_qb),
        out_shape=(
            jax.ShapeDtypeStruct((bkvh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bkvh, sk, d), v.dtype),
        ),
        grid=(bkvh, n_kb, group * n_qb),
        in_specs=[
            pl.BlockSpec((1, block_q, d),
                         lambda b, j, t: (b * group + t // n_qb, t % n_qb, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, t: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, t: (b, j, 0)),
            pl.BlockSpec((1, block_q, d),
                         lambda b, j, t: (b * group + t // n_qb, t % n_qb, 0)),
            pl.BlockSpec((1, block_q, 1),
                         lambda b, j, t: (b * group + t // n_qb,
                                          t % n_qb, 0)),
            pl.BlockSpec((1, block_q, 1),
                         lambda b, j, t: (b * group + t // n_qb,
                                          t % n_qb, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, block_k, d), lambda b, j, t: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, t: (b, j, 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True,
                    scale: Optional[float] = None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    interpret: bool = False) -> jax.Array:
    """Flash attention. q: (B, Sq, H, D); k/v: (B, Sk, KVH, D)."""
    out, _ = _flash_fwd_rule(q, k, v, causal, scale, block_q, block_k,
                             interpret)
    return out


def _pick_block(limit: int, s: int) -> Optional[int]:
    """Largest block <= limit that divides s and is a multiple of 8."""
    b = min(limit, s)
    b -= b % 8
    while b >= 8:
        if s % b == 0:
            return b
        b -= 8
    return None


def _resolve_blocks(sq, sk, block_q, block_k):
    bq = _pick_block(block_q, sq)
    bk = _pick_block(block_k, sk)
    if bq is None or bk is None:
        raise ValueError(
            f"flash_attention needs seq lengths with a divisor that is a "
            f"multiple of 8 (sq={sq}, sk={sk}); pad inputs or use "
            f"impl='xla'.")
    return bq, bk


def _flash_fwd_rule(q, k, v, causal, scale, block_q, block_k, interpret):
    b, sq, h, d = q.shape
    _, sk, kvh, _ = k.shape
    scale_val = scale if scale is not None else d ** -0.5
    bq, bk = _resolve_blocks(sq, sk, block_q, block_k)
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * kvh, sk, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * kvh, sk, d)
    of, lse = _flash_forward(qf, kf, vf, causal, scale_val, bq, bk, interpret)
    out = of.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
    return out, (qf, kf, vf, of, lse)


def _flash_bwd_rule(causal, scale, block_q, block_k, interpret,
                    residuals, g):
    qf, kf, vf, of, lse = residuals
    bh, sq, d = qf.shape
    bkvh, sk, _ = kf.shape
    b, _, h, _ = g.shape
    kvh = bkvh // b
    scale_val = scale if scale is not None else d ** -0.5
    bq, bk = _resolve_blocks(sq, sk, block_q, block_k)
    gf = g.transpose(0, 2, 1, 3).reshape(bh, sq, d)
    dqf, dkf, dvf = _flash_backward(
        qf, kf, vf, of, lse, gf, causal, scale_val, bq, bk, interpret)
    dq = dqf.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
    dk = dkf.reshape(b, kvh, sk, d).transpose(0, 2, 1, 3)
    dv = dvf.reshape(b, kvh, sk, d).transpose(0, 2, 1, 3)
    return dq, dk, dv


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


# --------------------------------------------------------------------------
# Dispatcher
# --------------------------------------------------------------------------

def attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
              causal: bool = True, impl: str = "auto") -> jax.Array:
    """Pick the best attention implementation for the current backend."""
    if impl == "auto":
        on_tpu = jax.devices()[0].platform == "tpu"
        sq, sk = q.shape[1], k.shape[1]
        bq = _pick_block(DEFAULT_BLOCK_Q, sq)
        bk = _pick_block(DEFAULT_BLOCK_K, sk)
        # tiny resolved blocks mean awkward seq lengths — XLA does better
        ok_shapes = (bq is not None and bk is not None and bq >= 128
                     and bk >= 128 and q.shape[-1] >= 64)
        impl = "pallas" if (on_tpu and ok_shapes) else "xla"
    if impl == "pallas":
        return flash_attention(q, k, v, causal)
    if impl == "pallas_interpret":
        return flash_attention(q, k, v, causal, None,
                               DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K, True)
    return reference_attention(q, k, v, causal=causal)
