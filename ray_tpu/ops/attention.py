"""Attention ops: XLA reference impl + Pallas TPU flash-attention kernel.

This is net-new TPU work: the reference has no in-repo attention (vLLM is
external; SURVEY.md §2.4 marks SP/long-context absent). Shapes follow
(batch, seq, heads, head_dim) with GQA (kv_heads divides heads).

The flash kernel uses the online-softmax accumulation pattern with a
3-D grid (batch*heads, q_blocks, kv_blocks): the kv grid dimension is
innermost and sequential on TPU, so the running max / denominator / output
accumulator live in VMEM scratch across kv steps. Backward currently
recomputes through the XLA reference (custom_vjp); a full Pallas backward
kernel is planned.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30


def _repeat_kv(k: jax.Array, num_heads: int) -> jax.Array:
    """(B, S, KVH, D) -> (B, S, H, D) by repeating each kv head."""
    b, s, kvh, d = k.shape
    if kvh == num_heads:
        return k
    reps = num_heads // kvh
    return jnp.repeat(k, reps, axis=2)


def reference_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        causal: bool = True,
                        q_offset: int | jax.Array = 0,
                        kv_offset: int | jax.Array = 0,
                        scale: Optional[float] = None) -> jax.Array:
    """Plain XLA attention. q: (B, Sq, H, D); k/v: (B, Sk, KVH, D).

    q_offset/kv_offset are the global positions of the first query/key —
    used by ring attention where each device holds a rotating kv chunk.
    """
    b, sq, h, d = q.shape
    scale = scale if scale is not None else d ** -0.5
    k = _repeat_kv(k, h)
    v = _repeat_kv(v, h)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        q_pos = q_offset + jnp.arange(sq)[:, None]
        k_pos = kv_offset + jnp.arange(k.shape[1])[None, :]
        mask = q_pos >= k_pos
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


# --------------------------------------------------------------------------
# Pallas flash attention (forward)
# --------------------------------------------------------------------------

def _flash_kernel(q_ref, k_ref, v_ref, o_ref,
                  m_scr, l_scr, acc_scr,
                  *, causal: bool, scale: float,
                  block_q: int, block_k: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Causal: the kv block is live iff its first key position can be seen
    # by the last query of this q block.
    if causal:
        live = ki * block_k <= qi * block_q + (block_q - 1)
    else:
        live = ki >= 0

    @pl.when(live)
    def _step():
        q = q_ref[0].astype(jnp.float32)          # (block_q, d)
        k = k_ref[0].astype(jnp.float32)          # (block_k, d)
        v = v_ref[0].astype(jnp.float32)          # (block_k, d)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # (bq, bk)
        if causal:
            row = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            col = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(row >= col, s, NEG_INF)
        m_prev = m_scr[:]                          # (bq, 1)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)                     # (bq, bk)
        corr = jnp.exp(m_prev - m_new)             # (bq, 1)
        l_scr[:] = l_scr[:] * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = m_new

    @pl.when(ki == n_k - 1)
    def _finish():
        o_ref[0] = (acc_scr[:] /
                    jnp.maximum(l_scr[:], 1e-30)).astype(o_ref.dtype)


def _flash_forward(q: jax.Array, k: jax.Array, v: jax.Array,
                   causal: bool, scale: float,
                   block_q: int, block_k: int,
                   interpret: bool = False) -> jax.Array:
    """q: (BH, Sq, D); k/v: (BKVH, Sk, D); grouped via index maps."""
    bh, sq, d = q.shape
    bkvh, sk, _ = k.shape
    group = bh // bkvh
    grid = (bh, pl.cdiv(sq, block_q), pl.cdiv(sk, block_k))

    return pl.pallas_call(
        functools.partial(_flash_kernel, causal=causal, scale=scale,
                          block_q=block_q, block_k=block_k),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b // group, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b // group, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True,
                    scale: Optional[float] = None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    interpret: bool = False) -> jax.Array:
    """Flash attention. q: (B, Sq, H, D); k/v: (B, Sk, KVH, D)."""
    out, _ = _flash_fwd_rule(q, k, v, causal, scale, block_q, block_k,
                             interpret)
    return out


def _flash_fwd_rule(q, k, v, causal, scale, block_q, block_k, interpret):
    b, sq, h, d = q.shape
    _, sk, kvh, _ = k.shape
    scale_val = scale if scale is not None else d ** -0.5
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    if sq % bq or sk % bk or bq % 8 or bk % 8:
        raise ValueError(
            f"flash_attention needs seq lengths divisible by 8 and by the "
            f"block size (sq={sq}, bq={bq}, sk={sk}, bk={bk}); pad inputs "
            f"or use impl='xla'.")
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * kvh, sk, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * kvh, sk, d)
    of = _flash_forward(qf, kf, vf, causal, scale_val, bq, bk, interpret)
    out = of.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
    return out, (q, k, v)


def _flash_bwd_rule(causal, scale, block_q, block_k, interpret,
                    residuals, g):
    q, k, v = residuals
    # Rematerialized backward through the XLA reference implementation.
    # TODO(perf): dedicated Pallas dq/dk/dv kernels.
    _, vjp = jax.vjp(
        lambda q_, k_, v_: reference_attention(
            q_, k_, v_, causal=causal, scale=scale), q, k, v)
    return vjp(g)


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


# --------------------------------------------------------------------------
# Dispatcher
# --------------------------------------------------------------------------

def attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
              causal: bool = True, impl: str = "auto") -> jax.Array:
    """Pick the best attention implementation for the current backend."""
    if impl == "auto":
        on_tpu = jax.devices()[0].platform == "tpu"
        sq, sk = q.shape[1], k.shape[1]
        ok_shapes = (sq % DEFAULT_BLOCK_Q == 0 and sk % DEFAULT_BLOCK_K == 0
                     and q.shape[-1] >= 64)
        impl = "pallas" if (on_tpu and ok_shapes) else "xla"
    if impl == "pallas":
        return flash_attention(q, k, v, causal)
    if impl == "pallas_interpret":
        return flash_attention(q, k, v, causal, None,
                               DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K, True)
    return reference_attention(q, k, v, causal=causal)
