"""Named-mesh construction + collective routing for explicit-tp serving.

ISSUE 17 / ROADMAP item 4: a serving replica is a tp-sharded engine on
a pod-slice mesh, not a single chip. This module owns the two pieces
the engine and the fleet both need:

- build_serving_mesh: turn ``EngineConfig.mesh_shape`` into a named 2D
  ``jax.sharding.Mesh`` — (data, tp) with the data axis pinned to 1
  (replication across slices is the FLEET's job; in-engine dp would
  double-count KV pages and break the slot accounting).
- logits_psum_fn: the reduction applied to the row-parallel lm_head's
  partial logits inside the engine's shard_map — plain ``lax.psum`` by
  default, or the EQuARX-style block-scaled quantized all-reduce
  (ops/quantized_collectives) when ``EngineConfig.quantized_collectives``
  is armed. Only the (B, V) logits reduction is routed through the
  quantized path: per-layer residual psums are small (B, H) and stay
  exact so KV pool contents never see quantization error twice.

Distinct from parallel/mesh.py (MeshSpec — the GSPMD auto-partitioning
path): here sharding is explicit shard_map with hand-placed
collectives, built via ops/jax_compat.shard_map_compat.

Tier-1 testability: `XLA_FLAGS=--xla_force_host_platform_device_count=N`
(`_private/cpu_mesh.py`) gives a virtual multi-chip CPU backend, so
tp=2 meshes run REAL psums in CI.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec

from .quantized_collectives import quantized_psum

# Leading (size-1) mesh axis name: reserved for cross-slice data
# parallelism, which the fleet implements as whole replicas.
DATA_AXIS = "data"


def parse_mesh_shape(text: str) -> Tuple[int, int]:
    """"1x2" / "1,2" / "2" -> (1, 2) — the bench/CLI surface for
    ``EngineConfig.mesh_shape``. A bare integer means (1, tp)."""
    s = text.strip().lower().replace(",", "x")
    parts = [p for p in s.split("x") if p]
    if len(parts) == 1:
        return (1, int(parts[0]))
    if len(parts) != 2:
        raise ValueError(f"mesh shape {text!r}: want DATAxTP, e.g. 1x2")
    return (int(parts[0]), int(parts[1]))


def build_serving_mesh(mesh_shape: Sequence[int], tp_axis: str = "tp",
                       devices: Optional[Sequence] = None) -> Mesh:
    """Build the named (DATA_AXIS, tp_axis) mesh for one engine replica.

    mesh_shape: (data, tp) — data must be 1 (see module docstring).
    devices: override the device list (tests); defaults to
    jax.devices(), taking the first data*tp entries.
    """
    shape = tuple(int(s) for s in mesh_shape)
    if len(shape) != 2:
        raise ValueError(
            f"mesh_shape must be 2D (data, tp), got {mesh_shape!r}")
    data, tp = shape
    if data != 1:
        raise ValueError(
            f"mesh_shape data axis must be 1 (got {data}): in-engine "
            "data parallelism is not supported — scale replicas via "
            "the fleet instead")
    if tp < 1:
        raise ValueError(f"mesh_shape tp axis must be >= 1, got {tp}")
    if not tp_axis or tp_axis == DATA_AXIS:
        raise ValueError(f"tp_axis must be a non-empty name other than "
                         f"{DATA_AXIS!r}, got {tp_axis!r}")
    devs = list(devices) if devices is not None else jax.devices()
    if len(devs) < data * tp:
        raise ValueError(
            f"mesh_shape {shape} needs {data * tp} devices, backend "
            f"has {len(devs)} (tests: force a virtual CPU mesh via "
            "XLA_FLAGS=--xla_force_host_platform_device_count=N)")
    grid = np.asarray(devs[: data * tp], dtype=object).reshape(data, tp)
    return Mesh(grid, (DATA_AXIS, tp_axis))


def mesh_chips(mesh: Optional[Mesh]) -> int:
    """Chips one replica occupies — the fleet's slice-accounting unit."""
    return int(mesh.devices.size) if mesh is not None else 1


def logits_psum_fn(kind: str = "f32"
                   ) -> Callable[[jax.Array, str], jax.Array]:
    """Reduction for the row-parallel lm_head partial logits.

    kind="f32" is exact lax.psum; "int8"/"fp8" route through
    quantized_psum (block-scaled wire format, ~4x less ICI traffic for
    the (B, V) tensor at int8 — the EQuARX trade documented in
    BENCH_CORE.md "Pod-scale serving anatomy")."""
    if kind == "f32":
        return lambda x, axis_name: jax.lax.psum(x, axis_name)

    def _q(x: jax.Array, axis_name: str) -> jax.Array:
        return quantized_psum(x, axis_name, kind=kind)

    return _q


def kv_pool_spec(tp_axis: str = "tp") -> PartitionSpec:
    """[L, pages, page, KVH, D] pools shard over the kv-head axis."""
    return PartitionSpec(None, None, None, tp_axis, None)


def kv_scale_spec(tp_axis: str = "tp") -> PartitionSpec:
    """[L, pages, page, KVH] quantized-pool row scales follow the heads."""
    return PartitionSpec(None, None, None, tp_axis)


__all__ = ["DATA_AXIS", "build_serving_mesh", "kv_pool_spec",
           "kv_scale_spec", "logits_psum_fn", "mesh_chips",
           "parse_mesh_shape"]
