"""EQuARX-style quantized collectives for the tp mesh (ISSUE 16c).

Tensor-parallel serving moves activations through two collectives per
layer (the psum closing each row-parallel matmul, the all_gather
opening column-parallel ones), and at small batch the wire time — not
the MXU — bounds the layer. EQuARX (PAPERS.md) cuts that wire time by
shipping int8 blocks + f32 block scales instead of wide activations,
quantizing at BOTH hops of the two-phase allreduce so every byte on
the ICI is narrow. This module is that scheme expressed in portable
lax collectives, callable inside any shard_map over the tp axis:

  phase 1 (reduce-scatter shaped): each shard splits its operand into
    one chunk per peer, quantizes every chunk block-wise, and
    all_to_alls the narrow values + scales; the receiver dequantizes
    to f32 and reduces its owned chunk exactly.
  phase 2 (all_gather shaped): the reduced chunk is re-quantized and
    all_gathered, again as narrow values + scales; every shard
    dequantizes the full result.

The f32 accumulate between the hops is what keeps the error one
quantization deep per hop (2 total) instead of growing with the ring —
the EQuARX design point. Block-wise scales (default 256 values per
f32 scale) bound the relative error per block; the payload helper
below accounts the exact bytes a transport layer would move.

The serving engine's llama path is GSPMD — XLA emits its collectives
from shardings, so there is no call site to swap mid-model. The
`EngineConfig.quantized_collectives` knob therefore ARMS these helpers
for explicitly shard_mapped programs (and future custom layers);
correctness is gated here by tolerance oracles vs the f32 collectives
(tests/test_kv_quant.py) on a forced multi-device host platform.

Storage kinds, qmax conventions, and the quantize/dequantize rules are
shared with the KV-page quantizer (`ops/kv_quant.py`) — one numeric
contract across pages, spills, ships, and collectives.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from . import kv_quant

# one f32 scale per this many values (EQuARX block scaling): small
# enough to bound per-block relative error, large enough that scale
# traffic stays ~1.5% of the narrow payload
DEFAULT_BLOCK = 256


def _axis_size(axis_name: str) -> int:
    return int(lax.psum(1, axis_name))


def _quantize_blocks(flat: jax.Array, kind: str, block: int):
    """[n] f32 (n % block == 0) -> ([n/block, block] narrow,
    [n/block] f32 scales)."""
    return kv_quant.quantize_rows(flat.reshape(-1, block), kind)


def payload_bytes(n_elems: int, kind: str,
                  block: int = DEFAULT_BLOCK) -> int:
    """Wire bytes one hop ships for `n_elems` values: narrow values
    plus one f32 scale per block (f32 ships wide, no scales)."""
    if kind == "f32":
        return int(n_elems) * 4
    blocks = -(-int(n_elems) // int(block))
    return (int(n_elems) * kv_quant.value_bytes(kind)
            + blocks * kv_quant.SCALE_BYTES)


def quantized_all_gather(x: jax.Array, axis_name: str,
                         kind: str = "int8",
                         block: int = DEFAULT_BLOCK) -> jax.Array:
    """lax.all_gather semantics (new leading axis of size P) with the
    shipped payload quantized block-wise. Call inside shard_map."""
    kind = kv_quant.validate_kind(kind)
    if kind == "f32":
        return lax.all_gather(x, axis_name)
    shape, dt = x.shape, x.dtype
    flat = x.astype(jnp.float32).reshape(-1)
    n = flat.shape[0]
    padded = -(-n // block) * block
    flat = jnp.pad(flat, (0, padded - n))
    q, s = _quantize_blocks(flat, kind, block)
    qg = lax.all_gather(q, axis_name)          # [P, nb, block] narrow
    sg = lax.all_gather(s, axis_name)          # [P, nb] f32
    full = kv_quant.dequantize_rows(qg, sg, kind)
    return full.reshape(full.shape[0], -1)[:, :n] \
        .reshape((full.shape[0],) + shape).astype(dt)


def quantized_psum(x: jax.Array, axis_name: str, kind: str = "int8",
                   block: int = DEFAULT_BLOCK) -> jax.Array:
    """lax.psum semantics with both hops of the two-phase allreduce
    shipping quantized blocks (EQuARX). Call inside shard_map."""
    kind = kv_quant.validate_kind(kind)
    if kind == "f32":
        return lax.psum(x, axis_name)
    P = _axis_size(axis_name)
    shape, dt = x.shape, x.dtype
    flat = x.astype(jnp.float32).reshape(-1)
    n = flat.shape[0]
    per = -(-n // (P * block)) * block         # chunk rows per peer
    flat = jnp.pad(flat, (0, per * P - n))
    chunks = flat.reshape(P, per // block, block)
    # hop 1: quantize every peer's chunk, ship narrow, reduce in f32
    q, s = kv_quant.quantize_rows(chunks, kind)
    q_r = lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0)
    s_r = lax.all_to_all(s, axis_name, split_axis=0, concat_axis=0)
    partial = kv_quant.dequantize_rows(q_r, s_r, kind).sum(axis=0)
    # hop 2: re-quantize the reduced chunk, gather narrow, dequantize
    q2, s2 = kv_quant.quantize_rows(partial, kind)
    qg = lax.all_gather(q2, axis_name)
    sg = lax.all_gather(s2, axis_name)
    out = kv_quant.dequantize_rows(qg, sg, kind).reshape(-1)[:n]
    return out.reshape(shape).astype(dt)


__all__ = ["DEFAULT_BLOCK", "payload_bytes", "quantized_all_gather",
           "quantized_psum"]
