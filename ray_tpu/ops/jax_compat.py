"""jax version-compat shims shared by the ops and model layers.

One home for API drift between the pinned 0.4.x line and newer jax:
each consumer importing its own copy is how the shims diverge (the
ring-attention and llama_infer copies had already drifted before this
module existed). Keep additions tiny and documented with the versions
they bridge.
"""

from __future__ import annotations

import jax


def set_mesh_compat(mesh):
    """Context manager installing `mesh` as the ambient mesh across
    jax versions: `jax.set_mesh` where it exists; on the 0.4.x line a
    Mesh is ITSELF the context manager that sets the thread-local
    physical mesh (resource env), which is what the logical-axis
    sharding fallback (parallel/sharding.get_abstract_mesh_or_none)
    reads there."""
    sm = getattr(jax, "set_mesh", None)
    return sm(mesh) if sm is not None else mesh


def shard_map_compat(fn, mesh, in_specs, out_specs):
    """shard_map across jax versions: the stable `jax.shard_map`
    (check_vma) when present, else the experimental one (check_rep) —
    0.4.x only ships the latter. 0.4.37's `jax.shard_map` is a
    deprecation stub whose getattr RAISES, which hasattr treats as
    absent, so the probe stays correct there too."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as sm
    return sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
              check_rep=False)
