"""Ragged all-to-all exchange for expert-parallel dispatch.

On TPU this is `jax.lax.ragged_all_to_all` — the ICI collective that
moves each shard's variable-size per-peer chunks without capacity
padding (the TPU-native answer to the reference stack's NCCL
all-to-all in vLLM's expert parallelism; SURVEY §2.4 EP row).

XLA:CPU has no lowering for the primitive ("HLO opcode
`ragged-all-to-all` is not supported by XLA:CPU ThunkEmitter"), so the
virtual-mesh tests and the driver's CPU dryrun run a semantics-exact
emulation built from all_gather + masked scatter. Same interface, same
offsets contract, chosen at trace time by backend.

Semantics (mirrors lax.ragged_all_to_all): for each peer j, rows
``operand[input_offsets[j] : input_offsets[j] + send_sizes[j]]`` land in
peer j's ``output`` at row ``output_offsets[j]`` (the offset in the
RECEIVER's buffer, known to the sender); ``recv_sizes[j]`` is how many
rows this shard receives from peer j. Rows of ``output`` not written to
keep their initial values.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
from jax import lax


def exchange_offsets(send_sizes: jax.Array, axis_name: str):
    """Derive (input_offsets, output_offsets, recv_sizes) from per-peer
    send_sizes: one all_to_all of the counts, one of the receiver-side
    exclusive cumsums (each peer must learn where ITS chunk starts in
    the receiver's buffer)."""
    recv_sizes = lax.all_to_all(send_sizes, axis_name, 0, 0)
    off_in_recv = jnp.cumsum(recv_sizes) - recv_sizes
    output_offsets = lax.all_to_all(off_in_recv, axis_name, 0, 0)
    input_offsets = jnp.cumsum(send_sizes) - send_sizes
    return input_offsets, output_offsets, recv_sizes


def _use_native() -> bool:
    if os.environ.get("RAY_TPU_RAGGED_EMULATE", "0") in ("1", "true"):
        return False
    return jax.default_backend() == "tpu"


def ragged_all_to_all(operand: jax.Array, output: jax.Array,
                      input_offsets: jax.Array, send_sizes: jax.Array,
                      output_offsets: jax.Array, recv_sizes: jax.Array,
                      *, axis_name: str) -> jax.Array:
    """Call inside shard_map over ``axis_name``. operand/output are 2-D
    ``[rows, features]`` per-shard buffers."""
    if _use_native():
        return lax.ragged_all_to_all(
            operand, output, input_offsets, send_sizes,
            output_offsets, recv_sizes, axis_name=axis_name)
    return _emulated(operand, output, input_offsets, send_sizes,
                     output_offsets, recv_sizes, axis_name=axis_name)


def _emulated(operand, output, input_offsets, send_sizes,
              output_offsets, recv_sizes, *, axis_name):
    del recv_sizes   # receiver layout is fully determined by the senders
    rows, _ = operand.shape
    row = jnp.arange(rows)
    # classify each operand row: destination peer + position in chunk
    inrange = ((row[None, :] >= input_offsets[:, None])
               & (row[None, :] < (input_offsets + send_sizes)[:, None]))
    dest = jnp.argmax(inrange, axis=0)               # [rows]
    valid = inrange.any(axis=0)
    pos = row - input_offsets[dest]
    me = lax.axis_index(axis_name)
    ops = lax.all_gather(operand, axis_name)          # [P, rows, H]
    dests = lax.all_gather(dest, axis_name)           # [P, rows]
    poss = lax.all_gather(pos, axis_name)
    valids = lax.all_gather(valid, axis_name)
    outoffs = lax.all_gather(output_offsets, axis_name)   # [P_sender, P]
    # sender s's chunk for me starts at outoffs[s, me]
    tgt = outoffs[:, me][:, None] + poss              # [P, rows]
    tgt = jnp.where((dests == me) & valids, tgt, output.shape[0])
    flat = ops.reshape(-1, operand.shape[1])
    return output.at[tgt.reshape(-1)].set(flat, mode="drop")
