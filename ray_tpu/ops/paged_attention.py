"""Paged attention: decode-time attention over a block-paged KV cache.

Net-new for the TPU build (the reference delegates paged attention to
external vLLM CUDA kernels; SURVEY.md §7 step 10). Layout decision
(TPU-first): one page pool shared by ALL layers —

    k_pages, v_pages: [num_pages, page_size, n_layers, n_kv_heads, head_dim]

so a decode token's KV for every layer lands in ONE scatter at
(page, offset), and the per-step gather of a sequence's context is one
take along the page axis (XLA turns both into efficient dynamic-slice
loops over HBM; no per-layer page tables needed).

The XLA path gathers pages into dense [B, ctx] KV then runs masked
attention — the standard fallback. A Pallas kernel can later stream pages
block-by-block without materializing the gather.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def gather_kv(k_pages: jax.Array, v_pages: jax.Array,
              page_tables: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """page_tables: [B, max_pages] int32 →
    k/v: [B, max_pages*page_size, n_layers, n_kv_heads, head_dim]."""
    def one(pages):
        g = pages[page_tables]            # [B, P, page, L, KVH, D]
        b, p, s, l, h, d = g.shape
        return g.reshape(b, p * s, l, h, d)
    return one(k_pages), one(v_pages)


def paged_attention(q: jax.Array, k_pages: jax.Array, v_pages: jax.Array,
                    page_tables: jax.Array, seq_lens: jax.Array,
                    layer: int) -> jax.Array:
    """Single-layer decode attention.

    q: [B, n_heads, head_dim] (one new token per sequence)
    seq_lens: [B] number of valid cached tokens (including the new one)
    Returns [B, n_heads, head_dim].
    """
    k, v = gather_kv(k_pages, v_pages, page_tables)
    return paged_attention_on_gathered(
        q, k[:, :, layer], v[:, :, layer], seq_lens)


def paged_attention_on_gathered(q: jax.Array, k: jax.Array, v: jax.Array,
                                seq_lens: jax.Array,
                                append_len: int = 0) -> jax.Array:
    """q: [B, H, D]; k/v: [B, ctx, KVH, D]; seq_lens: [B] → [B, H, D].

    Valid positions: the first seq_lens[b] cached entries plus the last
    `append_len` entries (decode appends the current token's KV at the
    tail before it has been scattered into the pool). GQA: H query heads
    share H//KVH groups. Softmax in float32, invalid positions -> -inf.
    """
    b, h, d = q.shape
    ctx, kvh = k.shape[1], k.shape[2]
    group = h // kvh
    qf = q.reshape(b, kvh, group, d).astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    scores = jnp.einsum("bkgd,bckd->bkgc", qf, kf) / jnp.sqrt(d)
    idx = jnp.arange(ctx)[None, :]
    mask = idx < seq_lens[:, None]                        # [B, ctx]
    if append_len:
        mask = mask | (idx >= ctx - append_len)
    scores = jnp.where(mask[:, None, None, :], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkgc,bckd->bkgd", probs, vf)
    return out.reshape(b, h, d).astype(q.dtype)


def scatter_kv(k_pages: jax.Array, v_pages: jax.Array,
               k_new: jax.Array, v_new: jax.Array,
               page_tables: jax.Array, positions: jax.Array,
               valid: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Write new KV rows into the page pool.

    k_new/v_new: [N, n_layers, n_kv_heads, head_dim] (N tokens, any mix of
    sequences); page_tables: [N, max_pages] each token's OWN sequence
    table; positions: [N] absolute position of each token; valid: [N]
    bool — invalid rows write to a scratch page (the last page, which the
    allocator never hands out) instead of branching.
    """
    page_size = k_pages.shape[1]
    scratch = k_pages.shape[0] - 1
    page_idx = jnp.take_along_axis(
        page_tables, (positions // page_size)[:, None], axis=1)[:, 0]
    page_idx = jnp.where(valid, page_idx, scratch)
    offset = positions % page_size
    k_pages = k_pages.at[page_idx, offset].set(k_new)
    v_pages = v_pages.at[page_idx, offset].set(v_new)
    return k_pages, v_pages
