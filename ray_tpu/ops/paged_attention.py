"""Paged attention: decode-time attention over a block-paged KV cache.

Net-new for the TPU build (the reference delegates paged attention to
external vLLM CUDA kernels; SURVEY.md §7 step 10). Layout decision
(TPU-first): one page pool shared by ALL layers, layer-major + head-major —

    k_pages, v_pages: [n_layers, num_pages, page_size, n_kv_heads, head_dim]

chosen for the hot paths at once: the decode scan over layers slices
dim 0 (no per-step transpose of the pool); (page, token) are adjacent so
KV writes flatten the pool to [L, P*page_size, KVH, D] and scatter on a
SINGLE index dim (row = page*page_size + offset) — the
two-index-dim form (.at[:, page_idx, :, offset]) lowers to a
pathologically slow XLA scatter on TPU; and the kernel block's last two
dims stay (KVH, head_dim), which satisfies the TPU lowering's
(8, 128)-divisibility natively.

Two decode paths:
- XLA fallback: gather pages into dense [B, ctx] KV then masked attention
  (cost scales with max_pages, not actual context).
- Pallas kernel (`paged_decode_attention`): stream each sequence's pages
  through VMEM with online softmax. Page indices come from the
  scalar-prefetched page table, so the BlockSpec DMAs exactly the pages a
  sequence owns; grid steps past the end of a sequence re-map to the same
  page (Pallas elides the repeat DMA) and skip compute — decode cost
  scales with the tokens actually cached.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ray_tpu.ops import kv_quant


def gather_kv(k_pages: jax.Array, v_pages: jax.Array,
              page_tables: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """page_tables: [B, max_pages] int32 →
    k/v: [n_layers, B, max_pages*page_size, n_kv_heads, head_dim]
    (layer-major, ready for a scan over layers)."""
    def one(pages):
        g = pages[:, page_tables]          # [L, B, P, page, KVH, D]
        l, b, p, s, h, d = g.shape
        return g.reshape(l, b, p * s, h, d)
    return one(k_pages), one(v_pages)


def gather_kv_quant(k_pages: jax.Array, v_pages: jax.Array,
                    k_scales: jax.Array, v_scales: jax.Array,
                    page_tables: jax.Array
                    ) -> Tuple[jax.Array, jax.Array]:
    """Quantized-pool gather (ISSUE 16): pools hold int8/fp8 values
    with per-(row, head) f32 scales ([L, P, page, KVH],
    ops/kv_quant.py layout). Gathers values AND scales by the table,
    dequantizes, and returns the same dense f32 layout as gather_kv —
    the XLA fallback paths stay byte-for-byte identical downstream of
    this call."""
    def one(pages, scales):
        g = pages[:, page_tables]          # [L, B, P, page, KVH, D]
        s = scales[:, page_tables]         # [L, B, P, page, KVH]
        l, b, p, sz, h, d = g.shape
        deq = g.astype(jnp.float32) * s.astype(jnp.float32)[..., None]
        return deq.reshape(l, b, p * sz, h, d)
    return one(k_pages, k_scales), one(v_pages, v_scales)


def paged_attention(q: jax.Array, k_pages: jax.Array, v_pages: jax.Array,
                    page_tables: jax.Array, seq_lens: jax.Array,
                    layer: int) -> jax.Array:
    """Single-layer decode attention (dense-gather path).

    q: [B, n_heads, head_dim] (one new token per sequence)
    seq_lens: [B] number of valid cached tokens (including the new one)
    Returns [B, n_heads, head_dim].
    """
    g_k = k_pages[layer][page_tables]      # [B, P, page, KVH, D]
    g_v = v_pages[layer][page_tables]
    b, p, s, h, d = g_k.shape
    k = g_k.reshape(b, p * s, h, d)
    v = g_v.reshape(b, p * s, h, d)
    return paged_attention_on_gathered(q, k, v, seq_lens)


def paged_attention_on_gathered(q: jax.Array, k: jax.Array, v: jax.Array,
                                seq_lens: jax.Array,
                                append_len: int = 0) -> jax.Array:
    """q: [B, H, D]; k/v: [B, ctx, KVH, D]; seq_lens: [B] → [B, H, D].

    Valid positions: the first seq_lens[b] cached entries plus the last
    `append_len` entries (decode appends the current token's KV at the
    tail before it has been scattered into the pool). GQA: H query heads
    share H//KVH groups. Softmax in float32, invalid positions -> -inf.
    """
    b, h, d = q.shape
    ctx, kvh = k.shape[1], k.shape[2]
    group = h // kvh
    qf = q.reshape(b, kvh, group, d).astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    scores = jnp.einsum("bkgd,bckd->bkgc", qf, kf) / jnp.sqrt(d)
    idx = jnp.arange(ctx)[None, :]
    mask = idx < seq_lens[:, None]                        # [B, ctx]
    if append_len:
        mask = mask | (idx >= ctx - append_len)
    scores = jnp.where(mask[:, None, None, :], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkgc,bckd->bkgd", probs, vf)
    return out.reshape(b, h, d).astype(q.dtype)


def chunk_attention_on_gathered(q: jax.Array, k_ctx: jax.Array,
                                v_ctx: jax.Array, k_chunk: jax.Array,
                                v_chunk: jax.Array, start: jax.Array,
                                chunk_lens: jax.Array) -> jax.Array:
    """Multi-token-query attention over cached context + the chunk itself
    (chunked prefill / prefix-cache suffix prefill).

    q: [B, C, H, D] queries at absolute positions start[b]+i;
    k_ctx/v_ctx: [B, ctx, KVH, D] gathered pool (valid: pos < start[b]);
    k_chunk/v_chunk: [B, C, KVH, D] the chunk's own KV;
    chunk_lens: [B] valid tokens in the chunk.
    Query i attends ctx positions < start[b] and chunk positions j <= i
    (j < chunk_lens[b]). Softmax in float32. Returns [B, C, H, D].
    """
    b, c, h, d = q.shape
    ctx, kvh = k_ctx.shape[1], k_ctx.shape[2]
    group = h // kvh
    qf = q.reshape(b, c, kvh, group, d).astype(jnp.float32)
    scale = 1.0 / jnp.sqrt(d)
    s_ctx = jnp.einsum("bikgd,bckd->bkgic", qf, k_ctx.astype(jnp.float32))
    s_chk = jnp.einsum("bikgd,bjkd->bkgij", qf,
                       k_chunk.astype(jnp.float32))
    ctx_mask = (jnp.arange(ctx)[None, :] < start[:, None])     # [B, ctx]
    i_idx = jnp.arange(c)[:, None]
    j_idx = jnp.arange(c)[None, :]
    chk_mask = ((j_idx <= i_idx)[None]
                & (j_idx[None] < chunk_lens[:, None, None]))   # [B, C, C]
    s_ctx = jnp.where(ctx_mask[:, None, None, None, :],
                      s_ctx * scale, -jnp.inf)
    s_chk = jnp.where(chk_mask[:, None, None, :, :],
                      s_chk * scale, -jnp.inf)
    scores = jnp.concatenate([s_ctx, s_chk], axis=-1)  # [B,KVH,G,C,ctx+C]
    probs = jax.nn.softmax(scores, axis=-1)
    p_ctx, p_chk = probs[..., :ctx], probs[..., ctx:]
    out = (jnp.einsum("bkgic,bckd->bikgd", p_ctx,
                      v_ctx.astype(jnp.float32))
           + jnp.einsum("bkgij,bjkd->bikgd", p_chk,
                        v_chunk.astype(jnp.float32)))
    return out.reshape(b, c, h, d).astype(q.dtype)


def _paged_decode_kernel(tables_ref, lens_ref, q_ref, k_ref, v_ref,
                         *rest, page_size: int, scale: float, kvh: int,
                         quantized: bool = False):
    """Grid (B, max_pages): each step consumes one page for ALL kv heads
    (the per-head loop is unrolled — kvh is small and static), keeping the
    grid shallow so dispatch overhead doesn't dominate decode.

    quantized=True (ISSUE 16): two extra refs after v_ref carry the
    page's per-(row, head) f32 scales; dequant folds into the f32
    upcast of each head's page slice."""
    if quantized:
        (ks_ref, vs_ref, o_ref, m_ref, l_ref,
         m_scr, l_scr, acc_scr) = rest
    else:
        (o_ref, m_ref, l_ref, m_scr, l_scr, acc_scr) = rest
    b = pl.program_id(0)
    j = pl.program_id(1)
    n_pages = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, -1e30)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    seq_len = lens_ref[b]
    live = j * page_size < seq_len

    @pl.when(live)
    def _step():
        pos = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1)
        valid = pos < seq_len                          # (1, page)
        group = q_ref.shape[2]
        for h in range(kvh):
            q = q_ref[0, h].astype(jnp.float32)        # (group, D)
            k = k_ref[0, :, h].astype(jnp.float32)     # (page, D)
            v = v_ref[0, :, h].astype(jnp.float32)     # (page, D)
            if quantized:
                k = k * ks_ref[0, :, h][:, None]
                v = v * vs_ref[0, :, h][:, None]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale  # (group, page)
            s = jnp.where(valid, s, -1e30)
            rows = slice(h * group, (h + 1) * group)
            m_prev = m_scr[rows]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
            p = jnp.exp(s - m_new)
            corr = jnp.exp(m_prev - m_new)
            l_scr[rows] = (l_scr[rows] * corr
                           + jnp.sum(p, axis=1, keepdims=True))
            acc_scr[rows] = acc_scr[rows] * corr + jax.lax.dot_general(
                p, v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            m_scr[rows] = m_new

    @pl.when(j == n_pages - 1)
    def _finish():
        group = q_ref.shape[2]
        safe_l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / safe_l).reshape(
            kvh, group, -1).astype(o_ref.dtype)
        m_ref[0] = m_scr[:].reshape(kvh, group, 1)
        l_ref[0] = l_scr[:].reshape(kvh, group, 1)


def _paged_decode_kernel_mp(tables_ref, lens_ref, q_ref, k_hbm, v_hbm,
                            *rest, page_size: int, ppb: int,
                            scale: float, kvh: int,
                            quantized: bool = False):
    """Multi-page variant: grid (B, max_pages // ppb); each step manually
    DMAs its block's ppb pages (all kv heads per page — our pool layout
    keeps heads together) into VMEM and runs one online-softmax update
    over ppb*page_size keys. 8x fewer grid steps and 8x larger matmuls
    than the one-page-per-step BlockSpec kernel, whose per-step dispatch
    overhead dominated decode (~5us x B x max_pages).

    quantized=True (ISSUE 16): two extra HBM refs carry the per-(row,
    head) f32 scale pools; each block DMAs its pages' scale rows in
    the same wave and fuses the dequant multiply into the f32 upcast —
    the streamed context bytes drop to ~1/4 of f32."""
    if quantized:
        (ks_hbm, vs_hbm, o_ref, m_ref, l_ref, k_vmem, v_vmem,
         ks_vmem, vs_vmem, sem, m_scr, l_scr, acc_scr) = rest
    else:
        (o_ref, m_ref, l_ref, k_vmem, v_vmem, sem,
         m_scr, l_scr, acc_scr) = rest
    b = pl.program_id(0)
    i = pl.program_id(1)
    n_blocks = pl.num_programs(1)
    bk = page_size * ppb
    length = jnp.maximum(lens_ref[b], 1)   # inactive rows attend 1 page

    @pl.when(i == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, -1e30)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(i * bk < length)
    def _step():
        last = jnp.maximum((length - 1) // page_size, 0)

        def copies():
            out = []
            for t in range(ppb):
                idx = tables_ref[b, jnp.minimum(i * ppb + t, last)]
                out.append(pltpu.make_async_copy(
                    k_hbm.at[idx], k_vmem.at[t], sem))
                out.append(pltpu.make_async_copy(
                    v_hbm.at[idx], v_vmem.at[t], sem))
                if quantized:
                    out.append(pltpu.make_async_copy(
                        ks_hbm.at[idx], ks_vmem.at[t], sem))
                    out.append(pltpu.make_async_copy(
                        vs_hbm.at[idx], vs_vmem.at[t], sem))
            return out

        for c in copies():
            c.start()
        for c in copies():
            c.wait()

        pos = i * bk + jax.lax.broadcasted_iota(jnp.int32, (1, bk), 1)
        valid = pos < length                           # (1, bk)
        group = q_ref.shape[2]
        d = q_ref.shape[3]
        # [ppb, page, kvh, D] -> per-head [bk, D]
        kb = k_vmem[...].astype(jnp.float32)
        vb = v_vmem[...].astype(jnp.float32)
        if quantized:
            # fused dequant against the scale rows from the same wave
            kb = kb * ks_vmem[...][..., None]
            vb = vb * vs_vmem[...][..., None]
        for h in range(kvh):
            q = q_ref[0, h].astype(jnp.float32)        # (group, D)
            k = kb[:, :, h].reshape(bk, d)
            v = vb[:, :, h].reshape(bk, d)
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale  # (group, bk)
            s = jnp.where(valid, s, -1e30)
            rows = slice(h * group, (h + 1) * group)
            m_prev = m_scr[rows]
            m_new = jnp.maximum(m_prev,
                                jnp.max(s, axis=1, keepdims=True))
            p = jnp.exp(s - m_new)
            corr = jnp.exp(m_prev - m_new)
            l_scr[rows] = (l_scr[rows] * corr
                           + jnp.sum(p, axis=1, keepdims=True))
            acc_scr[rows] = acc_scr[rows] * corr + jax.lax.dot_general(
                p, v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            m_scr[rows] = m_new

    @pl.when(i == n_blocks - 1)
    def _finish():
        group = q_ref.shape[2]
        safe_l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / safe_l).reshape(
            kvh, group, -1).astype(o_ref.dtype)
        m_ref[0] = m_scr[:].reshape(kvh, group, 1)
        l_ref[0] = l_scr[:].reshape(kvh, group, 1)


def _paged_decode_multipage(q, k_pages, v_pages, page_tables, seq_lens,
                            ppb: int, interpret: bool = False,
                            k_scales=None, v_scales=None):
    b, h, d = q.shape
    _, page_size, kvh, _ = k_pages.shape
    max_pages = page_tables.shape[1]
    group = h // kvh
    scale = d ** -0.5
    qg = q.reshape(b, kvh, group, d)
    n_blocks = max(-(-max_pages // ppb), 1)
    quantized = k_scales is not None

    fixed = lambda bi, i, tables, lens: (bi, 0, 0, 0)
    out_spec = pl.BlockSpec((1, kvh, group, d), fixed)
    stat_spec = pl.BlockSpec((1, kvh, group, 1), fixed)
    in_specs = [
        pl.BlockSpec((1, kvh, group, d), fixed),
        pl.BlockSpec(memory_space=pl.ANY),   # k pool stays in HBM
        pl.BlockSpec(memory_space=pl.ANY),   # v pool stays in HBM
    ]
    inputs = [qg, k_pages, v_pages]
    scratch = [
        pltpu.VMEM((ppb, page_size, kvh, d), k_pages.dtype),
        pltpu.VMEM((ppb, page_size, kvh, d), v_pages.dtype),
    ]
    if quantized:
        in_specs += [pl.BlockSpec(memory_space=pl.ANY),
                     pl.BlockSpec(memory_space=pl.ANY)]
        inputs += [k_scales.astype(jnp.float32),
                   v_scales.astype(jnp.float32)]
        scratch += [pltpu.VMEM((ppb, page_size, kvh), jnp.float32),
                    pltpu.VMEM((ppb, page_size, kvh), jnp.float32)]
    return pl.pallas_call(
        functools.partial(_paged_decode_kernel_mp, page_size=page_size,
                          ppb=ppb, scale=scale, kvh=kvh,
                          quantized=quantized),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b, n_blocks),
            in_specs=in_specs,
            out_specs=(out_spec, stat_spec, stat_spec),
            scratch_shapes=scratch + [
                pltpu.SemaphoreType.DMA,
                pltpu.VMEM((kvh * group, 1), jnp.float32),
                pltpu.VMEM((kvh * group, 1), jnp.float32),
                pltpu.VMEM((kvh * group, d), jnp.float32),
            ],
        ),
        out_shape=(
            jax.ShapeDtypeStruct((b, kvh, group, d), q.dtype),
            jax.ShapeDtypeStruct((b, kvh, group, 1), jnp.float32),
            jax.ShapeDtypeStruct((b, kvh, group, 1), jnp.float32),
        ),
        interpret=interpret,
    )(page_tables.astype(jnp.int32), seq_lens.astype(jnp.int32),
      *inputs)


def paged_decode_attention(q: jax.Array, k_pages: jax.Array,
                           v_pages: jax.Array, page_tables: jax.Array,
                           seq_lens: jax.Array, *,
                           return_stats: bool = False,
                           pages_per_block: int = 16,
                           k_scales: jax.Array = None,
                           v_scales: jax.Array = None,
                           interpret: bool = False):
    """Pallas paged decode attention for one layer.

    q: [B, H, D]; k_pages/v_pages: [num_pages, page_size, KVH, D]
    (already sliced to the layer); page_tables: [B, max_pages] int32;
    seq_lens: [B] int32. Returns [B, H, D], or with return_stats=True
    (out, m, l) where m/l are the [B, H] online-softmax row max /
    denominator — callers merge extra not-yet-paged KV (the token being
    decoded) with one more online-softmax step.

    The page-table BlockSpec index map clamps the page index for grid
    steps past a sequence's last page to the sequence's final page:
    consecutive identical block indices make Pallas skip the DMA, and
    `pl.when` skips the compute, so per-sequence work is proportional to
    ceil(seq_len / page_size), not max_pages.
    """
    b, h, d = q.shape
    _, page_size, kvh, _ = k_pages.shape
    max_pages = page_tables.shape[1]
    quantized = k_scales is not None
    if not interpret and max_pages >= pages_per_block > 1:
        out, m, l = _paged_decode_multipage(
            q, k_pages, v_pages, page_tables, seq_lens, pages_per_block,
            k_scales=k_scales, v_scales=v_scales)
        out = out.reshape(b, h, d)
        if return_stats:
            return out, m.reshape(b, h), l.reshape(b, h)
        return out
    group = h // kvh
    scale = d ** -0.5
    qg = q.reshape(b, kvh, group, d)

    def page_index(bi, j, tables, lens):
        last = jnp.maximum((lens[bi] - 1) // page_size, 0)
        return (tables[bi, jnp.minimum(j, last)], 0, 0, 0)

    def scale_index(bi, j, tables, lens):
        last = jnp.maximum((lens[bi] - 1) // page_size, 0)
        return (tables[bi, jnp.minimum(j, last)], 0, 0)

    grid = (b, max_pages)
    out_spec = pl.BlockSpec(
        (1, kvh, group, d), lambda bi, j, tables, lens: (bi, 0, 0, 0))
    stat_spec = pl.BlockSpec(
        (1, kvh, group, 1), lambda bi, j, tables, lens: (bi, 0, 0, 0))
    in_specs = [
        pl.BlockSpec((1, kvh, group, d),
                     lambda bi, j, tables, lens: (bi, 0, 0, 0)),
        pl.BlockSpec((1, page_size, kvh, d), page_index),
        pl.BlockSpec((1, page_size, kvh, d), page_index),
    ]
    inputs = [qg, k_pages, v_pages]
    if quantized:
        # scale blocks ride the same clamped page-index map as their
        # pages, so the DMA-elision for past-the-end steps holds
        in_specs += [pl.BlockSpec((1, page_size, kvh), scale_index),
                     pl.BlockSpec((1, page_size, kvh), scale_index)]
        inputs += [k_scales.astype(jnp.float32),
                   v_scales.astype(jnp.float32)]
    out, m, l = pl.pallas_call(
        functools.partial(_paged_decode_kernel, page_size=page_size,
                          scale=scale, kvh=kvh, quantized=quantized),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=in_specs,
            out_specs=(out_spec, stat_spec, stat_spec),
            scratch_shapes=[
                pltpu.VMEM((kvh * group, 1), jnp.float32),
                pltpu.VMEM((kvh * group, 1), jnp.float32),
                pltpu.VMEM((kvh * group, d), jnp.float32),
            ],
        ),
        out_shape=(
            jax.ShapeDtypeStruct((b, kvh, group, d), q.dtype),
            jax.ShapeDtypeStruct((b, kvh, group, 1), jnp.float32),
            jax.ShapeDtypeStruct((b, kvh, group, 1), jnp.float32),
        ),
        interpret=interpret,
    )(page_tables.astype(jnp.int32), seq_lens.astype(jnp.int32),
      *inputs)
    out = out.reshape(b, h, d)
    if return_stats:
        return out, m.reshape(b, h), l.reshape(b, h)
    return out


def paged_decode_with_new_token(q: jax.Array, k_pages: jax.Array,
                                v_pages: jax.Array, page_tables: jax.Array,
                                seq_lens: jax.Array, k_new: jax.Array,
                                v_new: jax.Array, *,
                                k_scales: jax.Array = None,
                                v_scales: jax.Array = None,
                                interpret: bool = False) -> jax.Array:
    """Kernel decode over cached pages + one online-softmax merge step for
    the current token's KV (not yet scattered into the pool).

    q/k_new/v_new: [B, H, D] / [B, KVH, D] / [B, KVH, D];
    seq_lens counts CACHED tokens only. Returns [B, H, D].
    k_scales/v_scales: per-(row, head) f32 scales when the pools are
    int8/fp8 (ISSUE 16); the new token's KV stays full-precision.
    """
    b, h, d = q.shape
    kvh = k_new.shape[1]
    group = h // kvh
    scale = d ** -0.5
    out, m, l = paged_decode_attention(
        q, k_pages, v_pages, page_tables, seq_lens,
        return_stats=True, k_scales=k_scales, v_scales=v_scales,
        interpret=interpret)
    # score of the new token against itself (always attendable)
    qf = q.reshape(b, kvh, group, d).astype(jnp.float32)
    kf = k_new.astype(jnp.float32)
    s_new = jnp.einsum("bkgd,bkd->bkg", qf, kf).reshape(b, h) * scale
    m_tot = jnp.maximum(m, s_new)
    c_old = jnp.exp(m - m_tot)
    c_new = jnp.exp(s_new - m_tot)
    l_tot = l * c_old + c_new
    vf = jnp.repeat(v_new.astype(jnp.float32), group, axis=1)  # [B, H, D]
    num = (out.astype(jnp.float32) * (l * c_old)[..., None]
           + vf * c_new[..., None])
    return (num / jnp.maximum(l_tot, 1e-30)[..., None]).astype(q.dtype)


def scatter_kv(k_pages: jax.Array, v_pages: jax.Array,
               k_new: jax.Array, v_new: jax.Array,
               page_tables: jax.Array, positions: jax.Array,
               valid: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Write new KV rows into the page pool.

    k_new/v_new: [N, n_layers, n_kv_heads, head_dim] (N tokens, any mix of
    sequences); page_tables: [N, max_pages] each token's OWN sequence
    table; positions: [N] absolute position of each token; valid: [N]
    bool — invalid rows write to a scratch page (the last page, which the
    allocator never hands out) instead of branching.

    Single-index-dim scatter over the flattened [L, P*page_size, KVH, D]
    view: row = page*page_size + offset. (The earlier two-index-dim form
    .at[:, page_idx, :, offset] lowered to an XLA scatter that took
    SECONDS per call on TPU.)
    """
    l, num_pages, page_size, kvh, d = k_pages.shape
    scratch = num_pages - 1
    page_idx = jnp.take_along_axis(
        page_tables, (positions // page_size)[:, None], axis=1)[:, 0]
    page_idx = jnp.where(valid, page_idx, scratch)
    rows = page_idx * page_size + positions % page_size          # [N]
    flat = lambda p: p.reshape(l, num_pages * page_size, kvh, d)
    # a single advanced index keeps its position: the updated view is
    # [L, N, KVH, D], so swap k_new's leading dims to match
    k_rows = jnp.swapaxes(k_new, 0, 1)
    v_rows = jnp.swapaxes(v_new, 0, 1)
    k_pages = flat(k_pages).at[:, rows].set(k_rows).reshape(k_pages.shape)
    v_pages = flat(v_pages).at[:, rows].set(v_rows).reshape(v_pages.shape)
    return k_pages, v_pages


def scatter_kv_quant(k_pages: jax.Array, v_pages: jax.Array,
                     k_scales: jax.Array, v_scales: jax.Array,
                     k_new: jax.Array, v_new: jax.Array,
                     page_tables: jax.Array, positions: jax.Array,
                     valid: jax.Array, kind: str
                     ) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """scatter_kv for quantized pools: quantize-at-append (ISSUE 16).

    Same row math as scatter_kv, but the fresh f32 rows are quantized
    to `kind` (int8/fp8) with per-(row, head) scales before the write,
    and the scale rows land in the [L, P, page, KVH] scale pools at the
    same flat rows. Append stays write-only: each row carries its own
    scale, so no neighbour rows are re-read. Invalid rows hit the
    scratch page in both the value and scale pools.
    """
    l, num_pages, page_size, kvh, d = k_pages.shape
    scratch = num_pages - 1
    page_idx = jnp.take_along_axis(
        page_tables, (positions // page_size)[:, None], axis=1)[:, 0]
    page_idx = jnp.where(valid, page_idx, scratch)
    rows = page_idx * page_size + positions % page_size          # [N]
    kq, ks = kv_quant.quantize_rows(k_new, kind)   # [N,L,KVH,D]/[N,L,KVH]
    vq, vs = kv_quant.quantize_rows(v_new, kind)
    flat = lambda p: p.reshape(l, num_pages * page_size, kvh, d)
    flat_s = lambda s: s.reshape(l, num_pages * page_size, kvh)
    k_pages = flat(k_pages).at[:, rows].set(
        jnp.swapaxes(kq, 0, 1)).reshape(k_pages.shape)
    v_pages = flat(v_pages).at[:, rows].set(
        jnp.swapaxes(vq, 0, 1)).reshape(v_pages.shape)
    k_scales = flat_s(k_scales).at[:, rows].set(
        jnp.swapaxes(ks, 0, 1)).reshape(k_scales.shape)
    v_scales = flat_s(v_scales).at[:, rows].set(
        jnp.swapaxes(vs, 0, 1)).reshape(v_scales.shape)
    return k_pages, v_pages, k_scales, v_scales
