"""Paged attention: decode-time attention over a block-paged KV cache.

Net-new for the TPU build (the reference delegates paged attention to
external vLLM CUDA kernels; SURVEY.md §7 step 10). Layout decision
(TPU-first): one page pool shared by ALL layers —

    k_pages, v_pages: [num_pages, page_size, n_layers, n_kv_heads, head_dim]

so a decode token's KV for every layer lands in ONE scatter at
(page, offset), and the per-step gather of a sequence's context is one
take along the page axis (XLA turns both into efficient dynamic-slice
loops over HBM; no per-layer page tables needed).

Two decode paths:
- XLA fallback: gather pages into dense [B, ctx] KV then masked attention
  (cost scales with max_pages, not actual context).
- Pallas kernel (`paged_decode_attention`): stream each sequence's pages
  through VMEM with online softmax. Page indices come from the
  scalar-prefetched page table, so the BlockSpec DMAs exactly the pages a
  sequence owns; grid steps past the end of a sequence re-map to the same
  page (Pallas elides the repeat DMA) and skip compute — decode cost
  scales with the tokens actually cached.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def gather_kv(k_pages: jax.Array, v_pages: jax.Array,
              page_tables: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """page_tables: [B, max_pages] int32 →
    k/v: [B, max_pages*page_size, n_layers, n_kv_heads, head_dim]."""
    def one(pages):
        g = pages[page_tables]            # [B, P, page, L, KVH, D]
        b, p, s, l, h, d = g.shape
        return g.reshape(b, p * s, l, h, d)
    return one(k_pages), one(v_pages)


def paged_attention(q: jax.Array, k_pages: jax.Array, v_pages: jax.Array,
                    page_tables: jax.Array, seq_lens: jax.Array,
                    layer: int) -> jax.Array:
    """Single-layer decode attention.

    q: [B, n_heads, head_dim] (one new token per sequence)
    seq_lens: [B] number of valid cached tokens (including the new one)
    Returns [B, n_heads, head_dim].
    """
    k, v = gather_kv(k_pages, v_pages, page_tables)
    return paged_attention_on_gathered(
        q, k[:, :, layer], v[:, :, layer], seq_lens)


def paged_attention_on_gathered(q: jax.Array, k: jax.Array, v: jax.Array,
                                seq_lens: jax.Array,
                                append_len: int = 0) -> jax.Array:
    """q: [B, H, D]; k/v: [B, ctx, KVH, D]; seq_lens: [B] → [B, H, D].

    Valid positions: the first seq_lens[b] cached entries plus the last
    `append_len` entries (decode appends the current token's KV at the
    tail before it has been scattered into the pool). GQA: H query heads
    share H//KVH groups. Softmax in float32, invalid positions -> -inf.
    """
    b, h, d = q.shape
    ctx, kvh = k.shape[1], k.shape[2]
    group = h // kvh
    qf = q.reshape(b, kvh, group, d).astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    scores = jnp.einsum("bkgd,bckd->bkgc", qf, kf) / jnp.sqrt(d)
    idx = jnp.arange(ctx)[None, :]
    mask = idx < seq_lens[:, None]                        # [B, ctx]
    if append_len:
        mask = mask | (idx >= ctx - append_len)
    scores = jnp.where(mask[:, None, None, :], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkgc,bckd->bkgd", probs, vf)
    return out.reshape(b, h, d).astype(q.dtype)


def _paged_decode_kernel(tables_ref, lens_ref, q_ref, k_ref, v_ref, o_ref,
                         m_scr, l_scr, acc_scr, *, page_size: int,
                         scale: float):
    b = pl.program_id(0)
    j = pl.program_id(2)
    n_pages = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, -1e30)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    seq_len = lens_ref[b]
    live = j * page_size < seq_len

    @pl.when(live)
    def _step():
        q = q_ref[0, 0].astype(jnp.float32)            # (group, D)
        k = k_ref[0, :, 0].astype(jnp.float32)         # (page, D)
        v = v_ref[0, :, 0].astype(jnp.float32)         # (page, D)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale    # (group, page)
        pos = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        s = jnp.where(pos < seq_len, s, -1e30)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_scr[:] = l_scr[:] * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = m_new

    @pl.when(j == n_pages - 1)
    def _finish():
        o_ref[0, 0] = (acc_scr[:]
                       / jnp.maximum(l_scr[:], 1e-30)).astype(o_ref.dtype)


def paged_decode_attention(q: jax.Array, k_pages: jax.Array,
                           v_pages: jax.Array, page_tables: jax.Array,
                           seq_lens: jax.Array, *,
                           interpret: bool = False) -> jax.Array:
    """Pallas paged decode attention for one layer.

    q: [B, H, D]; k_pages/v_pages: [num_pages, page_size, KVH, D]
    (already sliced to the layer); page_tables: [B, max_pages] int32;
    seq_lens: [B] int32. Returns [B, H, D].

    The page-table BlockSpec index map clamps the page index for grid
    steps past a sequence's last page to the sequence's final page:
    consecutive identical block indices make Pallas skip the DMA, and
    `pl.when` skips the compute, so per-sequence work is proportional to
    ceil(seq_len / page_size), not max_pages.
    """
    b, h, d = q.shape
    _, page_size, kvh, _ = k_pages.shape
    max_pages = page_tables.shape[1]
    group = h // kvh
    scale = d ** -0.5
    qg = q.reshape(b, kvh, group, d)

    def page_index(bi, hi, j, tables, lens):
        last = jnp.maximum((lens[bi] - 1) // page_size, 0)
        return (tables[bi, jnp.minimum(j, last)], 0, hi, 0)

    grid = (b, kvh, max_pages)
    out = pl.pallas_call(
        functools.partial(_paged_decode_kernel, page_size=page_size,
                          scale=scale),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 1, group, d),
                             lambda bi, hi, j, tables, lens: (bi, hi, 0, 0)),
                pl.BlockSpec((1, page_size, 1, d), page_index),
                pl.BlockSpec((1, page_size, 1, d), page_index),
            ],
            out_specs=pl.BlockSpec(
                (1, 1, group, d),
                lambda bi, hi, j, tables, lens: (bi, hi, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((group, 1), jnp.float32),
                pltpu.VMEM((group, 1), jnp.float32),
                pltpu.VMEM((group, d), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, kvh, group, d), q.dtype),
        interpret=interpret,
    )(page_tables.astype(jnp.int32), seq_lens.astype(jnp.int32),
      qg, k_pages, v_pages)
    return out.reshape(b, h, d)


def scatter_kv(k_pages: jax.Array, v_pages: jax.Array,
               k_new: jax.Array, v_new: jax.Array,
               page_tables: jax.Array, positions: jax.Array,
               valid: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Write new KV rows into the page pool.

    k_new/v_new: [N, n_layers, n_kv_heads, head_dim] (N tokens, any mix of
    sequences); page_tables: [N, max_pages] each token's OWN sequence
    table; positions: [N] absolute position of each token; valid: [N]
    bool — invalid rows write to a scratch page (the last page, which the
    allocator never hands out) instead of branching.
    """
    page_size = k_pages.shape[1]
    scratch = k_pages.shape[0] - 1
    page_idx = jnp.take_along_axis(
        page_tables, (positions // page_size)[:, None], axis=1)[:, 0]
    page_idx = jnp.where(valid, page_idx, scratch)
    offset = positions % page_size
    k_pages = k_pages.at[page_idx, offset].set(k_new)
    v_pages = v_pages.at[page_idx, offset].set(v_new)
    return k_pages, v_pages
