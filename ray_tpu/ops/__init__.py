from . import kv_quant, quantized_collectives
from .attention import attention, flash_attention, reference_attention
from .ring_attention import ring_attention, ring_attention_sharded
