"""Worker forkserver ("zygote"): pay interpreter + import cost once.

Reference parity: the role of worker prestarting
(worker_pool.h maximum_startup_concurrency / prestart) — but solving
the deeper cost: on this stack a cold `python -m worker_main` burns
1-2 s importing the interpreter, numpy, cloudpickle, and (via the
machine's sitecustomize) jax, which caps actor creation at <1/s per
core. The zygote imports everything once, then forks per worker in
~10 ms; children apply their env vars, re-open their log file, and run
the normal worker main. Safe because the zygote never initializes a
jax backend, starts an event loop, or spawns threads — fork happens
from a single-threaded, backend-less process.

Protocol (zygote stdin/stdout, one JSON line per message; replies are
routed by worker_id, and child exits are pushed asynchronously so the
daemon never has to probe possibly-reused pids):
    -> {"worker_id", "argv": [...], "env": {...}, "log_path", "cwd"}
    <- {"worker_id", "pid": N}
    <- {"exited": pid, "code": N}          (async, from the reaper)
The daemon holds one zygote per node and falls back to cold Popen if
the zygote dies (RAY_TPU_FORKSERVER=0 disables entirely).
"""

from __future__ import annotations

import json
import os
import signal
import sys


def _emit(out_fd: int, msg: dict) -> None:
    # os.write of a short line is atomic (< PIPE_BUF) and shares no
    # Python-level locks with the reaper thread or forked children
    os.write(out_fd, (json.dumps(msg) + "\n").encode())


def zygote_main() -> None:
    # Pre-import the worker's world. Everything imported here is shared
    # COW memory across all workers on the node.
    from . import worker_main  # noqa: F401  (pulls core/protocol/serialization)

    stdin = os.fdopen(os.dup(0), "rb")
    out_fd = os.dup(1)
    # stop anything imported later from scribbling on the protocol pipe
    devnull = os.open(os.devnull, os.O_WRONLY)
    os.dup2(devnull, 1)

    # Reap forked children ON THE MAIN THREAD via SIGCHLD (no zombies;
    # the daemon cannot reap them — they are OUR children). Python runs
    # signal handlers between bytecodes on the main thread, so a sweep
    # can never overlap a fork: the process stays single-threaded and
    # the fork-safety claim in the module docstring holds. PEP 475
    # transparently restarts the interrupted stdin read.
    signal.signal(signal.SIGCHLD, lambda _sig, _frm: _reap_sweep(out_fd))

    protocol_fds = [stdin.fileno(), out_fd, devnull]
    for line in stdin:
        try:
            req = json.loads(line)
        except Exception:
            continue
        pid = os.fork()
        if pid == 0:
            _child(req, protocol_fds)        # never returns
        _emit(out_fd, {"worker_id": req["worker_id"], "pid": pid})


def _reap_sweep(out_fd: int) -> None:
    """SIGCHLD handler body: drain every exited child (signals coalesce,
    so one delivery may cover several exits) and push exit notices."""
    while True:
        try:
            pid, status = os.waitpid(-1, os.WNOHANG)
        except (ChildProcessError, OSError):
            return
        if pid == 0:
            return
        code = (os.waitstatus_to_exitcode(status)
                if hasattr(os, "waitstatus_to_exitcode") else -1)
        _emit(out_fd, {"exited": pid, "code": code})


def _child(req: dict, protocol_fds) -> None:
    try:
        os.setsid()
        signal.signal(signal.SIGCHLD, signal.SIG_DFL)
        log_fd = os.open(req["log_path"],
                         os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        os.dup2(log_fd, 1)
        os.dup2(log_fd, 2)
        os.close(log_fd)
        # Drop the zygote's protocol fds: a worker holding the stdout
        # pipe's write end would keep the daemon from seeing EOF (and
        # thus zygote death) for as long as the worker lives.
        for fd in protocol_fds:
            try:
                os.close(fd)
            except OSError:
                pass
        for key, val in (req.get("env") or {}).items():
            if val is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = val
        # runtime-env import paths land at the FRONT so they shadow
        # driver-side modules (only ray_tpu itself + stdlib are already
        # imported and hence unshadowable — documented limitation)
        for p in reversed(req.get("path_prepend") or []):
            if p and p not in sys.path:
                sys.path.insert(0, p)
        if req.get("cwd"):
            os.chdir(req["cwd"])
        sys.argv = ["worker_main"] + list(req["argv"])
        from .worker_main import main
        main()
        os._exit(0)
    except BaseException:
        import traceback
        traceback.print_exc()
        os._exit(1)


if __name__ == "__main__":
    zygote_main()
