"""Asyncio RPC layer: length-prefixed pickle frames over TCP.

Reference parity: ray's gRPC layer (src/ray/rpc/grpc_server.h,
client_call.h). We use a minimal asyncio protocol instead of gRPC: every
process (controller, node daemons, workers, drivers) runs one RpcServer and
dials peers with RpcClient. Calls are request/response with out-of-band
binary buffers (pickle protocol 5) so large numpy/jax host arrays never get
copied into the pickle stream.

Frame layout:
    u32 header_len | header(pickle) | payload buffers...
header = (kind, msg_id, method, nbuf_lens: list[int])
kind: 0=request 1=response-ok 2=response-err 3=oneway
"""

from __future__ import annotations

import asyncio
import logging
import os
import pickle
import struct
import traceback
from typing import Any, Awaitable, Callable, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

_U32 = struct.Struct("<I")

KIND_REQUEST = 0
KIND_RESPONSE_OK = 1
KIND_RESPONSE_ERR = 2
KIND_ONEWAY = 3

# Messages above this size are chunked when written so a single huge frame
# doesn't monopolize the event loop.
MAX_FRAME = 1 << 31


def dumps_oob(obj: Any) -> Tuple[bytes, List[bytes]]:
    """Pickle with out-of-band buffers (zero-copy for large arrays)."""
    buffers: List[pickle.PickleBuffer] = []
    data = pickle.dumps(obj, protocol=5, buffer_callback=buffers.append)
    return data, [b.raw() for b in buffers]


def loads_oob(data: bytes, buffers: List[bytes]) -> Any:
    return pickle.loads(data, buffers=buffers)


class RemoteCallError(Exception):
    """An exception raised on the remote side of an RPC, re-raised locally."""

    def __init__(self, method: str, remote_traceback: str):
        self.method = method
        self.remote_traceback = remote_traceback
        super().__init__(f"RPC {method} failed remotely:\n{remote_traceback}")


class ConnectionLost(Exception):
    pass


async def _read_frame(reader: asyncio.StreamReader):
    raw_len = await reader.readexactly(4)
    (header_len,) = _U32.unpack(raw_len)
    header_bytes = await reader.readexactly(header_len)
    kind, msg_id, method, buf_lens = pickle.loads(header_bytes)
    bufs = []
    for n in buf_lens:
        bufs.append(await reader.readexactly(n))
    return kind, msg_id, method, bufs


def _write_frame(writer: asyncio.StreamWriter, kind: int, msg_id: int,
                 method: str, payload: Any) -> None:
    data, bufs = dumps_oob(payload)
    all_bufs = [data] + bufs
    header = pickle.dumps((kind, msg_id, method, [len(b) for b in all_bufs]))
    writer.write(_U32.pack(len(header)))
    writer.write(header)
    for b in all_bufs:
        writer.write(b)


def _decode_payload(bufs: List[bytes]) -> Any:
    return loads_oob(bufs[0], bufs[1:])


Handler = Callable[..., Awaitable[Any]]


class RpcServer:
    """Serves registered async handlers. One instance per process."""

    def __init__(self, handlers: Optional[Dict[str, Handler]] = None):
        self._handlers: Dict[str, Handler] = handlers or {}
        self._server: Optional[asyncio.AbstractServer] = None
        self.address: Optional[Tuple[str, int]] = None
        self._conn_tasks: set = set()
        self._writers: set = set()

    def register(self, name: str, handler: Handler) -> None:
        self._handlers[name] = handler

    def register_object(self, obj: Any, prefix: str = "") -> None:
        """Register every `rpc_*` coroutine method of obj as a handler."""
        for attr in dir(obj):
            if attr.startswith("rpc_"):
                self._handlers[prefix + attr[4:]] = getattr(obj, attr)

    async def start(self, host: Optional[str] = None,
                    port: int = 0) -> Tuple[str, int]:
        # Every server in the process tree (controller, daemons, the
        # driver's CoreClient, worker CoreClients) binds this default —
        # multi-host clusters need ALL of them reachable (owner_addr /
        # actor addrs cross hosts), not just the control plane.
        if host is None:
            from .config import get_config
            host = get_config().bind_host
        self._server = await asyncio.start_server(self._on_connection, host, port)
        sock = self._server.sockets[0]
        self.address = sock.getsockname()[:2]
        if self.address[0] in ("0.0.0.0", "::"):
            # Advertise a dialable address, not the wildcard bind: the
            # host's primary outbound IP (RAY_TPU_ADVERTISE_HOST overrides).
            from .config import get_config
            adv = get_config().advertise_host
            if not adv:
                import socket as _socket
                probe = _socket.socket(_socket.AF_INET, _socket.SOCK_DGRAM)
                try:
                    probe.connect(("8.8.8.8", 80))
                    adv = probe.getsockname()[0]
                except Exception:
                    adv = "127.0.0.1"
                finally:
                    probe.close()
            self.address = (adv, self.address[1])
        return self.address

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
        for w in list(self._writers):
            try:
                w.close()
            except Exception:
                pass
        if self._server is not None:
            try:
                # 3.12 wait_closed blocks until every connection closes;
                # we just force-closed them, but don't hang on stragglers.
                await asyncio.wait_for(self._server.wait_closed(), timeout=1.0)
            except Exception:
                pass
        for t in list(self._conn_tasks):
            t.cancel()

    async def _on_connection(self, reader: asyncio.StreamReader,
                             writer: asyncio.StreamWriter) -> None:
        write_lock = asyncio.Lock()
        self._writers.add(writer)
        try:
            while True:
                kind, msg_id, method, bufs = await _read_frame(reader)
                task = asyncio.ensure_future(
                    self._dispatch(kind, msg_id, method, bufs, writer, write_lock))
                self._conn_tasks.add(task)
                task.add_done_callback(self._conn_tasks.discard)
        except (asyncio.IncompleteReadError, ConnectionResetError,
                BrokenPipeError, asyncio.CancelledError):
            pass
        finally:
            self._writers.discard(writer)
            try:
                writer.close()
            except Exception:
                pass

    async def _dispatch(self, kind, msg_id, method, bufs, writer, write_lock):
        try:
            payload = _decode_payload(bufs)
        except Exception:
            logger.exception("failed to decode payload for %s", method)
            return
        handler = self._handlers.get(method)
        if handler is None:
            if kind == KIND_REQUEST:
                async with write_lock:
                    _write_frame(writer, KIND_RESPONSE_ERR, msg_id, method,
                                 f"no handler for method {method!r}")
                    await writer.drain()
            return
        try:
            result = await handler(**payload)
            if kind == KIND_REQUEST:
                async with write_lock:
                    _write_frame(writer, KIND_RESPONSE_OK, msg_id, method, result)
                    await writer.drain()
        except Exception:
            tb = traceback.format_exc()
            if kind == KIND_REQUEST:
                try:
                    async with write_lock:
                        _write_frame(writer, KIND_RESPONSE_ERR, msg_id, method, tb)
                        await writer.drain()
                except Exception:
                    pass
            else:
                logger.error("oneway handler %s failed:\n%s", method, tb)


class RpcClient:
    """A connection to one peer. Safe for concurrent calls from one loop."""

    def __init__(self, host: str, port: int):
        self.host = host
        self.port = port
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._next_id = 0
        self._pending: Dict[int, asyncio.Future] = {}
        self._read_task: Optional[asyncio.Task] = None
        self._write_lock: Optional[asyncio.Lock] = None
        self._connect_lock = asyncio.Lock()
        self._closed = False

    async def connect(self) -> None:
        async with self._connect_lock:
            if self._writer is not None or self._closed:
                return
            self._reader, self._writer = await asyncio.open_connection(
                self.host, self.port, limit=MAX_FRAME)
            self._write_lock = asyncio.Lock()
            self._read_task = asyncio.ensure_future(self._read_loop())

    async def _read_loop(self) -> None:
        try:
            while True:
                kind, msg_id, method, bufs = await _read_frame(self._reader)
                fut = self._pending.pop(msg_id, None)
                if fut is None or fut.done():
                    continue
                if kind == KIND_RESPONSE_OK:
                    try:
                        fut.set_result(_decode_payload(bufs))
                    except Exception as e:  # corrupt payload
                        fut.set_exception(e)
                else:
                    fut.set_exception(RemoteCallError(method, _decode_payload(bufs)))
        except (asyncio.IncompleteReadError, ConnectionResetError,
                BrokenPipeError, asyncio.CancelledError) as e:
            err = ConnectionLost(f"connection to {self.host}:{self.port} lost: {e!r}")
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(err)
            self._pending.clear()
            self._writer = None

    async def call(self, _method: str, **kwargs) -> Any:
        if self._writer is None:
            await self.connect()
        msg_id = self._next_id
        self._next_id += 1
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[msg_id] = fut
        async with self._write_lock:
            writer = self._writer  # may go None concurrently on disconnect
            if writer is None:
                self._pending.pop(msg_id, None)
                raise ConnectionError(f"connection closed before {_method}")
            _write_frame(writer, KIND_REQUEST, msg_id, _method, kwargs)
            await writer.drain()
        return await fut

    async def oneway(self, _method: str, **kwargs) -> None:
        if self._writer is None:
            if self._closed:
                return  # fire-and-forget during shutdown: drop silently
            await self.connect()
        async with self._write_lock:
            writer = self._writer
            if writer is None:
                if self._closed:
                    return  # shutdown race: drop silently
                raise ConnectionError(
                    f"connection lost before oneway {_method}")
            _write_frame(writer, KIND_ONEWAY, 0, _method, kwargs)
            await writer.drain()

    async def close(self) -> None:
        self._closed = True
        if self._read_task is not None:
            self._read_task.cancel()
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:
                pass
            self._writer = None

    @property
    def connected(self) -> bool:
        return self._writer is not None


class ClientPool:
    """Caches RpcClients per (host, port)."""

    def __init__(self):
        self._clients: Dict[Tuple[str, int], RpcClient] = {}

    def get(self, addr: Tuple[str, int]) -> RpcClient:
        addr = tuple(addr)
        client = self._clients.get(addr)
        if client is None or client._closed:
            client = RpcClient(*addr)
            self._clients[addr] = client
        return client

    async def close_all(self) -> None:
        for c in self._clients.values():
            await c.close()
        self._clients.clear()
