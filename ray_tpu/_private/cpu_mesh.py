"""Virtual multi-device CPU mesh bootstrap (the test-cluster equivalent).

Reference parity: SURVEY.md §4 — multi-node simulation via
``xla_force_host_platform_device_count``. One recipe, shared by
``tests/conftest.py`` (in-process) and ``__graft_entry__.dryrun_multichip``
(child process), so the two can't silently diverge.
"""

from __future__ import annotations

import os
from typing import MutableMapping

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def apply_cpu_mesh_env(env: MutableMapping[str, str],
                       n_devices: int = 8,
                       *,
                       keep_existing_count: bool = False) -> MutableMapping[str, str]:
    """Mutate *env* so a jax backend initialized under it boots a virtual
    n-device CPU mesh.

    Machine quirk handled here: this box's sitecustomize registers the axon
    TPU PJRT plugin (which then forces ``jax_platforms='axon,cpu'``) whenever
    ``PALLAS_AXON_POOL_IPS`` is set — clear it so fresh interpreters stay on
    the plain CPU backend.
    """
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    flags = env.get("XLA_FLAGS", "")
    if keep_existing_count and _COUNT_FLAG in flags:
        return env
    flags = " ".join(f for f in flags.split() if not f.startswith(_COUNT_FLAG))
    env["XLA_FLAGS"] = (flags + f" {_COUNT_FLAG}={n_devices}").strip()
    return env


def force_cpu_mesh(n_devices: int = 8) -> None:
    """Apply the recipe to this process. If jax is already imported (on this
    machine sitecustomize always imports it), also flip its platform config —
    env alone is read only at backend init."""
    import sys

    # Respect an operator-set device count (e.g. a 16-device pytest run).
    apply_cpu_mesh_env(os.environ, n_devices, keep_existing_count=True)
    if "jax" in sys.modules:
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
