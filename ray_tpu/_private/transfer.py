"""Cross-node object transfer primitives shared by the worker fetch path
and the daemon prefetcher (reference parity: ObjectManager chunked
push/pull, src/ray/object_manager/object_manager.h:208-216)."""

from __future__ import annotations

import asyncio
from typing import Optional

from .config import get_config


async def fetch_flat(node, object_id: str, size: Optional[int] = None,
                     per_call_timeout: Optional[float] = None) -> bytes:
    """Pull an object's flat bytes from a node daemon — one RPC below
    the chunk threshold, semaphore-windowed chunk gather above it.

    `node` is a ClientPool connection to the daemon holding the bytes.
    Raises ConnectionError if the node no longer has the object.
    """
    chunk_bytes = get_config().fetch_chunk_bytes
    chunk_window = get_config().fetch_chunk_window

    async def call(method, **kw):
        coro = node.call(method, **kw)
        if per_call_timeout is not None:
            return await asyncio.wait_for(coro, timeout=per_call_timeout)
        return await coro

    if size is None:
        meta = await call("fetch_object_meta", object_id=object_id)
        if meta is None:
            raise ConnectionError(f"object {object_id[:12]} not on node")
        size = meta["size"]
    if size <= chunk_bytes:
        flat = await call("fetch_object", object_id=object_id)
        if flat is None:
            raise ConnectionError(f"object {object_id[:12]} not on node")
        return flat
    buf = bytearray(size)
    sem = asyncio.Semaphore(chunk_window)

    async def pull(offset: int):
        async with sem:
            chunk = await call(
                "fetch_object_chunk", object_id=object_id, offset=offset,
                length=min(chunk_bytes, size - offset))
        if chunk is None:
            raise ConnectionError(
                f"object {object_id[:12]} chunk source lost")
        buf[offset:offset + len(chunk)] = chunk

    await asyncio.gather(*(pull(o) for o in range(0, size, chunk_bytes)))
    return buf     # bytearray: callers wrap via from_flat without a copy
