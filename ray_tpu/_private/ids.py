"""Unique identifiers for objects, tasks, actors, nodes, placement groups.

Reference parity: ray's binary IDs (src/ray/common/id.h). Ours are 16-byte
random IDs wrapped in typed classes; hex form is used on the wire for
readability. Object IDs embed no lineage info — ownership metadata travels
in the ObjectRef itself.
"""

from __future__ import annotations

import os


class BaseID:
    __slots__ = ("_hex",)
    PREFIX = "id"

    def __init__(self, hex_str: str):
        self._hex = hex_str

    @classmethod
    def generate(cls) -> "BaseID":
        return cls(os.urandom(16).hex())

    @classmethod
    def from_hex(cls, hex_str: str) -> "BaseID":
        return cls(hex_str)

    def hex(self) -> str:
        return self._hex

    def __eq__(self, other) -> bool:
        return type(other) is type(self) and other._hex == self._hex

    def __hash__(self) -> int:
        return hash((self.PREFIX, self._hex))

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self._hex[:12]})"

    def __reduce__(self):
        return (type(self), (self._hex,))


class ObjectID(BaseID):
    PREFIX = "obj"


class TaskID(BaseID):
    PREFIX = "task"


class ActorID(BaseID):
    PREFIX = "actor"


class NodeID(BaseID):
    PREFIX = "node"


class WorkerID(BaseID):
    PREFIX = "worker"


class PlacementGroupID(BaseID):
    PREFIX = "pg"


class JobID(BaseID):
    PREFIX = "job"
