"""Controller: the cluster control plane (GCS-equivalent).

Reference parity: src/ray/gcs/gcs_server/gcs_server.h — node membership,
actor directory (gcs_actor_manager.h) with max_restarts handling, named
actors, internal KV (kv_manager), pubsub broker, and the cluster-wide task
scheduler (a centralized stand-in for the reference's distributed
lease-based dispatch; daemons still own local worker pools).

Runs inside the head process's event loop; daemons and clients reach it
over its RpcServer.
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from typing import Any, Dict, List, Optional, Tuple

from .config import FN_STORE_PREFIX
from .protocol import ClientPool, RpcServer
from ..exceptions import ActorDiedError, InfeasibleResourceError, TaskError

logger = logging.getLogger(__name__)


class NodeEntry:
    __slots__ = ("node_id", "addr", "resources_total", "resources_avail",
                 "labels", "alive", "num_running", "last_heartbeat",
                 "sync_version", "view", "draining", "commands",
                 "cmd_seq")

    def __init__(self, node_id: str, addr: Tuple[str, int],
                 resources: Dict[str, float], labels: Dict[str, str]):
        self.node_id = node_id
        self.addr = tuple(addr)
        self.resources_total = dict(resources)
        self.resources_avail = dict(resources)
        self.labels = labels or {}
        self.alive = True
        self.num_running = 0
        self.last_heartbeat = time.monotonic()
        # Resource/stats gossip (reference parity: ray_syncer.h:39-83 —
        # versioned per-node snapshots, only deltas cross the wire):
        # the daemon's last-synced view and the version we acked.
        self.sync_version = 0
        self.view: Dict[str, Any] = {}
        # Draining: excluded from scheduling; terminated by the
        # autoscaler once idle (reference: DrainRaylet / autoscaler v2
        # drain-before-terminate).
        self.draining = False
        # Commands queued for the daemon, piggybacked on heartbeat
        # replies (reference: syncer command channel). Sequence-numbered
        # and re-delivered until the daemon acks — a dropped reply must
        # not lose a drain/set_resource.
        self.commands: List[dict] = []
        self.cmd_seq = 0

    def fits(self, req: Dict[str, float]) -> bool:
        for k, v in req.items():
            if self.resources_avail.get(k, 0.0) + 1e-9 < v:
                return False
        return True

    def feasible(self, req: Dict[str, float]) -> bool:
        for k, v in req.items():
            if self.resources_total.get(k, 0.0) + 1e-9 < v:
                return False
        return True

    def acquire(self, req: Dict[str, float]) -> None:
        for k, v in req.items():
            self.resources_avail[k] = self.resources_avail.get(k, 0.0) - v
        self.num_running += 1

    def release(self, req: Dict[str, float]) -> None:
        for k, v in req.items():
            self.resources_avail[k] = min(
                self.resources_total.get(k, 0.0),
                self.resources_avail.get(k, 0.0) + v)
        self.num_running = max(0, self.num_running - 1)

    def utilization(self) -> float:
        fracs = []
        for k, total in self.resources_total.items():
            if total > 0:
                fracs.append(1.0 - self.resources_avail.get(k, 0.0) / total)
        return max(fracs) if fracs else 0.0


def _pick_hybrid(fitting: List["NodeEntry"]) -> "NodeEntry":
    """Hybrid-lite placement, shared by the scheduler and the lease
    grantor: pack onto the most-utilized node below 50% utilization,
    else spread to the least utilized (reference:
    src/ray/raylet/scheduling/policy/hybrid_scheduling_policy.h:61)."""
    below = [n for n in fitting if n.utilization() < 0.5]
    if below:
        return max(below, key=lambda n: n.utilization())
    return min(fitting, key=lambda n: n.utilization())


class ActorEntry:
    __slots__ = ("actor_id", "name", "namespace", "state", "addr", "node_id",
                 "worker_id", "creation_spec", "max_restarts", "restarts",
                 "death_cause", "waiters", "lifetime")

    def __init__(self, actor_id: str, spec: dict):
        self.actor_id = actor_id
        self.name = spec.get("actor_name")
        self.namespace = spec.get("namespace", "default")
        self.state = "PENDING"          # PENDING -> ALIVE -> RESTARTING/DEAD
        self.addr: Optional[Tuple[str, int]] = None
        self.node_id: Optional[str] = None
        self.worker_id: Optional[str] = None
        self.creation_spec = spec
        self.max_restarts = spec.get("max_restarts", 0)
        self.restarts = 0
        self.death_cause = ""
        self.lifetime = spec.get("lifetime")
        self.waiters: List[asyncio.Event] = []


class Controller:
    def __init__(self, session_name: str, persist_path: Optional[str] = None):
        self.session_name = session_name
        # Durable control plane (reference parity: gcs_table_storage.h:213,
        # redis_store_client.h:111): actors, named actors, KV, and PG
        # definitions are written through to SQLite so a controller
        # restart resumes with live actor addresses and named lookups
        # intact. Disable with persist_path="" for throwaway controllers.
        if persist_path is None:
            from .config import get_config, session_dir
            persist_path = (get_config().gcs_persist_path
                            or os.path.join(session_dir(session_name),
                                            "gcs.db"))
        self.store = None
        if persist_path:
            from .gcs_store import GcsStore
            self.store = GcsStore(persist_path)
        self.server = RpcServer()
        self.server.register_object(self)
        self.pool = ClientPool()
        self.address: Optional[Tuple[str, int]] = None
        self.nodes: Dict[str, NodeEntry] = {}
        self.actors: Dict[str, ActorEntry] = {}
        self.named_actors: Dict[Tuple[str, str], str] = {}
        self.kv: Dict[str, bytes] = {}
        # worker leases: lease_id -> {node_id, req, worker_id,
        #                             owner_addr, granted_at}
        self.leases: Dict[str, dict] = {}
        # Resource blocks delegated to daemons for LOCAL lease granting
        # (distributed dispatch — reference parity: the raylet owns its
        # local dispatch, cluster_task_manager.h:45; here the controller
        # pre-acquires a block so daemon grants never double-book
        # against the scheduled path): (node_id, req_key) -> slot count.
        self.delegations: Dict[tuple, int] = {}
        self._reclaim_timer_armed = False   # re-pump while work pends
        self._pg_reserve_tasks: set = set()  # in-flight bundle 2PCs
        self.subscribers: Dict[str, List[Tuple[str, int]]] = {}
        self.pending: List[dict] = []          # specs waiting for resources
        self._spread_cursor = 0                # SPREAD round-robin state
        # task_id -> (node_id, resources, spec)
        self.running: Dict[str, Tuple[str, Dict[str, float], dict]] = {}
        # task-event table backing the state API (reference: GCS task
        # manager, src/ray/gcs/gcs_server/gcs_task_manager.h) — bounded
        self.task_events: "Dict[str, dict]" = {}
        self.task_events_cap = 10000
        self.node_timeout_s = 10.0
        self.placement_groups: Dict[str, Any] = {}
        self.pending_pgs: List[Any] = []
        # With an autoscaler attached, infeasible demand queues (waiting
        # for scale-up) instead of failing fast.
        self.autoscaling_enabled = False
        self._sched_event = asyncio.Event()
        self._sched_task: Optional[asyncio.Task] = None
        self._health_task: Optional[asyncio.Task] = None
        self._closed = False

    async def start(self, host=None, port: int = 0):
        self._restore_state()
        self.address = await self.server.start(host, port)
        self._sched_task = asyncio.ensure_future(self._schedule_loop())
        self._health_task = asyncio.ensure_future(self._health_loop())
        return self.address

    async def stop(self):
        self._closed = True
        if self._sched_task:
            self._sched_task.cancel()
        if self._health_task:
            self._health_task.cancel()
        await self.server.stop()
        await self.pool.close_all()
        if self.store is not None:
            self.store.close()

    # -------------------------------------------------------- persistence

    def _persist_actor(self, entry: "ActorEntry",
                       with_spec: bool = False) -> None:
        """Write-through actor state. The creation spec (which embeds the
        pickled class + args, potentially MBs) is immutable — it is
        written ONCE (with_spec=True at registration) into its own row so
        state transitions only re-serialize the small mutable fields."""
        if self.store is None:
            return
        if with_spec:
            self.store.put("actor_specs", entry.actor_id,
                           entry.creation_spec)
        self.store.put("actors", entry.actor_id, {
            "actor_id": entry.actor_id, "name": entry.name,
            "namespace": entry.namespace, "state": entry.state,
            "addr": entry.addr, "node_id": entry.node_id,
            "worker_id": entry.worker_id,
            "max_restarts": entry.max_restarts, "restarts": entry.restarts,
            "death_cause": entry.death_cause, "lifetime": entry.lifetime,
        })

    def _restore_state(self) -> None:
        """Reload durable tables after a restart. ALIVE actors keep their
        addresses (their worker processes are still up); PENDING/RESTARTING
        actors re-enter the scheduling queue; nodes re-register themselves
        via the heartbeat path (rpc_heartbeat -> 'unknown' -> daemon
        re-registers)."""
        if self.store is None:
            return
        specs = dict(self.store.items("actor_specs"))
        for _, data in self.store.items("actors"):
            entry = ActorEntry(data["actor_id"],
                               specs.get(data["actor_id"], {}))
            entry.name = data["name"]
            entry.namespace = data["namespace"]
            entry.state = data["state"]
            entry.addr = tuple(data["addr"]) if data["addr"] else None
            entry.node_id = data["node_id"]
            entry.worker_id = data["worker_id"]
            entry.max_restarts = data["max_restarts"]
            entry.restarts = data["restarts"]
            entry.death_cause = data["death_cause"]
            entry.lifetime = data["lifetime"]
            self.actors[entry.actor_id] = entry
            if entry.name and entry.state != "DEAD":
                self.named_actors[(entry.namespace, entry.name)] = \
                    entry.actor_id
            if entry.state in ("PENDING", "RESTARTING"):
                spec = dict(entry.creation_spec)
                spec["is_restart"] = entry.state == "RESTARTING"
                self.pending.append(spec)
        for key, actor_id in self.store.items("named_actors"):
            ns, _, name = key.partition("\x00")
            self.named_actors[(ns, name)] = actor_id
        for key, value in self.store.items("kv"):
            self.kv[key] = value
        for pg_id, data in self.store.items("placement_groups"):
            from .placement import PlacementGroupEntry
            pg = PlacementGroupEntry(pg_id, data["bundles"],
                                     data["strategy"], data["name"])
            self.placement_groups[pg_id] = pg
            if data.get("state") == "CREATED":
                # Keep the old placement: bundle reservations are
                # re-acquired per node as each daemon re-registers
                # (rpc_register_node) — re-placing would double-book.
                pg.state = "CREATED"
                for b, nid in zip(pg.bundles, data.get("assignments", [])):
                    b.node_id = nid
            elif data.get("state") in ("FAILED", "REMOVED"):
                # restored only so status queries answer; no reservations,
                # never re-placed
                pg.state = data["state"]
            else:
                self.pending_pgs.append(pg)   # re-place on live nodes
        if self.pending or self.pending_pgs:
            self._sched_event.set()

    def _persist_pg(self, pg) -> None:
        if self.store is None:
            return
        self.store.put("placement_groups", pg.pg_id, {
            "bundles": [dict(b.resources) for b in pg.bundles],
            "strategy": pg.strategy, "name": pg.name,
            "state": pg.state,
            "assignments": [b.node_id for b in pg.bundles],
        })

    def _persist_named(self, namespace: str, name: str,
                       actor_id: Optional[str]) -> None:
        if self.store is None:
            return
        key = f"{namespace}\x00{name}"
        if actor_id is None:
            self.store.delete("named_actors", key)
        else:
            self.store.put("named_actors", key, actor_id)

    # ------------------------------------------------------------- nodes

    async def rpc_register_node(self, node_id: str, addr, resources,
                                labels=None, pg_bundles=None) -> dict:
        node = NodeEntry(node_id, addr, resources, labels)
        prior = self.nodes.get(node_id)
        if prior is not None:
            # A re-registering node (health-blip recovery) keeps its
            # undelivered command queue and drain state — at-least-once
            # delivery must survive the dead-mark/re-register cycle.
            node.commands = prior.commands
            node.cmd_seq = prior.cmd_seq
            node.draining = prior.draining
        self.nodes[node_id] = node
        # Delegated-bundle reconciliation against the daemon's committed
        # ledger (pg_bundles; None = old-protocol daemon, trust our own
        # table). Three cases:
        #  - both sides agree -> re-acquire the reservation;
        #  - we expect a bundle the daemon does NOT hold (daemon process
        #    restarted) -> the group lost state: re-place the whole PG;
        #  - the daemon holds bundles for a PG we dropped/never created
        #    (removed while it was partitioned) -> tell it to release.
        release_pgs: List[str] = []
        reported = None if pg_bundles is None else set(pg_bundles)
        for pg in list(self.placement_groups.values()):
            if pg.state == "RESERVING":
                # mid-2PC: the pump already deducted these bundles from
                # the OLD NodeEntry; the fresh one must carry the same
                # deduction or a later commit/rollback corrupts the
                # books (daemon ledger audit doesn't apply — nothing is
                # committed daemon-side yet)
                for b in pg.bundles:
                    if b.node_id == node_id:
                        node.acquire(b.resources)
                continue
            if pg.state != "CREATED":
                continue
            on_node = [b for b in pg.bundles if b.node_id == node_id]
            if not on_node:
                continue
            if reported is not None and pg.pg_id not in reported:
                # daemon lost the reservation: release every node's part
                # and send the group back through placement
                for b in pg.bundles:
                    holder = self.nodes.get(b.node_id or "")
                    if holder is not None and b.node_id != node_id:
                        holder.release(b.resources)
                    b.node_id = None
                pg.state = "PENDING"
                if pg not in self.pending_pgs:
                    self.pending_pgs.append(pg)
                self._persist_pg(pg)
                continue
            for b in on_node:
                node.acquire(b.resources)
        if reported:
            known = {pg.pg_id for pg in self.placement_groups.values()
                     if pg.state in ("CREATED", "RESERVING")}
            release_pgs = [pid for pid in reported if pid not in known]
        logger.info("node %s registered at %s with %s",
                    node_id[:8], addr, resources)
        self._sched_event.set()
        # Actors this (possibly restarted) controller believes live on the
        # node: the daemon compares against what it actually hosts and
        # reports the dead ones — actors that died while the controller
        # was down must not stay ALIVE forever. Their creation resources
        # are re-acquired here (the fresh NodeEntry starts fully free) and
        # the running-table entry rebuilt so a later death releases them.
        expected = []
        for a in self.actors.values():
            if a.node_id == node_id and a.state == "ALIVE":
                expected.append(a.actor_id)
                task_id = a.creation_spec.get("task_id")
                if task_id and task_id not in self.running:
                    req = dict(a.creation_spec.get("resources") or {})
                    sched = (a.creation_spec.get("scheduling") or {})
                    pg = self.placement_groups.get(
                        sched.get("placement_group") or "")
                    if pg is None:
                        node.acquire(req)
                    elif pg.state == "CREATED" \
                            and task_id not in pg.task_usage:
                        # re-acquire bundle-internal usage so restored
                        # bundles don't look empty and oversubscribe
                        _, bidx = pg.resolve_bundle(
                            sched.get("bundle_index", -1), req)
                        if bidx is not None:
                            pg.acquire_for_task(task_id, bidx, req)
                        node.num_running += 1
                    else:
                        continue
                    self.running[task_id] = (node_id, req,
                                             a.creation_spec)
        return {"session_name": self.session_name,
                "expected_actors": expected,
                "release_pgs": release_pgs}

    async def rpc_unregister_node(self, node_id: str) -> None:
        node = self.nodes.get(node_id)
        if node:
            node.alive = False
            await self._on_node_death(node_id)

    async def rpc_heartbeat(self, node_id: str, num_workers: int = 0,
                            sync_version: int = 0,
                            view: Optional[dict] = None,
                            cmd_ack: int = 0) -> dict:
        node = self.nodes.get(node_id)
        if node and node.alive:
            node.last_heartbeat = time.monotonic()
            if view is not None and sync_version > node.sync_version:
                self._apply_node_view(node, view)
                node.sync_version = sync_version
            # at-least-once command delivery: drop acked, resend the rest
            node.commands = [c for c in node.commands
                             if c["seq"] > cmd_ack]
            return {"status": "ok", "sync_ack": node.sync_version,
                    "commands": list(node.commands)}
        # Either a restarted controller doesn't know this node yet, or
        # the health loop declared it dead during a blip — both ways the
        # daemon must re-register to rejoin (a dead-marked entry must not
        # swallow heartbeats forever).
        return {"status": "unknown"}

    def _apply_node_view(self, node: NodeEntry, view: dict) -> None:
        """Fold a daemon's versioned state snapshot into the cluster view
        (reference parity: ray_syncer RESOURCE_VIEW receiver side)."""
        node.view = view
        if view.get("draining"):
            # drain state gossiped back (e.g. after a controller restart
            # re-registered the node with a fresh entry): never resume
            # scheduling onto a node the operator drained
            node.draining = True
        new_total = view.get("resources_total")
        if new_total is not None and new_total != node.resources_total:
            # Dynamic resource change (chip lost, user set_resource):
            # shift availability by the delta so in-flight acquisitions
            # stay accounted.
            for k in set(new_total) | set(node.resources_total):
                if k not in new_total:
                    node.resources_avail.pop(k, None)   # deleted resource
                    continue
                delta = (new_total.get(k, 0.0)
                         - node.resources_total.get(k, 0.0))
                if delta:
                    node.resources_avail[k] = \
                        node.resources_avail.get(k, 0.0) + delta
            node.resources_total = dict(new_total)
            self._sched_event.set()   # freed capacity may place work

    async def rpc_drain_node(self, node_id: str) -> dict:
        """Start draining: no new work is scheduled here; the daemon is
        told via the heartbeat command channel; the autoscaler terminates
        it once idle. (Reference: autoscaler v2 drain-before-terminate.)"""
        node = self.nodes.get(node_id)
        if node is None or not node.alive:
            return {"status": "not_found"}
        if not node.draining:
            node.draining = True
            self._queue_command(node, {"type": "drain"})
        return {"status": "draining"}

    def _queue_command(self, node: NodeEntry, cmd: dict) -> None:
        node.cmd_seq += 1
        cmd["seq"] = node.cmd_seq
        node.commands.append(cmd)

    async def rpc_set_node_resource(self, node_id: str, name: str,
                                    capacity: float) -> dict:
        """Route a dynamic resource update to a node via the command
        channel; the daemon applies it locally and gossips the new totals
        back on its next heartbeat."""
        if name in ("CPU", "TPU", "memory") or name.startswith("TPU-"):
            # deleting/overriding builtin capacity would wedge scheduling
            # cluster-wide (reference set_resource has the same guard)
            return {"status": "rejected",
                    "error": f"cannot override builtin resource {name!r}"}
        node = self.nodes.get(node_id)
        if node is None or not node.alive:
            return {"status": "not_found"}
        self._queue_command(node, {"type": "set_resource", "name": name,
                                   "capacity": float(capacity)})
        return {"status": "queued"}

    async def _on_node_death(self, node_id: str) -> None:
        # leases on the dead node are void; clients discover via
        # ConnectionLost and fall back to the scheduled path
        for lease_id, lease in list(self.leases.items()):
            if lease["node_id"] == node_id:
                del self.leases[lease_id]
        # delegated lease blocks die with the node (their slots were
        # acquired on the now-gone NodeEntry; nothing to release)
        for key in [k for k in self.delegations if k[0] == node_id]:
            del self.delegations[key]
        # Placement groups with a bundle on the dead node become FAILED:
        # their gang guarantee is broken. Reservations on surviving nodes
        # are returned.
        for pg in list(self.placement_groups.values()):
            if pg.state == "CREATED" and any(
                    b.node_id == node_id for b in pg.bundles):
                for b in pg.bundles:
                    if b.node_id != node_id:
                        node = self.nodes.get(b.node_id)
                        if node is not None:
                            node.release(b.resources)
                pg.fail(f"bundle node {node_id[:8]} died")
                self._persist_pg(pg)
        for actor in list(self.actors.values()):
            if actor.node_id == node_id and actor.state == "ALIVE":
                await self._handle_actor_death(
                    actor.actor_id, f"node {node_id[:8]} died")
        # Fail in-flight normal tasks on the node; owners may retry.
        # Actor creations in flight are routed through actor-death handling
        # so max_restarts applies (resubmitted elsewhere if budget remains).
        from ..exceptions import WorkerCrashedError
        for task_id, (nid, req, spec) in list(self.running.items()):
            if nid != node_id:
                continue
            if spec.get("is_actor_creation"):
                await self._handle_actor_death(
                    spec["actor_id"],
                    f"node {node_id[:8]} died during actor creation")
            else:
                self.running.pop(task_id, None)
                await self._fail_task(spec, WorkerCrashedError(
                    f"node {node_id[:8]} died while running task"))
        self._sched_event.set()

    async def _health_loop(self) -> None:
        """Node failure detector (reference parity:
        src/ray/gcs/gcs_server/gcs_health_check_manager.h:45 — missed
        heartbeats trigger an ACTIVE probe before the node is declared
        dead: a daemon whose heartbeat path is wedged, e.g. its monitor
        loop stuck behind a slow spill, is not the same as a dead
        daemon, and killing its actors would be an unforced error)."""
        while not self._closed:
            await asyncio.sleep(2.0)
            try:
                await self._reap_dead_client_leases()
            except Exception:
                logger.exception("lease reap failed")
            now = time.monotonic()
            stale = [n for n in self.nodes.values()
                     if n.alive and now - n.last_heartbeat
                     > self.node_timeout_s]
            if not stale:
                continue
            # probe concurrently: a correlated failure of many nodes
            # must not serialize 2s timeouts per dead node
            verdicts = await asyncio.gather(
                *(self._probe_node(n) for n in stale))
            for node, ok in zip(stale, verdicts):
                if not node.alive:
                    continue        # died during the probe round
                if ok:
                    logger.warning(
                        "node %s missed heartbeats for %.0fs but "
                        "answers probes; keeping alive",
                        node.node_id[:8], now - node.last_heartbeat)
                    node.last_heartbeat = time.monotonic()
                    continue
                logger.warning("node %s missed heartbeats for %.0fs "
                               "and failed the probe; marking dead",
                               node.node_id[:8],
                               now - node.last_heartbeat)
                node.alive = False
                await self._on_node_death(node.node_id)

    async def _probe_node(self, node: NodeEntry) -> bool:
        """One direct health probe of the daemon's RPC server."""
        try:
            await asyncio.wait_for(
                self.pool.get(node.addr).call("node_stats"), timeout=2.0)
            return True
        except Exception:
            return False

    async def rpc_get_session_info(self) -> dict:
        """Bootstrap info for drivers attaching via init(address=...)."""
        head_addr = None
        for node in self.nodes.values():
            if node.alive:
                head_addr = list(node.addr)
                break
        return {"session_name": self.session_name,
                "head_daemon_addr": head_addr}

    async def rpc_list_nodes(self) -> List[dict]:
        return [{
            "node_id": n.node_id, "addr": n.addr, "alive": n.alive,
            "resources_total": n.resources_total,
            "resources_available": n.resources_avail,
            "labels": n.labels,
            "draining": n.draining,
            # gossiped daemon-side stats (workers, store bytes, spills…)
            "stats": n.view.get("stats", {}),
        } for n in self.nodes.values()]

    async def rpc_cluster_resources(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for n in self.nodes.values():
            if n.alive:
                for k, v in n.resources_total.items():
                    out[k] = out.get(k, 0.0) + v
        return out

    async def rpc_available_resources(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for n in self.nodes.values():
            if n.alive:
                for k, v in n.resources_avail.items():
                    out[k] = out.get(k, 0.0) + v
        return out

    # ---------------------------------------------------------- scheduling

    async def rpc_submit_tasks(self, specs: List[dict]) -> List[dict]:
        """Batched submission: one RPC for a burst of specs (the client
        coalesces same-tick submits). Per-spec error isolation: a spec
        that fails mid-batch must not poison the already-queued ones."""
        out = []
        for spec in specs:
            try:
                out.append(await self.rpc_submit_task(spec))
            except Exception as e:
                logger.exception("batched submit of %s failed",
                                 spec.get("task_id", "?")[:12])
                out.append({"status": "error", "error": repr(e)})
        return out

    async def rpc_submit_task(self, spec: dict) -> dict:
        if spec.get("is_actor_creation") and spec.get("actor_name") \
                and not spec.get("is_restart"):
            key = (spec.get("namespace", "default"), spec["actor_name"])
            if key in self.named_actors:
                await self._fail_task(spec, ValueError(
                    f"actor name {spec['actor_name']!r} already taken "
                    f"in namespace {key[0]!r}"))
                return {"status": "rejected"}
            self.named_actors[key] = spec["actor_id"]
            # NOT persisted here: the pending queue is volatile, so a
            # restart before dispatch must drop the claim with the task —
            # the name is written with the ActorEntry at registration.
        self._task_event(spec["task_id"], "PENDING_SCHEDULING", spec=spec)
        self.pending.append(spec)
        self._sched_event.set()
        return {"status": "queued"}

    def _task_event(self, task_id: str, state: str, spec: dict = None,
                    node_id: str = None, error: str = None) -> None:
        import time as _time
        ev = self.task_events.get(task_id)
        if ev is None:
            if len(self.task_events) >= self.task_events_cap:
                # evict terminal entries first (oldest insertion order);
                # live PENDING/RUNNING events must survive churn
                budget = self.task_events_cap // 10
                victims = [k for k, e in self.task_events.items()
                           if e["state"] in ("FINISHED", "FAILED")]
                for key in victims[:budget]:
                    del self.task_events[key]
                if len(self.task_events) >= self.task_events_cap:
                    for key in list(self.task_events)[:budget]:
                        del self.task_events[key]
            ev = self.task_events[task_id] = {
                "task_id": task_id, "name": "", "type": "NORMAL_TASK",
                "state": "", "node_id": None, "error": None,
                "start_time": None, "end_time": None,
                "creation_time": _time.time()}
        if spec is not None:
            ev["name"] = spec.get("name", "")
            ev["type"] = ("ACTOR_CREATION_TASK"
                          if spec.get("is_actor_creation")
                          else "NORMAL_TASK")
        ev["state"] = state
        if node_id is not None:
            ev["node_id"] = node_id
        if state == "RUNNING":
            ev["start_time"] = _time.time()
        if state in ("FINISHED", "FAILED"):
            ev["end_time"] = _time.time()
        if error is not None:
            ev["error"] = error

    # --------------------------------------------------------- autoscaler

    async def rpc_set_autoscaling(self, enabled: bool) -> None:
        self.autoscaling_enabled = bool(enabled)
        if enabled:
            self._sched_event.set()

    async def rpc_pending_demand(self) -> dict:
        """Unmet demand + node load, the reconciler's input (reference
        parity: autoscaler/v2 ResourceDemandScheduler inputs —
        instance_manager/reconciler.py:53)."""
        return {
            # PG-scheduled tasks draw from their group's reservation, not
            # cluster capacity — counting them would launch nodes they
            # can never use.
            "task_demands": [
                dict(s.get("resources") or {}) for s in self.pending
                if not (s.get("scheduling") or {}).get("placement_group")],
            "pg_demands": [{
                "pg_id": pg.pg_id,
                "strategy": pg.strategy,
                "bundles": [dict(b.resources) for b in pg.bundles],
            } for pg in self.pending_pgs],
            "nodes": [{
                "node_id": n.node_id,
                "alive": n.alive,
                "num_running": n.num_running,
                # placed PG bundles pin a node even when quiet: the gang
                # reservation must survive until the PG is removed
                "num_pg_bundles": sum(
                    1 for pg in self.placement_groups.values()
                    if pg.state == "CREATED"
                    for b in pg.bundles if b.node_id == n.node_id),
                "resources_total": dict(n.resources_total),
                "resources_avail": dict(n.resources_avail),
                "labels": dict(n.labels),
                "draining": n.draining,
            } for n in self.nodes.values()],
        }

    async def rpc_list_tasks(self, filters: dict = None) -> List[dict]:
        events = list(self.task_events.values())
        for key, val in (filters or {}).items():
            events = [e for e in events if e.get(key) == val]
        return events

    async def _schedule_loop(self) -> None:
        while not self._closed:
            await self._sched_event.wait()
            self._sched_event.clear()
            await self._pump()

    async def _pump(self) -> None:
        # Placement groups first: gang reservations beat individual tasks.
        still_pg: List[Any] = []
        for pg in self.pending_pgs:
            nodes = [n for n in self.nodes.values() if not n.draining]
            chosen, reason = pg.choose_nodes(nodes)
            if chosen is not None:
                # Optimistically deduct controller-side availability NOW
                # (nothing else may hand these resources out during the
                # daemon round-trips), then confirm the reservation on
                # the owning daemons in the background so one slow
                # daemon never stalls task scheduling.
                pg.commit(chosen, {n.node_id: n for n in nodes})
                pg.state = "RESERVING"
                task = asyncio.ensure_future(
                    self._finish_pg_reserve(pg, chosen))
                self._pg_reserve_tasks.add(task)
                task.add_done_callback(self._pg_reserve_tasks.discard)
            elif reason == "" or self.autoscaling_enabled:
                if reason:
                    pg.failure_reason = reason   # surfaced to autoscaler
                still_pg.append(pg)       # retry when resources free up
            else:
                pg.fail(reason)
                self._persist_pg(pg)
        self.pending_pgs = still_pg

        still_pending: List[dict] = []
        for spec in self.pending:
            # PG tasks wait until their PG's daemon reservation confirms
            sched = spec.get("scheduling") or {}
            pg = self.placement_groups.get(
                sched.get("placement_group") or "")
            if pg is not None and pg.state in ("PENDING", "RESERVING"):
                still_pending.append(spec)
                continue
            placed = await self._try_place(spec)
            if placed is None:
                still_pending.append(spec)
        self.pending = still_pending

        if (still_pending or still_pg) and self.delegations:
            # Scheduled work is waiting while daemons sit on delegated
            # lease blocks: command them to return free slots now
            # (spill-back pressure — the reference raylet spills queued
            # work instead; here capacity flows back to the global
            # scheduler). A single command only frees what is idle at
            # that instant — slots still backing live leases free up
            # later — so keep re-pumping (and re-commanding) on a short
            # timer until either the work places or the delegations are
            # gone.
            for node_id in {k[0] for k in self.delegations}:
                node = self.nodes.get(node_id)
                if node is None or not node.alive:
                    continue
                # only command nodes whose daemons report FREE block
                # slots (gossiped stat, ~0.5s stale; the re-pump timer
                # re-checks): reclaiming from a daemon whose slots all
                # back live leases frees nothing and just churns its
                # delegate/return cycle
                stats = (node.view or {}).get("stats", {})
                if stats.get("lease_block_free", 1) <= 0:
                    continue
                if not any(c.get("type") == "reclaim_lease_blocks"
                           for c in node.commands):
                    self._queue_command(
                        node, {"type": "reclaim_lease_blocks"})
            if not self._reclaim_timer_armed:
                self._reclaim_timer_armed = True

                def _rearm() -> None:
                    self._reclaim_timer_armed = False
                    self._sched_event.set()

                asyncio.get_running_loop().call_later(1.0, _rearm)

    async def _try_place(self, spec: dict) -> Optional[str]:
        req = dict(spec.get("resources") or {})
        strategy = spec.get("scheduling") or {}
        candidates = [n for n in self.nodes.values()
                      if n.alive and not n.draining]
        if strategy.get("type") == "node_affinity":
            target = [n for n in candidates
                      if n.node_id == strategy.get("node_id")]
            if not target and not strategy.get("soft"):
                await self._fail_task(
                    spec, InfeasibleResourceError(
                        f"node {strategy.get('node_id')} not found"))
                return "failed"
            if target:
                candidates = target
        pg_id = strategy.get("placement_group")
        if pg_id is not None:
            return await self._place_in_pg(spec, pg_id,
                                           strategy.get("bundle_index", -1),
                                           req)
        if not any(n.feasible(req) for n in candidates):
            # Infeasible for the (possibly affinity-narrowed) candidates.
            # Fail only if NO schedulable node could ever satisfy it —
            # an affinity target that is currently too small may grow
            # (set_resource) while other nodes stay feasible: keep waiting.
            schedulable = [n for n in self.nodes.values()
                           if n.alive and not n.draining]
            if all(not n.feasible(req) for n in schedulable):
                if self.autoscaling_enabled:
                    return None     # wait: the autoscaler may add a node
                await self._fail_task(spec, InfeasibleResourceError(
                    f"no node can ever satisfy {req} "
                    f"(cluster: {await self.rpc_cluster_resources()})"))
                return "failed"
            return None
        fitting = [n for n in candidates if n.fits(req)]
        if not fitting:
            return None
        if strategy.get("type") == "spread":
            # SPREAD strategy: true round-robin over fitting nodes
            # (reference scheduling_policy.h SpreadSchedulingPolicy) —
            # utilization-based picks degenerate to packing for
            # zero-resource requests, which never move utilization
            fitting.sort(key=lambda n: n.node_id)
            self._spread_cursor += 1
            node = fitting[self._spread_cursor % len(fitting)]
        else:
            node = _pick_hybrid(fitting)
        node.acquire(req)
        return await self._dispatch(spec, node,
                                    lambda: node.release(req))

    async def _place_in_pg(self, spec: dict, pg_id: str,
                           bundle_index: int, req: dict) -> Optional[str]:
        """Place a task inside a placement group bundle: resources come from
        the bundle's reservation, not from node-available accounting."""
        from ..exceptions import PlacementGroupUnavailableError
        pg = self.placement_groups.get(pg_id)
        if pg is None or pg.state in ("REMOVED", "FAILED"):
            await self._fail_task(spec, PlacementGroupUnavailableError(
                f"placement group {pg_id[:12]} "
                f"{'not found' if pg is None else pg.state.lower()}"
                + (f": {pg.failure_reason}" if pg and pg.failure_reason else "")))
            return "failed"
        node_id, bidx = pg.resolve_bundle(bundle_index, req)
        if node_id == "__pending__":
            return None
        if node_id is None:
            await self._fail_task(spec, PlacementGroupUnavailableError(
                f"no bundle in {pg_id[:12]} can hold {req} "
                f"(bundle_index={bundle_index})"))
            return "failed"
        node = self.nodes.get(node_id)
        if node is None or not node.alive:
            await self._fail_task(spec, PlacementGroupUnavailableError(
                f"placement group {pg_id[:12]} bundle node died"))
            return "failed"
        pg.acquire_for_task(spec["task_id"], bidx, req)
        node.num_running += 1

        def cleanup():
            pg.release_for_task(spec["task_id"])
            node.num_running = max(0, node.num_running - 1)

        return await self._dispatch(spec, node, cleanup)

    async def _dispatch(self, spec: dict, node: NodeEntry,
                        cleanup) -> Optional[str]:
        """Send the spec to the node's daemon; on connection failure, undo
        the resource acquisition (cleanup), mark the node dead, and leave
        the spec pending (the caller's pump retains it)."""
        self.running[spec["task_id"]] = (node.node_id,
                                         dict(spec.get("resources") or {}),
                                         spec)
        self._task_event(spec["task_id"], "RUNNING", node_id=node.node_id)
        if spec.get("is_actor_creation"):
            self._register_pending_actor(spec, node.node_id)
        try:
            await self.pool.get(node.addr).call("execute_task", spec=spec)
        except Exception as e:
            logger.warning("dispatch to node %s failed: %r",
                           node.node_id[:8], e)
            cleanup()
            self.running.pop(spec["task_id"], None)
            node.alive = False
            await self._on_node_death(node.node_id)
            self._sched_event.set()
            return None
        return node.node_id

    async def _fail_task(self, spec: dict, error: Exception) -> None:
        self._task_event(spec["task_id"], "FAILED", error=repr(error))
        if spec.get("is_actor_creation"):
            # Release the claimed name and mark the directory entry dead so
            # the name can be reused and get_actor fails fast.
            actor_id = spec.get("actor_id")
            name = spec.get("actor_name")
            if name:
                key = (spec.get("namespace", "default"), name)
                if self.named_actors.get(key) == actor_id:
                    del self.named_actors[key]
            entry = self.actors.get(actor_id)
            if entry is not None and entry.state != "DEAD":
                entry.state = "DEAD"
                entry.death_cause = str(error)
                for ev in entry.waiters:
                    ev.set()
                entry.waiters.clear()
        try:
            await self.pool.get(spec["owner_addr"]).oneway(
                "object_ready", error=error, task_id=spec["task_id"],
                object_ids=spec.get("return_ids") or [spec["return_id"]])
        except Exception:
            pass

    # ------------------------------------------------------------- leases

    async def rpc_lease_worker(self, resources: dict,
                               runtime_env: Optional[dict] = None,
                               owner_addr=None) -> dict:
        """Grant a worker lease for client-direct task submission
        (reference parity: lease-based dispatch,
        normal_task_submitter.h:72-140). Resources stay acquired for the
        lease's lifetime; release_lease (or node death) returns them."""
        req = dict(resources or {})
        candidates = [n for n in self.nodes.values()
                      if n.alive and not n.draining and n.fits(req)]
        if not candidates:
            return {"status": "unavailable"}
        node = _pick_hybrid(candidates)
        # acquire BEFORE awaiting the daemon: a concurrent lease request
        # must not pass fits() against unreserved capacity (TOCTOU)
        node.acquire(req)
        try:
            reply = await self.pool.get(node.addr).call(
                "reserve_worker", runtime_env=runtime_env)
        except Exception:
            node.release(req)
            node.alive = False
            await self._on_node_death(node.node_id)
            return {"status": "unavailable"}
        if reply.get("status") != "ok":
            node.release(req)
            return {"status": "unavailable",
                    "error": reply.get("error")}
        import uuid
        lease_id = uuid.uuid4().hex
        self.leases[lease_id] = {
            "node_id": node.node_id, "req": req,
            "worker_id": reply["worker_id"],
            "owner_addr": tuple(owner_addr) if owner_addr else None,
            "granted_at": time.monotonic(),
        }
        return {"status": "ok", "lease_id": lease_id,
                "worker_addr": list(reply["addr"]),
                "worker_id": reply["worker_id"],
                "daemon_addr": list(node.addr),
                "node_id": node.node_id}

    @staticmethod
    def _delegation_key(node_id: str, req: Dict[str, float]) -> tuple:
        return (node_id, tuple(sorted(req.items())))

    async def rpc_delegate_resources(self, node_id: str, resources: dict,
                                     count: int) -> dict:
        """Grant a daemon a block of resource slots for LOCAL lease
        dispatch (distributed dispatch — reference parity: the raylet's
        LocalTaskManager dispatches with no GCS round-trip,
        local_task_manager.h:102; our daemon holds a pre-acquired block
        instead, so the controller's scheduled path and the daemon's
        local grants can never double-book one node)."""
        node = self.nodes.get(node_id)
        if node is None or not node.alive or node.draining:
            return {"granted": 0}
        req = dict(resources or {})
        granted = 0
        while granted < count and node.fits(req):
            node.acquire(req)
            granted += 1
        if granted:
            key = self._delegation_key(node_id, req)
            self.delegations[key] = self.delegations.get(key, 0) + granted
        return {"granted": granted}

    async def rpc_return_delegation(self, node_id: str, resources: dict,
                                    count: int) -> None:
        """A daemon hands back unused delegated slots (idle shrink)."""
        req = dict(resources or {})
        key = self._delegation_key(node_id, req)
        n = min(int(count), self.delegations.get(key, 0))
        if n <= 0:
            return
        self.delegations[key] -= n
        if self.delegations[key] == 0:
            del self.delegations[key]
        node = self.nodes.get(node_id)
        if node is not None and node.alive:
            for _ in range(n):
                node.release(req)
        self._sched_event.set()

    async def rpc_release_lease(self, lease_id: str) -> None:
        await self._release_lease(lease_id, terminate=False)

    async def _release_lease(self, lease_id: str,
                             terminate: bool = False) -> None:
        """terminate=True kills the leased worker instead of re-pooling
        it: used when the OWNER (not the client's pump) initiates the
        release, so a still-alive pump can never race a daemon dispatch
        onto the same worker — its next run_task fails cleanly and falls
        back through the fate RPC."""
        ent = self.leases.pop(lease_id, None)
        if ent is None:
            return
        node = self.nodes.get(ent["node_id"])
        if node is not None and node.alive:
            node.release(ent["req"])
            try:
                await self.pool.get(node.addr).oneway(
                    "destroy_worker" if terminate else "release_worker",
                    worker_id=ent["worker_id"])
            except Exception:
                pass
        self._sched_event.set()

    LEASE_PROBE_AGE_S = 10.0
    LEASE_RECLAIM_SCORE = 4    # dead probe = +2, slow probe = +1

    async def _reap_dead_client_leases(self) -> None:
        """A client that crashed (or whose pump teardown lost the
        release_lease) must not pin CPU + a 'leased' worker forever
        (reference parity: leased workers are returned when the owning
        core worker dies, normal_task_submitter.h). Probe owners of
        mature leases and reclaim on accumulated evidence: a REFUSED
        connection means the process is gone (+2 per round — reclaim
        after 2 rounds, ~12 s), while a TIMEOUT may just be a
        GIL-starved-but-alive driver loop (+1 — reclaim only after ~4
        unresponsive rounds, ~28 s). Reclaim kills the worker (see
        _release_lease) so a zombie pump can never double-dispatch."""
        now = time.monotonic()
        mature = [(lid, l) for lid, l in self.leases.items()
                  if l["owner_addr"] is not None
                  and now - l["granted_at"] > self.LEASE_PROBE_AGE_S]
        if not mature:
            return
        owners = {l["owner_addr"] for _, l in mature}
        verdict: Dict[tuple, str] = {}

        async def _probe(addr: tuple) -> None:
            try:
                await asyncio.wait_for(
                    self.pool.get(addr).call("ping"), timeout=5.0)
                verdict[addr] = "ok"
            except (asyncio.TimeoutError, TimeoutError):
                # Must precede OSError: on py>=3.11 asyncio.TimeoutError
                # IS builtin TimeoutError, a subclass of OSError — a
                # GIL-busy-but-alive driver would otherwise score 'dead'.
                verdict[addr] = "slow"
            except (ConnectionError, OSError):
                verdict[addr] = "dead"      # nothing listening: definitive
            except Exception:
                verdict[addr] = "slow"      # hang/timeout: ambiguous

        await asyncio.gather(*(_probe(a) for a in owners))
        for lease_id, lease in mature:
            v = verdict.get(lease["owner_addr"], "ok")
            if v == "ok":
                lease["reclaim_score"] = 0
                continue
            lease["reclaim_score"] = (
                lease.get("reclaim_score", 0) + (2 if v == "dead" else 1))
            if lease["reclaim_score"] < self.LEASE_RECLAIM_SCORE:
                continue
            logger.warning(
                "reclaiming lease %s: owner %s unreachable "
                "(score %d, last probe %s)",
                lease_id[:8], lease["owner_addr"],
                lease["reclaim_score"], v)
            await self._release_lease(lease_id, terminate=True)

    async def rpc_task_event_push(self, task_id: str, name: str,
                                  state: str, node_id: str = None) -> None:
        """Worker-pushed task events for lease-dispatched tasks (the
        controller never sees those specs; reference parity:
        task_event_buffer.h workers reporting to the GCS task manager)."""
        self._task_event(task_id, state,
                         spec={"name": name} if name else None,
                         node_id=node_id)

    async def rpc_task_event_push_batch(self, events: list,
                                        node_id: str = None) -> None:
        """Batched form (one frame per lease-dispatched batch)."""
        for ev in events:
            self._task_event(ev["task_id"], ev["state"],
                             spec={"name": ev["name"]}
                             if ev.get("name") else None,
                             node_id=node_id)

    async def rpc_task_finished(self, task_id: str, node_id: str) -> None:
        self._task_event(task_id, "FINISHED")
        entry = self.running.pop(task_id, None)
        if entry is not None:
            nid, req, spec = entry
            node = self.nodes.get(nid)
            pg_id = (spec.get("scheduling") or {}).get("placement_group")
            pg = self.placement_groups.get(pg_id) if pg_id else None
            if pg is not None:
                pg.release_for_task(task_id)
                if node is not None:
                    node.num_running = max(0, node.num_running - 1)
            elif node is not None:
                node.release(req)
        self._sched_event.set()

    # -------------------------------------------------------------- actors

    def _register_pending_actor(self, spec: dict, node_id: str) -> None:
        # Name uniqueness was checked/claimed at submission (rpc_submit_task).
        actor_id = spec["actor_id"]
        entry = self.actors.get(actor_id)
        created = entry is None
        if created:
            entry = ActorEntry(actor_id, spec)
            self.actors[actor_id] = entry
        entry.node_id = node_id
        self._persist_actor(entry, with_spec=created)
        if created and entry.name:
            self._persist_named(entry.namespace, entry.name, actor_id)

    async def rpc_actor_started(self, actor_id: str, addr,
                                worker_id: str, spec: dict = None,
                                node_id: str = None) -> dict:
        entry = self.actors.get(actor_id)
        if entry is None and spec is not None and node_id is not None:
            # daemon-local creation (distributed dispatch): the daemon
            # granted from its delegated block and this report is the
            # controller's FIRST sight of the actor — registration is
            # off the creation critical path (reference parity:
            # gcs_actor_scheduler learns lease results after the fact)
            self._register_pending_actor(spec, node_id)
            entry = self.actors.get(actor_id)
        if entry is None or entry.state == "DEAD":
            # never resurrect a DEAD actor (e.g. killed mid-restart)
            return {"status": "superseded"}
        if entry.state == "RESTARTING" and worker_id == entry.worker_id:
            # A pre-death incarnation re-announcing itself (e.g. its node
            # rejoined after a blip) while a restart is already queued:
            # accepting it would run two live instances. The daemon kills
            # the stale worker on this reply.
            return {"status": "superseded"}
        if entry.state == "ALIVE" and entry.worker_id is not None \
                and worker_id != entry.worker_id:
            # Same race, later: the replacement is already ALIVE when the
            # stale incarnation re-announces — it must not hijack the
            # directory entry.
            return {"status": "superseded"}
        entry.addr = tuple(addr)
        entry.worker_id = worker_id
        entry.state = "ALIVE"
        self._persist_actor(entry)
        for ev in entry.waiters:
            ev.set()
        entry.waiters.clear()
        return {"status": "ok"}

    async def rpc_actor_creation_failed(self, actor_id: str,
                                        reason: str) -> None:
        await self._handle_actor_death(actor_id, reason, restartable=False)

    async def rpc_actor_died(self, actor_id: str, reason: str) -> None:
        await self._handle_actor_death(actor_id, reason)

    async def _handle_actor_death(self, actor_id: str, reason: str,
                                  restartable: bool = True) -> None:
        entry = self.actors.get(actor_id)
        if entry is None or entry.state == "DEAD":
            return
        # Release creation-task resources tied to this actor.
        task_id = entry.creation_spec.get("task_id")
        await self.rpc_task_finished(task_id, entry.node_id or "")
        if restartable and entry.restarts < entry.max_restarts:
            entry.restarts += 1
            entry.state = "RESTARTING"
            entry.addr = None
            self._persist_actor(entry)
            logger.info("restarting actor %s (%d/%d): %s", actor_id[:8],
                        entry.restarts, entry.max_restarts, reason)
            spec = dict(entry.creation_spec)
            spec["is_restart"] = True
            self.pending.append(spec)
            self._sched_event.set()
        else:
            entry.state = "DEAD"
            entry.death_cause = reason
            self._persist_actor(entry)
            for ev in entry.waiters:
                ev.set()
            entry.waiters.clear()
            if entry.name:
                self.named_actors.pop((entry.namespace, entry.name), None)
                self._persist_named(entry.namespace, entry.name, None)
            if entry.addr is None:
                # Never came up: resolve the owner's creation ref with the
                # death cause so nothing blocks on it.
                await self._fail_task(entry.creation_spec,
                                      ActorDiedError(actor_id, reason))

    async def rpc_get_actor_info(self, actor_id: str,
                                 wait: bool = True) -> Optional[dict]:
        entry = self.actors.get(actor_id)
        if entry is None:
            # May not be registered yet (submit in flight): wait briefly.
            if wait:
                for _ in range(200):
                    await asyncio.sleep(0.02)
                    entry = self.actors.get(actor_id)
                    if entry is not None:
                        break
            if entry is None:
                return None
        while wait and entry.state in ("PENDING", "RESTARTING"):
            ev = asyncio.Event()
            entry.waiters.append(ev)
            try:
                await asyncio.wait_for(ev.wait(), timeout=120.0)
            except asyncio.TimeoutError:
                return {"state": entry.state, "addr": None,
                        "death_cause": "timeout waiting for actor start"}
        return {"state": entry.state, "addr": entry.addr,
                "node_id": entry.node_id, "worker_id": entry.worker_id,
                "death_cause": entry.death_cause, "name": entry.name}

    async def rpc_get_named_actor(self, name: str,
                                  namespace: str = "default") -> Optional[dict]:
        actor_id = self.named_actors.get((namespace, name))
        if actor_id is None:
            return None
        info = await self.rpc_get_actor_info(actor_id, wait=True)
        if info is None or info.get("state") == "DEAD":
            return None
        info["actor_id"] = actor_id
        return info

    async def rpc_kill_actor(self, actor_id: str,
                             no_restart: bool = True) -> bool:
        entry = self.actors.get(actor_id)
        if entry is None:
            return False
        if no_restart:
            entry.max_restarts = entry.restarts  # disable further restarts
        if entry.state == "ALIVE" and entry.node_id:
            node = self.nodes.get(entry.node_id)
            if node is not None:
                try:
                    await self.pool.get(node.addr).call(
                        "kill_actor_worker", actor_id=actor_id)
                except Exception:
                    pass
        await self._handle_actor_death(
            actor_id, "killed via ray_tpu.kill()", restartable=not no_restart)
        return True

    async def rpc_list_actors(self) -> List[dict]:
        return [{
            "actor_id": a.actor_id, "name": a.name, "namespace": a.namespace,
            "state": a.state, "node_id": a.node_id, "restarts": a.restarts,
            "class_name": a.creation_spec.get("class_name"),
            "death_cause": a.death_cause,
        } for a in self.actors.values()]

    # --------------------------------------------------------- placement groups

    async def _finish_pg_reserve(self, pg, chosen: List[str]) -> None:
        """Background completion of a PG reservation: two-phase
        prepare/commit on every owning daemon (reference parity: GCS
        drives PrepareBundleResources/CommitBundleResources on raylets,
        gcs_placement_group_scheduler; the daemons' committed ledgers
        are what controller-restart reconciliation audits). On refusal
        the optimistic controller-side deduction rolls back and the PG
        re-enters the pending queue."""
        ok = await self._reserve_bundles_2pc(pg, chosen)
        if pg.state != "RESERVING":
            # removed/failed while we were on the wire: undo daemon state
            for nid in set(chosen):
                node = self.nodes.get(nid)
                if node is not None:
                    try:
                        await self.pool.get(node.addr).oneway(
                            "release_bundles", pg_id=pg.pg_id)
                    except Exception:
                        pass
            return
        if ok:
            pg.state = "CREATED"
            pg._wake()
            self._persist_pg(pg)
        else:
            # roll back the optimistic deduction WITHOUT mark_removed
            # (that would wake ready() waiters with a dead state)
            for b in pg.bundles:
                node = self.nodes.get(b.node_id)
                if node is not None:
                    node.release(b.resources)
                b.node_id = None
            pg.state = "PENDING"
            self.pending_pgs.append(pg)
        self._sched_event.set()

    async def _reserve_bundles_2pc(self, pg, chosen: List[str]) -> bool:
        by_node: Dict[str, list] = {}
        for b, nid in zip(pg.bundles, chosen):
            by_node.setdefault(nid, []).append(
                {"index": b.index, "resources": b.resources})

        async def _rpc(nid: str, method: str, **kw) -> bool:
            node = self.nodes.get(nid)
            if node is None or not node.alive:
                return False
            try:
                reply = await asyncio.wait_for(
                    self.pool.get(node.addr).call(
                        method, pg_id=pg.pg_id, **kw),
                    timeout=10.0)
                return bool(reply and reply.get("ok"))
            except Exception:
                return False

        nids = list(by_node)
        # concurrent prepares: serial rounds would add O(nodes) latency
        # AND let early prepares expire their daemon-side TTL before
        # the commit round reaches them
        acks = await asyncio.gather(
            *(_rpc(nid, "prepare_bundles", bundles=by_node[nid])
              for nid in nids))
        if not all(acks):
            await asyncio.gather(
                *(_rpc(nid, "release_bundles")
                  for nid, ok in zip(nids, acks) if ok))
            return False
        commits = await asyncio.gather(
            *(_rpc(nid, "commit_bundles") for nid in nids))
        if not all(commits):
            # a daemon died or expired its prepare mid-2PC: tear the
            # whole reservation down (committed daemons drop their
            # ledger entries) and let the pump retry
            await asyncio.gather(*(_rpc(nid, "release_bundles")
                                   for nid in nids))
            return False
        return True

    async def rpc_create_placement_group(self, pg_id: str, bundles,
                                         strategy: str = "PACK",
                                         name: str = "") -> dict:
        from .placement import PlacementGroupEntry
        pg = PlacementGroupEntry(pg_id, bundles, strategy, name)
        self.placement_groups[pg_id] = pg
        self.pending_pgs.append(pg)
        self._persist_pg(pg)
        self._sched_event.set()
        return {"placement_group_id": pg_id}

    async def rpc_pg_wait_ready(self, pg_id: str,
                                timeout: Optional[float] = None) -> dict:
        pg = self.placement_groups.get(pg_id)
        if pg is None:
            return {"state": "NOT_FOUND"}
        while pg.state in ("PENDING", "RESERVING"):
            ev = asyncio.Event()
            pg.waiters.append(ev)
            try:
                await asyncio.wait_for(ev.wait(), timeout=timeout or 120.0)
            except asyncio.TimeoutError:
                # Still pending at the deadline. If the scheduler has
                # recorded an infeasibility note, surface it so callers
                # can distinguish "cluster busy" from "can never fit on
                # current nodes".
                return {"state": pg.state, "timeout": True,
                        "reason": pg.failure_reason or "timeout"}
        return {"state": pg.state, "reason": pg.failure_reason}

    async def rpc_remove_placement_group(self, pg_id: str) -> bool:
        pg = self.placement_groups.get(pg_id)
        if pg is None:
            return False
        if pg in self.pending_pgs:
            self.pending_pgs.remove(pg)
        # Kill actors created inside this PG (reference semantics: removing
        # a PG stops its leaseholders).
        for actor in list(self.actors.values()):
            sched = (actor.creation_spec.get("scheduling") or {})
            if sched.get("placement_group") == pg_id \
                    and actor.state in ("ALIVE", "PENDING", "RESTARTING"):
                await self.rpc_kill_actor(actor.actor_id, no_restart=True)
        if pg.state in ("CREATED", "RESERVING"):
            # RESERVING: the in-flight _finish_pg_reserve sees the state
            # change and tells the daemons to drop their ledger entries
            pg.release_all(self.nodes)
            for b in pg.bundles:
                node = self.nodes.get(b.node_id or "")
                if node is not None:
                    try:
                        await self.pool.get(node.addr).oneway(
                            "release_bundles", pg_id=pg_id)
                    except Exception:
                        pass
        else:
            pg.mark_removed()       # wakes any pg.ready() waiters
        # Drop the entry so long-lived drivers creating/removing many PGs
        # (e.g. Tune sweeps) don't grow the table without bound. pop():
        # concurrent removals may race past the None check above.
        self.placement_groups.pop(pg_id, None)
        if self.store is not None:
            self.store.delete("placement_groups", pg_id)
        self._sched_event.set()
        return True

    async def rpc_placement_group_table(self) -> Dict[str, dict]:
        return {pg_id: pg.to_dict()
                for pg_id, pg in self.placement_groups.items()}

    # ------------------------------------------------------------------ kv

    async def rpc_kv_put(self, key: str, value: bytes,
                         overwrite: bool = True) -> bool:
        if not overwrite and key in self.kv:
            return False
        self.kv[key] = value
        if self.store is not None:
            self.store.put("kv", key, value)
        if key.startswith(FN_STORE_PREFIX):
            self._check_fn_store_growth()
        return True

    def _check_fn_store_growth(self) -> None:
        """Warn (once per doubling) when exported code blobs exceed
        fn_store_max_bytes.

        Exports are content-addressed and live for the session (reference
        parity: function_manager.py — GCS fn exports are never evicted
        mid-job, since queued specs may reference any of them). Unbounded
        growth here means a driver is re-capturing fresh state inside a
        decorator loop; surface that loudly instead of evicting blobs out
        from under queued tasks."""
        from .config import get_config
        limit = get_config().fn_store_max_bytes
        total = sum(len(v) for k, v in self.kv.items()
                    if k.startswith(FN_STORE_PREFIX))
        warned = getattr(self, "_fn_store_warned_at", 0)
        if total > limit and total >= 2 * max(warned, limit // 2):
            self._fn_store_warned_at = total
            logger.warning(
                "function store holds %.1f MB of exported code blobs "
                "(> %.1f MB): a driver may be re-creating remote "
                "functions with fresh captured state in a loop",
                total / 1e6, limit / 1e6)

    async def rpc_kv_get(self, key: str) -> Optional[bytes]:
        return self.kv.get(key)

    async def rpc_kv_del(self, key: str) -> bool:
        existed = self.kv.pop(key, None) is not None
        if existed and self.store is not None:
            self.store.delete("kv", key)
        return existed

    async def rpc_kv_keys(self, prefix: str = "") -> List[str]:
        return [k for k in self.kv if k.startswith(prefix)]

    # -------------------------------------------------------------- pubsub

    async def rpc_subscribe(self, topic: str, addr) -> None:
        subs = self.subscribers.setdefault(topic, [])
        if tuple(addr) not in subs:        # idempotent: clients refresh
            subs.append(tuple(addr))

    async def rpc_publish(self, topic: str, message) -> int:
        subs = self.subscribers.get(topic, [])
        delivered = 0
        for addr in list(subs):
            try:
                await self.pool.get(addr).oneway(
                    "pubsub_message", topic=topic, message=message)
                delivered += 1
            except Exception:
                subs.remove(addr)
        return delivered
