"""Node daemon: per-node worker pool + local object store host.

Reference parity: src/ray/raylet/node_manager.h (worker leases, worker pool
worker_pool.h:224, local object management). Differences by design: task
placement is done by the controller; the daemon's job is worker lifecycle
(spawn/reuse/kill/monitor), pushing tasks into workers, hosting the node's
shared-memory object registry, and serving cross-node object fetches.

Can run in-process (head node: inside the driver's event loop) or as a
standalone process (`python -m ray_tpu._private.daemon`) for multi-node
clusters / fake-multi-node tests (cluster_utils).
"""

from __future__ import annotations

import asyncio
import logging
import os
import subprocess
import sys
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from .ids import NodeID, WorkerID
from .object_store import NodeObjectStore
from .protocol import ClientPool, RpcServer

logger = logging.getLogger(__name__)


def system_memory_usage() -> Tuple[int, int]:
    """(used_bytes, total_bytes) from /proc/meminfo — the reference's
    memory_monitor.h reads the same counters (MemAvailable-based, so page
    cache doesn't count as pressure)."""
    total = avail = 0
    with open("/proc/meminfo") as f:
        for line in f:
            if line.startswith("MemTotal:"):
                total = int(line.split()[1]) * 1024
            elif line.startswith("MemAvailable:"):
                avail = int(line.split()[1]) * 1024
            if total and avail:
                break
    return total - avail, total


def pick_worker_to_kill(workers) -> Optional["WorkerHandle"]:
    """Worker-killing policy under memory pressure (reference parity:
    src/ray/raylet/worker_killing_policy.h:39 GroupByOwner /
    retriable-first): prefer killing retriable work, newest first, and
    only fall back to actors (whose state dies with them) when no plain
    task worker exists."""
    tasks = [w for w in workers
             if w.state in ("busy", "leased")
             and (w.current_task is not None
                  or getattr(w, "current_batch", None))]

    def _retriable(w) -> bool:
        specs = getattr(w, "current_batch", None) or [w.current_task]
        # a batch is cheap to kill only if EVERY member reruns
        return all((s.get("max_retries") or 0) > 0 for s in specs)

    if tasks:
        retriable = [w for w in tasks if _retriable(w)]
        pool = retriable or tasks      # retriable victims are cheap: they rerun
        return max(pool, key=lambda w: w.spawn_time)     # newest first
    actors = [w for w in workers if w.state == "actor"]
    if actors:
        return max(actors, key=lambda w: w.spawn_time)
    return None


def runtime_env_key(runtime_env: Optional[dict]) -> str:
    """Stable identity of a runtime env for worker reuse. Workers are only
    shared between tasks with the SAME key (reference parity:
    src/ray/raylet/worker_pool.h:224 — env-keyed idle pools)."""
    if not runtime_env:
        return ""
    import json
    return json.dumps(runtime_env, sort_keys=True, default=str)


class _ForkedProc:
    """Popen-like shim for zygote-forked workers. They are the ZYGOTE's
    children (it reaps them), so poll() re-verifies process identity and
    the exact exit code is unknowable (-1 once gone). kill() targets the
    process group — the child setsid()s, so pgid == pid.

    Identity: the zygote is spawned with a unique RAY_TPU_ZYGOTE_TAG env
    var. fork() inherits the exec-time environment, so every forked
    worker's /proc/<pid>/environ carries the tag while a recycled pid
    from an unrelated process does not — kill()/poll() check it before
    acting on the raw pid (an exit notice can be lost if the zygote dies
    before emitting it, and killing a reused pid would kill an innocent
    process group)."""

    def __init__(self, pid: int, tag: str = ""):
        self.pid = pid
        self.tag = tag
        self.returncode: Optional[int] = None

    def _identity(self) -> str:
        """'ours' | 'not_ours' | 'unknown' for the current owner of
        self.pid. 'unknown' covers zombies (environ reads empty — still
        our child, awaiting the zygote's exit notice) and platforms
        without /proc — never treat those as definitive either way."""
        if not self.tag:
            return "ours"     # no tag (legacy): trust the exit notices
        try:
            with open(f"/proc/{self.pid}/environ", "rb") as f:
                data = f.read()
        except OSError:
            return "unknown"
        if not data:
            return "unknown"  # zombie: environ is empty but pid is ours
        return "ours" if self.tag.encode() in data else "not_ours"

    def kill(self) -> None:
        if self.returncode is not None:
            return   # already reaped: the pid may belong to someone else
        if self._identity() == "not_ours":
            self.returncode = -1
            return   # recycled pid: killing it would hit an innocent pg
        import signal as _signal
        for target in (lambda: os.killpg(self.pid, _signal.SIGKILL),
                       lambda: os.kill(self.pid, _signal.SIGKILL)):
            try:
                target()
                return
            except Exception:
                continue

    def poll(self) -> Optional[int]:
        if self.returncode is not None:
            return self.returncode
        # Liveness comes from a signal-0 probe (works everywhere); the
        # environ tag is only an identity guard on top — a recycled pid
        # that probes alive but provably is not ours counts as dead.
        try:
            os.kill(self.pid, 0)
        except ProcessLookupError:
            self.returncode = -1
            return -1
        except OSError:
            pass              # EPERM etc.: pid exists
        if self._identity() == "not_ours":
            self.returncode = -1
            return -1
        return None


class WorkerHandle:
    __slots__ = ("worker_id", "addr", "pid", "proc", "state", "current_task",
                 "current_batch", "batch_progress", "actor_id", "spawn_time",
                 "env_key", "oom_reason", "last_settled_tasks",
                 "last_unstarted_tasks")

    def __init__(self, worker_id: str, proc, env_key: str = ""):
        self.worker_id = worker_id
        self.addr: Optional[Tuple[str, int]] = None
        self.proc = proc
        self.pid = proc.pid
        self.state = "starting"  # starting|idle|busy|leased|lease_draining|actor|dead
        self.current_task: Optional[dict] = None
        self.actor_id: Optional[str] = None
        self.spawn_time = time.monotonic()
        self.env_key = env_key
        # set when the memory monitor kills this worker: the normal
        # crash-report path then reports OutOfMemoryError ONCE instead of
        # a second generic crash
        self.oom_reason: Optional[str] = None
        # slim specs of the lease-dispatched batch currently executing
        # (client->worker direct; the worker self-reports per batch) and
        # the index of the member currently running (members past it
        # have not started)
        self.current_batch: list = []
        self.batch_progress: int = 0
        # task_ids whose failures _settle_leased_death already reported
        # to their owners — the fate RPC answers reported=True for them
        # so the lease pump never resubmits an already-settled task —
        # and task_ids it classified as never-started (the pump
        # resubmits those without consuming retries)
        self.last_settled_tasks: set = set()
        self.last_unstarted_tasks: set = set()


class NodeDaemon:
    def __init__(self, controller_addr: Tuple[str, int], session_name: str,
                 resources: Optional[Dict[str, float]] = None,
                 labels: Optional[Dict[str, str]] = None,
                 node_id: Optional[str] = None,
                 temp_dir: Optional[str] = None,
                 worker_env: Optional[Dict[str, str]] = None):
        self.node_id = node_id or NodeID.generate().hex()
        self.controller_addr = tuple(controller_addr)
        self.session_name = session_name
        self.resources = dict(resources or {})
        self.labels = labels or {}
        from .config import session_dir
        self.temp_dir = temp_dir or session_dir(session_name)
        self.worker_env = worker_env or {}
        self.server = RpcServer()
        self.server.register_object(self)
        self.pool = ClientPool()
        self.address: Optional[Tuple[str, int]] = None
        self.object_store = NodeObjectStore(session_name)
        self.workers: Dict[str, WorkerHandle] = {}
        # Idle pools and waiter queues are keyed by runtime-env identity:
        # a task only reuses a worker whose env matches (reference parity:
        # worker_pool.h:224 env-keyed reuse).
        self.idle: Dict[str, List[str]] = {}
        # Tasks waiting for a worker take WHICHEVER same-env worker frees
        # first (released or freshly registered) — never block on one
        # specific spawn: a worker boot costs seconds (interpreter + jax
        # import) while a release is sub-millisecond. Spawns are capped so
        # a burst can't fork-bomb a small host (reference parity:
        # worker_pool.h maximum_startup_concurrency).
        self._worker_waiters: Dict[str, "deque[asyncio.Future]"] = {}
        self._spawning: Dict[str, int] = {}
        self._runtime_envs: Dict[str, Optional[dict]] = {"": None}
        self._env_manager = None   # lazy RuntimeEnvPluginManager
        # dependency prefetch bookkeeping (_stage_remote_object)
        self._staging_inflight: Dict[str, asyncio.Future] = {}
        from collections import OrderedDict as _OD
        self._staged_lru: "_OD[str, Tuple[int, float]]" = _OD()
        self._max_concurrent_spawns = max(2, (os.cpu_count() or 1) // 2)
        self._register_events: Dict[str, asyncio.Event] = {}
        self._monitor_task: Optional[asyncio.Task] = None
        self._closed = False
        if "CPU" not in self.resources:
            self.resources["CPU"] = float(os.cpu_count() or 1)
        # TPU chip pool for device isolation (reference parity:
        # python/ray/_private/accelerators/tpu.py:193-209 TPU_VISIBLE_CHIPS).
        self._free_tpu_chips: List[int] = list(
            range(int(self.resources.get("TPU", 0))))
        self._task_tpu_chips: Dict[str, List[int]] = {}
        # Memory monitor (reference parity: memory_monitor.h:52): kill a
        # worker when node memory passes the threshold. usage fn is
        # injectable for tests. Threshold <= 0 disables.
        from .config import get_config
        self.memory_usage_fn = system_memory_usage
        self.memory_threshold = get_config().memory_usage_threshold
        self.oom_kills = 0
        # Log monitor (reference parity: _private/log_monitor.py): tail
        # each worker's log file and publish new lines on the controller
        # pubsub so drivers can print them (`(worker pid=...) ...`).
        self._log_offsets: Dict[str, int] = {}
        # Resource/stats gossip (reference parity: ray_syncer.h:39-83):
        # a versioned local view piggybacks on heartbeats only when it
        # changed since the controller's last ack.
        self._sync_version = 1
        self._sync_acked = 0
        self._last_view: Optional[dict] = None
        self._cmd_applied = 0    # highest command seq applied (acked back)
        self.draining = False
        # Daemon-local lease granting (distributed dispatch — reference
        # parity: LocalTaskManager dispatch, local_task_manager.h:102):
        # free slots per CPU size from controller-delegated blocks, plus
        # the locally-granted leases themselves. The controller stays
        # out of the per-lease critical path; it only sees block-sized
        # delegate/return calls.
        # delegated-block free slots, keyed by the canonical resource
        # tuple (e.g. (("CPU", 1.0),) or (("CPU", 1.0), ("TPU", 2.0)))
        self._lease_blocks: Dict[tuple, int] = {}
        self._local_leases: Dict[str, dict] = {}
        # actor_id -> delegated-block key claimed by a daemon-local
        # actor creation; credited back on actor death
        self._local_actor_slots: Dict[str, tuple] = {}
        # actor slots not re-acquired after a controller restart: their
        # death returns no block slot (controller's fresh view already
        # owns that capacity)
        self._unbacked_actor_slots: set = set()
        # Placement-group bundle ledger (two-phase reservation —
        # reference parity: the raylet's PrepareBundleResources /
        # CommitBundleResources, driven by the GCS scheduler). The
        # COMMITTED map is this daemon's authoritative statement of
        # which bundles it hosts; controller-restart reconciliation
        # audits it via the register_node payload.
        self._pg_prepared: Dict[str, tuple] = {}   # pg_id -> (bundles, ts)
        self._pg_bundles: Dict[str, list] = {}     # pg_id -> bundles
        self._lease_activity = 0.0       # last local grant/release
        self._lease_probe_at = 0.0       # next owner-liveness sweep
        self.local_leases_granted = 0    # counters for tests/stats
        self.local_leases_spilled = 0
        # Worker forkserver (zygote.py): interpreter+imports paid once,
        # workers fork in ~10ms. RAY_TPU_FORKSERVER=0 falls back to cold
        # Popen per worker. Replies route by worker_id; child exits are
        # pushed by the zygote's reaper (no pid-probe races).
        self._zygote = None
        self._zygote_tag = ""
        self._zygote_lock = asyncio.Lock()
        self._zygote_reader_task: Optional[asyncio.Task] = None
        self._zygote_replies: Dict[str, asyncio.Future] = {}
        self._forked_procs: Dict[int, _ForkedProc] = {}
        self._early_exits: Dict[int, int] = {}

    # ------------------------------------------------------------ lifecycle

    async def start(self, host=None, port: int = 0):
        os.makedirs(os.path.join(self.temp_dir, "logs"), exist_ok=True)
        self.address = await self.server.start(host, port)
        await self.pool.get(self.controller_addr).call(
            "register_node", node_id=self.node_id, addr=self.address,
            resources=self.resources, labels=self.labels,
            pg_bundles=self._pg_bundles)
        self._monitor_task = asyncio.ensure_future(self._monitor_loop())
        # warm the same-host check now (off-loop DNS): by the first
        # local-lease RPC the tri-state is usually already resolved
        self._controller_same_host_tristate()
        return self.address

    async def stop(self):
        self._closed = True
        for queue in self._worker_waiters.values():
            while queue:
                fut = queue.popleft()
                if not fut.done():
                    fut.set_exception(
                        RuntimeError("node daemon shut down while task "
                                     "waited for a worker"))
        if self._monitor_task:
            self._monitor_task.cancel()
        for w in self.workers.values():
            self._kill_proc(w)
        if self._zygote is not None and self._zygote.returncode is None:
            try:
                self._zygote.kill()
            except Exception:
                pass
        self.object_store.free_all()
        await self.server.stop()
        await self.pool.close_all()

    def _kill_proc(self, w: WorkerHandle) -> None:
        try:
            w.proc.kill()
        except Exception:
            pass
        w.state = "dead"

    # --------------------------------------------------------- worker pool

    async def _prepare_runtime_env(self, runtime_env: Optional[dict]):
        """Materialize a runtime env through the plugin manager
        (reference parity: python/ray/_private/runtime_env/plugin.py:118
        RuntimeEnvPluginManager; built-ins env_vars/working_dir/
        py_modules/pip/conda/uv/image_uri + externally registered
        plugins). Returns a RuntimeEnvContext."""
        from .runtime_env import RuntimeEnvPluginManager
        if self._env_manager is None:
            self._env_manager = RuntimeEnvPluginManager(self.temp_dir)
        return await self._env_manager.build(runtime_env)

    def _worker_pythonpath(self, extra_path,
                           existing: Optional[str] = None) -> str:
        """Workers must import ray_tpu (and the driver's user modules)
        even when the package isn't installed: propagate the package
        parent dir plus the driver's sys.path entries. Runtime-env paths
        go FIRST so they shadow driver-side modules; a user-supplied
        PYTHONPATH (worker_env / runtime_env env_vars) is preserved via
        `existing`."""
        pkg_parent = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        extra = list(extra_path) + [pkg_parent] + [
            p for p in sys.path if p and os.path.isdir(p)]
        if existing is None:
            existing = os.environ.get("PYTHONPATH", "")
        seen, parts = set(), []
        for p in extra + existing.split(os.pathsep):
            if p and p not in seen:
                seen.add(p)
                parts.append(p)
        return os.pathsep.join(parts)

    def _worker_argv(self, worker_id: str) -> list:
        return ["--controller",
                f"{self.controller_addr[0]}:{self.controller_addr[1]}",
                "--daemon", f"{self.address[0]}:{self.address[1]}",
                "--worker-id", worker_id,
                "--node-id", self.node_id,
                "--session", self.session_name]

    async def _ensure_zygote(self):
        async with self._zygote_lock:    # one zygote, even under a burst
            if self._zygote is not None and self._zygote.returncode is None:
                return self._zygote
            if self._zygote_reader_task is not None:
                self._zygote_reader_task.cancel()
            env = dict(os.environ)
            env.update(self.worker_env)
            env["RAY_TPU_SESSION"] = self.session_name
            # identity tag inherited (in /proc/<pid>/environ) by every
            # forked worker — see _ForkedProc._is_ours
            import uuid as _uuid
            self._zygote_tag = f"RAY_TPU_ZYGOTE_TAG={_uuid.uuid4().hex}"
            env["RAY_TPU_ZYGOTE_TAG"] = self._zygote_tag.split("=", 1)[1]
            env["PYTHONPATH"] = self._worker_pythonpath(
                [], env.get("PYTHONPATH"))
            zlog = open(os.path.join(self.temp_dir, "logs",
                                     f"zygote-{self.node_id[:8]}.log"),
                        "ab")
            self._zygote = await asyncio.create_subprocess_exec(
                sys.executable, "-m", "ray_tpu._private.zygote",
                stdin=asyncio.subprocess.PIPE,
                stdout=asyncio.subprocess.PIPE,
                stderr=zlog, env=env, start_new_session=True)
            zlog.close()
            self._zygote_reader_task = asyncio.ensure_future(
                self._zygote_reader(self._zygote))
            return self._zygote

    async def _zygote_reader(self, zygote) -> None:
        """Route zygote stdout lines: fork replies to their waiting
        spawn (by worker_id), exit notices onto the forked proc."""
        import json as _json
        try:
            while True:
                line = await zygote.stdout.readline()
                if not line:
                    break
                try:
                    msg = _json.loads(line)
                except Exception:
                    continue
                if "exited" in msg:
                    pid = int(msg["exited"])
                    proc = self._forked_procs.pop(pid, None)
                    if proc is not None:
                        proc.returncode = msg.get("code", -1)
                    else:   # exit raced ahead of the fork reply
                        if len(self._early_exits) > 4096:
                            self._early_exits.clear()
                        self._early_exits[pid] = msg.get("code", -1)
                else:
                    fut = self._zygote_replies.pop(
                        msg.get("worker_id"), None)
                    if fut is not None and not fut.done():
                        fut.set_result(msg)
        except asyncio.CancelledError:
            pass
        finally:
            # zygote gone: anything still waiting must fall back
            for fut in self._zygote_replies.values():
                if not fut.done():
                    fut.set_exception(RuntimeError("zygote died"))
            self._zygote_replies.clear()

    async def _fork_worker(self, worker_id: str, env_vars, extra_path,
                           cwd, log_path: str) -> _ForkedProc:
        import json as _json
        zygote = await self._ensure_zygote()
        # PYTHONPATH handed via env_vars cannot affect an already-running
        # interpreter — translate it into sys.path prepends for the child
        prepend = list(extra_path)
        for src in (env_vars.get("PYTHONPATH", ""),
                    self.worker_env.get("PYTHONPATH", "")):
            prepend += [p for p in src.split(os.pathsep) if p]
        req = {"worker_id": worker_id,
               "argv": self._worker_argv(worker_id),
               "env": dict(env_vars),
               "path_prepend": prepend,
               "log_path": log_path, "cwd": cwd}
        fut = asyncio.get_running_loop().create_future()
        self._zygote_replies[worker_id] = fut
        try:
            async with self._zygote_lock:
                zygote.stdin.write((_json.dumps(req) + "\n").encode())
                await zygote.stdin.drain()
            # first fork pays the zygote's one-time import cost
            reply = await asyncio.wait_for(fut, 90.0)
        finally:
            self._zygote_replies.pop(worker_id, None)
        proc = _ForkedProc(int(reply["pid"]),
                           tag=getattr(self, "_zygote_tag", ""))
        early = self._early_exits.pop(proc.pid, None)
        if early is not None:
            proc.returncode = early
        else:
            self._forked_procs[proc.pid] = proc
        return proc

    async def _spawn_worker(self, env_key: str = "") -> WorkerHandle:
        worker_id = WorkerID.generate().hex()
        log_path = self._worker_log_path(worker_id)
        runtime_env = self._runtime_envs.get(env_key)
        ctx = await self._prepare_runtime_env(runtime_env)
        env_vars, extra_path, cwd = ctx.env_vars, ctx.extra_paths, ctx.cwd
        from .config import get_config
        proc = None
        # Env vars that act at interpreter/import time (jax/XLA config,
        # python startup) cannot take effect in a fork of the pre-warmed
        # zygote — those workers must cold-spawn. So must conda/uv envs
        # (different interpreter) and containerized workers.
        import_sensitive = any(
            k.startswith(("JAX_", "XLA_", "PYTHON", "LD_", "TPU_"))
            for k in env_vars)
        if (get_config().worker_forkserver and not import_sensitive
                and ctx.py_executable is None and ctx.container is None):
            try:
                proc = await self._fork_worker(
                    worker_id, env_vars, extra_path, cwd, log_path)
            except Exception:
                logger.exception("zygote fork failed; cold-spawning")
                proc = None
        if proc is None:
            argv = ([ctx.py_executable or sys.executable, "-m",
                     "ray_tpu._private.worker_main"]
                    + self._worker_argv(worker_id))
            if ctx.container is not None:
                if ctx.container.get("run_prefix"):
                    # sandbox:// image: the plugin built the native
                    # namespace-chroot launcher (sandbox_run.py)
                    argv = list(ctx.container["run_prefix"]) + argv
                else:
                    # external image: a configured container runtime
                    # wraps the spawn; bare nodes fail loudly (the
                    # GKE/KubeRay integration supplies the prefix)
                    prefix = get_config().container_run_prefix
                    if not prefix:
                        raise RuntimeError(
                            "runtime_env image_uri requires a container "
                            "runtime (set RAY_TPU_CONTAINER_RUN_PREFIX, "
                            "use image_uri='sandbox://<rootfs>', or run "
                            "under the KubeRay/GKE integration)")
                    argv = [p.replace("{image}",
                                      ctx.container["image_uri"])
                            for p in prefix.split()] + argv
            env = dict(os.environ)
            env.update(self.worker_env)
            env.update(env_vars)
            env["RAY_TPU_SESSION"] = self.session_name
            env["PYTHONPATH"] = self._worker_pythonpath(
                extra_path, env.get("PYTHONPATH"))
            with open(log_path, "ab") as log_file:
                proc = subprocess.Popen(
                    argv,
                    stdout=log_file, stderr=subprocess.STDOUT, env=env,
                    cwd=cwd, start_new_session=True)
        handle = WorkerHandle(worker_id, proc, env_key)
        self.workers[worker_id] = handle
        ev = asyncio.Event()
        self._register_events[worker_id] = ev
        try:
            deadline = time.monotonic() + 60.0
            while True:
                try:
                    await asyncio.wait_for(
                        ev.wait(),
                        timeout=min(1.0, max(
                            deadline - time.monotonic(), 0.05)))
                    break
                except asyncio.TimeoutError:
                    # a worker that DIED before registering (bad
                    # container prefix, sandbox mount failure, import
                    # crash) fails the spawn immediately instead of
                    # burning the full registration timeout
                    rc = proc.poll()
                    if rc is not None:
                        raise RuntimeError(
                            f"worker exited (code {rc}) before "
                            f"registering; see {log_path}")
                    if time.monotonic() >= deadline:
                        self._kill_proc(handle)
                        raise RuntimeError(
                            f"worker failed to start within 60s; "
                            f"see {log_path}")
        finally:
            self._register_events.pop(worker_id, None)
        return handle

    async def rpc_register_worker(self, worker_id: str, addr) -> dict:
        handle = self.workers.get(worker_id)
        if handle is None:
            return {"status": "unknown"}
        handle.addr = tuple(addr)
        handle.state = "idle"
        ev = self._register_events.get(worker_id)
        if ev:
            ev.set()
        return {"status": "ok"}

    async def _acquire_worker(self, env_key: str = "",
                              runtime_env: Optional[dict] = None
                              ) -> WorkerHandle:
        self._runtime_envs.setdefault(env_key, runtime_env)
        pool = self.idle.setdefault(env_key, [])
        waiters = self._worker_waiters.setdefault(env_key, deque())
        while True:
            while pool:
                worker_id = pool.pop()
                handle = self.workers.get(worker_id)
                if handle is not None and handle.state == "idle":
                    return handle
            fut = asyncio.get_running_loop().create_future()
            waiters.append(fut)
            self._maybe_spawn(env_key)
            handle = await fut
            if handle.state == "idle":
                return handle
            # handed a worker that died in the window; go around again

    def _maybe_spawn(self, env_key: str = "") -> None:
        if self._closed:
            return
        spawning_here = self._spawning.get(env_key, 0)
        spawning_total = sum(self._spawning.values())
        deficit = (len(self._worker_waiters.get(env_key, ()))
                   - spawning_here)
        room = self._max_concurrent_spawns - spawning_total
        for _ in range(max(0, min(deficit, room))):
            self._spawning[env_key] = self._spawning.get(env_key, 0) + 1
            asyncio.ensure_future(self._spawn_into_pool(env_key))

    async def _spawn_into_pool(self, env_key: str = "") -> None:
        waiters = self._worker_waiters.setdefault(env_key, deque())
        try:
            handle = await self._spawn_worker(env_key)
            self._offer_worker(handle)
        except Exception as e:
            # surface the failure on one waiter instead of hanging it
            while waiters:
                fut = waiters.popleft()
                if not fut.done():
                    fut.set_exception(e)
                    break
        finally:
            self._spawning[env_key] = self._spawning.get(env_key, 1) - 1
            # waiters taken by actors never release a worker; keep
            # spawning while a deficit remains. Re-evaluate EVERY env's
            # queue, not just ours: another env's waiters may have been
            # blocked purely by the global spawn cap we just vacated.
            for key in list(self._worker_waiters):
                if self._worker_waiters.get(key):
                    self._maybe_spawn(key)

    def _offer_worker(self, handle: WorkerHandle) -> None:
        """Hand an idle worker to the longest-waiting same-env task, else
        pool it under its env key."""
        if handle.state == "dead" or handle.proc.poll() is not None:
            # e.g. a lease released right after its worker died, before
            # the monitor sweep noticed: never offer a corpse — a task
            # dispatched to it burns a retry on ConnectionRefused. The
            # sweep settles the death and removes the handle.
            return
        waiters = self._worker_waiters.setdefault(handle.env_key, deque())
        while waiters:
            fut = waiters.popleft()
            if not fut.done():
                fut.set_result(handle)
                return
        self.idle.setdefault(handle.env_key, []).append(handle.worker_id)

    def _release_worker(self, handle: WorkerHandle) -> None:
        if handle.state == "busy":
            handle.state = "idle"
            handle.current_task = None
            self._offer_worker(handle)

    async def rpc_reserve_worker(self, runtime_env: Optional[dict] = None
                                 ) -> dict:
        """Lease a worker to a client for direct task submission
        (reference parity: worker leases, normal_task_submitter.h:72-140
        — the task fast path bypasses the control plane per-task)."""
        key = runtime_env_key(runtime_env)
        try:
            handle = await self._acquire_worker(key, runtime_env)
        except Exception as e:
            return {"status": "error", "error": repr(e)}
        handle.state = "leased"
        handle.current_task = None
        handle.current_batch = []
        return {"status": "ok", "worker_id": handle.worker_id,
                "addr": handle.addr}

    async def rpc_destroy_worker(self, worker_id: str) -> None:
        """Kill a worker outright (controller-initiated lease reclaim:
        the owner vanished, so the worker must not be re-pooled where a
        zombie pump could still reach it). The monitor sweep settles any
        in-flight task and removes the handle."""
        handle = self.workers.get(worker_id)
        if handle is None:
            return
        try:
            handle.proc.kill()
        except Exception:
            pass

    async def rpc_release_worker(self, worker_id: str) -> None:
        handle = self.workers.get(worker_id)
        if handle is None or handle.state != "leased":
            return
        if handle.current_task is not None or handle.current_batch:
            # lease released mid-task (client->worker blip): drain —
            # the worker returns to the pool when the batch finishes
            handle.state = "lease_draining"
            return
        handle.state = "idle"
        self._offer_worker(handle)

    # Leased workers self-report their current task so the OOM killer /
    # failure attribution work exactly like daemon-dispatched tasks
    # (reference parity: the raylet always knows its workers' tasks).
    async def rpc_leased_task_started(self, worker_id: str,
                                      spec: dict) -> None:
        handle = self.workers.get(worker_id)
        if handle is not None:
            handle.current_task = spec

    async def rpc_leased_task_done(self, worker_id: str) -> None:
        handle = self.workers.get(worker_id)
        if handle is None:
            return
        if handle.state == "leased":
            handle.current_task = None
        elif handle.state == "lease_draining":
            handle.current_task = None
            handle.state = "idle"
            self._offer_worker(handle)

    async def rpc_leased_batch_started(self, worker_id: str,
                                       specs: list) -> None:
        """One self-report per dispatched BATCH (the lease fast path
        amortizes per-task wire cost; reference parity intent unchanged:
        the raylet always knows its workers' work)."""
        handle = self.workers.get(worker_id)
        if handle is not None:
            handle.current_batch = list(specs)
            handle.batch_progress = 0
            handle.current_task = None

    async def rpc_leased_batch_progress(self, worker_id: str,
                                        index: int) -> None:
        handle = self.workers.get(worker_id)
        if handle is not None:
            handle.batch_progress = max(handle.batch_progress, int(index))

    async def rpc_leased_batch_done(self, worker_id: str) -> None:
        handle = self.workers.get(worker_id)
        if handle is None:
            return
        handle.current_batch = []
        handle.batch_progress = 0
        if handle.state == "lease_draining":
            handle.state = "idle"
            self._offer_worker(handle)

    async def _settle_leased_death(self, handle: WorkerHandle) -> bool:
        """Report a dead leased worker's in-flight task(s) to their
        owners EXACTLY once (fate RPC and the monitor sweep both funnel
        here; check-and-clear on the daemon loop makes it atomic).

        Batch nuance: only members the worker STARTED (index <=
        batch_progress) are reported — the running one genuinely failed;
        earlier ones completed, and their owners drop the report
        (core.py rpc_object_ready late-failure guard) unless the result
        push itself was lost, which the report then covers. Never-
        started members are recorded in last_unstarted_tasks and handed
        back to the pump via the fate RPC for clean resubmission with no
        retry consumed."""
        if handle.current_batch:
            cut = handle.batch_progress + 1
            started = [s for s in handle.current_batch[:cut]
                       if s and s.get("_leased")]
            # Members past the last DELIVERED marker are ambiguous: the
            # worker sends a marker before every member, but the final
            # oneway frame can die with the worker. Resubmission is only
            # safe when re-execution is permitted — retriable members go
            # back to the pump as unstarted (no retry consumed), while
            # max_retries=0 members are failed like started ones:
            # re-executing a possibly-started at-most-once task would
            # break its guarantee.
            ambiguous = [s for s in handle.current_batch[cut:]
                         if s and s.get("_leased")]
            unstarted = [s for s in ambiguous
                         if (s.get("max_retries") or 0) > 0]
            started += [s for s in ambiguous
                        if (s.get("max_retries") or 0) <= 0]
        elif (handle.current_task is not None
              and handle.current_task.get("_leased")):
            started, unstarted = [handle.current_task], []
        else:
            return False
        handle.current_task = None
        handle.current_batch = []
        handle.batch_progress = 0
        handle.last_unstarted_tasks |= {
            s.get("task_id") for s in unstarted}
        from ..exceptions import OutOfMemoryError
        err = (OutOfMemoryError(handle.oom_reason)
               if handle.oom_reason else None)
        for spec in started:
            handle.last_settled_tasks.add(spec.get("task_id"))
            await self._report_failure(
                spec, "leased worker died while running task", error=err)
        return True

    async def rpc_leased_worker_fate(self, worker_id: str,
                                     task_id: str = None,
                                     task_ids: list = None) -> dict:
        """The client's lease pump asks after a connection failure:
        'did/will you report these tasks?' — settles on the spot so the
        pump never double-submits and owners never hang. A worker that
        is still ALIVE is a transient client->worker blip: the batch
        keeps executing and its results reach the owner directly, so
        nothing is settled and the pump must not resubmit."""
        ids = set(task_ids or ([task_id] if task_id else []))
        handle = self.workers.get(worker_id)
        if handle is None:
            return {"reported": False, "alive": False, "unstarted": []}
        dead = handle.state == "dead" or handle.proc.poll() is not None
        if not dead:
            return {"reported": False, "alive": True, "unstarted": []}
        current = {s.get("task_id") for s in handle.current_batch}
        if handle.current_task is not None:
            current.add(handle.current_task.get("task_id"))
        if ids & current:
            await self._settle_leased_death(handle)
        # either settled just now or by the sweep (reported=True for the
        # started members — resubmitting those would break at-most-once
        # and race the owner-side retry) or the worker died before
        # leased_batch_started landed (reported=False: pump resubmits
        # everything). "unstarted" members never executed: the pump
        # resubmits them through the scheduler, no retry consumed.
        return {"reported": bool(ids & handle.last_settled_tasks),
                "alive": False,
                "unstarted": sorted(ids & handle.last_unstarted_tasks)}

    # ------------------------------------------------ local lease granting

    LOCAL_LEASE_PROBE_AGE_S = 10.0     # lease age before owner probing
    LOCAL_LEASE_PROBE_PERIOD_S = 5.0   # sweep cadence

    async def _claim_local_slot(self, resources: dict):
        """Shared gate + slot claim for daemon-local grants (worker
        leases AND actor creations). Returns (cpu, None) with one
        delegated-block slot claimed, or (None, refusal_reply).

        Only plain-CPU requests are served locally (everything else
        needs global placement state)."""
        from .config import get_config
        cfg = get_config()
        res = dict(resources or {})
        mode = str(cfg.local_lease_enabled).lower()
        if mode in ("0", "false"):
            return None, {"status": "unsupported"}
        # CPU and TPU serve locally (TPU chips are node-local hardware
        # this daemon already owns the assignment of); anything else
        # needs global placement state
        if any(k not in ("CPU", "TPU") for k in res):
            return None, {"status": "unsupported"}
        if mode not in ("1", "true"):
            # auto: only worth it when the controller hop crosses hosts
            # (loopback grants measurably lose to the controller path —
            # delegation churn with no latency saved). Hosts are
            # resolved so hostname-vs-IP spellings of the same machine
            # still compare equal.
            same = self._controller_same_host_tristate()
            if same is None:
                # resolution still in flight (off-loop DNS): 'spill'
                # routes this one call to the controller WITHOUT the
                # client latching local-lease-unsupported for good
                return None, {"status": "spill"}
            if same:
                return None, {"status": "unsupported"}
        # zero entries normalize OUT of the block key: an explicit
        # "CPU: 0" request used to produce a {"CPU": 0.0} key and a
        # zero-CPU delegation block — a grant the controller's ledger
        # can't meaningfully meter. Zero-cpu requests take the
        # scheduled path via 'spill' (a per-key 5 s skip on the
        # client); 'unsupported' would latch the client's process-wide
        # local-lease-off flag and kill the fast path for every later
        # CPU>0 task too.
        req = {k: float(v) for k, v in res.items() if float(v) > 0}
        if "CPU" not in res:
            req["CPU"] = 1.0             # unspecified -> default 1
        if "CPU" not in req:
            return None, {"status": "spill"}
        key = tuple(sorted(req.items()))
        if self.draining:
            return None, {"status": "spill"}
        while self._lease_blocks.get(key, 0) <= 0:
            # grow the block; re-check after the await (a concurrent
            # grant may have consumed what this call delegated)
            try:
                reply = await self.pool.get(self.controller_addr).call(
                    "delegate_resources", node_id=self.node_id,
                    resources=dict(key),
                    count=max(1, cfg.lease_block_size))
            except Exception:
                reply = None
            if not reply or reply.get("granted", 0) <= 0:
                self.local_leases_spilled += 1
                return None, {"status": "spill"}
            self._lease_blocks[key] = (self._lease_blocks.get(key, 0)
                                       + reply["granted"])
        # slot claimed before any worker-acquire await (no double-grant)
        self._lease_blocks[key] -= 1
        return key, None

    async def rpc_create_actor_local(self, spec: dict) -> dict:
        """Create an actor WITHOUT the controller on the critical path
        (distributed dispatch for actors — reference parity: the GCS
        actor scheduler leases workers through raylets and learns the
        result afterwards, gcs_actor_scheduler.h; here the daemon
        grants from its controller-delegated block and the controller's
        directory is updated by the actor_started report, which carries
        the creation spec so registration is ASYNC).

        Serves plain-CPU, unnamed, non-detached, default-scheduled
        creations; everything else replies 'unsupported' and takes the
        scheduled path. The claimed slot is held until actor death
        (credited back by the worker monitor)."""
        if (spec.get("scheduling") or spec.get("runtime_env")
                or spec.get("actor_name")
                or spec.get("lifetime") == "detached"):
            return {"status": "unsupported"}
        claimed, refusal = await self._claim_local_slot(
            spec.get("resources"))
        if refusal is not None:
            return refusal
        cpu = claimed
        actor_id = spec["actor_id"]
        tpu_n = int((spec.get("resources") or {}).get("TPU", 0))
        if tpu_n and len(self._free_tpu_chips) < tpu_n:
            self._lease_blocks[cpu] = self._lease_blocks.get(cpu, 0) + 1
            self.local_leases_spilled += 1
            return {"status": "spill", "error": "tpu chips busy"}
        self._assign_tpu_chips(spec)   # chips held until actor death
        try:
            handle = await self._acquire_worker()
        except Exception as e:
            self._release_tpu_chips(spec["task_id"])
            self._lease_blocks[cpu] = self._lease_blocks.get(cpu, 0) + 1
            self.local_leases_spilled += 1
            return {"status": "spill", "error": repr(e)}
        handle.state = "actor"
        handle.actor_id = actor_id
        handle.current_task = spec
        self._local_actor_slots[actor_id] = cpu
        try:
            reply = await self.pool.get(handle.addr).call(
                "create_actor", spec=spec)
        except Exception as e:
            self._local_actor_slots.pop(actor_id, None)
            self._lease_blocks[cpu] = self._lease_blocks.get(cpu, 0) + 1
            self._release_tpu_chips(spec["task_id"])
            # transport error mid-create: __init__ MAY have succeeded
            # in a still-alive worker — kill it, or the client's
            # scheduled-path resubmission could start a SECOND live
            # incarnation of this actor_id (the monitor reaps the proc;
            # its actor_died for a never-registered actor is a no-op)
            self._kill_proc(handle)
            return {"status": "error", "error": repr(e)}
        if reply.get("status") != "ok":
            # __init__ raised: worker already pushed the owner the
            # error; reusable worker goes back to the pool
            self._local_actor_slots.pop(actor_id, None)
            self._lease_blocks[cpu] = self._lease_blocks.get(cpu, 0) + 1
            self._release_tpu_chips(spec["task_id"])
            handle.state = "busy"
            handle.actor_id = None
            handle.current_task = None
            self._release_worker(handle)
            return {"status": "created_failed"}
        self.local_leases_granted += 1
        self._lease_activity = time.monotonic()
        # async directory registration: the spec rides actor_started so
        # the controller can build the ActorEntry it never saw; retried
        # off the grant path — an actor the controller never learns is
        # unkillable/unrestartable, so after the retries fail the actor
        # is destroyed rather than left as a ghost
        asyncio.ensure_future(
            self._announce_local_actor(actor_id, handle, spec))
        return {"status": "ok", "addr": list(handle.addr),
                "worker_id": handle.worker_id}

    async def _announce_local_actor(self, actor_id: str, handle,
                                    spec: dict) -> None:
        for attempt in range(5):
            try:
                await asyncio.wait_for(
                    self.pool.get(self.controller_addr).call(
                        "actor_started", actor_id=actor_id,
                        addr=handle.addr, worker_id=handle.worker_id,
                        spec=spec, node_id=self.node_id),
                    timeout=10.0)
                return
            except Exception:
                await asyncio.sleep(min(2.0 ** attempt, 10.0))
        logger.warning(
            "local actor %s could not be registered with the "
            "controller after 5 attempts; destroying it (an "
            "unregistered actor cannot be killed or restarted)",
            actor_id[:12])
        await self.rpc_kill_actor_worker(actor_id)

    async def rpc_lease_worker_local(self, resources: dict = None,
                                     owner_addr=None) -> dict:
        """Grant a worker lease WITHOUT a controller round-trip, from a
        controller-delegated resource block (distributed dispatch —
        reference parity: the raylet grants leases locally,
        node_manager.cc HandleRequestWorkerLease; spillback here is the
        'spill' reply, which sends the client to the controller's
        global scheduler instead of a peer raylet — the controller is
        this design's spill target).

        CPU and CPU+TPU requests are served locally (TPU chips are
        pinned to the lease); everything else needs global placement
        state and replies 'unsupported'."""
        claimed, refusal = await self._claim_local_slot(resources)
        if refusal is not None:
            return refusal
        cpu = claimed
        # TPU leases pin specific chips for the lease lifetime; tasks
        # dispatched through the lease inherit them (the scheduled path
        # assigns per-task instead, _assign_tpu_chips)
        tpu_n = int(dict(cpu).get("TPU", 0))
        chips = None
        if tpu_n:
            if len(self._free_tpu_chips) < tpu_n:
                self._lease_blocks[cpu] += 1
                self.local_leases_spilled += 1
                return {"status": "spill", "error": "tpu chips busy"}
            chips = self._free_tpu_chips[:tpu_n]
            self._free_tpu_chips = self._free_tpu_chips[tpu_n:]
        reply = await self.rpc_reserve_worker()
        if reply.get("status") != "ok":
            self._lease_blocks[cpu] += 1
            if chips:
                self._free_tpu_chips.extend(chips)
            self.local_leases_spilled += 1
            return {"status": "spill", "error": reply.get("error")}
        import uuid as _uuid
        lease_id = "ll-" + _uuid.uuid4().hex
        self._local_leases[lease_id] = {
            "cpu": cpu, "worker_id": reply["worker_id"],
            "owner_addr": tuple(owner_addr) if owner_addr else None,
            "granted_at": time.monotonic(), "score": 0,
            "tpu_chips": chips,
        }
        self._lease_activity = time.monotonic()
        self.local_leases_granted += 1
        return {"status": "ok", "lease_id": lease_id, "local": True,
                "worker_addr": list(reply["addr"]),
                "worker_id": reply["worker_id"],
                "daemon_addr": list(self.address),
                "node_id": self.node_id,
                "tpu_chips": chips}

    def _controller_same_host_tristate(self):
        """True/False once known, None while resolving.

        True when the controller runs on this daemon's machine (so a
        'local' grant would save no network hop). DNS resolution runs
        OFF the event loop (a slow resolver must not stall daemon RPCs
        — heartbeats ride this loop). Loopback literals, equal strings,
        and hostname-vs-IP aliases all count as same-host; resolution
        failure conservatively reports same-host."""
        cached = getattr(self, "_same_host_cache", None)
        if cached is not None:
            return cached
        chost, dhost = self.controller_addr[0], self.address[0]
        loop_names = {"127.0.0.1", "localhost", "::1", "0.0.0.0"}
        if chost in loop_names or chost == dhost:
            self._same_host_cache = True
            return True
        if getattr(self, "_same_host_resolving", False):
            return None   # resolution in flight
        self._same_host_resolving = True

        def _resolve() -> bool:
            import socket
            try:
                cip = socket.gethostbyname(chost)
                dip = socket.gethostbyname(dhost)
                return (cip == dip or cip in loop_names
                        or dip in loop_names)
            except OSError:
                return True

        import asyncio

        def _store(fut):
            try:
                self._same_host_cache = bool(fut.result())
            except Exception:
                self._same_host_cache = True

        try:
            task = asyncio.get_running_loop().run_in_executor(
                None, _resolve)
            task.add_done_callback(_store)
        except RuntimeError:     # no running loop (unit-test direct call)
            self._same_host_cache = _resolve()
            return self._same_host_cache
        return None

    async def rpc_release_lease_local(self, lease_id: str,
                                      terminate: bool = False) -> None:
        ent = self._local_leases.pop(lease_id, None)
        if ent is None:
            return
        if terminate:
            await self.rpc_destroy_worker(ent["worker_id"])
        else:
            await self.rpc_release_worker(ent["worker_id"])
        if ent.get("tpu_chips"):
            self._free_tpu_chips.extend(ent["tpu_chips"])
        if not ent.get("unbacked"):
            # unbacked = granted before a controller restart and not
            # re-acquired since (_reconcile_delegations): its slot no
            # longer exists controller-side, so nothing returns
            self._lease_blocks[ent["cpu"]] = (
                self._lease_blocks.get(ent["cpu"], 0) + 1)
        self._lease_activity = time.monotonic()

    async def _reconcile_delegations(self) -> None:
        """After a controller restart (or dead-mark + re-register) the
        fresh NodeEntry has no record of our delegated slots — neither
        the free block nor the ones backing live local leases. Holding
        them anyway would let the scheduled path double-book this node
        forever. Re-acquire everything; what cannot be re-acquired is
        shed: free slots are dropped, and uncovered live leases are
        marked 'unbacked' (they run to completion but return no slot —
        transient oversubscription bounded by the lease lifetime)."""
        # Snapshot BOTH sides before any await: a lease granted while
        # this coroutine awaits the controller is already backed by a
        # fresh-controller delegation and must be neither covered here
        # nor have its block slots clobbered (+= below, not =).
        stale_free: Dict[float, int] = {
            cpu: max(0, n) for cpu, n in self._lease_blocks.items()}
        stale = list(self._local_leases.items())
        stale_actors = list(self._local_actor_slots.items())
        self._lease_blocks = {}
        # Pessimistic until re-acquired: a stale lease released DURING
        # the awaits below must not credit a block slot from the dead
        # controller epoch.
        for _, ent in stale:
            ent["unbacked"] = True
        self._unbacked_actor_slots.update(a for a, _ in stale_actors)
        need: Dict[float, int] = dict(stale_free)
        for _, ent in stale:
            need[ent["cpu"]] = need.get(ent["cpu"], 0) + 1
        for _, cpu in stale_actors:
            need[cpu] = need.get(cpu, 0) + 1
        controller = self.pool.get(self.controller_addr)
        for cpu, count in need.items():
            if count <= 0:
                continue
            granted = 0
            try:
                reply = await controller.call(
                    "delegate_resources", node_id=self.node_id,
                    resources=dict(cpu), count=count)
                granted = int((reply or {}).get("granted", 0))
            except Exception:
                granted = 0
            # cover stale leases that are STILL live (a released one is
            # skipped — its would-be slot flows into the block instead);
            # leftovers join anything delegated concurrently (+=)
            for lid, ent in stale:
                if (ent["cpu"] != cpu or granted <= 0
                        or self._local_leases.get(lid) is not ent):
                    continue
                ent["unbacked"] = False
                granted -= 1
            for aid, acpu in stale_actors:
                if (acpu != cpu or granted <= 0
                        or aid not in self._local_actor_slots):
                    continue
                self._unbacked_actor_slots.discard(aid)
                granted -= 1
            if granted > 0:
                self._lease_blocks[cpu] = (
                    self._lease_blocks.get(cpu, 0) + granted)

    async def _local_lease_sweep(self) -> None:
        """Monitor-loop duties for local leases: (a) reap leases whose
        owner died (same refused/slow scoring as the controller's
        reaper — a GIL-busy driver is 'slow', not dead), (b) return
        idle delegated slots so the scheduled path can use them."""
        from .config import get_config
        cfg = get_config()
        now = time.monotonic()
        if self._local_leases and now >= self._lease_probe_at:
            self._lease_probe_at = now + self.LOCAL_LEASE_PROBE_PERIOD_S
            mature = [(lid, l) for lid, l in self._local_leases.items()
                      if l["owner_addr"] is not None
                      and (now - l["granted_at"]
                           > self.LOCAL_LEASE_PROBE_AGE_S)]
            owners = {l["owner_addr"] for _, l in mature}
            verdict: Dict[tuple, int] = {}

            async def _probe(addr: tuple) -> None:
                # same classification as the controller's reaper
                # (controller.py _reap_dead_client_leases): refused is
                # definitive (+2); timeouts/odd errors are ambiguous
                # (+1) — a GIL-starved driver must not lose its worker
                try:
                    await asyncio.wait_for(
                        self.pool.get(addr).call("ping"), timeout=5.0)
                    verdict[addr] = 0
                except (asyncio.TimeoutError, TimeoutError):
                    verdict[addr] = 1
                except (ConnectionError, OSError):
                    verdict[addr] = 2
                except Exception:
                    verdict[addr] = 1

            await asyncio.gather(*(_probe(a) for a in owners))
            for lid, lease in mature:
                score = verdict.get(lease["owner_addr"], 0)
                lease["score"] = (0 if score == 0
                                  else lease["score"] + score)
                if lease["score"] >= 4:
                    # kill, don't re-pool: a zombie pump must never
                    # share a worker with a daemon dispatch
                    await self.rpc_release_lease_local(
                        lid, terminate=True)
        if (self._lease_blocks
                and now - self._lease_activity > cfg.lease_block_idle_s):
            for cpu, free in list(self._lease_blocks.items()):
                if free > 0:
                    # claim BEFORE the await: a grant racing this return
                    # must see 0 and grow a fresh block, not consume a
                    # slot the controller is about to release
                    self._lease_blocks[cpu] -= free
                    try:
                        await self.pool.get(self.controller_addr).call(
                            "return_delegation", node_id=self.node_id,
                            resources=dict(cpu), count=free)
                    except Exception:
                        self._lease_blocks[cpu] = (
                            self._lease_blocks.get(cpu, 0) + free)
                        continue
                if self._lease_blocks.get(cpu) == 0:
                    del self._lease_blocks[cpu]

    async def rpc_prestart_workers(self, count: int) -> int:
        started = 0
        for _ in range(count):
            try:
                h = await self._spawn_worker("")
                self._offer_worker(h)
                started += 1
            except Exception:
                break
        return started

    # ----------------------------------------------------------- execution

    async def rpc_execute_task(self, spec: dict) -> dict:
        asyncio.ensure_future(self._run_task(spec))
        return {"status": "accepted"}

    def _assign_tpu_chips(self, spec: dict) -> None:
        n = int((spec.get("resources") or {}).get("TPU", 0))
        if n > 0 and self._free_tpu_chips:
            chips = self._free_tpu_chips[:n]
            self._free_tpu_chips = self._free_tpu_chips[n:]
            self._task_tpu_chips[spec["task_id"]] = chips
            spec["_tpu_chips"] = chips

    def _release_tpu_chips(self, task_id: str) -> None:
        chips = self._task_tpu_chips.pop(task_id, None)
        if chips:
            self._free_tpu_chips.extend(chips)

    PREFETCH_DISPATCH_GRACE_S = 5.0    # max dispatch delay for staging
    STAGED_CACHE_BYTES = 256 << 20     # staged foreign copies kept around

    async def _prefetch_args(self, spec: dict) -> Dict[str, Any]:
        """Stage the task's arg objects while it waits for a worker
        (reference parity: raylet/dependency_manager.h pulls args to
        local plasma before dispatch). Returns {object_id: ShmLocation}
        or {object_id: ('payload', flat_bytes)} handed to the worker via
        the spec, so arg resolution skips the owner round trip. Cross-
        host objects are copied into this node's arena (deduped against
        concurrent prefetches of the same object); same-host shm is
        attachable directly, so only the location is recorded. Best
        effort: any failure just leaves the worker on its normal path."""
        arg_refs = dict(spec.get("arg_refs") or [])   # dedup repeat args
        if not arg_refs:
            return {}

        async def one(oid: str, owner):
            entry = self.object_store.get(oid)
            if (entry is not None and entry.sealed
                    and not entry.shm_name.startswith("spill:")):
                # "spill:" names are daemon-internal — a worker cannot
                # shm_open them; let it fetch through rpc_fetch_object
                from .object_store import ShmLocation
                return oid, ShmLocation(self.address, entry.shm_name,
                                        entry.size)
            if owner is None or tuple(owner) == self.address:
                return oid, None
            try:
                reply = await asyncio.wait_for(
                    self.pool.get(tuple(owner)).call(
                        "get_object", object_id=oid, timeout=60.0),
                    timeout=65.0)
            except Exception:
                return oid, None
            status = reply.get("status")
            if status == "location":
                loc = reply["location"]
                if loc.node_addr[0] == self.address[0]:
                    return oid, loc   # same host: shm attaches directly
                staged = await self._stage_remote_object(oid, loc)
                return oid, staged or loc
            if status == "inline" and reply.get("payload") is not None:
                # small/inline object: forward the bytes — the daemon
                # already paid the owner round trip, the worker must not
                # pay it again
                return oid, ("payload", reply["payload"])
            return oid, None

        results = await asyncio.gather(
            *(one(oid, owner) for oid, owner in arg_refs.items()),
            return_exceptions=True)
        return {oid: loc for r in results
                if not isinstance(r, BaseException)
                for oid, loc in [r] if loc is not None}

    async def _stage_remote_object(self, object_id: str, loc):
        """Chunk-fetch a cross-host object into THIS node's arena and
        register it; returns the local ShmLocation (None on failure).
        Concurrent stagings of one object share a single pull, and
        staged foreign copies are LRU-capped — the owner's free_object
        never reaches this unsolicited copy, so the daemon bounds it."""
        fut = self._staging_inflight.get(object_id)
        if fut is not None:
            try:
                return await asyncio.shield(fut)
            except Exception:
                return None
        fut = asyncio.get_running_loop().create_future()
        self._staging_inflight[object_id] = fut
        try:
            result = await self._stage_remote_object_inner(object_id, loc)
            fut.set_result(result)
            return result
        except BaseException as e:
            fut.set_exception(e)
            fut.exception()   # consumed
            return None
        finally:
            self._staging_inflight.pop(object_id, None)

    STAGED_PIN_S = 60.0     # staged copies safe from eviction this long
    STAGED_HARD_CAP = 2      # x STAGED_CACHE_BYTES: pin yields to this

    async def _stage_remote_object_inner(self, object_id: str, loc):
        from .object_store import ShmLocation, write_to_shm
        from .serialization import SerializedObject
        from .transfer import fetch_flat
        try:
            flat = await fetch_flat(
                self.pool.get(loc.node_addr), object_id, loc.size,
                per_call_timeout=30.0)
            # arena_room=None: a discardable cache copy must never force
            # PRIMARY objects to spill (write_to_shm falls back to a
            # per-object segment when the arena is full)
            shm_name, size = await asyncio.get_running_loop().run_in_executor(
                None, write_to_shm, object_id,
                SerializedObject.from_flat(flat), self.session_name,
                None)
            self.object_store.register(object_id, shm_name, size)
            self._staged_lru[object_id] = (size, time.monotonic())
            self._staged_lru.move_to_end(object_id)
            # SOFT cap: entries younger than STAGED_PIN_S may hold
            # ShmLocations already handed to dispatched-but-unresolved
            # tasks — freeing those would fail the task (the owner can't
            # 'reconstruct' a live put() object), so the cap first
            # evicts only aged entries. But the pin must not let a storm
            # of young copies fill /dev/shm: past the HARD cap the
            # oldest entries go regardless (a rare spurious task retry
            # beats a node out of shm).
            now = time.monotonic()
            total = sum(s for s, _ in self._staged_lru.values())
            hard = total > self.STAGED_CACHE_BYTES * self.STAGED_HARD_CAP
            for old_oid in list(self._staged_lru):
                if total <= self.STAGED_CACHE_BYTES:
                    break
                old_size, staged_at = self._staged_lru[old_oid]
                if old_oid == object_id or (
                        not hard
                        and now - staged_at < self.STAGED_PIN_S):
                    continue
                del self._staged_lru[old_oid]
                self.object_store.free(old_oid)
                total -= old_size
                hard = (total
                        > self.STAGED_CACHE_BYTES * self.STAGED_HARD_CAP)
            return ShmLocation(self.address, shm_name, size)
        except Exception:
            return None

    async def _run_task(self, spec: dict) -> None:
        controller = self.pool.get(self.controller_addr)
        self._assign_tpu_chips(spec)
        renv = spec.get("runtime_env")
        prefetch = asyncio.ensure_future(self._prefetch_args(spec))
        try:
            handle = await self._acquire_worker(runtime_env_key(renv), renv)
        except Exception as e:
            prefetch.cancel()
            await self._report_failure(spec, f"worker spawn failed: {e!r}")
            self._release_tpu_chips(spec["task_id"])
            await controller.oneway("task_finished", task_id=spec["task_id"],
                                    node_id=self.node_id)
            return
        handle.state = "busy"
        handle.current_task = spec
        try:
            # Staging overlapped worker acquisition. A short grace keeps
            # a warm-pool dispatch from waiting on a wedged peer or a
            # multi-GiB pull — past it the worker fetches its own args,
            # while the staging keeps running DETACHED (deduped via
            # _staging_inflight): cancelling would throw away the bytes
            # already pulled AND could abandon a mid-write arena object
            # with no owner; letting it land serves the next task.
            locs = await asyncio.wait_for(
                asyncio.shield(prefetch),
                timeout=self.PREFETCH_DISPATCH_GRACE_S)
            if locs:
                spec["_arg_locations"] = locs
        except asyncio.CancelledError:
            prefetch.cancel()
            raise            # _run_task itself was cancelled: unwind
        except Exception:    # TimeoutError included: leave it running
            pass
        if spec.get("is_actor_creation"):
            handle.state = "actor"
            handle.actor_id = spec["actor_id"]
            try:
                reply = await self.pool.get(handle.addr).call(
                    "create_actor", spec=spec)
            except Exception as e:
                self._release_tpu_chips(spec["task_id"])
                if handle.oom_reason:
                    # The memory monitor killed this worker and already
                    # sent actor_died (restart-eligible); reporting
                    # creation-failed too would mark the actor DEAD and
                    # burn the restart the first report queued.
                    return
                await controller.oneway(
                    "actor_creation_failed", actor_id=spec["actor_id"],
                    reason=f"worker died during actor creation: {e!r}")
                await self._report_failure(
                    spec, f"actor creation crashed: {e!r}")
                return
            if reply.get("status") == "ok":
                await controller.oneway(
                    "actor_started", actor_id=spec["actor_id"],
                    addr=handle.addr, worker_id=handle.worker_id)
                # Creation-task resources stay held until actor death;
                # do NOT send task_finished here.
            else:
                handle.state = "busy"
                handle.actor_id = None
                self._release_worker(handle)
                self._release_tpu_chips(spec["task_id"])
                await controller.oneway(
                    "actor_creation_failed", actor_id=spec["actor_id"],
                    reason=reply.get("error_tb", "init failed"))
                await controller.oneway(
                    "task_finished", task_id=spec["task_id"],
                    node_id=self.node_id)
        else:
            try:
                await self.pool.get(handle.addr).call(
                    "run_task", spec=spec)
            except Exception as e:
                if self._closed:
                    return  # our own shutdown cancelled the call
                from ..exceptions import OutOfMemoryError
                err = (OutOfMemoryError(handle.oom_reason)
                       if handle.oom_reason else None)
                await self._report_failure(
                    spec, f"worker crashed while running task: {e!r}",
                    error=err)
                if handle.state != "dead":
                    self._kill_proc(handle)
            else:
                self._release_worker(handle)
            self._release_tpu_chips(spec["task_id"])
            await controller.oneway("task_finished", task_id=spec["task_id"],
                                    node_id=self.node_id)

    async def _report_failure(self, spec: dict, reason: str,
                              error: Optional[Exception] = None) -> None:
        from ..exceptions import WorkerCrashedError
        try:
            await self.pool.get(spec["owner_addr"]).oneway(
                "object_ready", error=error or WorkerCrashedError(reason),
                task_id=spec["task_id"],
                object_ids=spec.get("return_ids") or [spec["return_id"]])
        except Exception:
            pass

    def _worker_log_path(self, worker_id: str) -> str:
        return os.path.join(self.temp_dir, "logs",
                            f"worker-{worker_id[:12]}.log")

    async def _pump_worker_logs(self, controller) -> None:
        """Publish new worker-log lines (bounded per tick) to the driver
        log topic (reference parity: _private/log_monitor.py tailing)."""
        for handle in list(self.workers.values()):
            await self._pump_one_log(controller, handle)

    async def _pump_one_log(self, controller, handle,
                            final: bool = False) -> None:
        """Tail one worker's log. `final` (worker died) drains to EOF
        including an unterminated last line — the crash output matters
        most; otherwise partial lines are held for the next tick."""
        path = self._worker_log_path(handle.worker_id)
        for _ in range(64 if final else 4):      # chunks per tick, bounded
            offset = self._log_offsets.get(handle.worker_id, 0)
            try:
                if os.path.getsize(path) <= offset:
                    return
                with open(path, "rb") as f:
                    f.seek(offset)
                    chunk = f.read(64 << 10)
            except OSError:
                return
            full = len(chunk) == (64 << 10)
            cut = chunk.rfind(b"\n")
            if cut < 0:
                if not (final or full):
                    return                       # hold the partial line
                cut = len(chunk) - 1
            elif final and cut < len(chunk) - 1:
                cut = len(chunk) - 1             # flush the tail too
            self._log_offsets[handle.worker_id] = offset + cut + 1
            text = chunk[:cut + 1].decode("utf-8", errors="replace")
            try:
                await controller.oneway(
                    "publish", topic="__worker_logs__",
                    message={"node_id": self.node_id, "pid": handle.pid,
                             "worker_id": handle.worker_id, "data": text})
            except Exception:
                return
            if not full:
                return

    async def _check_memory_pressure(self) -> None:
        """Kill one worker per tick while above the threshold (reference
        parity: memory_monitor.h:52 + worker_killing_policy.h:39)."""
        if self.memory_threshold <= 0:
            return
        try:
            used, total = self.memory_usage_fn()
        except Exception:
            return
        if total <= 0 or used / total < self.memory_threshold:
            return
        victim = pick_worker_to_kill(list(self.workers.values()))
        if victim is None:
            return
        reason = (f"node memory pressure: {used / total:.0%} of "
                  f"{total >> 20} MiB used (threshold "
                  f"{self.memory_threshold:.0%}); killed "
                  f"{'actor' if victim.state == 'actor' else 'task'} "
                  f"worker {victim.pid} per the newest-retriable-first "
                  f"policy")
        logger.warning("OOM kill: %s", reason)
        self.oom_kills += 1
        spec = victim.current_task
        is_actor = victim.state == "actor"
        # Busy-task reporting happens exactly once, in _run_task's except
        # branch when the killed worker's run_task RPC aborts — oom_reason
        # upgrades that report to OutOfMemoryError. Reporting here too
        # would double-consume retries and double-submit the task.
        victim.oom_reason = reason
        self._kill_proc(victim)
        if is_actor and victim.actor_id:
            if spec is not None:
                self._release_tpu_chips(spec["task_id"])
            try:
                await self.pool.get(self.controller_addr).oneway(
                    "actor_died", actor_id=victim.actor_id, reason=reason)
            except Exception:
                pass

    # ------------------------------------------------- placement bundles

    PG_PREPARE_TTL_S = 30.0

    async def rpc_prepare_bundles(self, pg_id: str, bundles: list) -> dict:
        """Phase 1 of the bundle 2PC: tentatively hold the bundles.
        Expires after PG_PREPARE_TTL_S if no commit arrives (controller
        died mid-2PC)."""
        if self.draining or self._closed:
            return {"ok": False}
        self._pg_prepared[pg_id] = (list(bundles), time.monotonic())
        return {"ok": True}

    async def rpc_commit_bundles(self, pg_id: str) -> dict:
        ent = self._pg_prepared.pop(pg_id, None)
        if ent is None:
            return {"ok": False}
        self._pg_bundles.setdefault(pg_id, []).extend(ent[0])
        return {"ok": True}

    async def rpc_release_bundles(self, pg_id: str) -> dict:
        self._pg_prepared.pop(pg_id, None)
        self._pg_bundles.pop(pg_id, None)
        return {"ok": True}

    def _sweep_prepared_bundles(self) -> None:
        now = time.monotonic()
        for pg_id, (_, ts) in list(self._pg_prepared.items()):
            if now - ts > self.PG_PREPARE_TTL_S:
                self._pg_prepared.pop(pg_id, None)

    def _credit_actor_slot(self, actor_id: str) -> None:
        """Return a locally-created actor's delegated-block slot on its
        death (no-op for scheduled actors; unbacked slots — shed by a
        controller-restart reconciliation — credit nothing)."""
        slot_cpu = self._local_actor_slots.pop(actor_id, None)
        unbacked = actor_id in self._unbacked_actor_slots
        self._unbacked_actor_slots.discard(actor_id)
        if slot_cpu is not None and not unbacked:
            self._lease_blocks[slot_cpu] = (
                self._lease_blocks.get(slot_cpu, 0) + 1)
            self._lease_activity = time.monotonic()

    async def rpc_kill_actor_worker(self, actor_id: str) -> bool:
        for handle in self.workers.values():
            if handle.actor_id == actor_id and handle.state == "actor":
                if handle.current_task is not None:
                    self._release_tpu_chips(handle.current_task["task_id"])
                self._kill_proc(handle)
                self._credit_actor_slot(actor_id)
                return True
        return False

    # -------------------------------------------------------------- objects

    async def rpc_register_object(self, object_id: str, shm_name: str,
                                  size: int) -> None:
        self.object_store.register(object_id, shm_name, size)

    async def rpc_fetch_object(self, object_id: str) -> Optional[bytes]:
        return await asyncio.get_running_loop().run_in_executor(
            None, self.object_store.read_bytes, object_id)

    async def rpc_fetch_object_meta(self, object_id: str) -> Optional[dict]:
        size = self.object_store.size_of(object_id)
        return None if size is None else {"size": size}

    async def rpc_fetch_object_chunk(self, object_id: str, offset: int,
                                     length: int) -> Optional[bytes]:
        """One chunk of a large object (reference parity: chunked
        ObjectManager::Push/Pull, src/ray/object_manager/object_manager.h
        :208-216 + object_buffer_pool.h)."""
        return await asyncio.get_running_loop().run_in_executor(
            None, self.object_store.read_range, object_id, offset, length)

    async def rpc_ensure_arena_room(self, nbytes: int) -> int:
        """Spill until ~nbytes of arena space are free. Returns bytes
        spilled (0 = nothing to spill; caller falls back to a segment)."""
        pressure = self.object_store.arena_pressure()
        if pressure is None:
            return 0
        allocated, capacity = pressure
        deficit = nbytes - max(0, capacity - allocated)
        if deficit <= 0:
            return 0
        return await asyncio.get_running_loop().run_in_executor(
            None, self.object_store.spill_until, deficit)

    async def rpc_free_object(self, object_id: str) -> None:
        self.object_store.free(object_id)

    async def rpc_list_objects(self) -> List[dict]:
        """State-API view of this node's sealed shm objects."""
        return [{"object_id": oid, "size": e.size,
                 "node_id": self.node_id,
                 "backend": ("arena" if e.shm_name.startswith("arena:")
                             else "segment")}
                for oid, e in self.object_store._entries.items()]

    async def rpc_node_stacks(self) -> str:
        """Thread stacks of this daemon's process and every live worker
        (py-spy-equivalent; reference: reporter profile_manager)."""
        from ..util.profiling import dump_stacks
        parts = [f"=== daemon {self.node_id[:12]} (pid {os.getpid()}) ===",
                 dump_stacks()]
        for handle in list(self.workers.values()):
            if handle.state == "dead" or handle.addr is None:
                continue
            parts.append(f"=== worker pid {handle.pid} "
                         f"({handle.state}) ===")
            try:
                parts.append(await asyncio.wait_for(
                    self.pool.get(handle.addr).call("dump_stacks"), 5.0))
            except Exception as e:
                parts.append(f"<unreachable: {e!r}>")
        return "\n".join(parts)

    def _stats(self) -> dict:
        """One stats block for both the node-stats RPC and the gossiped
        view — a single source so the two can't drift."""
        return {
            "num_workers": len([w for w in self.workers.values()
                                if w.state != "dead"]),
            "num_idle": sum(len(v) for v in self.idle.values()),
            "object_store_objects": self.object_store.num_objects,
            "object_store_bytes": self.object_store.bytes_used,
            "bytes_spilled": self.object_store.bytes_spilled,
            "objects_spilled": self.object_store.objects_spilled,
            "oom_kills": self.oom_kills,
            # distributed dispatch: leases granted without a controller
            # round-trip, spills to it, and currently-free block slots
            "local_leases_granted": self.local_leases_granted,
            "local_leases_spilled": self.local_leases_spilled,
            "lease_block_free": sum(self._lease_blocks.values()),
            # one native snapshot (one arena-mutex acquisition) feeds
            # both the pressure fraction and the flat arena_* counters
            # that ride gossip into the /metrics node gauges — the
            # native stats pipeline (reference role:
            # src/ray/stats/metric_defs.h)
            **self._arena_stat_block(),
        }

    def _arena_stat_block(self) -> dict:
        ns = self.object_store.native_stats()
        cap = ns.get("heap_capacity", 0)
        return {
            "arena_pressure": (ns.get("bytes_allocated", 0) / cap
                               if cap else 0.0),
            **{f"arena_{k}": v for k, v in ns.items()
               if k in ("allocs", "alloc_fails", "frees", "coalesces",
                        "crash_sweeps")},
        }

    async def rpc_node_stats(self) -> dict:
        return {"node_id": self.node_id, **self._stats()}

    # ------------------------------------------------------------- monitor

    def _build_view(self) -> dict:
        """Local state snapshot for the gossip channel. Versioned: the
        monitor loop only ships it when it differs from the last one."""
        return {"stats": self._stats(),
                "resources_total": dict(self.resources),
                "draining": self.draining}

    def _apply_commands(self, commands) -> None:
        """Heartbeat-reply command channel (reference parity: ray_syncer
        COMMANDS + raylet DrainRaylet)."""
        for cmd in commands or []:
            if cmd.get("seq", 0) <= self._cmd_applied:
                continue          # redelivered duplicate
            kind = cmd.get("type")
            if kind == "drain":
                self.draining = True
            elif kind == "set_resource":
                name, cap = cmd["name"], cmd["capacity"]
                if cap <= 0:
                    self.resources.pop(name, None)
                else:
                    self.resources[name] = cap
            elif kind == "reclaim_lease_blocks":
                # scheduled work is pending cluster-side: force the next
                # sweep tick (<=0.5s) to return all free delegated slots
                self._lease_activity = 0.0
            else:
                logger.warning("unknown syncer command %r", kind)
            self._cmd_applied = cmd["seq"]

    async def _monitor_loop(self) -> None:
        controller = self.pool.get(self.controller_addr)
        from .config import get_config
        high = get_config().arena_spill_high
        low = get_config().arena_spill_low
        while not self._closed:
            await asyncio.sleep(0.5)
            try:
                view = self._build_view()
                if view != self._last_view:
                    self._sync_version += 1
                    self._last_view = view
                hb_kw = {"cmd_ack": self._cmd_applied}
                if self._sync_version > self._sync_acked:
                    hb_kw.update(sync_version=self._sync_version,
                                 view=view)
                reply = await controller.call(
                    "heartbeat", node_id=self.node_id, **hb_kw)
                if (reply or {}).get("status") == "ok":
                    self._sync_acked = reply.get(
                        "sync_ack", self._sync_acked)
                    self._apply_commands(reply.get("commands"))
                if (reply or {}).get("status") == "unknown":
                    # Controller restarted and lost volatile node state:
                    # re-register, re-announce hosted actors so its
                    # persisted actor table gets fresh addresses, and
                    # report actors it expects here that died during the
                    # outage (their actor_died was lost).
                    reg = await controller.call(
                        "register_node", node_id=self.node_id,
                        addr=self.address, resources=self.resources,
                        labels=self.labels,
                        # committed-bundle ledger: the fresh controller
                        # audits it against its persisted PG table
                        pg_bundles=self._pg_bundles)
                    for pg_id in (reg or {}).get("release_pgs", []):
                        self._pg_bundles.pop(pg_id, None)
                    # fresh controller: resync view, restart command seqs
                    # (its new NodeEntry numbers commands from 1 again)
                    self._sync_acked = 0
                    self._cmd_applied = 0
                    hosted = set()
                    for h in list(self.workers.values()):
                        if h.state == "actor" and h.actor_id:
                            hosted.add(h.actor_id)
                            ack = await controller.call(
                                "actor_started", actor_id=h.actor_id,
                                addr=h.addr, worker_id=h.worker_id,
                                # spec rides along so a controller that
                                # never saw this (locally-created) actor
                                # can still rebuild the directory entry
                                spec=h.current_task,
                                node_id=self.node_id)
                            if (ack or {}).get("status") == "superseded":
                                # a replacement is already queued/running;
                                # two live incarnations must never coexist
                                if h.current_task is not None:
                                    self._release_tpu_chips(
                                        h.current_task["task_id"])
                                self._kill_proc(h)
                    for aid in (reg or {}).get("expected_actors", []):
                        if aid not in hosted:
                            await controller.oneway(
                                "actor_died", actor_id=aid,
                                reason="worker died while the controller "
                                       "was down")
                    # the fresh NodeEntry knows nothing of our delegated
                    # lease slots: re-acquire or shed them
                    await self._reconcile_delegations()
            except Exception:
                pass
            # arena pressure: spill LRU sealed objects down to the low
            # water mark so allocations keep landing in shared memory.
            # Bounded per tick (256 MB) so a huge arena drain can't pause
            # this loop's heartbeats past the controller's node timeout.
            pressure = self.object_store.arena_pressure()
            if pressure is not None:
                allocated, capacity = pressure
                if capacity > 0 and allocated / capacity > high:
                    target = min(int(allocated - low * capacity), 256 << 20)
                    await asyncio.get_running_loop().run_in_executor(
                        None, self.object_store.spill_until, target)
            await self._check_memory_pressure()
            try:
                await self._local_lease_sweep()
                self._sweep_prepared_bundles()
            except Exception:
                pass
            await self._pump_worker_logs(controller)
            for handle in list(self.workers.values()):
                if handle.state == "dead":
                    await self._settle_leased_death(handle)
                    await self._pump_one_log(controller, handle,
                                             final=True)
                    self._log_offsets.pop(handle.worker_id, None)
                    self.workers.pop(handle.worker_id, None)
                    continue
                if handle.proc.poll() is not None:
                    await self._pump_one_log(controller, handle,
                                             final=True)
                    self._log_offsets.pop(handle.worker_id, None)
                    prev_state = handle.state
                    handle.state = "dead"
                    pool = self.idle.get(handle.env_key, [])
                    if handle.worker_id in pool:
                        pool.remove(handle.worker_id)
                    spec = handle.current_task
                    if spec is not None:
                        self._release_tpu_chips(spec["task_id"])
                    if prev_state == "actor" and handle.actor_id:
                        # locally-created actor: its delegated-block
                        # slot frees with the worker (unless shed by a
                        # controller-restart reconciliation)
                        self._credit_actor_slot(handle.actor_id)
                        try:
                            await controller.oneway(
                                "actor_died", actor_id=handle.actor_id,
                                reason=f"worker process {handle.pid} exited "
                                       f"with code {handle.proc.returncode}")
                        except Exception:
                            pass
                    elif prev_state == "busy" and spec is not None:
                        await self._report_failure(
                            spec, f"worker process {handle.pid} died "
                                  f"(exit code {handle.proc.returncode})")
                        try:
                            await controller.oneway(
                                "task_finished", task_id=spec["task_id"],
                                node_id=self.node_id)
                        except Exception:
                            pass
                    self.workers.pop(handle.worker_id, None)


async def _standalone_main(args) -> None:
    host, port = args.controller.rsplit(":", 1)
    daemon = NodeDaemon(
        controller_addr=(host, int(port)),
        session_name=args.session,
        resources=dict(eval(args.resources)) if args.resources else None,
        node_id=args.node_id or None)
    await daemon.start()
    print(f"ray_tpu daemon {daemon.node_id} at {daemon.address}", flush=True)
    while True:
        await asyncio.sleep(3600)


def main() -> None:
    import argparse
    parser = argparse.ArgumentParser()
    parser.add_argument("--controller", required=True)
    parser.add_argument("--session", required=True)
    parser.add_argument("--resources", default="")
    parser.add_argument("--node-id", default="")
    args = parser.parse_args()
    asyncio.run(_standalone_main(args))


if __name__ == "__main__":
    main()
