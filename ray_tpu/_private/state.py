"""Process-global runtime state (the connected client, if any)."""

from __future__ import annotations

from typing import Optional

_client = None


def set_client(client) -> None:
    global _client
    _client = client


def current_client_or_none():
    return _client


def current_client():
    if _client is None:
        raise RuntimeError(
            "ray_tpu is not initialized; call ray_tpu.init() first.")
    return _client


def is_initialized() -> bool:
    return _client is not None
