"""Driver-side bootstrap: ray_tpu.init()/shutdown() and the head node.

Reference parity: python/ray/_private/worker.py (ray.init :1285, connect
:2279, shutdown :1901) + node.py — collapsed: the controller and the head
node daemon run inside the driver process's background event loop (worker
processes are real subprocesses), so bring-up is milliseconds and teardown
is deterministic. Additional daemons (real or fake multi-node) register
with the same controller over TCP.
"""

from __future__ import annotations

import atexit
import os
import time
from typing import Dict, List, Optional

from .controller import Controller
from .core import CoreClient, LoopRunner
from .daemon import NodeDaemon
from . import state

_runtime = None


class Runtime:
    """Everything owned by this driver's session."""

    def __init__(self, client: CoreClient, controller: Controller,
                 head_daemon: Optional[NodeDaemon], loop_runner: LoopRunner,
                 session_name: str):
        self.client = client
        self.controller = controller
        self.head_daemon = head_daemon
        self.loop_runner = loop_runner
        self.session_name = session_name
        self.extra_daemons: List[NodeDaemon] = []
        self.usage_reporter = None   # UsageReporter when stats enabled


def init(address: Optional[str] = None,
         num_cpus: Optional[float] = None,
         num_tpus: Optional[float] = None,
         resources: Optional[Dict[str, float]] = None,
         labels: Optional[Dict[str, str]] = None,
         namespace: str = "default",
         ignore_reinit_error: bool = False,
         local_mode: bool = False,
         log_to_driver: Optional[bool] = None,
         _prestart_workers: int = 0,
         **_ignored) -> "Runtime":
    global _runtime
    if _runtime is not None:
        if ignore_reinit_error:
            return _runtime
        raise RuntimeError("ray_tpu.init() called twice; "
                           "pass ignore_reinit_error=True to allow.")
    if local_mode:
        from .local_mode import LocalModeClient
        client = LocalModeClient(namespace=namespace)
        state.set_client(client)
        _runtime = Runtime(client, None, None, None, "local")
        atexit.register(shutdown)
        return _runtime

    if address is not None and address.startswith("client://"):
        # thin-client attach over ONE connection through the client
        # proxy (reference parity: ray.init("ray://…") client mode);
        # no cluster-routable addresses needed on this side.
        from .client_proxy import ProxyModeClient
        hostport = address[len("client://"):]
        host, _, port = hostport.rpartition(":")
        client = ProxyModeClient(host or "127.0.0.1", int(port),
                                 namespace=namespace)
        state.set_client(client)
        _runtime = Runtime(client, None, None, client.loop_runner,
                           f"client-{client.session_id[:8]}")
        atexit.register(shutdown)
        return _runtime

    if address is not None:
        # attach to an existing cluster: address = "host:port" of the
        # controller (written to the cluster-address file by `ray_tpu
        # start --head`). The reference's client scheme "ray://host:port"
        # is accepted as an alias.
        if address.startswith("ray://"):
            address = address[len("ray://"):]
        host, _, port = address.rpartition(":")
        controller_addr = (host or "127.0.0.1", int(port))
        loop_runner = LoopRunner()

        async def _fetch_info():
            from .protocol import RpcClient
            rpc = RpcClient(*controller_addr)
            try:
                return await rpc.call("get_session_info")
            finally:
                await rpc.close()

        info = loop_runner.run_sync(_fetch_info(), timeout=15)
        daemon_addr = info.get("head_daemon_addr")
        client = CoreClient(controller_addr,
                            tuple(daemon_addr) if daemon_addr else None,
                            info["session_name"], loop_runner=loop_runner,
                            namespace=namespace)
        client.start()
        state.set_client(client)
        # Attached drivers share the cluster's single log topic with the
        # head driver, so log streaming is opt-in for them (the head
        # driver gets it by default).
        if log_to_driver:
            _attach_log_stream(client)
        _runtime = Runtime(client, None, None, loop_runner,
                           info["session_name"])
        atexit.register(shutdown)
        return _runtime

    # urandom suffix: back-to-back init/shutdown/init in one process and
    # second would otherwise reuse the session dir (and now its persisted
    # gcs.db, resurrecting the previous session's actor table).
    session_name = f"s{int(time.time())}_{os.getpid()}_{os.urandom(2).hex()}"
    loop_runner = LoopRunner()

    node_resources = dict(resources or {})
    if num_cpus is not None:
        node_resources["CPU"] = float(num_cpus)
    elif "CPU" not in node_resources:
        node_resources["CPU"] = float(os.cpu_count() or 1)
    if num_tpus is not None:
        node_resources["TPU"] = float(num_tpus)
    else:
        from ..accelerators.tpu import TPUAcceleratorManager
        detected = TPUAcceleratorManager.autodetect_resources()
        for k, v in detected.items():
            node_resources.setdefault(k, v)

    async def _bootstrap():
        # RAY_TPU_BIND_HOST=0.0.0.0 makes every server this session
        # starts (controller, daemons, driver + worker CoreClients)
        # reachable from other hosts — see protocol.RpcServer.start.
        controller = Controller(session_name)
        await controller.start()
        daemon = NodeDaemon(controller.address, session_name,
                            resources=node_resources, labels=labels)
        await daemon.start()
        return controller, daemon

    controller, head_daemon = loop_runner.run_sync(_bootstrap(), timeout=30)
    client = CoreClient(controller.address,
                        head_daemon.address if head_daemon else None,
                        session_name, loop_runner=loop_runner,
                        namespace=namespace)
    client.start()
    state.set_client(client)
    if log_to_driver is None or log_to_driver:
        _attach_log_stream(client)
    _runtime = Runtime(client, controller, head_daemon, loop_runner,
                       session_name)
    from . import usage as _usage
    if _usage.usage_stats_enabled():
        _runtime.usage_reporter = _usage.UsageReporter(client, session_name)
        _runtime.usage_reporter.start()
    if _prestart_workers:
        loop_runner.run_sync(
            client.pool.get(head_daemon.address).call(
                "prestart_workers", count=_prestart_workers), timeout=120)
    atexit.register(shutdown)
    return _runtime




def _attach_log_stream(client) -> None:
    """Print worker log lines on the driver (reference parity:
    log_monitor + worker_process_out streaming to the driver)."""
    import sys

    def _print(message):
        try:
            pid = message.get("pid")
            for line in message.get("data", "").splitlines():
                sys.stderr.write(f"(worker pid={pid}) {line}\n")
            sys.stderr.flush()
        except Exception:
            pass

    client.subscribe("__worker_logs__", _print)

def shutdown() -> None:
    global _runtime
    if _runtime is None:
        return
    rt, _runtime = _runtime, None
    state.set_client(None)
    if rt.usage_reporter is not None:
        rt.usage_reporter.stop()           # join first: no tmp-file race
        rt.usage_reporter.report_once()    # final snapshot
    if rt.loop_runner is None:   # local mode
        return
    rt.client.is_shutdown = True

    async def _teardown():
        for d in rt.extra_daemons:
            try:
                await d.stop()
            except Exception:
                pass
        if rt.head_daemon is not None:
            await rt.head_daemon.stop()
        if rt.controller is not None:
            await rt.controller.stop()

    for proxy in getattr(rt, "client_proxies", []):
        try:
            proxy.stop()
        except Exception:
            pass
    try:
        rt.loop_runner.run_sync(_teardown(), timeout=10)
    except Exception:
        pass
    try:
        rt.client.shutdown()
    except Exception:
        pass
    # session-wide arena teardown — only when this process OWNS the
    # session (attached drivers must not yank the arena from a live head)
    if rt.controller is not None:
        try:
            from .object_store import unlink_session_arena
            unlink_session_arena(rt.client.session_name)
        except Exception:
            pass
    rt.loop_runner.stop()
    try:
        atexit.unregister(shutdown)
    except Exception:
        pass


def current_runtime() -> Optional[Runtime]:
    return _runtime


def start_client_proxy(port: int = 10001, host: Optional[str] = None):
    """Start the Ray-Client-equivalent proxy on this driver/head
    (reference parity: the ray:// client server). Thin clients attach
    with init(address="client://host:port"). Returns (host, port)."""
    rt = _runtime
    if rt is None or rt.loop_runner is None:
        raise RuntimeError("init() a non-local session first")
    from .client_proxy import ClientProxyServer
    server = ClientProxyServer(rt.client, host=host, port=port)
    addr = server.start()
    rt.client_proxies = getattr(rt, "client_proxies", [])
    rt.client_proxies.append(server)
    return addr


def add_fake_node(num_cpus: float = 1.0,
                  resources: Optional[Dict[str, float]] = None,
                  labels: Optional[Dict[str, str]] = None) -> str:
    """Register an extra in-process node daemon (multi-node testing).

    Reference parity: python/ray/cluster_utils.py:135 (Cluster.add_node) —
    each fake node runs a real NodeDaemon with its own worker processes.
    """
    rt = _runtime
    if rt is None or rt.controller is None:
        raise RuntimeError("init() a non-local session first")
    node_resources = dict(resources or {})
    node_resources.setdefault("CPU", num_cpus)

    async def _add():
        daemon = NodeDaemon(rt.controller.address, rt.session_name,
                            resources=node_resources, labels=labels)
        await daemon.start()
        return daemon

    daemon = rt.loop_runner.run_sync(_add(), timeout=30)
    rt.extra_daemons.append(daemon)
    return daemon.node_id


def remove_node(node_id: str) -> bool:
    """Stop a fake node's daemon (kills its workers) — chaos testing."""
    rt = _runtime
    if rt is None:
        return False
    for d in list(rt.extra_daemons):
        if d.node_id == node_id:
            async def _stop():
                await d.stop()
                await rt.controller.rpc_unregister_node(node_id=node_id)
            rt.loop_runner.run_sync(_stop(), timeout=15)
            rt.extra_daemons.remove(d)
            return True
    return False
