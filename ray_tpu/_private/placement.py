"""Placement groups: controller-side bundle reservation (two-phase).

Reference parity: src/ray/gcs/gcs_server/gcs_placement_group_manager.h:232
and the bundle scheduling policies (policy/bundle_scheduling_policy.h:82-106
— PACK / SPREAD / STRICT_PACK / STRICT_SPREAD). Our controller owns all
resource accounting, so prepare/commit is atomic by construction; the
prepare/commit split is kept in the data model for when daemons hold
authoritative local state.

Tasks/actors scheduled into a PG consume from the bundle's reservation,
not from node-available resources.
"""

from __future__ import annotations

import asyncio
from typing import Dict, List, Optional, Tuple

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class Bundle:
    __slots__ = ("index", "resources", "node_id", "available")

    def __init__(self, index: int, resources: Dict[str, float]):
        self.index = index
        self.resources = dict(resources)
        self.node_id: Optional[str] = None
        self.available = dict(resources)   # remaining capacity inside bundle

    def fits(self, req: Dict[str, float]) -> bool:
        return all(self.available.get(k, 0.0) + 1e-9 >= v
                   for k, v in req.items())

    def acquire(self, req: Dict[str, float]) -> None:
        for k, v in req.items():
            self.available[k] = self.available.get(k, 0.0) - v

    def release(self, req: Dict[str, float]) -> None:
        for k, v in req.items():
            self.available[k] = min(self.resources.get(k, 0.0),
                                    self.available.get(k, 0.0) + v)


class PlacementGroupEntry:
    def __init__(self, pg_id: str, bundles: List[Dict[str, float]],
                 strategy: str, name: str = ""):
        if strategy not in VALID_STRATEGIES:
            raise ValueError(f"invalid strategy {strategy!r}; "
                             f"one of {VALID_STRATEGIES}")
        self.pg_id = pg_id
        self.strategy = strategy
        self.name = name
        self.bundles = [Bundle(i, b) for i, b in enumerate(bundles)]
        self.state = "PENDING"           # PENDING | CREATED | REMOVED | FAILED
        self.failure_reason = ""
        self.waiters: List[asyncio.Event] = []
        # task_id -> (bundle_index, resources) for release on completion
        self.task_usage: Dict[str, Tuple[int, Dict[str, float]]] = {}

    # ------------------------------------------------------------ placement

    def choose_nodes(self, nodes: List):
        """Pick a node per bundle from the controller's availability
        view WITHOUT committing. Returns (chosen, "") on success or
        (None, reason) — "" retry later, non-empty never placeable.
        """
        alive = [n for n in nodes if n.alive]
        # Each attempt re-derives the infeasibility note from the CURRENT
        # nodes — clear any stale reason from earlier cluster states.
        self.failure_reason = ""
        # Infeasibility note (every strategy): a bundle larger than every
        # CURRENT node's total capacity. Reference semantics keep the PG
        # PENDING (the cluster may grow / late nodes may register), so this
        # is retryable — the reason is recorded for ready()-timeout
        # messages and the state API.
        for i, b in enumerate(self.bundles):
            if alive and not any(
                    all(n.resources_total.get(k, 0.0) + 1e-9 >= v
                        for k, v in b.resources.items()) for n in alive):
                self.failure_reason = (
                    f"bundle {i} {b.resources} exceeds every alive node's "
                    f"total capacity (cluster may still be scaling up)")
                return None, ""
        # Work on a scratch copy of availability so failed prepares roll back.
        scratch = {n.node_id: dict(n.resources_avail) for n in alive}

        def fits(node, req):
            return all(scratch[node.node_id].get(k, 0.0) + 1e-9 >= v
                       for k, v in req.items())

        def take(node, req):
            for k, v in req.items():
                scratch[node.node_id][k] = \
                    scratch[node.node_id].get(k, 0.0) - v

        chosen: List[Optional[str]] = [None] * len(self.bundles)
        if self.strategy in ("STRICT_PACK", "PACK"):
            # Try to fit everything on one node first.
            packed = None
            for n in alive:
                ok = True
                snap = dict(scratch[n.node_id])
                for b in self.bundles:
                    if fits(n, b.resources):
                        take(n, b.resources)
                    else:
                        ok = False
                        break
                if ok:
                    packed = n.node_id
                    break
                scratch[n.node_id] = snap
            if packed is not None:
                chosen = [packed] * len(self.bundles)
            elif self.strategy == "STRICT_PACK":
                if not self._feasible_on_one_node(alive):
                    self.failure_reason = (
                        "STRICT_PACK infeasible on current nodes: no "
                        "single node can hold all bundles")
                return None, ""         # retry when resources free up
            else:
                # PACK soft-fallback: greedy first-fit across nodes.
                chosen = self._greedy(alive, scratch, fits, take)
                if chosen is None:
                    return None, ""
        elif self.strategy in ("STRICT_SPREAD", "SPREAD"):
            used_nodes: set = set()
            for i, b in enumerate(self.bundles):
                cand = [n for n in alive
                        if n.node_id not in used_nodes and fits(n, b.resources)]
                if not cand and self.strategy == "SPREAD":
                    cand = [n for n in alive if fits(n, b.resources)]
                if not cand:
                    if self.strategy == "STRICT_SPREAD" \
                            and len(alive) < len(self.bundles):
                        self.failure_reason = (
                            "STRICT_SPREAD infeasible on current nodes: "
                            f"{len(self.bundles)} bundles > "
                            f"{len(alive)} nodes")
                    return None, ""     # retry when nodes join/free up
                node = max(cand, key=lambda n: sum(
                    scratch[n.node_id].get(k, 0.0) for k in b.resources))
                chosen[i] = node.node_id
                used_nodes.add(node.node_id)
                take(node, b.resources)

        return chosen, ""

    def commit(self, chosen: List[str], nodes_by_id: Dict) -> None:
        """Deduct the chosen bundles from the controller's node
        availability and pin the assignments (does NOT flip state —
        the caller sets CREATED once the daemon-side reservation is
        confirmed)."""
        for b, node_id in zip(self.bundles, chosen):
            b.node_id = node_id
            node = nodes_by_id.get(node_id)
            if node is not None:
                node.acquire(b.resources)

    def _wake(self) -> None:
        for ev in self.waiters:
            ev.set()
        self.waiters.clear()

    def fail(self, reason: str) -> None:
        self.state = "FAILED"
        self.failure_reason = reason
        self._wake()

    def mark_removed(self) -> None:
        self.state = "REMOVED"
        self._wake()

    def _greedy(self, alive, scratch, fits, take):
        chosen = []
        for b in self.bundles:
            cand = [n for n in alive if fits(n, b.resources)]
            if not cand:
                return None
            node = cand[0]
            chosen.append(node.node_id)
            take(node, b.resources)
        return chosen

    def _feasible_on_one_node(self, alive) -> bool:
        total: Dict[str, float] = {}
        for b in self.bundles:
            for k, v in b.resources.items():
                total[k] = total.get(k, 0.0) + v
        return any(all(n.resources_total.get(k, 0.0) + 1e-9 >= v
                       for k, v in total.items()) for n in alive)

    # ------------------------------------------------------------ task use

    def resolve_bundle(self, bundle_index: int, req: Dict[str, float]):
        """Pick a bundle for a task. Returns (node_id, bundle_index) or
        ('__pending__', None) while the PG is still being placed, or
        (None, None) if the PG is gone/unsatisfiable."""
        if self.state == "PENDING":
            return "__pending__", None
        if self.state != "CREATED":
            return None, None
        def exceeds_total(b):
            return any(b.resources.get(k, 0.0) + 1e-9 < v
                       for k, v in req.items())

        if bundle_index is not None and bundle_index >= 0:
            if bundle_index >= len(self.bundles):
                return None, None
            b = self.bundles[bundle_index]
            if exceeds_total(b):
                return None, None          # can never fit: fail fast
            return (b.node_id, b.index) if b.fits(req) else ("__pending__", None)
        for b in self.bundles:
            if b.fits(req):
                return b.node_id, b.index
        if all(exceeds_total(b) for b in self.bundles):
            return None, None
        return "__pending__", None

    def acquire_for_task(self, task_id: str, bundle_index: int,
                         req: Dict[str, float]) -> None:
        b = self.bundles[bundle_index]
        b.acquire(req)
        self.task_usage[task_id] = (bundle_index, dict(req))

    def release_for_task(self, task_id: str) -> None:
        entry = self.task_usage.pop(task_id, None)
        if entry is not None:
            self.bundles[entry[0]].release(entry[1])

    def release_all(self, nodes_by_id: Dict) -> None:
        """Return every bundle's reservation to its node (PG removal)."""
        for b in self.bundles:
            node = nodes_by_id.get(b.node_id)
            if node is not None:
                node.release(b.resources)
        self.mark_removed()

    def to_dict(self) -> dict:
        return {
            "placement_group_id": self.pg_id,
            "name": self.name,
            "strategy": self.strategy,
            "state": self.state,
            "bundles": [{"bundle_index": b.index, "resources": b.resources,
                         "node_id": b.node_id} for b in self.bundles],
        }
