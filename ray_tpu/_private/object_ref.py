"""ObjectRef: a first-class future for a value owned by some process.

Reference parity: ray.ObjectRef (python/ray/includes/object_ref.pxi) and the
ownership model of src/ray/core_worker/reference_count.h — the process that
created a ref (by task submission or put) owns the value and serves it to
borrowers; borrowers notify the owner on deserialize/del so the owner can
free the value when the distributed count reaches zero.
"""

from __future__ import annotations

from typing import Optional, Tuple


class ObjectRef:
    __slots__ = ("id", "owner_addr", "_client", "__weakref__")

    def __init__(self, object_id: str, owner_addr: Tuple[str, int],
                 _client=None, _borrowed: bool = False):
        self.id = object_id
        self.owner_addr = tuple(owner_addr) if owner_addr else None
        if _client is None:
            from . import state
            _client = state.current_client_or_none()
        self._client = _client
        if _client is not None:
            _client.ref_counter.add_local_ref(self.id, self.owner_addr,
                                              borrowed=_borrowed)

    def hex(self) -> str:
        return self.id

    def future(self):
        """Return a concurrent.futures.Future resolving to the value."""
        return self._client.as_future(self)

    def __await__(self):
        """Allow `await ref` inside async actors."""
        return self._client.aio_get(self).__await__()

    def __reduce__(self):
        return (_deserialize_ref, (self.id, self.owner_addr))

    def __eq__(self, other) -> bool:
        return isinstance(other, ObjectRef) and other.id == self.id

    def __hash__(self) -> int:
        return hash(self.id)

    def __repr__(self) -> str:
        return f"ObjectRef({self.id[:16]})"

    def __del__(self):
        client = self._client
        if client is not None:
            try:
                client.ref_counter.remove_local_ref(self.id)
            except Exception:
                pass


def _deserialize_ref(object_id: str, owner_addr) -> ObjectRef:
    return ObjectRef(object_id, owner_addr, _borrowed=True)
