"""Object stores: per-process memory store + per-node shared-memory store.

Reference parity:
- memory store: src/ray/core_worker/store_provider/memory_store/memory_store.h
  (small objects / direct-call returns live in the owner process).
- shm store: src/ray/object_manager/plasma/store.h — ours maps each large
  object to one POSIX shared-memory segment (multiprocessing.shared_memory)
  registered with the node daemon, which owns lifecycle (free/unlink) and
  serves cross-node fetches. Readers attach and deserialize zero-copy:
  numpy arrays reference the mapped segment directly.
"""

from __future__ import annotations

import asyncio
import hashlib
import functools as _functools
import os
import threading
from multiprocessing import resource_tracker, shared_memory
from typing import Any, Dict, Optional, Tuple

from .serialization import SerializedObject

_MISSING = object()

# ------------------------------------------------------------------ arena
# Native C++ shared-memory arena (src/arena_store.cc) — the plasma-
# equivalent data plane. One arena per session machine-wide; the first
# node daemon creates it, workers/drivers attach lazily. When the native
# lib is unavailable (or the arena is full), the per-object-segment path
# below is the fallback.

ARENA_DEFAULT_BYTES = int(os.environ.get("RAY_TPU_ARENA_BYTES",
                                         256 << 20))

_arenas: Dict[str, Any] = {}      # attached arenas by segment name


def arena_name_for(session_name: str) -> str:
    # full session name (timestamp+pid): arena names must never collide
    # across concurrent sessions on one machine
    return f"rtpu_{session_name}_arena"


def _native_arena_mod():
    if os.environ.get("RAY_TPU_DISABLE_NATIVE_ARENA"):
        return None
    try:
        from .._native import arena as native_arena
    except Exception:
        return None
    return native_arena if native_arena.available() else None


def attach_arena(name: str):
    mod = _native_arena_mod()
    if mod is None:
        return None
    a = _arenas.get(name)
    if a is None:
        a = mod.Arena.attach(name)
        if a is not None:
            _arenas[name] = a
    return a


def create_arena(session_name: str) -> Optional[Any]:
    """Daemon-side: create the session arena, or attach if another
    daemon on this machine already created it."""
    mod = _native_arena_mod()
    if mod is None:
        return None
    name = arena_name_for(session_name)
    a = _arenas.get(name)
    if a is None:
        a, _ = mod.Arena.create_or_attach(name, ARENA_DEFAULT_BYTES)
        if a is not None:
            _arenas[name] = a
    return a


def unlink_session_arena(session_name: str) -> None:
    """Session-wide teardown (driver shutdown): remove the arena segment.
    Node-level daemon stops must NOT do this — other daemons of the same
    session still serve objects out of it."""
    name = arena_name_for(session_name)
    _arenas.pop(name, None)
    try:
        from .._native.arena import Arena, available
        if available():
            a = Arena.attach(name)
            if a is not None:
                a.unlink()
                a.detach()
    except Exception:
        pass


def segment_name(session_name: str, object_id: str) -> str:
    """Canonical shm segment name (POSIX shm names cap ~250 chars and must
    be unique machine-wide). Hash the id rather than truncate it:
    structured ids (e.g. streaming items "<gen_id>_<n>") differ only past
    the truncation point and would collide."""
    digest = hashlib.sha1(object_id.encode()).hexdigest()[:24]
    return f"rtpu_{session_name[:8]}_{digest}"


class _ViewTolerantSharedMemory(shared_memory.SharedMemory):
    """SharedMemory whose teardown tolerates live zero-copy views.

    Readers deserialize directly out of the mapping (numpy arrays view
    shm.buf), so at GC time the handle can be collected while exported
    views still exist — stock close() then raises "BufferError: cannot
    close exported pointers exist". Skipping the eager close is safe:
    the OS mapping is released once the mmap object and every view into
    it are collected.
    """

    def close(self):
        try:
            super().close()
        except BufferError:
            # Views still alive: the mmap must be left to the GC, but the
            # fd is released NOW — mmap holds its own dup of it, so
            # skipping os.close here would leak one fd per segment until
            # EMFILE takes down the process's sockets.
            self._buf = None
            self._mmap = None
            fd = getattr(self, "_fd", -1)
            if fd >= 0:
                try:
                    os.close(fd)
                except OSError:
                    pass
                self._fd = -1


def create_untracked_shm(name: str, size: int) -> shared_memory.SharedMemory:
    """Create a shm segment not owned by this process's resource tracker.

    Workers create segments but the node daemon owns their lifecycle; without
    unregistering, a worker exiting would unlink segments that must outlive it.
    """
    shm = _ViewTolerantSharedMemory(name=name, create=True, size=size)
    try:
        resource_tracker.unregister(shm._name, "shared_memory")  # type: ignore[attr-defined]
    except Exception:
        pass
    return shm


def _unlink_shm(name: str) -> None:
    """Unlink a segment without touching any resource tracker.

    Both create_untracked_shm and attach_shm unregister from the tracker
    (segment lifecycle belongs to the node daemon, not to whichever process
    happens to exit first), so SharedMemory.unlink()'s internal unregister
    would hit a tracker cache miss. Unlink at the POSIX level instead.
    """
    try:
        from multiprocessing import shared_memory as _sm
        _sm._posixshmem.shm_unlink("/" + name if not name.startswith("/")
                                   else name)
    except FileNotFoundError:
        pass
    except Exception:
        pass


def attach_shm(name: str) -> shared_memory.SharedMemory:
    shm = _ViewTolerantSharedMemory(name=name)
    try:
        resource_tracker.unregister(shm._name, "shared_memory")  # type: ignore[attr-defined]
    except Exception:
        pass
    return shm


class ShmLocation:
    """Where a large object's bytes live: a segment on some node."""

    __slots__ = ("node_addr", "shm_name", "size")

    def __init__(self, node_addr: Tuple[str, int], shm_name: str, size: int):
        self.node_addr = tuple(node_addr)
        self.shm_name = shm_name
        self.size = size

    def __reduce__(self):
        return (ShmLocation, (self.node_addr, self.shm_name, self.size))


class MemoryStoreEntry:
    __slots__ = ("serialized", "location", "value", "has_value", "is_error",
                 "shm_keepalive")

    def __init__(self):
        self.serialized: Optional[SerializedObject] = None
        self.location: Optional[ShmLocation] = None
        self.value: Any = _MISSING
        self.has_value = False
        self.is_error = False
        self.shm_keepalive = None  # keeps mapped segment alive while value cached


class MemoryStore:
    """Owner-process store: inline values or locations of shm-backed ones."""

    def __init__(self):
        self._entries: Dict[str, MemoryStoreEntry] = {}
        self._events: Dict[str, asyncio.Event] = {}

    def contains(self, object_id: str) -> bool:
        return object_id in self._entries

    def put_serialized(self, object_id: str, serialized: SerializedObject) -> None:
        entry = self._entries.setdefault(object_id, MemoryStoreEntry())
        self._clear_error(entry)
        entry.serialized = serialized
        self._signal(object_id)

    def put_value(self, object_id: str, value: Any,
                  serialized: Optional[SerializedObject] = None) -> None:
        entry = self._entries.setdefault(object_id, MemoryStoreEntry())
        self._clear_error(entry)
        entry.value = value
        entry.has_value = True
        entry.serialized = serialized
        self._signal(object_id)

    def put_location(self, object_id: str, location: ShmLocation) -> None:
        entry = self._entries.setdefault(object_id, MemoryStoreEntry())
        self._clear_error(entry)
        entry.location = location
        self._signal(object_id)

    @staticmethod
    def _clear_error(entry: MemoryStoreEntry) -> None:
        # A successful (retried) result replaces a previously stored error.
        if entry.is_error:
            entry.is_error = False
            entry.has_value = False
            entry.value = _MISSING

    def put_error(self, object_id: str, error: Exception) -> None:
        """Store an exception as the object's value (raised on get)."""
        entry = self._entries.setdefault(object_id, MemoryStoreEntry())
        entry.value = error
        entry.has_value = True
        entry.is_error = True
        self._signal(object_id)

    def get_entry(self, object_id: str) -> Optional[MemoryStoreEntry]:
        return self._entries.get(object_id)

    def delete(self, object_id: str) -> Optional[MemoryStoreEntry]:
        self._events.pop(object_id, None)
        return self._entries.pop(object_id, None)

    def _signal(self, object_id: str) -> None:
        ev = self._events.get(object_id)
        if ev is not None:
            ev.set()

    async def wait_available(self, object_id: str,
                             timeout: Optional[float] = None) -> bool:
        if object_id in self._entries:
            return True
        ev = self._events.setdefault(object_id, asyncio.Event())
        try:
            await asyncio.wait_for(ev.wait(), timeout)
            return True
        except asyncio.TimeoutError:
            return False
        finally:
            if object_id in self._entries:
                self._events.pop(object_id, None)

    def __len__(self) -> int:
        return len(self._entries)


class ShmStoreEntry:
    __slots__ = ("shm_name", "size", "sealed", "shm", "pinned")

    def __init__(self, shm_name: str, size: int):
        self.shm_name = shm_name
        self.size = size
        self.sealed = False
        self.shm: Optional[shared_memory.SharedMemory] = None
        self.pinned = 0


class NodeObjectStore:
    """Node-daemon-side registry of shm segments holding sealed objects.

    Spilling (reference parity: src/ray/raylet/local_object_manager.h:113
    SpillObjects + python/ray/_private/external_storage.py): under arena
    pressure, sealed objects are copied to disk files and their arena/
    segment copies freed; entries become "spill:<path>" and reads restore
    from disk transparently.
    """

    def __init__(self, session_name: str, spill_dir: Optional[str] = None):
        self.session_name = session_name
        self._entries: Dict[str, ShmStoreEntry] = {}
        self._seq = 0
        from .config import session_dir
        # RAY_TPU_SPILL_STORAGE may be a URI (gs://bucket/prefix,
        # mock://spill, custom://...) — remote spill rides the same
        # pyarrow-fs layer as checkpoints (train/storage.py), matching
        # the reference's external storage backends
        # (python/ray/_private/external_storage.py:72,272,482 —
        # filesystem / S3-smart_open / mock)
        self.spill_dir = (spill_dir
                          or os.environ.get("RAY_TPU_SPILL_STORAGE")
                          or os.path.join(
                              session_dir(session_name), "spill"))
        from ..train.storage import is_uri
        self._spill_remote = is_uri(self.spill_dir)
        # Per-store namespace in the remote key: every node may share
        # one RAY_TPU_SPILL_STORAGE prefix, and object ids alias across
        # nodes (staged foreign copies carry the owner's id) — without
        # the namespace, node B freeing its staged copy would delete
        # node A's spilled primary.
        import uuid as _uuid
        self._spill_ns = _uuid.uuid4().hex[:12]
        self.bytes_spilled = 0
        self.objects_spilled = 0
        self._spill_lock = threading.Lock()
        # plasma-equivalent arena: first daemon on the machine creates
        # it; lifetime is session-wide (unlink_session_arena at driver
        # shutdown), NOT tied to this daemon
        self.arena = create_arena(session_name)

    def segment_name(self, object_id: str) -> str:
        return segment_name(self.session_name, object_id)

    def register(self, object_id: str, shm_name: str, size: int) -> None:
        entry = ShmStoreEntry(shm_name, size)
        entry.sealed = True
        self._entries[object_id] = entry

    def contains(self, object_id: str) -> bool:
        e = self._entries.get(object_id)
        return e is not None and e.sealed

    def get(self, object_id: str) -> Optional[ShmStoreEntry]:
        return self._entries.get(object_id)

    def size_of(self, object_id: str) -> Optional[int]:
        e = self._entries.get(object_id)
        return e.size if e is not None and e.sealed else None

    def read_bytes(self, object_id: str) -> Optional[bytes]:
        """Copy an object's flat bytes out (for cross-node transfer)."""
        return self.read_range(object_id, 0, None)

    def read_range(self, object_id: str, offset: int,
                   length: Optional[int]) -> Optional[bytes]:
        """Copy out `length` bytes at `offset` (None = to the end) without
        materializing the rest — the chunked-transfer read primitive.

        Retries once on a miss: a concurrent spill may move the bytes from
        shm to disk between the prefix check and the read."""
        for _ in range(2):
            entry = self._entries.get(object_id)
            if entry is None or not entry.sealed:
                return None
            end = entry.size if length is None else min(offset + length,
                                                        entry.size)
            shm_name = entry.shm_name
            if shm_name.startswith("spill:"):
                path = shm_name[len("spill:"):]
                try:
                    if "://" in path:      # remote spill backend
                        return _external_read(path, offset, end - offset)
                    with open(path, "rb") as f:
                        f.seek(offset)
                        return f.read(end - offset)
                except OSError:
                    return None
            if shm_name.startswith("arena:"):
                _, arena_seg, oid = shm_name.split(":", 2)
                arena = attach_arena(arena_seg)
                ref = arena.get(oid) if arena is not None else None
                if ref is None:
                    continue  # raced with a spill; re-read the entry
                try:
                    return bytes(ref.buf[offset:end])
                finally:
                    ref.release()
            try:
                if entry.shm is None:
                    entry.shm = attach_shm(shm_name)
                return bytes(entry.shm.buf[offset:end])
            except FileNotFoundError:
                continue  # raced with a spill
        return None

    # ------------------------------------------------------------- spilling

    def spill(self, object_id: str) -> bool:
        """Copy one sealed object to disk and free its shm copy."""
        with self._spill_lock:
            entry = self._entries.get(object_id)
            if entry is None or not entry.sealed \
                    or entry.shm_name.startswith("spill:"):
                return False
            data = self.read_bytes(object_id)
            if data is None:
                return False
            if self._spill_remote:
                path = (self.spill_dir.rstrip("/") + "/"
                        + self._spill_ns + "/" + object_id)
                try:
                    _external_write(path, data)
                except Exception:
                    return False       # backend down: keep the shm copy
            else:
                os.makedirs(self.spill_dir, exist_ok=True)
                path = os.path.join(self.spill_dir, object_id)
                tmp = path + ".tmp"
                with open(tmp, "wb") as f:
                    f.write(data)
                os.replace(tmp, path)
            old_name = entry.shm_name
            # publish the new location BEFORE freeing the shm copy so a
            # concurrent reader either sees the old (still-valid) copy or
            # the spill file, never neither
            entry.shm_name = f"spill:{path}"
            self._free_shm_copy(old_name, entry)
            self.bytes_spilled += entry.size
            self.objects_spilled += 1
            return True

    def spill_until(self, bytes_needed: int,
                    arena_only: bool = True) -> int:
        """Spill oldest-registered objects until roughly `bytes_needed`
        bytes of shm have been released. arena_only counts (and spills)
        only arena-backed entries — the deficit callers compute is arena
        space, which freeing per-object segments can't satisfy. Returns
        bytes spilled (approximate LRU: registration order)."""
        released = 0
        for object_id in list(self._entries):
            if released >= bytes_needed:
                break
            entry = self._entries.get(object_id)
            if entry is None or entry.shm_name.startswith("spill:"):
                continue
            if arena_only and not entry.shm_name.startswith("arena:"):
                continue
            if self.spill(object_id):
                released += entry.size
        return released

    def arena_pressure(self):
        """(allocated, capacity) of the arena, or None without one."""
        if self.arena is None:
            return None
        try:
            st = self.arena.stats()
            return st["bytes_allocated"], st["heap_capacity"]
        except Exception:
            return None

    def native_stats(self) -> dict:
        """Operation counters maintained INSIDE the C++ arena (allocs,
        failures, coalesces, crash sweeps) — the native end of the
        metrics pipeline. Empty without a native arena."""
        if self.arena is None:
            return {}
        try:
            return self.arena.stats()
        except Exception:
            return {}

    def _free_shm_copy(self, shm_name: str, entry: ShmStoreEntry) -> None:
        if shm_name.startswith("arena:"):
            _, arena_seg, oid = shm_name.split(":", 2)
            arena = attach_arena(arena_seg)
            if arena is not None:
                arena.delete(oid)
            return
        if entry.shm is not None:
            try:
                entry.shm.close()
            except Exception:
                pass
            entry.shm = None
        _unlink_shm(shm_name)

    def free(self, object_id: str) -> None:
        entry = self._entries.pop(object_id, None)
        if entry is None:
            return
        if entry.shm_name.startswith("spill:"):
            path = entry.shm_name[len("spill:"):]
            try:
                if "://" in path:
                    _external_delete(path)
                else:
                    os.unlink(path)
            except Exception:
                pass
            return
        self._free_shm_copy(entry.shm_name, entry)

    def free_all(self) -> None:
        for object_id in list(self._entries):
            self.free(object_id)

    @property
    def num_objects(self) -> int:
        return len(self._entries)

    @property
    def bytes_used(self) -> int:
        return sum(e.size for e in self._entries.values())


def write_to_shm(object_id: str, serialized: SerializedObject,
                 session_name: str,
                 arena_room=None) -> Tuple[str, int]:
    """Write `serialized` into shared memory for other processes.

    Preferred path: allocate+seal inside the native arena (one mmap per
    process for ALL objects). On a full arena, `arena_room(nbytes)` (if
    given) may free space by asking the daemon to spill — the allocation
    is retried once after it. Final fallback (native lib missing or still
    full): one POSIX segment per object. Returns (shm_name, size) where
    an arena-backed name is "arena:<segment>:<object_id>". Caller must
    register it with the node daemon.
    """
    size = serialized.flat_size()
    arena = attach_arena(arena_name_for(session_name))
    if arena is not None:
        buf = arena.create_buffer(object_id, size)
        if buf is None and arena_room is not None:
            try:
                arena_room(size)
            except Exception:
                pass
            else:
                buf = arena.create_buffer(object_id, size)
        if buf is not None:
            try:
                serialized.write_flat(buf)
            except BaseException:
                # reclaim the unsealed slot — eviction never touches
                # unsealed objects, so leaking it would be permanent
                buf.release()
                arena.delete(object_id)
                raise
            buf.release()
            arena.seal(object_id)
            return f"arena:{arena.name}:{object_id}", size
    name = segment_name(session_name, object_id)
    shm = create_untracked_shm(name, size)
    try:
        serialized.write_flat(shm.buf)
    finally:
        shm.close()
    return name, size


def read_from_shm(shm_name: str, size: int):
    """Map a sealed object and deserialize zero-copy.

    Returns (value, keepalive). The keepalive (arena Ref or shm handle)
    must outlive the value — numpy arrays view into the mapping.
    """
    if shm_name.startswith("arena:"):
        _, arena_seg, object_id = shm_name.split(":", 2)
        arena = attach_arena(arena_seg)
        ref = arena.get(object_id) if arena is not None else None
        if ref is None:
            raise FileNotFoundError(
                f"object {object_id[:12]} not in arena {arena_seg}")
        serialized = SerializedObject.from_flat(ref.buf[:size])
        return serialized.deserialize(), ref
    shm = attach_shm(shm_name)
    serialized = SerializedObject.from_flat(shm.buf[:size])
    value = serialized.deserialize()
    return value, shm

@_functools.lru_cache(maxsize=32)
def _spill_fs_for(base_uri: str):
    from ..train.storage import get_fs_and_path
    return get_fs_and_path(base_uri)


def _spill_fs_and_path(uri: str):
    """Resolve + CACHE the filesystem by URI base: chunked reads hit
    this once per chunk, and rebuilding a cloud FileSystem (auth,
    channel setup) per chunk would dominate the transfer."""
    base, _, name = uri.rpartition("/")
    fs, dir_path = _spill_fs_for(base)
    return fs, dir_path.rstrip("/") + "/" + name


def _external_write(uri: str, data: bytes) -> None:
    """Spill to a remote backend through the pyarrow-fs layer."""
    fs, fs_path = _spill_fs_and_path(uri)
    parent = fs_path.rsplit("/", 1)[0]
    try:
        fs.create_dir(parent, recursive=True)
    except Exception:
        pass
    with fs.open_output_stream(fs_path) as f:
        f.write(data)


def _external_read(uri: str, offset: int = 0,
                   length: int = None) -> bytes:
    """Ranged read from the spill backend: chunked cross-node transfers
    call this once per chunk — seek+read, never a full-object
    download per chunk."""
    fs, fs_path = _spill_fs_and_path(uri)
    with fs.open_input_file(fs_path) as f:
        if offset:
            f.seek(offset)
        return f.read(length)


def _external_delete(uri: str) -> None:
    fs, fs_path = _spill_fs_and_path(uri)
    fs.delete_file(fs_path)
