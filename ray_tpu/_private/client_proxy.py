"""Ray-Client-equivalent proxy: thin clients over ONE connection.

Reference parity: python/ray/util/client/ (`ray.init("ray://…")` — a
gRPC proxy server re-executes the API in-cluster and owns the objects;
the client holds opaque ids). Ours rides the existing frame protocol:

- `ClientProxyServer` runs inside the head driver/cluster process. Per
  client session it executes API calls against the real in-cluster
  client and PINS the resulting ObjectRefs in a session table, so thin
  clients never need cluster-routable addresses (the proxy is the
  owner-facing peer for everything).
- `ProxyModeClient` implements the same client surface as
  CoreClient/LocalModeClient (submit_task/create_actor/actor calls/
  get/put/wait/kill/kv/controller_rpc) by forwarding each call; the
  public API and libraries work unchanged. Select it with
  `ray_tpu.init(address="client://host:port")`.

Sessions expire after `SESSION_TTL_S` of inactivity (a crashed thin
client must not pin objects forever); each RPC refreshes the TTL.
Streaming generators are not proxied (reference client has the same
late-arrival; use a driver attach for `num_returns="streaming"`).
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

from .object_ref import ObjectRef
from .protocol import RpcClient, RpcServer
from .serialization import SerializedObject, serialize, serialize_code

SESSION_TTL_S = 600.0


def _walk_replace(obj, mapper, depth: int = 0):
    """Shallow-structure walk (tuple/list/dict, two levels like the
    worker-side arg resolution) replacing ObjectRefs via mapper."""
    if isinstance(obj, ObjectRef):
        return mapper(obj)
    if depth >= 2:
        return obj
    if isinstance(obj, tuple):
        return tuple(_walk_replace(x, mapper, depth + 1) for x in obj)
    if isinstance(obj, list):
        return [_walk_replace(x, mapper, depth + 1) for x in obj]
    if isinstance(obj, dict):
        return {k: _walk_replace(v, mapper, depth + 1)
                for k, v in obj.items()}
    return obj


class _Session:
    def __init__(self, namespace: str):
        self.namespace = namespace
        self.refs: Dict[str, ObjectRef] = {}   # pinned for the client
        self.last_seen = time.monotonic()


class ClientProxyServer:
    """In-cluster side. Runs on its OWN event loop; blocking calls into
    the inner client go through a thread pool (the inner client's sync
    surface bounces work onto the driver loop via run_coroutine_
    threadsafe, which requires calling from a non-driver-loop thread —
    and one client's long-blocking get must not freeze other clients).
    """

    def __init__(self, inner_client, host: str = None, port: int = 10001):
        import concurrent.futures

        from .core import LoopRunner
        self.inner = inner_client
        self.host = host
        self.port = port
        self.server = RpcServer()
        self.server.register_object(self)    # rpc_client_* -> client_*
        self.sessions: Dict[str, _Session] = {}
        self.address: Optional[Tuple[str, int]] = None
        self.loop_runner = LoopRunner()      # dedicated thread + loop
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=32, thread_name_prefix="client-proxy")
        # content-addressed fn blobs: thin clients send each unique
        # function once (the in-cluster function store dedupes onward)
        self._fn_blobs: Dict[str, bytes] = {}

    def start(self) -> Tuple[str, int]:
        self.address = self.loop_runner.run_sync(
            self.server.start(self.host, self.port), timeout=10)
        return self.address

    def stop(self) -> None:
        try:
            self.loop_runner.run_sync(self.server.stop(), timeout=5)
        except Exception:
            pass
        self._pool.shutdown(wait=False)
        self.loop_runner.stop()

    async def _blocking(self, fn, *args):
        import asyncio
        return await asyncio.get_running_loop().run_in_executor(
            self._pool, fn, *args)

    # ------------------------------------------------------------ session

    def _session(self, session_id: str) -> _Session:
        s = self.sessions.get(session_id)
        if s is None:
            raise RuntimeError(f"unknown client session {session_id[:8]} "
                               "(expired after inactivity?)")
        s.last_seen = time.monotonic()
        self._sweep()
        return s

    def _sweep(self) -> None:
        now = time.monotonic()
        for sid, s in list(self.sessions.items()):
            if now - s.last_seen > SESSION_TTL_S:
                del self.sessions[sid]       # drops pins -> normal GC

    async def rpc_client_hello(self, namespace: str = "default") -> dict:
        sid = uuid.uuid4().hex
        self.sessions[sid] = _Session(namespace)
        return {"session_id": sid,
                "namespace": namespace}

    async def rpc_client_bye(self, session_id: str) -> None:
        self.sessions.pop(session_id, None)

    async def rpc_client_touch(self, session_id: str) -> bool:
        """Keepalive: thin clients ping every ~60s so local compute or
        one long blocking get cannot TTL-expire the session."""
        s = self.sessions.get(session_id)
        if s is None:
            return False
        s.last_seen = time.monotonic()
        return True

    # ------------------------------------------------------------ helpers

    def _pin(self, session: _Session, ref: ObjectRef) -> str:
        session.refs[ref.id] = ref
        return ref.id

    def _real(self, session: _Session, obj):
        """Swap client-side refs (deserialized with unknown owner) for
        the session's pinned real refs."""
        def mapper(r):
            pinned = session.refs.get(r.id)
            if pinned is None:
                raise KeyError(
                    f"client passed unknown/released ref {r.id[:12]}")
            return pinned
        return _walk_replace(obj, mapper)

    # ------------------------------------------------------------ objects

    async def rpc_client_put(self, session_id: str, blob: bytes) -> str:
        s = self._session(session_id)
        value = self._real(
            s, SerializedObject.from_flat(blob).deserialize())
        ref = await self._blocking(self.inner.put, value)
        return self._pin(s, ref)

    async def rpc_client_get(self, session_id: str, ref_ids: List[str],
                             timeout: Optional[float] = None) -> bytes:
        import asyncio
        s = self._session(session_id)
        refs = [s.refs[i] for i in ref_ids]

        async def gather():
            deadline = (None if timeout is None
                        else time.monotonic() + timeout)
            return await asyncio.gather(
                *[self.inner.aio_get(r, deadline=deadline) for r in refs])

        try:
            # no executor thread held: a long get parks a coroutine on
            # the inner client's loop, so N waiting clients cost nothing
            values = await asyncio.wrap_future(
                asyncio.run_coroutine_threadsafe(
                    gather(), self.inner.loop_runner.loop))
        except Exception as e:
            # exception-type parity: ship the typed error for the thin
            # client to re-raise (the RPC layer would flatten it into a
            # RemoteCallError string otherwise)
            try:
                return serialize(("err", e)).to_flat()
            except Exception:
                raise e
        # nested refs inside returned values (e.g. a task returning
        # [ray_tpu.put(x)]) must be pinned or the client cannot use them
        s.last_seen = time.monotonic()     # long get ≠ idle session
        _walk_replace(values, lambda r: (self._pin(s, r), r)[1])
        return serialize(("ok", values)).to_flat()

    async def rpc_client_wait(self, session_id: str, ref_ids: List[str],
                              num_returns: int,
                              timeout: Optional[float]) -> dict:
        s = self._session(session_id)
        refs = [s.refs[i] for i in ref_ids]
        ready, pending = await self._blocking(
            lambda: self.inner.wait(refs, num_returns=num_returns,
                                    timeout=timeout))
        return {"ready": [r.id for r in ready],
                "pending": [r.id for r in pending]}

    async def rpc_client_release(self, session_id: str,
                                 ref_ids: List[str]) -> None:
        s = self.sessions.get(session_id)
        if s is None:
            return
        s.last_seen = time.monotonic()
        for i in ref_ids:
            s.refs.pop(i, None)

    # ------------------------------------------------------------- tasks

    async def rpc_client_task(self, session_id: str, fn_blob: bytes,
                              args_blob: bytes, opts: dict,
                              fn_hash: Optional[str] = None):
        s = self._session(session_id)
        from .serialization import deserialize_code
        if fn_blob is None:
            fn_blob = self._fn_blobs.get(fn_hash)
            if fn_blob is None:
                return {"need_blob": True}
        elif fn_hash is not None:
            self._fn_blobs[fn_hash] = fn_blob
        fn = deserialize_code(fn_blob)
        args, kwargs = SerializedObject.from_flat(args_blob).deserialize()
        args = self._real(s, tuple(args))
        kwargs = self._real(s, kwargs)
        if opts.get("num_returns") == "streaming":
            raise NotImplementedError(
                "streaming generators are not proxied; attach a driver")
        out = await self._blocking(
            lambda: self.inner.submit_task(fn, args, kwargs, opts,
                                           fn_blob=fn_blob,
                                           fn_hash=fn_hash))
        refs = out if isinstance(out, list) else [out]
        return {"refs": [self._pin(s, r) for r in refs]}

    async def rpc_client_create_actor(self, session_id: str,
                                      cls_blob: bytes, args_blob: bytes,
                                      opts: dict) -> dict:
        s = self._session(session_id)
        from .serialization import deserialize_code
        cls = deserialize_code(cls_blob)
        args, kwargs = SerializedObject.from_flat(args_blob).deserialize()
        args = self._real(s, tuple(args))
        kwargs = self._real(s, kwargs)
        opts = dict(opts)
        opts.setdefault("namespace", s.namespace)
        actor_id, creation_ref = await self._blocking(
            lambda: self.inner.create_actor(cls, args, kwargs, opts,
                                            cls_blob=cls_blob))
        return {"actor_id": actor_id,
                "creation_ref": self._pin(s, creation_ref)}

    async def rpc_client_actor_call(self, session_id: str, actor_id: str,
                                    method_name: str, args_blob: bytes,
                                    opts: dict) -> str:
        s = self._session(session_id)
        args, kwargs = SerializedObject.from_flat(args_blob).deserialize()
        args = self._real(s, tuple(args))
        kwargs = self._real(s, kwargs)
        if opts.get("num_returns") == "streaming":
            raise NotImplementedError(
                "streaming generators are not proxied; attach a driver")
        ref = await self._blocking(
            lambda: self.inner.submit_actor_task(actor_id, method_name,
                                                 args, kwargs, opts))
        return self._pin(s, ref)

    async def rpc_client_kill(self, session_id: str, actor_id: str,
                              no_restart: bool = True) -> None:
        self._session(session_id)
        await self._blocking(
            lambda: self.inner.kill_actor(actor_id, no_restart=no_restart))

    async def rpc_client_get_actor(self, session_id: str, name: str,
                                   namespace: Optional[str]) -> Optional[dict]:
        s = self._session(session_id)
        return await self._blocking(
            lambda: self.inner.get_actor_handle_info(
                name, namespace or s.namespace))

    # ------------------------------------------------------------ cluster

    async def rpc_client_rpc(self, session_id: str, method: str,
                             kwargs: dict):
        """Controller passthrough (nodes/resources/kv/state API)."""
        self._session(session_id)
        return await self._blocking(
            lambda: self.inner.controller_rpc(method, **kwargs))


# ======================================================== thin client


class _ProxyRefCounter:
    """Local counts only; zero -> release RPC to the proxy."""

    def __init__(self, client: "ProxyModeClient"):
        self._client = client
        self._counts: Dict[str, int] = {}
        self._lock = threading.Lock()

    def add_local_ref(self, ref_id, owner_addr=None, borrowed=False):
        with self._lock:
            self._counts[ref_id] = self._counts.get(ref_id, 0) + 1

    def remove_local_ref(self, ref_id):
        with self._lock:
            n = self._counts.get(ref_id, 0) - 1
            if n > 0:
                self._counts[ref_id] = n
                return
            self._counts.pop(ref_id, None)
        self._client._release(ref_id)

    def register_owned(self, *a, **k):
        pass

    def pin(self, *a, **k):
        pass

    def unpin(self, *a, **k):
        pass


class ProxyModeClient:
    """The surface of CoreClient/LocalModeClient, forwarded over one
    connection to a ClientProxyServer."""

    is_local_mode = False
    is_proxy_mode = True

    def __init__(self, host: str, port: int, namespace: str = "default"):
        from .core import LoopRunner
        self.namespace = namespace
        self.loop_runner = LoopRunner()      # owns a background thread
        self._rpc = RpcClient(host, port)
        self.ref_counter = _ProxyRefCounter(self)
        self.is_shutdown = False
        self._sent_fn_hashes = set()
        hello = self._call("client_hello", _rpc_timeout=30,
                           namespace=namespace)
        self.session_id = hello["session_id"]
        self._start_keepalive()

    # ------------------------------------------------------------- plumbing

    def _call(self, _method: str, _rpc_timeout: Optional[float] = None,
              **kwargs):
        # unbounded by default: get(timeout=None) must block like a
        # driver attach, not fail at an arbitrary RPC ceiling
        return self.loop_runner.run_sync(
            self._rpc.call(_method, **kwargs), timeout=_rpc_timeout)

    def _scall(self, _method: str, _rpc_timeout: Optional[float] = None,
               **kwargs):
        return self._call(_method, _rpc_timeout=_rpc_timeout,
                          session_id=self.session_id, **kwargs)

    def _start_keepalive(self) -> None:
        import asyncio

        async def beat():
            while not self.is_shutdown:
                await asyncio.sleep(60.0)
                try:
                    await self._rpc.call("client_touch",
                                         session_id=self.session_id)
                except Exception:
                    pass

        self.loop_runner.call_soon(beat())

    def _release(self, ref_id: str) -> None:
        if self.is_shutdown:
            return
        try:
            self.loop_runner.call_soon(self._rpc.oneway(
                "client_release", session_id=self.session_id,
                ref_ids=[ref_id]))
        except Exception:
            pass

    def _ref(self, ref_id: str) -> ObjectRef:
        return ObjectRef(ref_id, None, _client=self)

    @staticmethod
    def _args_blob(args, kwargs) -> bytes:
        return serialize((tuple(args), dict(kwargs))).to_flat()

    # ------------------------------------------------------------- objects

    def put(self, value: Any) -> ObjectRef:
        return self._ref(self._scall(
            "client_put", blob=serialize(value).to_flat()))

    @staticmethod
    def _decode_get(blob: bytes):
        tag, payload = SerializedObject.from_flat(blob).deserialize()
        if tag == "err":
            raise payload            # typed server-side error (TaskError…)
        return payload

    def get(self, refs, timeout: Optional[float] = None):
        single = isinstance(refs, ObjectRef)
        ref_list = [refs] if single else list(refs)
        blob = self._scall("client_get",
                           ref_ids=[r.id for r in ref_list],
                           timeout=timeout)
        values = self._decode_get(blob)
        return values[0] if single else values

    async def _get_on_own_loop(self, ref_ids, timeout):
        # runs ON loop_runner's loop — the RpcClient connection is bound
        # there and must not be driven from a foreign loop
        blob = await self._rpc.call(
            "client_get", session_id=self.session_id, ref_ids=ref_ids,
            timeout=timeout)
        return self._decode_get(blob)

    def as_future(self, ref: ObjectRef):
        import asyncio
        return asyncio.run_coroutine_threadsafe(
            self._get_on_own_loop([ref.id], None), self.loop_runner.loop)

    async def aio_get(self, ref: ObjectRef, deadline=None):
        import asyncio
        values = await asyncio.wrap_future(self.as_future(ref))
        return values[0]

    def wait(self, refs, num_returns: int = 1, timeout=None):
        ref_list = list(refs)
        by_id = {r.id: r for r in ref_list}
        reply = self._scall("client_wait",
                            ref_ids=[r.id for r in ref_list],
                            num_returns=num_returns, timeout=timeout)
        return ([by_id[i] for i in reply["ready"]],
                [by_id[i] for i in reply["pending"]])

    # ------------------------------------------------------------- tasks

    def submit_task(self, fn, args, kwargs, opts, fn_blob=None,
                    fn_hash=None):
        blob = fn_blob if fn_blob is not None else serialize_code(fn)
        if fn_hash is None:
            import hashlib
            fn_hash = hashlib.sha1(blob).hexdigest()
        args_blob = self._args_blob(args, kwargs)
        # send each unique function's blob once; afterwards only the
        # hash crosses the wire (server replies need_blob if it lost it)
        send_blob = fn_hash not in self._sent_fn_hashes
        reply = self._scall("client_task",
                            fn_blob=blob if send_blob else None,
                            fn_hash=fn_hash, args_blob=args_blob,
                            opts=_plain_opts(opts))
        if isinstance(reply, dict) and reply.get("need_blob"):
            reply = self._scall("client_task", fn_blob=blob,
                                fn_hash=fn_hash, args_blob=args_blob,
                                opts=_plain_opts(opts))
        self._sent_fn_hashes.add(fn_hash)
        refs = [self._ref(i) for i in reply["refs"]]
        return refs[0] if len(refs) == 1 else refs

    def create_actor(self, cls, args, kwargs, opts, cls_blob=None,
                     cls_hash=None):
        blob = cls_blob if cls_blob is not None else serialize_code(cls)
        reply = self._scall("client_create_actor", cls_blob=blob,
                            args_blob=self._args_blob(args, kwargs),
                            opts=_plain_opts(opts))
        return reply["actor_id"], self._ref(reply["creation_ref"])

    def submit_actor_task(self, actor_id, method, args, kwargs, opts):
        ref_id = self._scall("client_actor_call", actor_id=actor_id,
                             method_name=method,
                             args_blob=self._args_blob(args, kwargs),
                             opts=_plain_opts(opts))
        return self._ref(ref_id)

    def kill_actor(self, actor_id, no_restart=True):
        self._scall("client_kill", actor_id=actor_id,
                    no_restart=no_restart)

    def get_actor_handle_info(self, name, namespace=None):
        return self._scall("client_get_actor", name=name,
                           namespace=namespace)

    # ------------------------------------------------------------- cluster

    def controller_rpc(self, method: str, **kwargs):
        return self._scall("client_rpc", method=method, kwargs=kwargs)

    def cluster_resources(self):
        return self.controller_rpc("cluster_resources")

    def available_resources(self):
        return self.controller_rpc("available_resources")

    def nodes(self):
        return self.controller_rpc("list_nodes")

    def kv_put(self, key, value, overwrite=True):
        return self.controller_rpc("kv_put", key=key, value=value,
                                   overwrite=overwrite)

    def kv_get(self, key):
        return self.controller_rpc("kv_get", key=key)

    def kv_del(self, key):
        return self.controller_rpc("kv_del", key=key)

    def kv_keys(self, prefix=""):
        return self.controller_rpc("kv_keys", prefix=prefix)

    # ------------------------------------------------------------- lifecycle

    def shutdown(self):
        if getattr(self, "_closed", False):
            return
        self._closed = True
        self.is_shutdown = True
        try:
            self.loop_runner.run_sync(self._rpc.oneway(
                "client_bye", session_id=self.session_id), timeout=5)
        except Exception:
            pass
        try:
            self.loop_runner.run_sync(self._rpc.close(), timeout=5)
        except Exception:
            pass
        self.loop_runner.stop()


def _plain_opts(opts: dict) -> dict:
    """Options must cross the wire as plain data (scheduling strategies
    normalized to dicts by the callers already)."""
    return {k: v for k, v in (opts or {}).items() if v is not None}
