"""Streaming generators: num_returns="streaming".

Reference parity: python/ray/_raylet.pyx:295 (ObjectRefGenerator) +
src/ray/core_worker/task_manager.h:364 (dynamic return streaming).
Architecture here follows the repo's owner-push model: the executing
worker pushes each yielded value to the owner as a normal object
(object_ready with stream metadata), then an end-of-stream marker. The
owner keeps a StreamState per generator; consumers block on the next
index. Backpressure: the worker pauses when more than
`backpressure` items are unconsumed; the consumer acks each item it
takes and the owner forwards the ack to the worker.
"""

from __future__ import annotations

import asyncio
from typing import Any, Dict, List, Optional, Tuple

from .object_ref import ObjectRef


class StreamState:
    """Owner-side bookkeeping for one streaming generator."""

    def __init__(self, generator_id: str, wants_ack: bool = False):
        self.generator_id = generator_id
        self.items: Dict[int, str] = {}          # index -> object_id
        self.total: Optional[int] = None         # set at end-of-stream
        self.worker_addr: Optional[Tuple[str, int]] = None
        self.error: Optional[Exception] = None   # submission-level failure
        self.wants_ack = wants_ack               # backpressure requested
        self.cancelled = False                   # consumer abandoned
        self.cancel_sent = False
        self.event = asyncio.Event()

    def put(self, index: int, object_id: str,
            worker_addr=None) -> None:
        self.items[index] = object_id
        if worker_addr is not None:
            self.worker_addr = tuple(worker_addr)
        self.event.set()

    def finish(self, total: int) -> None:
        self.total = total
        self.event.set()

    def fail(self, error: Exception) -> None:
        self.error = error
        self.total = len(self.items)
        self.event.set()

    async def wait_for(self, index: int) -> Optional[str]:
        """Object id for item `index`, or None past end-of-stream."""
        while True:
            if index in self.items:
                return self.items[index]
            if self.total is not None and index >= self.total:
                if self.error is not None:
                    raise self.error
                return None
            self.event.clear()
            await self.event.wait()


class ObjectRefGenerator:
    """Iterator over a streaming task's yielded ObjectRefs.

    Supports sync iteration (driver/worker threads) and async iteration
    (async actors). Each next() returns an ObjectRef whose value is
    already owned by this process — `ray_tpu.get(ref)` on it is local.
    """

    def __init__(self, generator_id: str, client):
        self._id = generator_id
        self._client = client
        self._cursor = 0
        self._closed = False
        #: optional hook invoked exactly once when the stream ends
        #: (exhaustion, close, or GC) — used e.g. by serve's router to
        #: track per-replica live streams
        self.on_finish = None

    def _finish(self) -> None:
        cb, self.on_finish = self.on_finish, None
        if cb is not None:
            try:
                cb()
            except Exception:
                pass

    @property
    def generator_id(self) -> str:
        return self._id

    def close(self) -> None:
        """Stop the producer and release unconsumed items. Called
        automatically when the generator is garbage-collected."""
        if self._closed:
            return
        self._closed = True
        try:
            self._client.release_stream(self._id, self._cursor)
        except Exception:
            pass
        self._finish()

    def __del__(self):
        self.close()

    # -- sync protocol ----------------------------------------------------

    def __iter__(self) -> "ObjectRefGenerator":
        return self

    def __next__(self) -> ObjectRef:
        ref = self._client.next_stream_item(self._id, self._cursor)
        if ref is None:
            self._finish()
            raise StopIteration
        self._cursor += 1
        return ref

    # -- async protocol ---------------------------------------------------

    def __aiter__(self) -> "ObjectRefGenerator":
        return self

    async def __anext__(self) -> ObjectRef:
        ref = await self._client.aio_next_stream_item(self._id, self._cursor)
        if ref is None:
            self._finish()
            raise StopAsyncIteration
        self._cursor += 1
        return ref

    def completed(self) -> List[ObjectRef]:
        """Drain the rest of the stream synchronously."""
        return list(self)

    def __repr__(self) -> str:
        return f"ObjectRefGenerator({self._id[:16]}, next={self._cursor})"
