"""Local mode: eager in-process execution (no subprocesses).

Reference parity: ray.init(local_mode=True) — tasks execute synchronously
at submission, actors are plain in-process instances. Used for debugging
and for fast library tests on constrained machines.
"""

from __future__ import annotations

import asyncio
import uuid
from typing import Any, Dict, Optional, Tuple

from .object_ref import ObjectRef
from ..exceptions import (ActorDiedError, ActorError, GetTimeoutError,
                          TaskError)
import traceback


class _LocalRefCounter:
    def add_local_ref(self, *a, **k): pass
    def remove_local_ref(self, *a, **k): pass
    def register_owned(self, *a, **k): pass
    def pin(self, *a, **k): pass
    def unpin(self, *a, **k): pass
    def on_borrower_event(self, *a, **k): pass


class LocalActorState:
    def __init__(self, instance):
        self.instance = instance
        self.dead = False
        self.death_cause = ""


class _LocalGenerator:
    """Eager local-mode stand-in for ObjectRefGenerator: all items were
    produced at submission; iteration just walks the refs."""

    def __init__(self, refs):
        self._refs = list(refs)
        self._cursor = 0

    def __iter__(self):
        return self

    def __next__(self):
        if self._cursor >= len(self._refs):
            raise StopIteration
        ref = self._refs[self._cursor]
        self._cursor += 1
        return ref

    def __aiter__(self):
        return self

    async def __anext__(self):
        try:
            return self.__next__()
        except StopIteration:
            raise StopAsyncIteration

    def completed(self):
        return list(self)


class LocalModeClient:
    is_local_mode = True

    def __init__(self, namespace: str = "default"):
        self.namespace = namespace
        self.ref_counter = _LocalRefCounter()
        self.store: Dict[str, Any] = {}
        self.errors: Dict[str, Exception] = {}
        self.actors: Dict[str, LocalActorState] = {}
        self.named: Dict[Tuple[str, str], str] = {}
        self.kv: Dict[str, bytes] = {}
        self.address = None
        self.is_shutdown = False
        self.placement_groups: Dict[str, Any] = {}

    # -- objects --

    def put(self, value: Any) -> ObjectRef:
        oid = uuid.uuid4().hex
        self.store[oid] = value
        return ObjectRef(oid, None, _client=self)

    def get(self, refs, timeout: Optional[float] = None):
        single = isinstance(refs, ObjectRef)
        ref_list = [refs] if single else list(refs)
        out = []
        for r in ref_list:
            if r.id in self.errors:
                raise self.errors[r.id]
            if r.id not in self.store:
                raise GetTimeoutError(f"object {r.id[:12]} never produced "
                                      "(local mode is eager)")
            out.append(self.store[r.id])
        return out[0] if single else out

    async def aio_get(self, ref: ObjectRef, deadline=None):
        return self.get(ref)

    def as_future(self, ref):
        import concurrent.futures
        f = concurrent.futures.Future()
        try:
            f.set_result(self.get(ref))
        except Exception as e:
            f.set_exception(e)
        return f

    def wait(self, refs, num_returns: int = 1, timeout=None):
        refs = list(refs)
        return refs[:num_returns], refs[num_returns:]

    # -- tasks --

    def _resolve(self, obj):
        if isinstance(obj, ObjectRef):
            return self.get(obj)
        return obj

    def _run_streaming(self, fn_name: str, result) -> "_LocalGenerator":
        refs = []
        try:
            if hasattr(result, "__anext__"):     # async generator parity
                async def _drain():
                    return [item async for item in result]
                items = asyncio.new_event_loop().run_until_complete(_drain())
            else:
                items = list(result)
            for item in items:
                oid = uuid.uuid4().hex
                self.store[oid] = item
                refs.append(ObjectRef(oid, None, _client=self))
        except Exception:
            oid = uuid.uuid4().hex
            self.errors[oid] = TaskError(fn_name, traceback.format_exc())
            refs.append(ObjectRef(oid, None, _client=self))
        return _LocalGenerator(refs)

    def submit_task(self, fn, args, kwargs, opts, fn_blob=None,
                    fn_hash=None):
        num_returns = opts.get("num_returns") or 1
        streaming = num_returns == "streaming"
        if streaming:
            num_returns = 1
        oids = [uuid.uuid4().hex for _ in range(num_returns)]
        args = tuple(self._resolve(a) for a in args)
        kwargs = {k: self._resolve(v) for k, v in kwargs.items()}
        try:
            result = fn(*args, **kwargs)
            if streaming:
                return self._run_streaming(
                    getattr(fn, "__name__", "task"), result)
            if asyncio.iscoroutine(result):
                result = asyncio.new_event_loop().run_until_complete(result)
            if num_returns == 1:
                self.store[oids[0]] = result
            else:
                for oid, part in zip(oids, result):
                    self.store[oid] = part
        except Exception:
            err = TaskError(
                getattr(fn, "__name__", "task"), traceback.format_exc())
            for oid in oids:
                self.errors[oid] = err
        refs = [ObjectRef(oid, None, _client=self) for oid in oids]
        return refs[0] if num_returns == 1 else refs

    # -- actors --

    def create_actor(self, cls, args, kwargs, opts, cls_blob=None,
                     cls_hash=None):
        actor_id = uuid.uuid4().hex
        oid = uuid.uuid4().hex
        args = tuple(self._resolve(a) for a in args)
        kwargs = {k: self._resolve(v) for k, v in kwargs.items()}
        try:
            instance = cls(*args, **kwargs)
            self.actors[actor_id] = LocalActorState(instance)
            self.store[oid] = None
            name = opts.get("name")
            if name:
                self.named[(opts.get("namespace") or self.namespace, name)] = \
                    actor_id
        except Exception:
            self.errors[oid] = ActorDiedError(
                actor_id, f"__init__ failed:\n{traceback.format_exc()}")
            state = LocalActorState(None)
            state.dead = True
            state.death_cause = traceback.format_exc()
            self.actors[actor_id] = state
        return actor_id, ObjectRef(oid, None, _client=self)

    def submit_actor_task(self, actor_id, method, args, kwargs, opts):
        oid = uuid.uuid4().hex
        streaming = (opts or {}).get("num_returns") == "streaming"
        actor = self.actors.get(actor_id)
        if actor is None or actor.dead:
            self.errors[oid] = ActorDiedError(
                actor_id, actor.death_cause if actor else "unknown actor")
            return ObjectRef(oid, None, _client=self)
        args = tuple(self._resolve(a) for a in args)
        kwargs = {k: self._resolve(v) for k, v in kwargs.items()}
        try:
            result = getattr(actor.instance, method)(*args, **kwargs)
            if streaming:
                return self._run_streaming(method, result)
            if asyncio.iscoroutine(result):
                result = asyncio.new_event_loop().run_until_complete(result)
            self.store[oid] = result
        except Exception:
            self.errors[oid] = ActorError(method, traceback.format_exc())
        return ObjectRef(oid, None, _client=self)

    def kill_actor(self, actor_id, no_restart=True):
        actor = self.actors.get(actor_id)
        if actor:
            actor.dead = True
            actor.death_cause = "killed"
            for key, aid in list(self.named.items()):
                if aid == actor_id:
                    del self.named[key]

    def get_actor_handle_info(self, name, namespace):
        actor_id = self.named.get((namespace or self.namespace, name))
        if actor_id is None:
            return None
        return {"actor_id": actor_id, "state": "ALIVE", "addr": None}

    # -- cluster --

    def cluster_resources(self):
        import os
        return {"CPU": float(os.cpu_count() or 1)}

    def available_resources(self):
        return self.cluster_resources()

    def nodes(self):
        return [{"node_id": "local", "alive": True,
                 "resources_total": self.cluster_resources(),
                 "resources_available": self.cluster_resources(),
                 "addr": None, "labels": {}}]

    def kv_put(self, key, value, overwrite=True):
        if not overwrite and key in self.kv:
            return False
        self.kv[key] = value
        return True

    def kv_get(self, key):
        return self.kv.get(key)

    def kv_del(self, key):
        return self.kv.pop(key, None) is not None

    def kv_keys(self, prefix=""):
        return [k for k in self.kv if k.startswith(prefix)]

    def shutdown(self):
        self.is_shutdown = True
