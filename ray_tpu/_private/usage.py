"""Usage stats: anonymized cluster/library usage collection.

Reference parity: python/ray/_private/usage/usage_lib.py:95-390 —
library-usage recording (record_library_usage), extra usage tags,
cluster metadata snapshot, and a periodic reporter. Deltas, by design:

- **Opt-in, not opt-out** (RAY_TPU_USAGE_STATS=1 enables; the reference
  enables by default with RAY_USAGE_STATS_ENABLED=0 to disable). This
  build targets zero-egress environments, so there is no default
  network report.
- The "report" sink is a JSON file in the session dir
  (usage_stats.json) plus the cluster KV (namespace "usage"), where the
  reference POSTs to a usage server. A custom sink can read either.

Recording is always allowed and never raises: library imports call
record_library_usage() unconditionally (matching the reference, which
records locally regardless of the enabled flag and only *reports* when
enabled); the reporter is only started when enabled.
"""

from __future__ import annotations

import json
import os
import platform
import sys
import threading
import time
from typing import Dict, Optional, Set

_lock = threading.Lock()
_library_usages: Set[str] = set()
_extra_tags: Dict[str, str] = {}

KV_NAMESPACE = "usage"


def usage_stats_enabled() -> bool:
    return os.environ.get("RAY_TPU_USAGE_STATS", "0") == "1"


def record_library_usage(name: str) -> None:
    """Record that a library (train/tune/serve/…) was imported in this
    process. Cheap, idempotent, and never raises — this runs inside
    library __init__ imports."""
    with _lock:
        if name in _library_usages:
            return
        _library_usages.add(name)
    _try_push_kv(f"lib:{name}", "1")


def record_extra_usage_tag(key: str, value: str) -> None:
    """Record a free-form usage tag (reference:
    usage_lib.record_extra_usage_tag)."""
    with _lock:
        _extra_tags[key] = str(value)
    _try_push_kv(f"tag:{key}", str(value))


def get_library_usages() -> Set[str]:
    with _lock:
        return set(_library_usages)


def _try_push_kv(key: str, value: str) -> None:
    """Best-effort mirror into the cluster KV so the head's reporter
    sees usages recorded in any connected process. Silent no-op when
    not connected (pre-init imports) — the reporter re-flushes local
    state periodically."""
    try:
        from . import state as _state
        client = _state.current_client_or_none()
        if client is None:
            return
        kv_key = f"__usage__:{key}"
        lr = getattr(client, "loop_runner", None)
        if (lr is not None and lr.on_loop_thread()
                and hasattr(client, "_controller")):
            # worker RPC handlers run ON the loop: fire-and-forget the
            # put (the sync kv_put would deadlock-guard and raise) —
            # same pattern as util/tracing's flush
            lr.call_soon(client._controller().call(
                "kv_put", key=kv_key, value=value.encode(),
                overwrite=True))
        else:
            client.kv_put(kv_key, value.encode(), overwrite=True)
    except Exception:
        pass


def cluster_metadata() -> dict:
    """One anonymized snapshot of what this cluster is (reference:
    usage_lib.put_cluster_metadata fields)."""
    meta = {
        "schema_version": "0.1",
        "source": "ray_tpu",
        "python_version": sys.version.split()[0],
        "os": sys.platform,
        "platform_machine": platform.machine(),
        "session_start_unix_s": int(time.time()),
    }
    try:
        import jax
        meta["jax_version"] = jax.__version__
    except Exception:
        pass
    try:
        from .. import __version__ as v
        meta["ray_tpu_version"] = v
    except Exception:
        meta["ray_tpu_version"] = "dev"
    return meta


def usage_snapshot(client=None) -> dict:
    """The full report payload: metadata + library usages + tags +
    cluster shape (total resources / node count, if connected)."""
    snap = dict(cluster_metadata())
    libs = get_library_usages()
    tags = dict(_extra_tags)
    if client is not None:
        try:
            for k in client.kv_keys("__usage__:"):
                key = k.decode() if isinstance(k, bytes) else k
                rest = key[len("__usage__:"):]
                kind, _, name = rest.partition(":")
                if kind == "lib":
                    libs.add(name)
                elif kind == "tag":
                    raw = client.kv_get(key)
                    if raw is not None:
                        tags[name] = (raw.decode()
                                      if isinstance(raw, bytes) else raw)
        except Exception:
            pass
        try:
            nodes = client.controller_rpc("list_nodes")
            alive = [n for n in nodes if n.get("alive", True)]
            snap["num_nodes"] = len(alive)
            totals: Dict[str, float] = {}
            for n in alive:
                for r, v in (n.get("resources_total")
                             or n.get("resources") or {}).items():
                    totals[r] = totals.get(r, 0.0) + float(v)
            snap["total_resources"] = totals
        except Exception:
            pass
    snap["library_usages"] = sorted(libs)
    snap["extra_usage_tags"] = tags
    return snap


class UsageReporter:
    """Head-side periodic reporter: writes usage_stats.json under the
    session dir every interval (reference: usage_stats_head.py loop).
    Started from ray_tpu.init() only when usage_stats_enabled()."""

    def __init__(self, client, session_name: str,
                 interval_s: Optional[float] = None):
        from .config import session_dir
        self._client = client
        self._path = os.path.join(session_dir(session_name),
                                  "usage_stats.json")
        if interval_s is None:
            try:
                interval_s = float(os.environ.get(
                    "RAY_TPU_USAGE_REPORT_INTERVAL_S", "300"))
            except ValueError:
                interval_s = 300.0   # never fail init() over a bad env var
        self._interval = interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def report_once(self) -> dict:
        snap = usage_snapshot(self._client)
        snap["reported_at_unix_s"] = int(time.time())
        try:
            os.makedirs(os.path.dirname(self._path), exist_ok=True)
            tmp = self._path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(snap, f, indent=1, sort_keys=True)
            os.replace(tmp, self._path)
        except OSError:
            pass
        return snap

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="usage-reporter", daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.report_once()
            except Exception:
                pass
            self._stop.wait(self._interval)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
