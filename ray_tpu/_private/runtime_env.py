"""Runtime-env plugin system: pluggable env materialization + URI cache.

Reference parity: python/ray/_private/runtime_env/plugin.py:24
(RuntimeEnvPlugin) and :118 (RuntimeEnvPluginManager), conda.py, uv.py,
image_uri.py, working_dir.py — re-shaped for this runtime: plugins run
inside the node daemon (there is no separate runtime-env agent process)
and produce a RuntimeEnvContext the worker spawn consumes. Per-node URI
caching: every expensive artifact (downloaded working_dir, pip target,
conda env, uv venv) lands in a content-keyed cache directory guarded by
a marker file + flock, so N workers (and N daemons sharing a session
temp dir) build each env once.

Built-ins: env_vars, working_dir (local dir or storage URI), py_modules
(local paths or URIs), pip, conda (named env or create-on-demand), uv
(venv + packages), image_uri (container stub for the GKE story).
External plugins register via `register_plugin()` or the
RAY_TPU_RUNTIME_ENV_PLUGINS env var ("module:Class,module:Class").
"""

from __future__ import annotations

import asyncio
import dataclasses
import hashlib
import json
import os
import shutil
import subprocess
import sys
from typing import Any, Callable, Dict, List, Optional

import logging

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class RuntimeEnvContext:
    """What a materialized env means for a worker process."""

    env_vars: Dict[str, str] = dataclasses.field(default_factory=dict)
    extra_paths: List[str] = dataclasses.field(default_factory=list)
    cwd: Optional[str] = None
    # conda/uv envs run the worker under a different interpreter
    py_executable: Optional[str] = None
    # image_uri stub: propagated so a container runtime integration
    # (KubeRay/GKE) can wrap the worker command
    container: Optional[Dict[str, Any]] = None


class RuntimeEnvPlugin:
    """One runtime_env key's materializer (reference plugin.py:24).

    Subclass and set `name` to the runtime_env dict key consumed;
    `priority` orders creation (lower first, reference: priority field).
    """

    name: str = ""
    priority: int = 50

    def validate(self, value: Any) -> None:
        """Raise on malformed config (called at env build start)."""

    async def create(self, value: Any, ctx: RuntimeEnvContext,
                     node: "NodeServices") -> None:
        raise NotImplementedError


class NodeServices:
    """What plugins may use from the hosting daemon."""

    def __init__(self, temp_dir: str):
        self.temp_dir = temp_dir
        self.cache = URICache(os.path.join(temp_dir, "runtime_envs"))

    async def run(self, cmd: List[str], timeout: float = 600.0
                  ) -> "subprocess.CompletedProcess":
        """Run a subprocess off the event loop (stderr kept separate so
        callers can parse stdout — e.g. conda's JSON — cleanly)."""
        def _run():
            return subprocess.run(
                cmd, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                timeout=timeout)
        return await asyncio.get_running_loop().run_in_executor(None, _run)


class URICache:
    """Per-node content-keyed artifact cache (reference: the runtime-env
    agent's URI cache). get_or_create builds once under flock; every
    later env with the same key reuses the directory."""

    def __init__(self, root: str):
        self.root = root
        self.hits = 0
        self.misses = 0

    def path_for(self, key: str) -> str:
        return os.path.join(self.root, key)

    async def get_or_create(
            self, key: str,
            create: Callable[[str], None]) -> str:
        """`create(target_dir)` materializes the artifact; it runs in an
        executor thread, at most once per key per node."""
        target = self.path_for(key)
        marker = os.path.join(target, ".ready")
        if os.path.exists(marker):
            self.hits += 1
            return target
        os.makedirs(self.root, exist_ok=True)
        lock_path = target + ".lock"

        def _locked_create():
            import fcntl
            with open(lock_path, "w") as lock:
                fcntl.flock(lock, fcntl.LOCK_EX)
                if os.path.exists(marker):
                    return False
                # Partial artifacts must never poison the key (e.g. a
                # half-made conda prefix fails 'prefix already exists'
                # on every retry): clear any prior debris, build, and
                # clean up again on failure. Build-and-rename is NOT an
                # option — conda/venv prefixes bake absolute paths.
                shutil.rmtree(target, ignore_errors=True)
                os.makedirs(target)
                try:
                    create(target)
                except BaseException:
                    shutil.rmtree(target, ignore_errors=True)
                    raise
                with open(marker, "w") as f:
                    f.write("ok")
                return True

        created = await asyncio.get_running_loop().run_in_executor(
            None, _locked_create)
        if created:
            self.misses += 1
        else:
            self.hits += 1
        return target


def _content_key(prefix: str, payload: Any) -> str:
    blob = json.dumps(payload, sort_keys=True, default=str).encode()
    return f"{prefix}-{hashlib.sha1(blob).hexdigest()[:16]}"


def _is_uri(path: str) -> bool:
    from ..train.storage import is_uri
    return is_uri(path)


# ------------------------------------------------------------ built-ins

class EnvVarsPlugin(RuntimeEnvPlugin):
    name = "env_vars"
    priority = 10

    def validate(self, value):
        if not isinstance(value, dict):
            raise ValueError("env_vars must be a dict of str -> str")

    async def create(self, value, ctx, node):
        ctx.env_vars.update({str(k): str(v) for k, v in value.items()})


class WorkingDirPlugin(RuntimeEnvPlugin):
    """Local directory, or a storage URI (gs://, mock://, ...) that is
    downloaded once per node into the URI cache (reference
    working_dir.py + URI caching)."""

    name = "working_dir"
    priority = 20

    async def create(self, value, ctx, node):
        wd = str(value)
        if _is_uri(wd):
            from ..train.storage import download_dir
            wd = await node.cache.get_or_create(
                _content_key("workdir", wd),
                lambda target: download_dir(value, target))
        else:
            wd = os.path.abspath(wd)
            if not os.path.isdir(wd):
                raise RuntimeError(f"runtime_env working_dir {wd!r} "
                                   "does not exist on this node")
        ctx.cwd = wd
        ctx.extra_paths.append(wd)


class PyModulesPlugin(RuntimeEnvPlugin):
    name = "py_modules"
    priority = 30

    async def create(self, value, ctx, node):
        for mod in value or []:
            mod = str(mod)
            if _is_uri(mod):
                from ..train.storage import download_dir
                local = await node.cache.get_or_create(
                    _content_key("pymod", mod),
                    lambda target, uri=mod: download_dir(uri, target))
                ctx.extra_paths.append(local)
                continue
            mod = os.path.abspath(mod)
            if not os.path.exists(mod):
                raise RuntimeError(f"runtime_env py_module {mod!r} "
                                   "does not exist on this node")
            # a module's import root is its parent directory (works for
            # both package dirs and single .py files)
            ctx.extra_paths.append(os.path.dirname(mod))


class PipPlugin(RuntimeEnvPlugin):
    # priority AFTER conda/uv: combined envs must install with the
    # worker's actual interpreter (ABI) — host-interpreter wheels on a
    # conda py3.10 path would fail to import
    name = "pip"
    priority = 45

    async def create(self, value, ctx, node):
        pkgs = list(value.get("packages", [])) if isinstance(value, dict) \
            else list(value)
        if not pkgs:
            return
        py = ctx.py_executable or sys.executable

        def install(target):
            cmd = [py, "-m", "pip", "install",
                   "--target", target, "--quiet"]
            from .config import get_config
            find_links = get_config().pip_find_links
            if find_links:
                cmd += ["--no-index", "--find-links", find_links]
            cmd += pkgs
            proc = subprocess.run(cmd, stdout=subprocess.PIPE,
                                  stderr=subprocess.STDOUT)
            if proc.returncode != 0:
                raise RuntimeError(
                    f"runtime_env pip install failed "
                    f"(rc={proc.returncode}): "
                    f"{proc.stdout.decode(errors='replace')[-2000:]}")

        target = await node.cache.get_or_create(
            _content_key("pip", [py] + pkgs), install)
        ctx.extra_paths.append(target)


class CondaPlugin(RuntimeEnvPlugin):
    """Named existing env, or {dependencies: [...]} created on demand
    (reference conda.py). The worker runs under the env's interpreter."""

    name = "conda"
    priority = 40

    def validate(self, value):
        if not isinstance(value, (str, dict)):
            raise ValueError(
                "conda must be an env name or an environment dict")

    def _conda_exe(self) -> str:
        exe = os.environ.get("CONDA_EXE") or shutil.which("conda")
        if not exe:
            raise RuntimeError(
                "runtime_env conda requested but no conda executable "
                "found (set CONDA_EXE or install conda on this node)")
        return exe

    async def create(self, value, ctx, node):
        exe = self._conda_exe()
        if isinstance(value, str):
            # named env: resolve its prefix once
            out = await node.run([exe, "env", "list", "--json"])
            if out.returncode != 0:
                raise RuntimeError(
                    f"conda env list failed (rc={out.returncode}): "
                    f"{out.stderr.decode(errors='replace')[-1000:]}")
            envs = json.loads(out.stdout.decode())["envs"]
            prefix = next(
                (e for e in envs
                 if os.path.basename(e) == value or e == value), None)
            if prefix is None:
                raise RuntimeError(f"conda env {value!r} not found")
        else:
            def build(target):
                import yaml
                spec_path = os.path.join(target, "environment.yml")
                with open(spec_path, "w") as f:
                    yaml.safe_dump(value, f)
                proc = subprocess.run(
                    [exe, "env", "create", "-p",
                     os.path.join(target, "env"), "-f", spec_path],
                    stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
                if proc.returncode != 0:
                    raise RuntimeError(
                        f"conda env create failed: "
                        f"{proc.stdout.decode(errors='replace')[-2000:]}")

            target = await node.cache.get_or_create(
                _content_key("conda", value), build)
            prefix = os.path.join(target, "env")
        ctx.py_executable = os.path.join(prefix, "bin", "python")


class UvPlugin(RuntimeEnvPlugin):
    """uv-managed venv with packages (reference uv.py): a venv is built
    once per package set in the URI cache; packages install with `uv
    pip` when uv is on PATH, plain pip otherwise."""

    name = "uv"
    priority = 40

    async def create(self, value, ctx, node):
        pkgs = list(value.get("packages", [])) if isinstance(value, dict) \
            else list(value)

        def build(target):
            venv_dir = os.path.join(target, "venv")
            import venv as venv_mod
            venv_mod.EnvBuilder(with_pip=True,
                                system_site_packages=True).create(venv_dir)
            py = os.path.join(venv_dir, "bin", "python")
            if pkgs:
                uv = shutil.which("uv")
                cmd = ([uv, "pip", "install", "--python", py] if uv
                       else [py, "-m", "pip", "install", "--quiet"])
                from .config import get_config
                find_links = get_config().pip_find_links
                if find_links:
                    cmd += ["--no-index", "--find-links", find_links]
                proc = subprocess.run(cmd + pkgs, stdout=subprocess.PIPE,
                                      stderr=subprocess.STDOUT)
                if proc.returncode != 0:
                    raise RuntimeError(
                        f"uv env install failed: "
                        f"{proc.stdout.decode(errors='replace')[-2000:]}")

        target = await node.cache.get_or_create(
            _content_key("uv", pkgs), build)
        ctx.py_executable = os.path.join(target, "venv", "bin", "python")


class ImageURIPlugin(RuntimeEnvPlugin):
    """Container images (reference image_uri.py, which shells out to
    podman). Two modes:

    - ``sandbox://<rootfs-dir>``: NATIVE container-lite — workers run
      inside an unprivileged user+mount namespace chrooted into the
      rootfs, with the host runtime bind-mounted in
      (_private/sandbox_run.py). No container runtime needed; works on
      bare TPU nodes.
    - any other URI: propagated for an external runtime to wrap the
      worker command (KubeRay/GKE supplies
      RAY_TPU_CONTAINER_RUN_PREFIX; bare nodes fail loudly).
    """

    name = "image_uri"
    priority = 5

    def validate(self, value):
        if not isinstance(value, str) or not value:
            raise ValueError("image_uri must be a non-empty string")
        if value.startswith("sandbox://"):
            rootfs = value[len("sandbox://"):]
            if not os.path.isdir(rootfs):
                raise ValueError(
                    f"sandbox:// rootfs {rootfs!r} is not a directory")

    async def create(self, value, ctx, node):
        if value.startswith("sandbox://"):
            import sys
            rootfs = os.path.abspath(value[len("sandbox://"):])
            pkg_parent = os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))))
            prefix_args = ["--bind", pkg_parent]
            # a venv/conda interpreter outside the default bind set
            # (e.g. /root/venv) must be visible inside the chroot or
            # the exec dies invisibly after the pivot
            from .sandbox_run import DEFAULT_BINDS
            for pfx in {sys.prefix, sys.base_prefix}:
                if not any(pfx == b or pfx.startswith(b + "/")
                           for b in DEFAULT_BINDS):
                    prefix_args += ["--bind", pfx]
            ctx.container = {
                "image_uri": value,
                # the daemon prepends this to the worker argv
                "run_prefix": [sys.executable, "-m",
                               "ray_tpu._private.sandbox_run", rootfs]
                              + prefix_args + ["--"],
            }
            return
        ctx.container = {"image_uri": value}


_BUILTIN_PLUGINS = (ImageURIPlugin, EnvVarsPlugin, WorkingDirPlugin,
                    PyModulesPlugin, PipPlugin, CondaPlugin, UvPlugin)
_registered: Dict[str, RuntimeEnvPlugin] = {}


def register_plugin(plugin: RuntimeEnvPlugin) -> None:
    """Register an external RuntimeEnvPlugin (reference
    RuntimeEnvPluginManager.add; also loadable via the
    RAY_TPU_RUNTIME_ENV_PLUGINS env var)."""
    if not plugin.name:
        raise ValueError("plugin needs a non-empty name")
    _registered[plugin.name] = plugin


def _load_env_var_plugins() -> None:
    spec = os.environ.get("RAY_TPU_RUNTIME_ENV_PLUGINS", "")
    for item in filter(None, (s.strip() for s in spec.split(","))):
        try:
            module_name, cls_name = item.split(":", 1)
            import importlib
            cls = getattr(importlib.import_module(module_name), cls_name)
            register_plugin(cls())
        except Exception:
            logger.exception("failed to load runtime-env plugin %r", item)


class RuntimeEnvPluginManager:
    """Builds RuntimeEnvContexts by running each configured key's plugin
    in priority order (reference plugin.py:118)."""

    def __init__(self, temp_dir: str):
        self.node = NodeServices(temp_dir)
        self.plugins: Dict[str, RuntimeEnvPlugin] = {
            p.name: p for p in (cls() for cls in _BUILTIN_PLUGINS)}
        _load_env_var_plugins()
        self.plugins.update(_registered)

    async def build(self, runtime_env: Optional[dict]
                    ) -> RuntimeEnvContext:
        ctx = RuntimeEnvContext()
        if not runtime_env:
            return ctx
        # late-registered plugins (register_plugin after daemon start)
        for name, plugin in _registered.items():
            self.plugins.setdefault(name, plugin)
        unknown = set(runtime_env) - set(self.plugins)
        if unknown:
            raise ValueError(
                f"unknown runtime_env key(s) {sorted(unknown)} "
                f"(known: {sorted(self.plugins)})")
        todo = sorted((self.plugins[k] for k in runtime_env),
                      key=lambda p: p.priority)
        for plugin in todo:
            value = runtime_env[plugin.name]
            plugin.validate(value)
            await plugin.create(value, ctx, self.node)
        return ctx
