"""Durable control-plane storage: SQLite-backed write-through tables.

Reference parity: src/ray/gcs/gcs_server/gcs_table_storage.h:213 +
store_client/redis_store_client.h:111 — the reference persists GCS tables
(actors, named actors, KV, placement groups) in Redis so the GCS survives
restart. Here a single SQLite file per session plays that role: every
mutation is written through synchronously (SQLite WAL keeps this cheap on
the control-plane's mutation rates), and a restarting controller reloads
the full state before serving.
"""

from __future__ import annotations

import os
import pickle
import sqlite3
import threading
from typing import Any, Dict, Iterable, List, Optional, Tuple

_TABLES = ("actors", "actor_specs", "named_actors", "kv",
           "placement_groups", "meta")


class GcsStore:
    """Thread-safe write-through persistence for controller tables."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path), exist_ok=True)
        self._lock = threading.Lock()
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.execute("PRAGMA journal_mode=WAL")
        self._db.execute("PRAGMA synchronous=NORMAL")
        for table in _TABLES:
            self._db.execute(
                f"CREATE TABLE IF NOT EXISTS {table} "
                f"(key TEXT PRIMARY KEY, value BLOB)")
        self._db.commit()

    # ------------------------------------------------------------- generic

    def put(self, table: str, key: str, value: Any) -> None:
        blob = pickle.dumps(value)
        with self._lock:
            self._db.execute(
                f"INSERT OR REPLACE INTO {table} (key, value) VALUES (?, ?)",
                (key, blob))
            self._db.commit()

    def get(self, table: str, key: str) -> Optional[Any]:
        with self._lock:
            row = self._db.execute(
                f"SELECT value FROM {table} WHERE key = ?", (key,)).fetchone()
        return pickle.loads(row[0]) if row else None

    def delete(self, table: str, key: str) -> None:
        with self._lock:
            self._db.execute(f"DELETE FROM {table} WHERE key = ?", (key,))
            self._db.commit()

    def items(self, table: str) -> List[Tuple[str, Any]]:
        with self._lock:
            rows = self._db.execute(
                f"SELECT key, value FROM {table}").fetchall()
        return [(k, pickle.loads(v)) for k, v in rows]

    def close(self) -> None:
        with self._lock:
            try:
                self._db.commit()
                self._db.close()
            except Exception:
                pass
