"""RayTpuConfig: the one place runtime knobs live.

Reference parity: src/ray/common/ray_config_def.h:18-224 (RayConfig) —
every tunable is declared once with its default and env override, rather
than scattered os.environ reads. Values are read at import (matching the
reference's process-start semantics); tests monkeypatch the instance.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional


def _env(name: str, default, cast=None):
    raw = os.environ.get(name)
    if raw is None:
        return default
    cast = cast or type(default)
    try:
        return cast(raw)
    except (TypeError, ValueError):
        return default


def _f(name, default, cast=None):
    """Deferred env read: evaluated when the config is INSTANTIATED (at
    first get_config(), i.e. first real use — typically ray_tpu.init),
    not at import. Preserves the set-env-after-import pattern."""
    return dataclasses.field(
        default_factory=lambda: _env(name, default, cast))


def session_dir(session_name: str) -> str:
    """The session's on-disk root (logs, spill, runtime envs, metrics
    configs) — the ONE place the /tmp/ray_tpu/<session> convention
    lives."""
    return os.path.join("/tmp/ray_tpu", session_name)


@dataclasses.dataclass
class RayTpuConfig:
    # -- object plane --------------------------------------------------
    #: cross-node fetch chunk size (bytes); RAY_TPU_FETCH_CHUNK
    fetch_chunk_bytes: int = _f("RAY_TPU_FETCH_CHUNK", 32 << 20)
    #: chunks in flight per fetch; RAY_TPU_FETCH_WINDOW
    fetch_chunk_window: int = _f("RAY_TPU_FETCH_WINDOW", 4)
    #: arena spill high/low water marks (fractions)
    arena_spill_high: float = _f("RAY_TPU_ARENA_SPILL_HIGH", 0.85)
    arena_spill_low: float = _f("RAY_TPU_ARENA_SPILL_LOW", 0.65)

    # -- lineage / recovery -------------------------------------------
    #: max producing-task specs retained for object reconstruction
    lineage_cap: int = _f("RAY_TPU_LINEAGE_CAP", 10000)
    #: byte bound on retained lineage specs
    lineage_max_bytes: int = _f("RAY_TPU_LINEAGE_MAX_BYTES", 512 << 20)

    # -- node daemon ---------------------------------------------------
    #: fork workers from a preloaded zygote (interpreter+imports paid
    #: once per node; ~10ms/worker instead of 1-2s); 0 = cold Popen
    worker_forkserver: int = _f("RAY_TPU_FORKSERVER", 1)
    #: node memory fraction that triggers the OOM killer (<=0 disables)
    memory_usage_threshold: float = _f(
        "RAY_TPU_MEMORY_USAGE_THRESHOLD", 0.95)
    #: pip runtime_env local wheel index (offline installs)
    pip_find_links: Optional[str] = _f(
        "RAY_TPU_PIP_FIND_LINKS", None, str)
    #: command prefix (space-separated) wrapping worker spawns for
    #: image_uri runtime envs, e.g. "podman run --rm -v /tmp:/tmp
    #: {image}" — "{image}" substitutes the env's image_uri. Empty =
    #: no container runtime on this node (image_uri envs fail to spawn).
    container_run_prefix: Optional[str] = _f(
        "RAY_TPU_CONTAINER_RUN_PREFIX", None, str)

    # -- distributed dispatch (daemon-local lease granting) --------------
    #: clients lease plain-CPU workers from their LOCAL daemon, which
    #: grants from a controller-delegated resource block (reference
    #: parity: raylet-local dispatch). "auto" enables it only when the
    #: controller lives on a DIFFERENT host than the daemon — the
    #: optimization removes a cross-host round-trip, and measurably
    #: LOSES on loopback (delegation churn, no hop saved: see
    #: BENCH_CORE round-4 A/B). "1"/"0" force it on/off.
    local_lease_enabled: str = _f("RAY_TPU_LOCAL_LEASE", "auto", str)
    #: slots per delegation request (block growth quantum)
    lease_block_size: int = _f("RAY_TPU_LEASE_BLOCK", 4)
    #: idle seconds before unused delegated slots return to the controller
    lease_block_idle_s: float = _f("RAY_TPU_LEASE_BLOCK_IDLE_S", 10.0)

    # -- function store --------------------------------------------------
    #: code blobs larger than this are exported once to the controller KV
    #: and referenced by content hash in task specs (function manager
    #: parity); smaller blobs ride inline in the spec
    fn_inline_limit: int = _f("RAY_TPU_FN_INLINE_LIMIT", 2048)
    #: warn when controller-resident exported code blobs exceed this
    #: (blobs are never evicted mid-session — queued specs may reference
    #: any of them; growth past this means a driver re-captures fresh
    #: state in a decorator loop)
    fn_store_max_bytes: int = _f("RAY_TPU_FN_STORE_MAX_BYTES", 1 << 30)

    # -- control plane ---------------------------------------------------
    #: GCS persistence path ("" disables); RAY_TPU_GCS_PERSIST
    gcs_persist_path: Optional[str] = _f("RAY_TPU_GCS_PERSIST", None, str)
    #: bind host for every server in the process tree
    bind_host: str = _f("RAY_TPU_BIND_HOST", "127.0.0.1")
    #: advertised host when binding a wildcard address
    advertise_host: Optional[str] = _f("RAY_TPU_ADVERTISE_HOST", None, str)

    # -- workflows -------------------------------------------------------
    #: durable workflow storage root
    workflow_storage: str = _f("RAY_TPU_WORKFLOW_STORAGE",
                               "/tmp/ray_tpu/workflows")


# KV key prefix for content-addressed exported code blobs (function
# store). Lives here so client, worker, and controller share it without
# import cycles.
FN_STORE_PREFIX = "__fn__:"

_config: Optional[RayTpuConfig] = None


def get_config() -> RayTpuConfig:
    global _config
    if _config is None:
        _config = RayTpuConfig()
    return _config
