"""Container-lite worker launcher: user+mount namespaces + chroot.

Role of the reference's container runtime-env plugin
(python/ray/_private/runtime_env/image_uri.py, which shells out to
podman): bare TPU nodes in this stack's target environments have no
container runtime, but Linux user namespaces are available everywhere —
so ``image_uri: sandbox://<rootfs-dir>`` activates an unprivileged
filesystem sandbox instead:

- a new user namespace (root inside, unprivileged outside) + mount
  namespace;
- the image rootfs is mounted through an OVERLAY with a tmpfs upper
  layer, so launches never mutate the user's image directory and
  read-only rootfs (squashfs, ro binds) work; kernels without
  unprivileged overlayfs fall back to binding into the rootfs
  directly;
- the HOST runtime is recursively bind-mounted in (/usr, /lib, /opt,
  /proc, /dev incl. /dev/shm — the object-store arena must stay
  shared — plus ``--bind`` extras such as the ray_tpu package dir and
  the worker interpreter's prefix), so the sandbox always has a
  working Python;
- everything else inside the image shadows or extends the host view,
  and host paths OUTSIDE the bind set are invisible;
- ``chroot`` pivots in, the pre-chroot working directory is restored
  when it exists inside (working_dir runtime envs compose), and the
  worker command execs.

Usage:  python -m ray_tpu._private.sandbox_run ROOTFS \
            [--bind PATH]... -- CMD ARG...
"""

from __future__ import annotations

import ctypes
import os
import shlex
import sys

DEFAULT_BINDS = ("/usr", "/lib", "/lib64", "/bin", "/sbin", "/opt",
                 "/etc", "/proc", "/sys", "/dev", "/tmp", "/var",
                 "/run")

_CLONE_NEWUSER = 0x10000000
_CLONE_NEWNS = 0x00020000

_STAGE = "/tmp/.ray_tpu_sbx"


def build_script(rootfs: str, binds, cmd) -> str:
    q = shlex.quote
    lines = [
        "set -e",
        f"mkdir -p {_STAGE}",
        # per-namespace tmpfs: upper/work dirs and any mkdir fallout
        # live here, never in the user's image directory
        f"mount -t tmpfs tmpfs {_STAGE}",
        f"mkdir -p {_STAGE}/u {_STAGE}/w {_STAGE}/m",
        f"if mount -t overlay overlay -o "
        f"lowerdir={q(rootfs)},upperdir={_STAGE}/u,workdir={_STAGE}/w "
        f"{_STAGE}/m 2>/dev/null; then R={_STAGE}/m; "
        f"else R={q(rootfs)}; fi",
    ]
    for b in binds:
        if not os.path.exists(b):
            continue
        target = "$R" + b
        lines.append(f"mkdir -p {target}")
        lines.append(f"mount --rbind {q(b)} {target}")
    inner = (' cd "$RAY_TPU_SANDBOX_CWD" 2>/dev/null || cd /; '
             'exec "$@" ')
    lines.append(f"exec chroot \"$R\" /bin/sh -c {q(inner)} sh "
                 + " ".join(q(c) for c in cmd))
    return "\n".join(lines)


def _run_wide_map(script: str) -> None:
    """Root-launched namespaces get a FULL-RANGE uid/gid map.

    ``--map-root-user`` maps only the caller's uid; every file owned
    by any OTHER uid appears as the overflow uid inside the
    namespace, and mode-700 directories owned by such uids become
    untraversable even for namespace-root (no CAP_DAC_OVERRIDE over
    unmapped owners) — so binding a working dir that lives under
    e.g. a 700 /root owned by a different account fails EACCES.
    A real-root launcher holds CAP_SETUID/CAP_SETGID and may map the
    whole range, restoring normal DAC behavior with no privilege
    gained (root outside is root inside). Multi-entry maps can only
    be written by a PARENT process, so: fork, child unshares and
    execs the mount script, parent writes the maps and mirrors the
    child's exit status. Returns (falls through to the single-map
    CLI path) if the namespace or map setup is refused.
    """
    r_ready, w_ready = os.pipe()
    r_go, w_go = os.pipe()
    pid = os.fork()
    if pid == 0:
        os.close(r_ready)
        os.close(w_go)
        libc = ctypes.CDLL(None, use_errno=True)
        if libc.unshare(_CLONE_NEWUSER | _CLONE_NEWNS) != 0:
            os._exit(125)
        os.write(w_ready, b"x")
        os.close(w_ready)
        go = os.read(r_go, 1)
        os.close(r_go)
        if go != b"g":
            os._exit(125)
        os.execvp("sh", ["sh", "-c", script])
    os.close(w_ready)
    os.close(r_go)
    mapped = False
    try:
        if os.read(r_ready, 1) == b"x":
            try:
                with open(f"/proc/{pid}/setgroups", "w") as f:
                    f.write("deny")
            except OSError:
                pass            # not required when CAP_SETGID held
            with open(f"/proc/{pid}/uid_map", "w") as f:
                f.write("0 0 65536\n")
            with open(f"/proc/{pid}/gid_map", "w") as f:
                f.write("0 0 65536\n")
            mapped = True
    except OSError:
        mapped = False
    finally:
        os.close(r_ready)
    go_ok = False
    try:
        os.write(w_go, b"g" if mapped else b"n")
        go_ok = True
    except OSError:
        pass
    os.close(w_go)
    _, status = os.waitpid(pid, 0)
    code = (os.WEXITSTATUS(status) if os.WIFEXITED(status)
            else 128 + os.WTERMSIG(status))
    if not (mapped and go_ok):
        # the child never exec'd (unshare refused, map write failed,
        # or the go byte was lost) — fall back to the unshare CLI
        # path. A mapped child that received its go byte DID exec the
        # script, so its exit status is the command's own (even 125)
        # and must be mirrored, never re-run.
        return
    sys.exit(code)


def main() -> None:
    args = sys.argv[1:]
    if not args or "--" not in args:
        sys.stderr.write(__doc__ + "\n")
        sys.exit(2)
    split = args.index("--")
    head, cmd = args[:split], args[split + 1:]
    rootfs = os.path.abspath(head[0])
    extra = []
    i = 1
    while i < len(head):
        if head[i] == "--bind" and i + 1 < len(head):
            extra.append(head[i + 1])
            i += 2
        else:
            sys.stderr.write(f"unknown arg {head[i]!r}\n")
            sys.exit(2)
    if not os.path.isdir(rootfs):
        sys.stderr.write(f"rootfs {rootfs} is not a directory\n")
        sys.exit(2)
    binds = list(DEFAULT_BINDS)
    for b in extra:
        if b not in binds:
            binds.append(b)
    os.environ.setdefault("RAY_TPU_SANDBOX_CWD", os.getcwd())
    script = build_script(rootfs, binds, cmd)
    if os.geteuid() == 0:
        _run_wide_map(script)   # exits unless setup was refused
    os.execvp("unshare", ["unshare", "--user", "--map-root-user",
                          "--mount", "sh", "-c", script])


if __name__ == "__main__":
    main()
