"""CoreClient: the per-process runtime used by drivers AND workers.

Reference parity: src/ray/core_worker/core_worker.h (SubmitTask/CreateActor/
SubmitActorTask/Get/Put/Wait) + reference_count.h, collapsed into one
asyncio-native Python class. Key design choices (deliberately different from
the reference's C++ lease-based dispatch):

- Ownership: the submitting process owns task results. Workers push results
  directly to the owner's RPC server (small inline, large as a shm location),
  so `get` never touches the control plane.
- Actor calls go direct caller->actor-worker over a cached connection (one
  RTT, result inline in the response) — the controller is only consulted to
  resolve the actor's address. This is the reference's ActorTaskSubmitter
  fast path (transport/actor_task_submitter.h).
- Normal tasks go through the controller's scheduler (centralized for now;
  the lease-reuse optimization lives in the daemon's worker pool).
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import logging
import os
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import state
from .ids import ActorID, ObjectID, TaskID, WorkerID
from .object_ref import ObjectRef
from .object_store import (MemoryStore, ShmLocation, read_from_shm,
                           write_to_shm)
from .protocol import ClientPool, ConnectionLost, RpcServer
from .serialization import (INLINE_OBJECT_LIMIT, SerializedObject,
                            serialize, serialize_code)
from .streaming import ObjectRefGenerator, StreamState
from ..exceptions import (ActorDiedError, ActorError, GetTimeoutError,
                          ObjectLostError, RayTpuError, TaskCancelledError,
                          TaskError, WorkerCrashedError)

logger = logging.getLogger(__name__)

from .config import FN_STORE_PREFIX, get_config


class LoopRunner:
    """An asyncio loop, either owned (background thread) or external."""

    def __init__(self, loop: Optional[asyncio.AbstractEventLoop] = None):
        if loop is not None:
            self.loop = loop
            self._thread = None
        else:
            self.loop = asyncio.new_event_loop()
            started = threading.Event()

            def _run():
                asyncio.set_event_loop(self.loop)
                self.loop.call_soon(started.set)
                self.loop.run_forever()

            self._thread = threading.Thread(target=_run, daemon=True,
                                            name="ray_tpu-io")
            self._thread.start()
            started.wait()

    def run_sync(self, coro, timeout: Optional[float] = None):
        if self.on_loop_thread():
            raise RuntimeError(
                "blocking ray_tpu API called from the event-loop thread; "
                "inside async actors use `await ref` / the aio_* APIs.")
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        try:
            return fut.result(timeout)
        except concurrent.futures.TimeoutError:
            fut.cancel()
            raise GetTimeoutError("operation timed out")

    def call_soon(self, coro) -> None:
        try:
            asyncio.run_coroutine_threadsafe(coro, self.loop)
        except RuntimeError:
            pass  # loop already closed (interpreter shutdown)

    def on_loop_thread(self) -> bool:
        try:
            return asyncio.get_running_loop() is self.loop
        except RuntimeError:
            return False

    def stop(self) -> None:
        """Stop an OWNED loop thread (no-op for external loops)."""
        if self._thread is not None:
            try:
                self.loop.call_soon_threadsafe(self.loop.stop)
            except RuntimeError:
                return   # loop already closed (interpreter shutdown)
            self._thread.join(timeout=5)


class ReferenceCounter:
    """Distributed ref counting (owner-side authoritative).

    Reference parity: src/ray/core_worker/reference_count.h — simplified to
    local counts + a borrower count maintained at the owner.
    """

    def __init__(self, client: "CoreClient"):
        self._client = client
        self._local: Dict[str, int] = {}
        self._owner_of: Dict[str, Optional[Tuple[str, int]]] = {}
        self._borrowers: Dict[str, int] = {}   # owner side: remote holders
        self._owned: Dict[str, bool] = {}      # ids this process owns
        self._lock = threading.Lock()

    def register_owned(self, object_id: str) -> None:
        with self._lock:
            self._owned[object_id] = True
            self._borrowers.setdefault(object_id, 0)

    def add_local_ref(self, object_id: str, owner_addr, borrowed: bool) -> None:
        notify = False
        with self._lock:
            n = self._local.get(object_id, 0)
            self._local[object_id] = n + 1
            self._owner_of[object_id] = owner_addr
            if borrowed and n == 0 and not self._owned.get(object_id):
                notify = True
        if notify and owner_addr and not self._client.is_shutdown:
            self._client.loop_runner.call_soon(
                self._client._send_ref_event(owner_addr, object_id, +1))

    def remove_local_ref(self, object_id: str) -> None:
        if self._client is None or self._client.is_shutdown:
            return
        free = notify = False
        owner_addr = None
        with self._lock:
            n = self._local.get(object_id, 0) - 1
            if n <= 0:
                self._local.pop(object_id, None)
                owner_addr = self._owner_of.pop(object_id, None)
                if self._owned.get(object_id):
                    if self._borrowers.get(object_id, 0) <= 0:
                        free = True
                else:
                    notify = True
            else:
                self._local[object_id] = n
        if notify and owner_addr:
            self._client.loop_runner.call_soon(
                self._client._send_ref_event(owner_addr, object_id, -1))
        if free:
            self._client._free_owned(object_id)

    def on_borrower_event(self, object_id: str, delta: int) -> None:
        free = False
        with self._lock:
            n = self._borrowers.get(object_id, 0) + delta
            self._borrowers[object_id] = n
            if (n <= 0 and self._owned.get(object_id)
                    and self._local.get(object_id, 0) == 0):
                free = True
        if free:
            self._client._free_owned(object_id)

    def pin(self, object_id: str) -> None:
        """Prevent freeing while e.g. a task holding this arg is in flight."""
        with self._lock:
            self._borrowers[object_id] = self._borrowers.get(object_id, 0) + 1

    def unpin(self, object_id: str) -> None:
        self.on_borrower_event(object_id, -1)


class PendingTask:
    __slots__ = ("spec", "retries_left", "arg_ids")

    def __init__(self, spec: dict, retries_left: int, arg_ids=()):
        self.spec = spec
        self.retries_left = retries_left
        self.arg_ids = tuple(arg_ids)


class CoreClient:
    """Per-process runtime: submission, ownership, object access."""

    def __init__(self, controller_addr: Tuple[str, int],
                 node_addr: Optional[Tuple[str, int]],
                 session_name: str,
                 loop_runner: Optional[LoopRunner] = None,
                 worker_id: Optional[str] = None,
                 job_id: Optional[str] = None,
                 namespace: str = "default"):
        self.controller_addr = tuple(controller_addr)
        self.node_addr = tuple(node_addr) if node_addr else None
        self.session_name = session_name
        self.namespace = namespace
        self.worker_id = worker_id or WorkerID.generate().hex()
        self.job_id = job_id
        self.loop_runner = loop_runner or LoopRunner()
        self.memory_store = MemoryStore()
        self.ref_counter = ReferenceCounter(self)
        self.pool = ClientPool()
        self.server = RpcServer()
        self.server.register_object(self)
        self.address: Optional[Tuple[str, int]] = None
        self.is_shutdown = False
        self._pending_tasks: Dict[str, PendingTask] = {}
        # actor_id -> (addr or None, generation); cached resolution
        self._actor_addrs: Dict[str, Tuple[str, int]] = {}
        # Per-actor submission sequence numbers (ordering guarantee like the
        # reference's SequentialActorSubmitQueue).
        self._actor_seq: Dict[str, int] = {}
        self._actor_seq_lock = threading.Lock()
        self._actor_resolve_locks: Dict[str, asyncio.Lock] = {}
        self._shm_keepalive: Dict[str, Any] = {}
        # Pull dedup: object_id -> future resolved when the pull lands.
        self._inflight_pulls: Dict[str, asyncio.Future] = {}
        # Lineage (reference parity: task_manager.h:278 ResubmitTask):
        # return object_id -> producing task spec, kept after completion so
        # a lost object can be recomputed. Bounded FIFO.
        self._lineage: "OrderedDict[str, dict]" = OrderedDict()
        self._lineage_cap = get_config().lineage_cap
        # byte bound too: specs retain args/fn blobs (reference parity:
        # RayConfig max_lineage_bytes)
        self._lineage_max_bytes = get_config().lineage_max_bytes
        self._lineage_bytes = 0
        self._reconstructing: Dict[str, asyncio.Future] = {}
        # Streaming generators we own: generator_id -> StreamState.
        self._streams: Dict[str, "StreamState"] = {}
        # pubsub topic -> callbacks (messages arrive via rpc_pubsub_message)
        self._subscriptions: Dict[str, list] = {}
        # Function store (reference parity: _private/function_manager.py —
        # fn/class defs exported once to GCS KV, workers lazy-import):
        # fn_hash -> asyncio.Future resolved when the KV export landed.
        self._exported_fns: Dict[str, asyncio.Future] = {}
        # Submit batching (reference parity: the lease/fast-path goal of
        # normal_task_submitter.h — amortize control-plane RPCs): specs
        # issued in the same loop tick ride one controller call.
        self._submit_batch: List[Tuple[dict, asyncio.Future]] = []
        self._submit_flush_scheduled = False
        # Worker-lease fast path (reference parity:
        # normal_task_submitter.h:72-140 lease caching): plain CPU tasks
        # go client->worker directly on leased workers; leases scale
        # with backlog and idle out. key -> _LeaseGroup.
        self._lease_groups: Dict[tuple, "_LeaseGroup"] = {}
        self._lease_pump_tasks: set = set()
        # key -> monotonic time before which lease requests are skipped
        # (set on an 'unavailable' miss; survives group teardown)
        self._lease_cooldown_until: Dict[tuple, float] = {}
        # local-daemon grant path (distributed dispatch): after a
        # 'spill' (or local-daemon connection failure), skip the local
        # attempt for that lease key for a while so saturated nodes
        # don't pay a wasted hop per pump; 'unsupported' (local leasing
        # disabled by config) turns the path off for good
        self._local_lease_skip_until: Dict[tuple, float] = {}
        self._local_lease_unsupported = False

    # ------------------------------------------------------------- lifecycle

    async def async_start(self) -> None:
        self.address = await self.server.start()

    def start(self) -> None:
        self.loop_runner.run_sync(self.async_start(), timeout=10)

    def shutdown(self) -> None:
        self.is_shutdown = True
        try:
            self.loop_runner.run_sync(self._async_shutdown(), timeout=5)
        except Exception:
            pass

    async def _async_shutdown(self) -> None:
        task = getattr(self, "_subscription_task", None)
        if task is not None:
            task.cancel()
        pumps = list(self._lease_pump_tasks)
        for pump in pumps:
            pump.cancel()
        if pumps:
            # Reap them: a cancelled-but-unawaited task logs
            # "Task was destroyed but it is pending" at loop close.
            await asyncio.gather(*pumps, return_exceptions=True)
        await self.server.stop()
        await self.pool.close_all()

    def _controller(self):
        return self.pool.get(self.controller_addr)

    def _daemon(self):
        return self.pool.get(self.node_addr)

    def arena_room(self, nbytes: int) -> None:
        """Ask our node daemon to spill until ~nbytes of arena space are
        free. Callable from any non-loop thread (write_to_shm hook)."""
        self.loop_runner.run_sync(self._daemon().call(
            "ensure_arena_room", nbytes=nbytes), timeout=60)

    # ----------------------------------------------------------- server rpcs

    async def rpc_object_ready(self, object_id: str = None, payload=None,
                               location=None, error=None,
                               task_id: Optional[str] = None,
                               object_ids=None, stream_of: str = None,
                               stream_index: int = None,
                               worker_addr=None) -> None:
        """A worker pushed a task result to us (we are the owner).

        Errors may carry `object_ids` (all return ids of a failed task) so a
        multi-return task fails every ref atomically — and a retry decision
        is made once, before anything is stored.

        Streaming items carry `stream_of` (generator id) + `stream_index`;
        they are stored like any owned object and indexed into the
        generator's StreamState.
        """
        if stream_of is not None:
            stream = self._streams.get(stream_of)
            if stream is None or stream.cancelled:
                # Abandoned stream: nothing will ever consume this item.
                # Drop it immediately and (re)send the producer cancel —
                # this is also how a producer whose first push raced the
                # consumer's close() learns to stop.
                self.memory_store.delete(object_id)
                if stream is not None:
                    if worker_addr is not None:
                        stream.worker_addr = tuple(worker_addr)
                    await self._send_stream_cancel(stream)
                return
            if error is not None:
                err = (error if isinstance(error, Exception)
                       else RayTpuError(str(error)))
                self.memory_store.put_error(object_id, err)
            elif location is not None:
                self.memory_store.put_location(object_id, location)
            else:
                self.memory_store.put_serialized(
                    object_id, SerializedObject.from_flat(payload))
            self.ref_counter.register_owned(object_id)
            stream.put(stream_index, object_id, worker_addr)
            return
        pending = self._pending_tasks.pop(task_id, None) if task_id else None
        if error is not None:
            err = error if isinstance(error, Exception) else RayTpuError(str(error))
            # Streaming tasks are NOT retried wholesale: items 0..k may
            # already be consumed and replaying them would double-register
            # the same object ids. The stream fails instead.
            retriable = (isinstance(err, WorkerCrashedError)
                         and not (pending is not None and pending.spec.get(
                             "num_returns") == "streaming"))
            if retriable and pending is not None and pending.retries_left > 0:
                pending.retries_left -= 1
                self._pending_tasks[task_id] = pending
                logger.warning("retrying task %s (%d retries left)",
                               pending.spec.get("name"), pending.retries_left)
                await self._controller().call("submit_task", spec=pending.spec)
                return
            for oid in (object_ids or [object_id]):
                if pending is None and self.memory_store.contains(oid):
                    # Late failure report for a task whose result already
                    # arrived (e.g. a daemon shutting down cancels its
                    # long-completed run_task RPC and reports a spurious
                    # crash) — never clobber a completed object.
                    continue
                self.memory_store.put_error(oid, err)
                stream = self._streams.get(oid)
                if stream is not None:
                    # a streaming task failed wholesale (e.g. worker crash
                    # before/while generating): wake its consumers
                    stream.fail(err)
            self._unpin_args(pending)
            return
        if location is not None:
            self.memory_store.put_location(object_id, location)
        else:
            self.memory_store.put_serialized(
                object_id, SerializedObject.from_flat(payload))
        if pending is not None:
            self._record_lineage(pending.spec)
        self._unpin_args(pending)

    def _unpin_args(self, pending: Optional[PendingTask]) -> None:
        if pending is None:
            return
        for arg_id in pending.arg_ids:
            self.ref_counter.unpin(arg_id)

    # ------------------------------------------------------------- lineage

    @staticmethod
    def _spec_bytes(spec: dict) -> int:
        return (len(spec.get("args_blob") or b"")
                + len(spec.get("fn_blob") or b""))

    def _record_lineage(self, spec: dict) -> None:
        """Remember which task produced each return object, so a lost copy
        can be recomputed (reference: task_manager.h:278 ResubmitTask)."""
        if spec.get("is_actor_creation"):
            return
        for rid in spec.get("return_ids") or [spec["return_id"]]:
            if rid not in self._lineage:
                self._lineage_bytes += self._spec_bytes(spec)
            self._lineage[rid] = spec
            self._lineage.move_to_end(rid)
        while self._lineage and (
                len(self._lineage) > self._lineage_cap
                or self._lineage_bytes > self._lineage_max_bytes):
            _, old = self._lineage.popitem(last=False)
            self._lineage_bytes -= self._spec_bytes(old)

    async def _reconstruct_object(self, object_id: str) -> bool:
        """Re-execute the producing task of a lost owned object. Returns
        True when the object is available again. Concurrent calls for the
        same producing task share one resubmission."""
        spec = self._lineage.get(object_id)
        if spec is None:
            return False
        task_id = spec["task_id"]
        fut = self._reconstructing.get(task_id)
        if fut is None:
            fut = asyncio.get_running_loop().create_future()
            self._reconstructing[task_id] = fut

            async def _run():
                try:
                    for rid in spec.get("return_ids") or [spec["return_id"]]:
                        self.memory_store.delete(rid)
                    # A hard node pin is meaningless on reconstruction —
                    # the pinned node is typically the one that died.
                    resubmit = spec
                    sched = spec.get("scheduling") or {}
                    if sched.get("type") == "node_affinity" \
                            and not sched.get("soft"):
                        resubmit = dict(spec)
                        resubmit["scheduling"] = dict(sched, soft=True)
                    self._pending_tasks[task_id] = PendingTask(resubmit, 1, ())
                    logger.warning(
                        "reconstructing lost object %s by re-executing %s",
                        object_id[:12], spec.get("name"))
                    try:
                        await self._controller().call(
                            "submit_task", spec=resubmit)
                    except Exception:
                        self._pending_tasks.pop(task_id, None)
                        raise
                    fut.set_result(True)
                except Exception as e:
                    fut.set_exception(e)
                finally:
                    self._reconstructing.pop(task_id, None)

            asyncio.ensure_future(_run())
        try:
            await fut
        except Exception:
            return False
        return await self.memory_store.wait_available(object_id, 120.0)

    async def rpc_reconstruct_object(self, object_id: str) -> dict:
        """A borrower observed our object's copy is gone; recompute it."""
        try:
            ok = await self._reconstruct_object(object_id)
        except Exception as e:
            return {"status": "failed", "error": repr(e)}
        return {"status": "ok" if ok else "unavailable"}

    async def rpc_get_object(self, object_id: str, timeout: Optional[float] = None):
        """Serve one of our owned objects to a borrower."""
        ok = await self.memory_store.wait_available(object_id, timeout or 120.0)
        if not ok:
            return {"status": "timeout"}
        entry = self.memory_store.get_entry(object_id)
        if entry is None:
            return {"status": "lost"}
        if entry.location is not None:
            return {"status": "location", "location": entry.location}
        if entry.serialized is not None:
            return {"status": "inline", "payload": entry.serialized.to_flat()}
        if entry.has_value:
            if isinstance(entry.value, Exception):
                return {"status": "error", "error": entry.value}
            return {"status": "inline", "payload": serialize(entry.value).to_flat()}
        return {"status": "lost"}

    async def rpc_stream_end(self, generator_id: str, count: int,
                             task_id: Optional[str] = None) -> None:
        """End-of-stream from the executing worker (a generator that
        raised delivers the error as its final item first)."""
        pending = self._pending_tasks.pop(task_id, None) if task_id else None
        stream = self._streams.get(generator_id)
        if stream is not None:
            stream.finish(count)
            if stream.cancelled:
                self._streams.pop(generator_id, None)
        self._unpin_args(pending)

    # --------------------------------------------------------------- pubsub

    async def rpc_pubsub_message(self, topic: str, message) -> None:
        for cb in self._subscriptions.get(topic, []):
            try:
                cb(message)
            except Exception:
                logger.exception("pubsub callback for %r failed", topic)

    def subscribe(self, topic: str, callback) -> None:
        """Register a callback for a pubsub topic (controller-brokered).
        Registration is refreshed periodically: a transient controller
        outage or a controller RESTART (whose subscriber table is
        volatile) must not silently end the stream. The controller
        dedupes, so refreshes are idempotent."""
        first = not self._subscriptions
        self._subscriptions.setdefault(topic, []).append(callback)
        if first:
            async def _spawn():
                self._subscription_task = asyncio.ensure_future(
                    self._subscription_keeper())

            self.loop_runner.call_soon(_spawn())

    async def _subscription_keeper(self) -> None:
        try:
            while not self.is_shutdown:
                for topic in list(self._subscriptions):
                    try:
                        await self._controller().call(
                            "subscribe", topic=topic, addr=self.address)
                    except Exception:
                        pass
                await asyncio.sleep(5.0)
        except asyncio.CancelledError:
            pass

    async def rpc_ref_event(self, object_id: str, delta: int) -> None:
        self.ref_counter.on_borrower_event(object_id, delta)

    async def rpc_ping(self) -> str:
        return "pong"

    async def _send_ref_event(self, owner_addr, object_id: str, delta: int):
        try:
            await self.pool.get(owner_addr).oneway(
                "ref_event", object_id=object_id, delta=delta)
        except Exception:
            pass

    # ------------------------------------------------------------------ put

    def put(self, value: Any) -> ObjectRef:
        object_id = ObjectID.generate().hex()
        self.ref_counter.register_owned(object_id)
        serialized = serialize(value)
        if serialized.total_size <= INLINE_OBJECT_LIMIT or self.node_addr is None:
            self.memory_store.put_value(object_id, value, serialized)
        else:
            shm_name, size = write_to_shm(
                object_id, serialized, self.session_name,
                arena_room=self.arena_room)
            location = ShmLocation(self.node_addr, shm_name, size)
            self.loop_runner.run_sync(self._daemon().call(
                "register_object", object_id=object_id,
                shm_name=shm_name, size=size))
            self.memory_store.put_location(object_id, location)
        return ObjectRef(object_id, self.address, _client=self)

    # ------------------------------------------------------------------ get

    def get(self, refs, timeout: Optional[float] = None):
        single = isinstance(refs, ObjectRef)
        ref_list = [refs] if single else list(refs)
        deadline = None if timeout is None else time.monotonic() + timeout

        async def _gather():
            return await asyncio.gather(
                *[self.aio_get(r, deadline=deadline) for r in ref_list])

        outer = None if timeout is None else timeout + 5.0
        values = self.loop_runner.run_sync(_gather(), timeout=outer)
        return values[0] if single else values

    async def aio_get(self, ref: ObjectRef, deadline: Optional[float] = None):
        object_id = ref.id
        lost_attempts = 0
        while True:
            entry = self.memory_store.get_entry(object_id)
            is_owner = ref.owner_addr == self.address or ref.owner_addr is None
            if entry is not None:
                try:
                    return await self._materialize(object_id, entry)
                except ObjectLostError:
                    # A copy existed but is gone (node death, spill race,
                    # freed shm). Try lineage reconstruction, ours or the
                    # owner's, then loop to re-fetch.
                    lost_attempts += 1
                    if lost_attempts > 2:
                        raise
                    self.memory_store.delete(object_id)
                    if is_owner:
                        if not await self._reconstruct_object(object_id):
                            raise
                    else:
                        try:
                            reply = await self.pool.get(ref.owner_addr).call(
                                "reconstruct_object", object_id=object_id)
                        except (ConnectionLost, OSError):
                            raise ObjectLostError(
                                f"owner of {object_id[:12]} is gone")
                        if reply.get("status") != "ok":
                            raise
                    continue
            if is_owner:
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise GetTimeoutError(f"get timed out on {object_id[:12]}")
                ok = await self.memory_store.wait_available(object_id, remaining)
                if not ok:
                    raise GetTimeoutError(f"get timed out on {object_id[:12]}")
                continue
            # Borrowed ref: fetch from owner.
            remaining = None if deadline is None else deadline - time.monotonic()
            if remaining is not None and remaining <= 0:
                raise GetTimeoutError(f"get timed out on {object_id[:12]}")
            try:
                reply = await self.pool.get(ref.owner_addr).call(
                    "get_object", object_id=object_id, timeout=remaining)
            except ConnectionLost:
                raise ObjectLostError(
                    f"owner of {object_id[:12]} at {ref.owner_addr} is gone")
            status = reply["status"]
            if status == "timeout":
                continue  # loop re-checks deadline
            if status == "lost":
                raise ObjectLostError(f"object {object_id[:12]} was freed")
            if status == "error":
                raise reply["error"]
            if status == "location":
                if self.memory_store.get_entry(object_id) is None:
                    self.memory_store.put_location(object_id, reply["location"])
                continue  # loop: materialize via the reconstruction-guarded path
            serialized = SerializedObject.from_flat(reply["payload"])
            value = serialized.deserialize()
            self.memory_store.put_value(object_id, value)
            return value

    async def _materialize(self, object_id: str, entry):
        if entry.has_value:
            if entry.is_error:
                raise entry.value
            return entry.value
        if entry.serialized is not None:
            value = entry.serialized.deserialize()
            entry.value = value
            entry.has_value = True
            return value
        if entry.location is not None:
            # Dedup concurrent pulls of the same object: one puller, the
            # rest await its result (reference parity: pull_manager.h).
            fut = self._inflight_pulls.get(object_id)
            if fut is not None:
                await fut
                return await self._materialize(
                    object_id, self.memory_store.get_entry(object_id) or entry)
            fut = asyncio.get_running_loop().create_future()
            self._inflight_pulls[object_id] = fut
            try:
                value = await self._pull_location(object_id, entry)
                entry.value = value
                entry.has_value = True
                fut.set_result(None)
                return value
            except BaseException as e:
                fut.set_exception(e)
                fut.exception()  # consumed; don't warn on GC
                raise
            finally:
                self._inflight_pulls.pop(object_id, None)
        raise ObjectLostError(f"object {object_id[:12]} has no data")

    async def _pull_location(self, object_id: str, entry):
        loc: ShmLocation = entry.location
        if self._shm_is_local(loc):
            try:
                value, shm = await asyncio.get_running_loop().run_in_executor(
                    None, read_from_shm, loc.shm_name, loc.size)
                entry.shm_keepalive = shm
                return value
            except FileNotFoundError:
                # shm copy gone (e.g. spilled to disk); the owning daemon
                # can still serve the bytes
                pass
        return await self._fetch_from_node(object_id, loc)

    async def _fetch_from_node(self, object_id: str, loc: ShmLocation):
        """Pull an object's bytes from its node daemon — chunked above the
        threshold so a multi-GiB object is never one RPC frame (reference
        parity: ObjectManager chunked push/pull, object_manager.h:208-216)."""
        from .transfer import fetch_flat
        try:
            flat = await fetch_flat(self.pool.get(loc.node_addr),
                                    object_id, loc.size)
            # from_flat wraps a memoryview: no second multi-GiB copy
            return SerializedObject.from_flat(flat).deserialize()
        except ConnectionError as e:
            raise ObjectLostError(str(e))
        except (ConnectionLost, OSError):
            raise ObjectLostError(
                f"node holding object {object_id[:12]} is gone")

    def _shm_is_local(self, loc: ShmLocation) -> bool:
        # Single-machine sessions: every daemon's shm is attachable. Probe by
        # host equality; cross-host transfer goes through fetch_object.
        return loc.node_addr[0] in ("127.0.0.1", "localhost") or (
            self.node_addr is not None and loc.node_addr[0] == self.node_addr[0])

    def as_future(self, ref: ObjectRef) -> concurrent.futures.Future:
        return asyncio.run_coroutine_threadsafe(
            self.aio_get(ref), self.loop_runner.loop)

    # ----------------------------------------------------------------- wait

    def wait(self, refs: Sequence[ObjectRef], num_returns: int = 1,
             timeout: Optional[float] = None):
        if num_returns > len(refs):
            raise ValueError("num_returns exceeds number of refs")
        coro = self._aio_wait(list(refs), num_returns, timeout)
        outer = None if timeout is None else timeout + 5.0
        return self.loop_runner.run_sync(coro, timeout=outer)

    async def _aio_wait(self, refs, num_returns, timeout):
        done_ids: set = set()

        async def _one(ref):
            try:
                await self.aio_get(ref)
            except GetTimeoutError:
                raise
            except Exception:
                pass  # errored objects count as ready
            done_ids.add(ref.id)

        tasks = {asyncio.ensure_future(_one(r)): r for r in refs}
        deadline = None if timeout is None else time.monotonic() + timeout
        try:
            while len(done_ids) < num_returns:
                remaining = None if deadline is None else max(
                    0, deadline - time.monotonic())
                pending = [t for t in tasks if not t.done()]
                if not pending:
                    break
                finished, _ = await asyncio.wait(
                    pending, timeout=remaining,
                    return_when=asyncio.FIRST_COMPLETED)
                if not finished:
                    break
        finally:
            for t in tasks:
                if not t.done():
                    t.cancel()
        ready = [r for r in refs if r.id in done_ids][:max(num_returns, 0)]
        ready_set = {r.id for r in ready}
        not_ready = [r for r in refs if r.id not in ready_set]
        return ready, not_ready

    # -------------------------------------------------------- streaming

    async def aio_next_stream_item(self, generator_id: str, index: int,
                                   timeout: float = 300.0):
        stream = self._streams.get(generator_id)
        if stream is None:
            raise ValueError(f"unknown stream {generator_id[:12]}")
        try:
            oid = await asyncio.wait_for(stream.wait_for(index), timeout)
        except asyncio.TimeoutError:
            raise GetTimeoutError(
                f"stream item {index} of {generator_id[:12]} timed out")
        if oid is None:
            self._streams.pop(generator_id, None)    # fully consumed
            return None
        if stream.wants_ack and stream.worker_addr is not None:
            # ack for producer backpressure (fire-and-forget; only sent
            # when the task was submitted with a backpressure bound)
            addr = stream.worker_addr
            try:
                await self.pool.get(addr).oneway(
                    "stream_ack", generator_id=generator_id, index=index)
            except Exception:
                pass
        return ObjectRef(oid, self.address, _client=self)

    def next_stream_item(self, generator_id: str, index: int,
                         timeout: float = 300.0):
        return self.loop_runner.run_sync(
            self.aio_next_stream_item(generator_id, index, timeout))

    def release_stream(self, generator_id: str, consumed: int) -> None:
        """Drop an abandoned/finished generator: tell the producer to stop
        (it may be blocked on backpressure or producing unboundedly) and
        free unconsumed item objects this process owns.

        The StreamState stays registered (marked cancelled) until the
        producer's stream_end arrives: items still in flight are freed on
        arrival, and a producer whose address we don't know yet (nothing
        pushed so far) gets the cancel as soon as its first push lands."""
        stream = self._streams.get(generator_id)
        if stream is None:
            return
        stream.cancelled = True
        if stream.total is not None:
            self._streams.pop(generator_id, None)   # already ended

        async def _release():
            await self._send_stream_cancel(stream)
            for idx, oid in stream.items.items():
                if idx >= consumed:
                    self.memory_store.delete(oid)

        self.loop_runner.call_soon(_release())

    async def _send_stream_cancel(self, stream) -> None:
        if (stream.cancel_sent or stream.worker_addr is None
                or stream.total is not None):
            return
        stream.cancel_sent = True
        try:
            await self.pool.get(stream.worker_addr).oneway(
                "stream_cancel", generator_id=stream.generator_id)
        except Exception:
            pass

    # ----------------------------------------------------- function store

    def _fn_ref(self, blob: bytes, fn_hash: Optional[str] = None):
        """Split a code blob into task-spec fields.

        Small blobs ride inline; large ones are referenced by content hash
        and exported once to the controller KV (reference parity:
        python/ray/_private/function_manager.py export + lazy import —
        keeps hot-loop task specs and retained lineage small). Callers on
        hot loops (RemoteFunction/ActorClass) pass the hash they computed
        once at serialization time.
        Returns (spec_fields, hash_to_export_or_None).
        """
        if len(blob) <= get_config().fn_inline_limit:
            return {"fn_blob": blob}, None
        if fn_hash is None:
            import hashlib
            fn_hash = hashlib.sha1(blob).hexdigest()
        return {"fn_blob": None, "fn_hash": fn_hash}, fn_hash

    async def _ensure_fn_exported(self, fn_hash: str, blob: bytes) -> None:
        fut = self._exported_fns.get(fn_hash)
        if fut is None:
            fut = asyncio.get_running_loop().create_future()
            self._exported_fns[fn_hash] = fut
            try:
                await self._controller().call(
                    "kv_put", key=FN_STORE_PREFIX + fn_hash, value=blob,
                    overwrite=False)
            except Exception as e:
                # Drop the memo so a later submit retries the export.
                self._exported_fns.pop(fn_hash, None)
                fut.set_exception(e)
                fut.exception()      # mark retrieved for lone submitter
                raise
            else:
                fut.set_result(None)
                if len(self._exported_fns) > 4096:
                    # Drop an old resolved memo: worst case a later submit
                    # re-exports, and kv_put(overwrite=False) is idempotent.
                    for k, f in self._exported_fns.items():
                        if f.done():
                            del self._exported_fns[k]
                            break
        else:
            await asyncio.shield(fut)

    # --------------------------------------------------- lease fast path

    MAX_LEASES_PER_KEY = 8
    LEASE_IDLE_S = 2.0
    LEASE_DISPATCH_BATCH = 16    # specs per run_task_batch frame

    def _lease_key(self, spec: dict) -> Optional[tuple]:
        """Fast-path eligibility: CPU-only or CPU+TPU tasks with
        default scheduling (TPU leases are granted by the local daemon
        with chips pinned to the lease). Everything else (placement,
        custom resources, runtime envs, streaming, actors) takes the
        scheduled path."""
        if (spec.get("num_returns") == "streaming"
                or spec.get("is_actor_creation")
                or spec.get("scheduling")
                or spec.get("runtime_env")):
            return None
        res = spec.get("resources") or {}
        # CPU-only and CPU+TPU qualify: TPU leases are granted by the
        # LOCAL daemon with specific chips pinned to the lease (the
        # daemon owns chip assignment); other custom resources need
        # global placement.
        if any(k not in ("CPU", "TPU") for k in res):
            return None
        return ("cpu", float(res.get("CPU", 1.0)),
                float(res.get("TPU", 0.0)))

    async def _submit_via_lease(self, key: tuple, spec: dict) -> None:
        if time.monotonic() < self._lease_cooldown_until.get(key, 0.0):
            await self._submit_spec(spec)      # capacity miss: back off
            return
        group = self._lease_groups.get(key)
        if group is None:
            group = self._lease_groups[key] = _LeaseGroup(key)
        # flag a COPY for the wire: pending.spec (used by retries) must
        # stay clean or a retried task would double-report through the
        # leased-death sweep
        wire = dict(spec)
        wire["_leased"] = True
        group.queue.append(wire)
        # scale pumps with backlog, one new pump per enqueue at most
        if (len(group.queue) > group.num_pumps
                and group.num_pumps < self.MAX_LEASES_PER_KEY):
            group.num_pumps += 1
            task = asyncio.ensure_future(self._lease_pump(key, group))
            self._lease_pump_tasks.add(task)
            task.add_done_callback(self._lease_pump_tasks.discard)

    async def _resubmit_scheduled(self, spec: dict) -> None:
        """Send a (possibly lease-flagged) spec through the scheduled path.
        On failure the owners' refs are failed rather than stranded."""
        spec.pop("_leased", None)
        try:
            await self._submit_spec(spec)
        except Exception as e:
            err = TaskError(spec.get("name", "task"),
                            f"submission failed: {e!r}")
            for rid in spec.get("return_ids") or [spec["return_id"]]:
                self.memory_store.put_error(rid, err)
                stream = self._streams.get(rid)
                if stream is not None:
                    stream.fail(err)
            self._unpin_args(self._pending_tasks.pop(spec["task_id"], None))

    async def _drain_lease_queue(self, group: "_LeaseGroup") -> None:
        while group.queue:
            await self._resubmit_scheduled(group.queue.popleft())

    async def _lease_pump(self, key: tuple, group: "_LeaseGroup") -> None:
        """One pump = one lease: acquire a worker, drain the shared queue
        serially, idle out after LEASE_IDLE_S, release."""
        lease_id = None
        lease_local = False
        lease_daemon = None
        worker = None
        try:
            reply = None
            # Local-daemon grant first (distributed dispatch — reference
            # parity: lease requests go to the LOCAL raylet,
            # normal_task_submitter.h:189; its 'spill' sends us to the
            # controller's global scheduler).
            if (self.node_addr is not None
                    and not self._local_lease_unsupported
                    and time.monotonic()
                    >= self._local_lease_skip_until.get(key, 0.0)):
                try:
                    req = {"CPU": key[1]}
                    if key[2]:
                        req["TPU"] = key[2]
                    reply = await self.pool.get(self.node_addr).call(
                        "lease_worker_local", resources=req,
                        owner_addr=list(self.address))
                except Exception:
                    reply = None
                    self._local_lease_skip_until[key] = (
                        time.monotonic() + 5.0)
                if reply is not None and reply.get("status") != "ok":
                    if reply.get("status") == "unsupported":
                        self._local_lease_unsupported = True
                    elif reply.get("status") == "spill":
                        self._local_lease_skip_until[key] = (
                            time.monotonic() + 5.0)
                    reply = None
            if reply is None:
                if key[2]:
                    # TPU leases are local-daemon only (chip pinning);
                    # without a local grant the tasks take the scheduled
                    # path, where the daemon assigns chips per task
                    self._lease_cooldown_until[key] = (
                        time.monotonic() + 5.0)
                    await self._drain_lease_queue(group)
                    return
                reply = await self._controller().call(
                    "lease_worker", resources={"CPU": key[1]},
                    owner_addr=list(self.address))
            if reply.get("status") != "ok":
                # no capacity for MORE leases: existing pumps (if any)
                # keep draining; without any, fall back to the scheduler
                if group.num_pumps == 1:
                    self._lease_cooldown_until[key] = (
                        time.monotonic() + 5.0)
                    await self._drain_lease_queue(group)
                return
            lease_id = reply["lease_id"]
            lease_local = bool(reply.get("local"))
            lease_chips = reply.get("tpu_chips")
            worker = self.pool.get(tuple(reply["worker_addr"]))
            daemon_addr = tuple(reply["daemon_addr"])
            lease_daemon = daemon_addr
            worker_id = reply["worker_id"]
            idle_since = None
            while True:
                if not group.queue:
                    now = time.monotonic()
                    if idle_since is None:
                        idle_since = now
                    elif now - idle_since >= self.LEASE_IDLE_S:
                        return
                    await asyncio.sleep(0.05)
                    continue
                idle_since = None
                batch = [group.queue.popleft()]
                # Batch only what this pump's fair share of the backlog
                # is: deep queues amortize to LEASE_DISPATCH_BATCH per
                # frame, while a 2-task burst with 2 pumps still runs in
                # parallel (no head-of-line blocking behind a slow task).
                target = min(
                    self.LEASE_DISPATCH_BATCH,
                    max(1, (len(group.queue) + 1)
                        // max(group.num_pumps, 1)))
                while group.queue and len(batch) < target:
                    batch.append(group.queue.popleft())
                if lease_chips:
                    for s in batch:      # lease-pinned chip isolation
                        s["_tpu_chips"] = lease_chips
                try:
                    # one frame for the whole batch: tiny tasks are wire
                    # (syscall) bound, not compute bound
                    await worker.call("run_task_batch", specs=batch)
                except Exception:
                    # worker/conn gone. The daemon settles the STARTED
                    # members' failures (incl. OOM attribution) exactly
                    # once; never-started members come back as
                    # "unstarted" for clean resubmission (no retry
                    # consumed). The backlog flows back through the
                    # scheduled path.
                    reported = alive = False
                    unstarted: set = set()
                    try:
                        fate = await self.pool.get(daemon_addr).call(
                            "leased_worker_fate", worker_id=worker_id,
                            task_ids=[s["task_id"] for s in batch])
                        reported = bool(fate.get("reported"))
                        alive = bool(fate.get("alive"))
                        unstarted = set(fate.get("unstarted") or [])
                    except Exception:
                        pass
                    if not alive:
                        for s in batch:
                            if s["task_id"] in unstarted or not reported:
                                await self._resubmit_scheduled(s)
                    await self._drain_lease_queue(group)
                    return
        except Exception:
            logger.exception("lease pump failed")
            # Never strand the backlog (a stranded spec hangs its owner's
            # get() forever): push everything queued back through the
            # scheduled path, failing the owners' refs as a last resort.
            await self._drain_lease_queue(group)
        finally:
            group.num_pumps -= 1
            if group.num_pumps == 0 and not group.queue:
                self._lease_groups.pop(key, None)
            if lease_id is not None:
                try:
                    if lease_local and lease_daemon is not None:
                        await self.pool.get(lease_daemon).oneway(
                            "release_lease_local", lease_id=lease_id)
                    else:
                        await self._controller().oneway(
                            "release_lease", lease_id=lease_id)
                except Exception:
                    pass

    # ----------------------------------------------------- submit batching

    async def _submit_spec(self, spec: dict) -> dict:
        """Queue a task spec; a burst submitted in one event-loop tick is
        flushed as a single submit_tasks controller RPC."""
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        self._submit_batch.append((spec, fut))
        if not self._submit_flush_scheduled:
            self._submit_flush_scheduled = True
            loop.call_soon(
                lambda: asyncio.ensure_future(self._flush_submits()))
        return await fut

    async def _flush_submits(self) -> None:
        batch, self._submit_batch = self._submit_batch, []
        self._submit_flush_scheduled = False
        if not batch:
            return
        try:
            if len(batch) == 1:
                replies = [await self._controller().call(
                    "submit_task", spec=batch[0][0])]
            else:
                replies = await self._controller().call(
                    "submit_tasks", specs=[s for s, _ in batch])
        except Exception as e:
            for _, fut in batch:
                if not fut.done():
                    fut.set_exception(e)
            return
        for (_, fut), reply in zip(batch, replies):
            if fut.done():
                continue
            if isinstance(reply, dict) and reply.get("status") == "error":
                fut.set_exception(RuntimeError(
                    reply.get("error", "submission failed")))
            else:
                fut.set_result(reply)

    # ------------------------------------------------------------ tasks

    def submit_task(self, fn, args: tuple, kwargs: dict, opts: dict,
                    fn_blob: Optional[bytes] = None,
                    fn_hash: Optional[str] = None):
        task_id = TaskID.generate().hex()
        num_returns = opts.get("num_returns") or 1
        streaming = num_returns == "streaming"
        if streaming:
            num_returns = 1
        return_ids = [ObjectID.generate().hex() for _ in range(num_returns)]
        for rid in return_ids:
            self.ref_counter.register_owned(rid)
        arg_refs = _collect_refs(args) + _collect_refs(kwargs)
        for r in arg_refs:
            self.ref_counter.pin(r.id)
        blob = fn_blob if fn_blob is not None else serialize_code(fn)
        fn_fields, export_hash = self._fn_ref(blob, fn_hash)
        spec = {
            "task_id": task_id,
            "name": opts.get("name") or getattr(fn, "__name__", "task"),
            **fn_fields,
            "args_blob": serialize((args, kwargs)).to_flat(),
            "return_id": return_ids[0],
            "return_ids": return_ids,
            "num_returns": "streaming" if streaming else num_returns,
            "owner_addr": self.address,
            "resources": _resources_from_opts(opts, default_cpu=1.0),
            "scheduling": opts.get("scheduling_strategy"),
            "is_actor_creation": False,
            "runtime_env": opts.get("runtime_env"),
            # surfaced so the daemon's OOM kill policy can prefer
            # retriable victims (worker_killing_policy.h:39)
            "max_retries": opts.get("max_retries", 0),
            # top-level arg refs (the ones _resolve_args unwraps),
            # surfaced so the daemon can prefetch them while the task
            # waits for a worker (reference: raylet/dependency_manager.h)
            "arg_refs": _top_level_arg_refs(args, kwargs),
            # span context propagation (reference:
            # tracing_helper.py:165 _DictPropagator in task specs)
            "_trace_ctx": _inject_trace(),
        }
        if streaming:
            bp = opts.get("_generator_backpressure_num_objects")
            spec["backpressure"] = bp
            self._streams[return_ids[0]] = StreamState(
                return_ids[0], wants_ack=bool(bp))
        retries = opts.get("max_retries", 0)
        pend = PendingTask(spec, retries, [r.id for r in arg_refs])
        self._pending_tasks[task_id] = pend
        refs = [ObjectRef(rid, self.address, _client=self)
                for rid in return_ids]

        async def _submit():
            try:
                if export_hash is not None:
                    await self._ensure_fn_exported(export_hash, blob)
                key = self._lease_key(spec)
                if key is not None:
                    await self._submit_via_lease(key, spec)
                else:
                    await self._submit_spec(spec)
            except Exception as e:
                err = TaskError(spec["name"], f"submission failed: {e!r}")
                for rid in return_ids:
                    self.memory_store.put_error(rid, err)
                    stream = self._streams.get(rid)
                    if stream is not None:
                        stream.fail(err)
                self._unpin_args(self._pending_tasks.pop(task_id, None))

        self.loop_runner.call_soon(_submit())
        if streaming:
            return ObjectRefGenerator(return_ids[0], self)
        return refs[0] if num_returns == 1 else refs

    # ------------------------------------------------------------ actors

    def create_actor(self, cls, args: tuple, kwargs: dict, opts: dict,
                     cls_blob: Optional[bytes] = None,
                     cls_hash: Optional[str] = None):
        actor_id = ActorID.generate().hex()
        task_id = TaskID.generate().hex()
        return_id = ObjectID.generate().hex()
        self.ref_counter.register_owned(return_id)
        blob = cls_blob if cls_blob is not None else serialize_code(cls)
        fn_fields, export_hash = self._fn_ref(blob, cls_hash)
        spec = {
            "task_id": task_id,
            "name": opts.get("name") or f"{cls.__name__}.__init__",
            "class_name": cls.__name__,
            **fn_fields,
            "args_blob": serialize((args, kwargs)).to_flat(),
            "return_id": return_id,
            "owner_addr": self.address,
            "resources": _resources_from_opts(opts, default_cpu=0.0),
            "scheduling": opts.get("scheduling_strategy"),
            "is_actor_creation": True,
            "actor_id": actor_id,
            "actor_name": opts.get("name"),
            "namespace": opts.get("namespace") or self.namespace,
            "max_concurrency": opts.get("max_concurrency"),
            "concurrency_groups": opts.get("concurrency_groups"),
            "max_restarts": opts.get("max_restarts", 0),
            "lifetime": opts.get("lifetime"),
            "runtime_env": opts.get("runtime_env"),
            "arg_refs": _top_level_arg_refs(args, kwargs),
            "_trace_ctx": _inject_trace(),
        }
        creation_ref = ObjectRef(return_id, self.address, _client=self)

        async def _submit():
            try:
                if export_hash is not None:
                    await self._ensure_fn_exported(export_hash, blob)
                if await self._try_create_actor_local(spec):
                    return
                await self._submit_spec(spec)
            except Exception as e:
                self.memory_store.put_error(
                    return_id,
                    ActorDiedError(actor_id, f"creation submission failed: {e!r}"))

        self.loop_runner.call_soon(_submit())
        return actor_id, creation_ref

    async def _try_create_actor_local(self, spec: dict) -> bool:
        """Daemon-local actor creation (distributed dispatch): ask the
        LOCAL daemon to grant the creation from its delegated resource
        block, keeping the controller off the per-actor critical path
        (reference parity: raylet-granted actor leases,
        gcs_actor_scheduler.h). Returns True when fully handled —
        including a failed __init__, which the worker reports to us
        directly. Ineligible/declined creations return False and take
        the scheduled path."""
        if (spec.get("scheduling") or spec.get("runtime_env")
                or spec.get("actor_name")
                or spec.get("lifetime") == "detached"
                or any(k not in ("CPU", "TPU")
                       for k in (spec.get("resources") or {}))):
            return False
        if (self.node_addr is None or self._local_lease_unsupported):
            return False
        try:
            reply = await self.pool.get(self.node_addr).call(
                "create_actor_local", spec=spec)
        except Exception:
            return False
        status = (reply or {}).get("status")
        if status == "unsupported":
            self._local_lease_unsupported = True
            return False
        if status == "ok":
            # the grant reply carries the worker address: first method
            # call skips the controller directory resolve entirely
            self._actor_addrs[spec["actor_id"]] = tuple(reply["addr"])
            return True
        if status == "created_failed":
            return True          # worker already pushed us the error
        return False             # spill/error: scheduled path

    async def _reresolve_actor(self, actor_id: str, old_addr):
        lock = self._actor_resolve_locks.setdefault(actor_id, asyncio.Lock())
        async with lock:
            cur = self._actor_addrs.get(actor_id)
            if cur is not None and cur != old_addr:
                return cur  # another caller already re-resolved
            self._actor_addrs.pop(actor_id, None)
            addr = await self._resolve_actor(actor_id)
            # New incarnation: restart our submission sequence.
            with self._actor_seq_lock:
                self._actor_seq[actor_id] = 0
            return addr

    async def _resolve_actor(self, actor_id: str, wait: bool = True):
        addr = self._actor_addrs.get(actor_id)
        if addr is not None:
            return addr
        reply = await self._controller().call(
            "get_actor_info", actor_id=actor_id, wait=wait)
        if reply is None or reply.get("state") == "DEAD" \
                or reply.get("addr") is None:
            raise ActorDiedError(actor_id, (reply or {}).get("death_cause", ""))
        addr = tuple(reply["addr"])
        self._actor_addrs[actor_id] = addr
        return addr

    def submit_actor_task(self, actor_id: str, method: str, args: tuple,
                          kwargs: dict, opts: dict):
        return_id = ObjectID.generate().hex()
        streaming = opts.get("num_returns") == "streaming"
        self.ref_counter.register_owned(return_id)
        ref = ObjectRef(return_id, self.address, _client=self)
        arg_refs = _collect_refs(args) + _collect_refs(kwargs)
        for r in arg_refs:
            self.ref_counter.pin(r.id)
        args_blob = serialize((args, kwargs)).to_flat()
        with self._actor_seq_lock:
            seq = self._actor_seq.get(actor_id, 0)
            self._actor_seq[actor_id] = seq + 1
        if streaming:
            self._streams[return_id] = StreamState(
                return_id, wants_ack=bool(opts.get(
                    "_generator_backpressure_num_objects")))

        async def _call():
            try:
                await self._call_actor_inner(
                    actor_id, method, args_blob, return_id, seq,
                    streaming=streaming,
                    backpressure=opts.get(
                        "_generator_backpressure_num_objects"),
                    concurrency_group=opts.get("concurrency_group"))
            finally:
                for r in arg_refs:
                    self.ref_counter.unpin(r.id)

        self.loop_runner.call_soon(_call())
        if streaming:
            return ObjectRefGenerator(return_id, self)
        return ref

    async def _call_actor_inner(self, actor_id, method, args_blob,
                                return_id, seq, streaming=False,
                                backpressure=None, concurrency_group=None):
            addr = None
            extra = ({"streaming": True, "owner_addr": self.address,
                      "backpressure": backpressure} if streaming else {})
            if concurrency_group:
                extra["concurrency_group"] = concurrency_group

            def _fail(err):
                self.memory_store.put_error(return_id, err)
                stream = self._streams.get(return_id)
                if stream is not None:
                    stream.fail(err)

            try:
                addr = await self._resolve_actor(actor_id)
                reply = await self.pool.get(addr).call(
                    "call_actor", actor_id=actor_id, method=method,
                    args_blob=args_blob, caller=self.worker_id, seq=seq,
                    return_id=return_id, **extra)
            except ActorDiedError as e:
                _fail(e)
                return
            except (ConnectionLost, OSError):
                # The actor may have restarted elsewhere: re-resolve once and
                # retry with a fresh sequence number from the reset counter.
                try:
                    addr = await self._reresolve_actor(actor_id, addr)
                    with self._actor_seq_lock:
                        seq2 = self._actor_seq.get(actor_id, 0)
                        self._actor_seq[actor_id] = seq2 + 1
                    reply = await self.pool.get(addr).call(
                        "call_actor", actor_id=actor_id, method=method,
                        args_blob=args_blob, caller=self.worker_id, seq=seq2,
                        return_id=return_id, **extra)
                except Exception as e2:
                    _fail(e2 if isinstance(e2, ActorDiedError) else
                          ActorDiedError(actor_id,
                                         f"actor connection lost: {e2!r}"))
                    return
            except Exception as e:
                _fail(ActorDiedError(actor_id, f"call failed: {e!r}"))
                # Don't stall later seqs behind this one.
                if addr is not None:
                    try:
                        await self.pool.get(addr).oneway(
                            "skip_actor_seq", actor_id=actor_id,
                            caller=self.worker_id, seq=seq)
                    except Exception:
                        pass
                return
            status = reply["status"]
            if status == "ok":
                self.memory_store.put_serialized(
                    return_id, SerializedObject.from_flat(reply["payload"]))
            elif status == "location":
                self.memory_store.put_location(return_id, reply["location"])
            elif status == "streaming":
                pass      # items arrive via object_ready / stream_end pushes
            else:
                err = ActorError(method, reply["error_tb"])
                if streaming:
                    _fail(err)
                else:
                    self.memory_store.put_error(return_id, err)

    def kill_actor(self, actor_id: str, no_restart: bool = True) -> None:
        self.loop_runner.run_sync(self._controller().call(
            "kill_actor", actor_id=actor_id, no_restart=no_restart))
        self._actor_addrs.pop(actor_id, None)

    def controller_rpc(self, method: str, **kwargs):
        """Generic control-plane RPC (state API, job submission)."""
        return self.loop_runner.run_sync(
            self._controller().call(method, **kwargs))

    def daemon_rpc(self, addr, method: str, **kwargs):
        return self.loop_runner.run_sync(
            self.pool.get(tuple(addr)).call(method, **kwargs))

    def get_actor_handle_info(self, name: str, namespace: Optional[str]):
        return self.loop_runner.run_sync(self._controller().call(
            "get_named_actor", name=name,
            namespace=namespace or self.namespace))

    async def aio_get_actor_handle_info(self, name: str,
                                        namespace: Optional[str]):
        """Event-loop-safe named-actor lookup (for async actors)."""
        return await self._controller().call(
            "get_named_actor", name=name,
            namespace=namespace or self.namespace)

    # -------------------------------------------------------------- cluster

    def cluster_resources(self) -> Dict[str, float]:
        return self.loop_runner.run_sync(
            self._controller().call("cluster_resources"))

    def available_resources(self) -> Dict[str, float]:
        return self.loop_runner.run_sync(
            self._controller().call("available_resources"))

    def nodes(self) -> List[dict]:
        return self.loop_runner.run_sync(self._controller().call("list_nodes"))

    def kv_put(self, key: str, value: bytes, overwrite: bool = True) -> bool:
        return self.loop_runner.run_sync(self._controller().call(
            "kv_put", key=key, value=value, overwrite=overwrite))

    def kv_get(self, key: str) -> Optional[bytes]:
        return self.loop_runner.run_sync(self._controller().call("kv_get", key=key))

    def kv_del(self, key: str) -> bool:
        return self.loop_runner.run_sync(self._controller().call("kv_del", key=key))

    def kv_keys(self, prefix: str = "") -> List[str]:
        return self.loop_runner.run_sync(
            self._controller().call("kv_keys", prefix=prefix))

    # ------------------------------------------------------------- freeing

    def _free_owned(self, object_id: str) -> None:
        entry = self.memory_store.delete(object_id)
        if entry is not None and entry.location is not None:
            loc = entry.location

            async def _free():
                try:
                    await self.pool.get(loc.node_addr).oneway(
                        "free_object", object_id=object_id)
                except Exception:
                    pass

            if not self.is_shutdown:
                self.loop_runner.call_soon(_free())


class _LeaseGroup:
    """Shared backlog + pump bookkeeping for one lease key."""

    __slots__ = ("key", "queue", "num_pumps")

    def __init__(self, key: tuple):
        self.key = key
        self.queue: "deque[dict]" = deque()
        self.num_pumps = 0


def _inject_trace():
    from ..util import tracing
    return tracing.inject_context()


def _top_level_arg_refs(args: tuple, kwargs: dict) -> List[tuple]:
    """(object_id, owner_addr) for every DIRECT ObjectRef argument —
    exactly the set _resolve_args unwraps; nested refs stay refs."""
    refs = ([a for a in args if isinstance(a, ObjectRef)]
            + [v for v in kwargs.values() if isinstance(v, ObjectRef)])
    return [(r.id, r.owner_addr) for r in refs]


def _collect_refs(obj, out=None) -> List[ObjectRef]:
    if out is None:
        out = []
    if isinstance(obj, ObjectRef):
        out.append(obj)
    elif isinstance(obj, (list, tuple, set)):
        for x in obj:
            _collect_refs(x, out)
    elif isinstance(obj, dict):
        for v in obj.values():
            _collect_refs(v, out)
    return out


def _resources_from_opts(opts: dict, default_cpu: float) -> Dict[str, float]:
    resources = dict(opts.get("resources") or {})
    num_cpus = opts.get("num_cpus")
    resources["CPU"] = float(default_cpu if num_cpus is None else num_cpus)
    num_tpus = opts.get("num_tpus")
    if num_tpus:
        resources["TPU"] = float(num_tpus)
    num_gpus = opts.get("num_gpus")
    if num_gpus:
        resources["GPU"] = float(num_gpus)
    memory = opts.get("memory")
    if memory:
        resources["memory"] = float(memory)
    return {k: v for k, v in resources.items() if v}
