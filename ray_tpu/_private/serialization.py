"""Serialization: cloudpickle for code, pickle-5 out-of-band for data.

Reference parity: python/ray/_private/serialization.py — but there is no
custom binary format here; numpy / jax host arrays ride as out-of-band
buffers so they can live zero-copy in shared memory. jax.Array device
buffers are converted to host numpy at the boundary (device_get) — device
state never crosses processes (on TPU each process owns its chips).
"""

from __future__ import annotations

import io
import pickle
from typing import Any, List, Sequence, Tuple

import cloudpickle

# Objects whose serialized size is at or below this are returned inline in
# RPC replies and stored in the owner's memory store; larger ones go to the
# node shared-memory store. (Ray: max_direct_call_object_size, 100KB.)
INLINE_OBJECT_LIMIT = 100 * 1024


class SerializedObject:
    """A serialized value: pickle stream + out-of-band buffers."""

    __slots__ = ("data", "buffers")

    def __init__(self, data: bytes, buffers: Sequence[Any]):
        self.data = data
        self.buffers = list(buffers)

    @property
    def total_size(self) -> int:
        return len(self.data) + sum(len(b) for b in self.buffers)

    def deserialize(self) -> Any:
        return pickle.loads(self.data, buffers=self.buffers)

    # -- flat byte layout (for shm segments / network transfer) --
    # u32 nbuf | u64 len * (nbuf+1) | data | buffers...
    def to_flat(self) -> bytes:
        out = io.BytesIO()
        lens = [len(self.data)] + [len(b) for b in self.buffers]
        out.write(len(self.buffers).to_bytes(4, "little"))
        for n in lens:
            out.write(n.to_bytes(8, "little"))
        out.write(self.data)
        for b in self.buffers:
            out.write(b if isinstance(b, bytes) else bytes(b))
        return out.getvalue()

    def flat_size(self) -> int:
        return 4 + 8 * (1 + len(self.buffers)) + self.total_size

    def write_flat(self, view: memoryview) -> int:
        lens = [len(self.data)] + [len(b) for b in self.buffers]
        off = 0
        view[off:off + 4] = len(self.buffers).to_bytes(4, "little")
        off += 4
        for n in lens:
            view[off:off + 8] = n.to_bytes(8, "little")
            off += 8
        view[off:off + len(self.data)] = self.data
        off += len(self.data)
        for b in self.buffers:
            bl = len(b)
            view[off:off + bl] = b if isinstance(b, (bytes, memoryview)) else bytes(b)
            off += bl
        return off

    @classmethod
    def from_flat(cls, view) -> "SerializedObject":
        """Parse from a buffer; returned buffers are zero-copy views."""
        view = memoryview(view)
        nbuf = int.from_bytes(view[0:4], "little")
        off = 4
        lens = []
        for _ in range(nbuf + 1):
            lens.append(int.from_bytes(view[off:off + 8], "little"))
            off += 8
        data = bytes(view[off:off + lens[0]])
        off += lens[0]
        buffers = []
        for n in lens[1:]:
            buffers.append(view[off:off + n])
            off += n
        return cls(data, buffers)


def serialize(value: Any) -> SerializedObject:
    buffers: List[pickle.PickleBuffer] = []
    try:
        data = cloudpickle.dumps(value, protocol=5, buffer_callback=buffers.append)
    except TypeError:
        # Some objects reject out-of-band buffers; retry without.
        buffers = []
        data = cloudpickle.dumps(value, protocol=5)
    return SerializedObject(data, [b.raw() for b in buffers])


def serialize_code(obj: Any) -> bytes:
    """Serialize a function/class definition (no out-of-band split)."""
    return cloudpickle.dumps(obj)


def deserialize_code(data: bytes) -> Any:
    return pickle.loads(data)
